"""Sharded training checkpoints on the framework's own FileSystem.

This is where the two halves of the framework meet: the trainer's sharded
params/optimizer state persist into the DFS (or any FileSystem SPI impl),
the way the reference persists everything durable into HDFS (job history,
RM state, log aggregation — e.g. ZKRMStateStore.java:180,
LogAggregationService.java). Layout per checkpoint:

    <dir>/step_<N>/manifest.json        tree structure, dtypes, shapes,
                                        shard index map — written LAST
    <dir>/step_<N>/shard_<i>.bin        one file per UNIQUE device shard

Write protocol mirrors the two-phase commit used everywhere else in the
stack (attempt dir + atomic publish; ref: FileOutputCommitter): shards go
to ``step_<N>._tmp``, the manifest is written after every shard, then the
directory is renamed — a crash mid-save never corrupts the previous
checkpoint, and ``latest_step`` only ever sees complete checkpoints.

Sharding: each param/opt leaf is saved as its unique device shards
(replicated copies deduped by shard index), so N-way model parallelism
writes 1/N of each sharded leaf per "host slice" — the JAX-native
equivalent of Megatron's per-rank distributed checkpointing. On load the
global value is reassembled and re-placed with ``device_put`` under the
TARGET mesh/spec — loading into a different parallelism plan than the one
that saved is free (resharding happens at placement).
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hadoop_tpu.fs import FileSystem

log = logging.getLogger(__name__)


def _leaf_paths(tree) -> List[Tuple[str, Any]]:
    flat = jax.tree_util.tree_leaves_with_path(tree)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat]


def snapshot_tree(tree) -> List[Dict[str, Any]]:
    """Device→host snapshot of ``tree``: per leaf, its dtype/shape and
    the OWNED copies of its unique device shards (replicas deduped).

    This is the only part of a save that must happen synchronously —
    once it returns, the live arrays may be updated or donated freely
    while a background writer streams the copies out (the split behind
    ``Trainer``'s async checkpointing). Copies, not views: ``np.asarray``
    of a CPU-backed jax array can alias the device buffer, and an
    aliased snapshot would race the steps that keep training.
    """
    snap: List[Dict[str, Any]] = []
    for name, leaf in _leaf_paths(tree):
        arr = leaf
        entry: Dict[str, Any] = {
            "name": name,
            "dtype": str(np.dtype(arr.dtype)),
            "shape": list(np.shape(arr)),
            "shards": [],
        }
        if hasattr(arr, "addressable_shards"):
            seen = set()
            for sh in arr.addressable_shards:
                key = tuple((s.start, s.stop) for s in
                            _norm_index(sh.index, np.shape(arr)))
                if key in seen:
                    continue  # replicated copy
                seen.add(key)
                entry["shards"].append(
                    ([list(k) for k in key],
                     np.array(sh.data, copy=True)))
        else:
            entry["shards"].append(
                ([[0, d] for d in np.shape(arr)],
                 np.array(arr, copy=True)))
        snap.append(entry)
    return snap


def write_snapshot(fs: FileSystem, base_dir: str, step: int,
                   snap: List[Dict[str, Any]], *, keep: int = 3,
                   meta: Optional[Dict[str, Any]] = None) -> str:
    """Write a host snapshot as one checkpoint (see snapshot_tree).

    Publish protocol: shards are written straight into the final
    directory and the manifest goes LAST — its presence is the
    completeness marker list_checkpoints keys on. No rename: on an
    object store a directory rename is a lexicographic copy loop that
    lands ``manifest.json`` before the shards, so a crash mid-rename
    used to publish a manifest-complete checkpoint with missing shard
    files. A crash (or writer death) mid-write leaves a manifest-less
    directory that readers never see and the next save's retention
    sweep removes — which is exactly what makes the write safe to run
    on a background thread.

    ``meta``: an optional JSON block stored under ``manifest["meta"]``
    — the elastic plane records the writing plan here
    (``elastic.reshard.manifest_meta``) so a restore can tell whether
    it must reshard. Manifests without it are legacy same-plan-only."""
    final_dir = f"{base_dir}/step_{step:012d}"
    fs.delete(final_dir, recursive=True)
    fs.mkdirs(final_dir)

    manifest: Dict[str, Any] = {"step": step, "leaves": {}, "shards": []}
    if meta is not None:
        manifest["meta"] = meta
    shard_idx = 0
    for entry in snap:
        mentry: Dict[str, Any] = {
            "dtype": entry["dtype"],
            "shape": entry["shape"],
            "shards": [],
        }
        for index, data in entry["shards"]:
            fname = f"shard_{shard_idx:06d}.bin"
            shard_idx += 1
            fs.write_all(f"{final_dir}/{fname}", data.tobytes())
            mentry["shards"].append({"file": fname, "index": index})
        manifest["leaves"][entry["name"]] = mentry
    fs.write_all(f"{final_dir}/manifest.json",
                 json.dumps(manifest).encode())
    _retain(fs, base_dir, keep)
    return final_dir


def assemble_snapshot_leaf(entry: Dict[str, Any]) -> np.ndarray:
    """One snapshot entry's full host array, reassembled from shards."""
    out = np.empty(tuple(entry["shape"]), np.dtype(entry["dtype"]))
    for index, data in entry["shards"]:
        out[tuple(slice(a, b) for a, b in index)] = data
    return out


def reorder_snapshot_axis0(snap: List[Dict[str, Any]], perm,
                           match: Callable[[str], bool]
                           ) -> List[Dict[str, Any]]:
    """Apply ``take(perm, axis=0)`` to every snapshot entry whose name
    ``match``es — on HOST arrays, so the device never materializes the
    permuted copy (the vpp logical-reorder moved off the step path).
    A permuted axis no longer aligns with the device shard grid, so the
    affected entries collapse to one full-array shard; load_checkpoint
    reshards at placement either way."""
    perm = np.asarray(perm)
    out = []
    for entry in snap:
        if not match(entry["name"]) or len(entry["shape"]) == 0:
            out.append(entry)
            continue
        full = np.take(assemble_snapshot_leaf(entry), perm, axis=0)
        out.append({
            "name": entry["name"], "dtype": entry["dtype"],
            "shape": entry["shape"],
            "shards": [([[0, d] for d in entry["shape"]], full)],
        })
    return out


def save_checkpoint(fs: FileSystem, base_dir: str, step: int, tree,
                    *, keep: int = 3,
                    meta: Optional[Dict[str, Any]] = None) -> str:
    """Write one checkpoint of ``tree`` (any pytree of jax/np arrays),
    synchronously: snapshot_tree + write_snapshot. Returns the final
    checkpoint directory. Retains the newest ``keep`` checkpoints (ref
    intent: FSImage's NNStorageRetentionManager keeps a bounded number
    of images)."""
    return write_snapshot(fs, base_dir, step, snapshot_tree(tree),
                          keep=keep, meta=meta)


class AsyncCheckpointWriter:
    """One background writer thread, at most one write in flight.

    ``submit`` fences the previous write (so checkpoints always land in
    order and a slow DFS can never pile up host snapshots), then hands
    the job to a fresh daemon thread. A failed write surfaces at the
    NEXT fence — ``wait()``, the next ``submit``, or close — never
    silently: the job that failed left a manifest-less directory, so
    the previous complete checkpoint still wins (see write_snapshot).
    """

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._lock = threading.Lock()

    def submit(self, fn: Callable[[], Any]) -> None:
        """Fence the previous write, then run ``fn`` in the background.
        The writer thread carries the submitter's span context, so any
        span the write creates joins the training step's trace instead
        of starting an orphan root."""
        self.wait()
        from hadoop_tpu.tracing.tracer import carry_context
        traced_fn = carry_context(fn)

        def run():
            try:
                traced_fn()
            except BaseException as e:  # noqa: BLE001 — deferred to wait()
                log.warning("async checkpoint write failed: %s", e)
                with self._lock:
                    self._error = e

        t = threading.Thread(target=run, daemon=True, name="ckpt-writer")
        with self._lock:
            self._thread = t
        t.start()

    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until the in-flight write (if any) finishes; re-raise
        its error exactly once."""
        with self._lock:
            t = self._thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                raise TimeoutError("checkpoint write still in flight")
            with self._lock:
                if self._thread is t:
                    self._thread = None
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise err

    @property
    def in_flight(self) -> bool:
        with self._lock:
            t = self._thread
        return t is not None and t.is_alive()


def _norm_index(index, shape):
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else sl.start
        stop = dim if sl.stop is None else sl.stop
        out.append(slice(start, stop))
    return tuple(out)


def _retain(fs: FileSystem, base_dir: str, keep: int
            ) -> List[Tuple[str, str]]:
    """Retention sweep. Returns — and logs, one structured breadcrumb
    per removal — every ``(path, reason)`` it swept, with reason
    ``"retention"`` (a complete checkpoint aged past ``keep``) or
    ``"crash-mid-write"`` (a manifest-less orphan from a crashed or
    killed publish). An elastic resume that lands on an older snapshot
    than expected is auditable from these lines alone."""
    swept: List[Tuple[str, str]] = []
    steps = list_checkpoints(fs, base_dir)
    complete = {f"step_{s:012d}" for s in steps}
    for step in steps[:-keep] if keep > 0 else []:
        path = f"{base_dir}/step_{step:012d}"
        fs.delete(path, recursive=True)
        complete.discard(f"step_{step:012d}")
        swept.append((path, "retention"))
    # Sweep manifest-less orphans from crashed publishes (single-writer:
    # any incomplete step dir other than the one just written is ours).
    try:
        entries = fs.list_status(base_dir)
    except (IOError, OSError, FileNotFoundError):
        entries = []
    for st in entries:
        name = st.path.rstrip("/").rsplit("/", 1)[-1]
        if name.startswith("step_") and name not in complete:
            path = f"{base_dir}/{name}"
            fs.delete(path, recursive=True)
            swept.append((path, "crash-mid-write"))
    for path, reason in swept:
        log.info("checkpoint sweep: path=%s reason=%s keep=%d",
                 path, reason, keep)
    return swept


def list_checkpoints(fs: FileSystem, base_dir: str) -> List[int]:
    """Complete (manifest-bearing) checkpoint steps, ascending."""
    try:
        entries = fs.list_status(base_dir)
    except (IOError, OSError, FileNotFoundError):
        return []
    steps = []
    for st in entries:
        name = st.path.rstrip("/").rsplit("/", 1)[-1]
        if name.startswith("step_") and not name.endswith("._tmp"):
            if fs.exists(f"{base_dir}/{name}/manifest.json"):
                steps.append(int(name[len("step_"):]))
    return sorted(steps)


def latest_step(fs: FileSystem, base_dir: str) -> Optional[int]:
    steps = list_checkpoints(fs, base_dir)
    return steps[-1] if steps else None


def read_manifest(fs: FileSystem, base_dir: str, step: int
                  ) -> Dict[str, Any]:
    """One checkpoint's manifest (the elastic restore path reads the
    plan block before deciding how to load the shards)."""
    path = f"{base_dir}/step_{step:012d}/manifest.json"
    return json.loads(fs.read_all(path).decode())


def load_checkpoint(fs: FileSystem, base_dir: str, like, *,
                    step: Optional[int] = None,
                    mesh: Optional[Mesh] = None, specs=None,
                    io_workers: int = 1,
                    leaf_transform: Optional[Callable[[str, np.ndarray],
                                                      Any]] = None):
    """Load a checkpoint into the structure of ``like`` (a pytree of
    arrays or ShapeDtypeStructs). With ``mesh``+``specs`` the leaves are
    placed sharded (resharding from the saved layout is implicit).

    ``io_workers > 1`` fetches the shard files of the requested leaves
    through a bounded thread pool (each read opens its own stream, so
    concurrent fetches are independent) — cold-start over a DFS is pure
    IO fan-in latency, and the pool overlaps it the way hedged reads
    overlap a single slow replica. Only shards of leaves present in
    ``like`` are fetched (a serving load never reads optimizer shards).

    ``leaf_transform(name, array)`` switches the load to STREAMING
    mode: leaves are fetched one at a time (shards of each leaf still
    ride the pool concurrently), the transform consumes the assembled
    host array immediately, and its result — a plain array or a small
    pytree of arrays (the serving weight plane returns int8 payload +
    scale dicts) — is what lands on device. The assembled f32 buffer
    dies as soon as the transform returns, so peak host memory is
    bounded by the LARGEST leaf, never the whole checkpoint — the
    contract quantize-at-load relies on. Not combinable with
    ``mesh``/``specs`` (a transformed leaf has no single spec).
    """
    if step is None:
        step = latest_step(fs, base_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {base_dir}")
    ckpt_dir = f"{base_dir}/step_{step:012d}"
    manifest = json.loads(fs.read_all(f"{ckpt_dir}/manifest.json").decode())

    spec_by_name = dict(_leaf_paths(specs)) if specs is not None else {}
    flat, treedef = jax.tree_util.tree_flatten_with_path(like)

    if leaf_transform is not None:
        if mesh is not None or specs is not None:
            raise NotImplementedError(
                "leaf_transform streams leaves through a host-side "
                "transform and cannot compose with sharded placement")
        return _load_streaming(fs, ckpt_dir, manifest, flat, treedef,
                               step, io_workers, leaf_transform)

    raw_by_file: Dict[str, bytes] = {}
    if io_workers > 1:
        needed: List[str] = []
        for path, _ in flat:
            entry = manifest["leaves"].get(jax.tree_util.keystr(path))
            if entry is not None:
                needed.extend(sh["file"] for sh in entry["shards"])
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=io_workers) as ex:
            raw_by_file = dict(zip(needed, ex.map(
                lambda f: fs.read_all(f"{ckpt_dir}/{f}"), needed)))

    def build(path, leaf):
        name = jax.tree_util.keystr(path)
        entry = manifest["leaves"].get(name)
        if entry is None:
            raise KeyError(f"checkpoint {ckpt_dir} missing leaf {name}")
        shape = tuple(entry["shape"])
        dtype = np.dtype(entry["dtype"])
        if tuple(np.shape(leaf)) != shape:
            raise ValueError(f"shape mismatch for {name}: checkpoint "
                             f"{shape} vs expected {tuple(np.shape(leaf))}")
        out = np.empty(shape, dtype)
        for sh in entry["shards"]:
            # pop, don't get: the prefetched bytes free as each leaf is
            # assembled, so peak memory stays ~one checkpoint, not two
            raw = raw_by_file.pop(sh["file"], None)
            if raw is None:
                raw = fs.read_all(f"{ckpt_dir}/{sh['file']}")
            idx = tuple(slice(a, b) for a, b in sh["index"])
            sub_shape = tuple(b - a for a, b in sh["index"])
            out[idx] = np.frombuffer(raw, dtype).reshape(sub_shape)
        if mesh is not None and specs is not None:
            spec = spec_by_name.get(name, P())
            return jax.device_put(out, NamedSharding(mesh, spec))
        return jax.numpy.asarray(out)

    rebuilt = [build(p, leaf) for p, leaf in flat]
    return jax.tree_util.tree_unflatten(treedef, rebuilt), step


def _load_streaming(fs: FileSystem, ckpt_dir: str, manifest: Dict,
                    flat, treedef, step: int, io_workers: int,
                    leaf_transform: Callable[[str, np.ndarray], Any]):
    """The ``leaf_transform`` mode of :func:`load_checkpoint`: one leaf
    in flight at a time (its shard files fetched concurrently), the
    transform's result placed on device, the f32 assembly dropped —
    peak host memory stays ~the largest leaf plus its raw shards."""
    from concurrent.futures import ThreadPoolExecutor

    ex = ThreadPoolExecutor(max_workers=max(1, io_workers))
    try:
        def build(path, leaf):
            name = jax.tree_util.keystr(path)
            entry = manifest["leaves"].get(name)
            if entry is None:
                raise KeyError(f"checkpoint {ckpt_dir} missing leaf "
                               f"{name}")
            shape = tuple(entry["shape"])
            dtype = np.dtype(entry["dtype"])
            if tuple(np.shape(leaf)) != shape:
                raise ValueError(
                    f"shape mismatch for {name}: checkpoint {shape} vs "
                    f"expected {tuple(np.shape(leaf))}")
            shards = entry["shards"]
            raws = list(ex.map(
                lambda sh: fs.read_all(f"{ckpt_dir}/{sh['file']}"),
                shards))
            out = np.empty(shape, dtype)
            for sh, raw in zip(shards, raws):
                idx = tuple(slice(a, b) for a, b in sh["index"])
                sub = tuple(b - a for a, b in sh["index"])
                out[idx] = np.frombuffer(raw, dtype).reshape(sub)
            del raws
            res = leaf_transform(name, out)
            del out
            return jax.tree_util.tree_map(jax.numpy.asarray, res)

        rebuilt = [build(p, leaf) for p, leaf in flat]
    finally:
        ex.shutdown(wait=True)
    return jax.tree_util.tree_unflatten(treedef, rebuilt), step
