"""Sharded training checkpoints on the framework's own FileSystem.

This is where the two halves of the framework meet: the trainer's sharded
params/optimizer state persist into the DFS (or any FileSystem SPI impl),
the way the reference persists everything durable into HDFS (job history,
RM state, log aggregation — e.g. ZKRMStateStore.java:180,
LogAggregationService.java). Layout per checkpoint:

    <dir>/step_<N>/manifest.json        tree structure, dtypes, shapes,
                                        shard index map — written LAST
    <dir>/step_<N>/shard_<i>.bin        one file per UNIQUE device shard

Write protocol mirrors the two-phase commit used everywhere else in the
stack (attempt dir + atomic publish; ref: FileOutputCommitter): shards go
to ``step_<N>._tmp``, the manifest is written after every shard, then the
directory is renamed — a crash mid-save never corrupts the previous
checkpoint, and ``latest_step`` only ever sees complete checkpoints.

Sharding: each param/opt leaf is saved as its unique device shards
(replicated copies deduped by shard index), so N-way model parallelism
writes 1/N of each sharded leaf per "host slice" — the JAX-native
equivalent of Megatron's per-rank distributed checkpointing. On load the
global value is reassembled and re-placed with ``device_put`` under the
TARGET mesh/spec — loading into a different parallelism plan than the one
that saved is free (resharding happens at placement).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hadoop_tpu.fs import FileSystem


def _leaf_paths(tree) -> List[Tuple[str, Any]]:
    flat = jax.tree_util.tree_leaves_with_path(tree)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat]


def save_checkpoint(fs: FileSystem, base_dir: str, step: int, tree,
                    *, keep: int = 3) -> str:
    """Write one checkpoint of ``tree`` (any pytree of jax/np arrays).

    Returns the final checkpoint directory. Retains the newest ``keep``
    checkpoints (ref intent: FSImage's NNStorageRetentionManager keeps a
    bounded number of images).

    Publish protocol: shards are written straight into the final
    directory and the manifest goes LAST — its presence is the
    completeness marker list_checkpoints keys on. No rename: on an
    object store a directory rename is a lexicographic copy loop that
    lands ``manifest.json`` before the shards, so a crash mid-rename
    used to publish a manifest-complete checkpoint with missing shard
    files. A crash mid-write now leaves a manifest-less directory that
    readers never see and the next save's retention sweep removes."""
    final_dir = f"{base_dir}/step_{step:012d}"
    tmp_dir = final_dir
    fs.delete(final_dir, recursive=True)
    fs.mkdirs(tmp_dir)

    manifest: Dict[str, Any] = {"step": step, "leaves": {}, "shards": []}
    shard_idx = 0
    for name, leaf in _leaf_paths(tree):
        arr = leaf
        entry: Dict[str, Any] = {
            "dtype": str(np.dtype(arr.dtype)),
            "shape": list(np.shape(arr)),
            "shards": [],
        }
        if hasattr(arr, "addressable_shards"):
            seen = set()
            for sh in arr.addressable_shards:
                key = tuple((s.start, s.stop) for s in
                            _norm_index(sh.index, np.shape(arr)))
                if key in seen:
                    continue  # replicated copy
                seen.add(key)
                fname = f"shard_{shard_idx:06d}.bin"
                shard_idx += 1
                fs.write_all(f"{tmp_dir}/{fname}",
                             np.asarray(sh.data).tobytes())
                entry["shards"].append({"file": fname,
                                        "index": [list(k) for k in key]})
        else:
            fname = f"shard_{shard_idx:06d}.bin"
            shard_idx += 1
            fs.write_all(f"{tmp_dir}/{fname}", np.asarray(arr).tobytes())
            entry["shards"].append({
                "file": fname,
                "index": [[0, d] for d in np.shape(arr)]})
        manifest["leaves"][name] = entry
    fs.write_all(f"{tmp_dir}/manifest.json",
                 json.dumps(manifest).encode())
    _retain(fs, base_dir, keep)
    return final_dir


def _norm_index(index, shape):
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else sl.start
        stop = dim if sl.stop is None else sl.stop
        out.append(slice(start, stop))
    return tuple(out)


def _retain(fs: FileSystem, base_dir: str, keep: int) -> None:
    steps = list_checkpoints(fs, base_dir)
    complete = {f"step_{s:012d}" for s in steps}
    for step in steps[:-keep] if keep > 0 else []:
        fs.delete(f"{base_dir}/step_{step:012d}", recursive=True)
        complete.discard(f"step_{step:012d}")
    # Sweep manifest-less orphans from crashed publishes (single-writer:
    # any incomplete step dir other than the one just written is ours).
    try:
        entries = fs.list_status(base_dir)
    except (IOError, OSError, FileNotFoundError):
        return
    for st in entries:
        name = st.path.rstrip("/").rsplit("/", 1)[-1]
        if name.startswith("step_") and name not in complete:
            fs.delete(f"{base_dir}/{name}", recursive=True)


def list_checkpoints(fs: FileSystem, base_dir: str) -> List[int]:
    """Complete (manifest-bearing) checkpoint steps, ascending."""
    try:
        entries = fs.list_status(base_dir)
    except (IOError, OSError, FileNotFoundError):
        return []
    steps = []
    for st in entries:
        name = st.path.rstrip("/").rsplit("/", 1)[-1]
        if name.startswith("step_") and not name.endswith("._tmp"):
            if fs.exists(f"{base_dir}/{name}/manifest.json"):
                steps.append(int(name[len("step_"):]))
    return sorted(steps)


def latest_step(fs: FileSystem, base_dir: str) -> Optional[int]:
    steps = list_checkpoints(fs, base_dir)
    return steps[-1] if steps else None


def load_checkpoint(fs: FileSystem, base_dir: str, like, *,
                    step: Optional[int] = None,
                    mesh: Optional[Mesh] = None, specs=None,
                    io_workers: int = 1):
    """Load a checkpoint into the structure of ``like`` (a pytree of
    arrays or ShapeDtypeStructs). With ``mesh``+``specs`` the leaves are
    placed sharded (resharding from the saved layout is implicit).

    ``io_workers > 1`` fetches the shard files of the requested leaves
    through a bounded thread pool (each read opens its own stream, so
    concurrent fetches are independent) — cold-start over a DFS is pure
    IO fan-in latency, and the pool overlaps it the way hedged reads
    overlap a single slow replica. Only shards of leaves present in
    ``like`` are fetched (a serving load never reads optimizer shards).
    """
    if step is None:
        step = latest_step(fs, base_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {base_dir}")
    ckpt_dir = f"{base_dir}/step_{step:012d}"
    manifest = json.loads(fs.read_all(f"{ckpt_dir}/manifest.json").decode())

    spec_by_name = dict(_leaf_paths(specs)) if specs is not None else {}
    flat, treedef = jax.tree_util.tree_flatten_with_path(like)

    raw_by_file: Dict[str, bytes] = {}
    if io_workers > 1:
        needed: List[str] = []
        for path, _ in flat:
            entry = manifest["leaves"].get(jax.tree_util.keystr(path))
            if entry is not None:
                needed.extend(sh["file"] for sh in entry["shards"])
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=io_workers) as ex:
            raw_by_file = dict(zip(needed, ex.map(
                lambda f: fs.read_all(f"{ckpt_dir}/{f}"), needed)))

    def build(path, leaf):
        name = jax.tree_util.keystr(path)
        entry = manifest["leaves"].get(name)
        if entry is None:
            raise KeyError(f"checkpoint {ckpt_dir} missing leaf {name}")
        shape = tuple(entry["shape"])
        dtype = np.dtype(entry["dtype"])
        if tuple(np.shape(leaf)) != shape:
            raise ValueError(f"shape mismatch for {name}: checkpoint "
                             f"{shape} vs expected {tuple(np.shape(leaf))}")
        out = np.empty(shape, dtype)
        for sh in entry["shards"]:
            # pop, don't get: the prefetched bytes free as each leaf is
            # assembled, so peak memory stays ~one checkpoint, not two
            raw = raw_by_file.pop(sh["file"], None)
            if raw is None:
                raw = fs.read_all(f"{ckpt_dir}/{sh['file']}")
            idx = tuple(slice(a, b) for a, b in sh["index"])
            sub_shape = tuple(b - a for a, b in sh["index"])
            out[idx] = np.frombuffer(raw, dtype).reshape(sub_shape)
        if mesh is not None and specs is not None:
            spec = spec_by_name.get(name, P())
            return jax.device_put(out, NamedSharding(mesh, spec))
        return jax.numpy.asarray(out)

    rebuilt = [build(p, leaf) for p, leaf in flat]
    return jax.tree_util.tree_unflatten(treedef, rebuilt), step
