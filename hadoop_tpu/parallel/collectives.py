"""Device-resident exchange primitives: the ICI data plane's shuffle.

The reference's shuffle moves map output between hosts over HTTP
(ref: hadoop-mapreduce-project/.../ShuffleHandler.java:145 serving
IFile segments; reduce-side Fetcher.java:305 pulling them). When the
records are numeric and already device-resident, that exchange is
literally an all-to-all over the mesh (SURVEY.md §5.8) — so here it is
as one: a hash/range partitioned ``lax.all_to_all`` inside a
``shard_map`` program, with static shapes (capacity-bounded send
buckets + validity masks) so XLA can compile the whole exchange into
ICI DMAs.

Design notes (TPU/XLA constraints drive the shape of this code):

- **Static capacity.** XLA needs static shapes; a real shuffle has
  skew. Each device therefore sends at most ``cap`` records to each
  peer, buckets are padded with a sentinel, and the program returns a
  per-device overflow count so callers can detect truncation and retry
  with a bigger capacity factor (the MR host shuffle solves the same
  problem with spill files; here memory is pre-committed).
- **Sort as the grouping engine.** Host shuffles group by hashing into
  per-partition buffers; on the MXU/VPU the cheap grouping primitive
  is sort. Records are bucketed by ``argsort(dest)`` and positioned
  with a ``searchsorted`` prefix — no scatter with data-dependent
  shapes anywhere.
- **One collective.** The exchange is a single ``lax.all_to_all`` on
  a ``[n_dev, cap, ...]`` buffer — exactly the transpose the ICI
  fabric is optimized for (same collective the MoE dispatch uses,
  models/moe.py).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class ShuffleResult(NamedTuple):
    """Per-device post-exchange shard (leading dim = n_dev * cap,
    padded; ``valid`` marks real records, ``dropped`` counts records
    that exceeded a bucket's capacity on the SEND side)."""
    keys: jax.Array
    values: jax.Array
    valid: jax.Array
    dropped: jax.Array


def hash_partitioner(n_parts: int) -> Callable[[jax.Array], jax.Array]:
    """key → partition via a multiplicative hash (ref: the default
    HashPartitioner.getPartition — ``hash % parts`` — but mixed first:
    sequential integer keys would otherwise stripe, not spread)."""
    def part(keys: jax.Array) -> jax.Array:
        h = keys.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
        h ^= h >> 15
        return (h % jnp.uint32(n_parts)).astype(jnp.int32)
    # program-cache identity: every hash_partitioner(n) compiles (and
    # caches) the same exchange program
    part.cache_key = ("hash", n_parts)
    return part


def range_partitioner(splits: jax.Array) -> Callable[[jax.Array], jax.Array]:
    """key → partition by cut points (ref: TeraSort's
    TotalOrderPartitioner over sampled split points): partition i gets
    keys in (splits[i-1], splits[i]]. ``splits`` has n_parts-1 entries,
    ascending."""
    def part(keys: jax.Array) -> jax.Array:
        return jnp.searchsorted(splits, keys, side="left").astype(jnp.int32)
    # splits ride into the cached program as a TRACED argument — new cut
    # points (every device_sorted call samples fresh ones) reuse the
    # same compiled exchange
    part.cache_key = ("range", splits.shape[0], str(splits.dtype))
    part.splits = splits
    return part


# Compiled-program cache: jax.jit memoizes on the wrapped callable's
# identity, so rebuilding shard_map(partial(...)) per call would retrace
# and recompile the whole exchange every time — the opposite of the
# "one compiled collective" this module exists for. Keyed on everything
# that changes the lowered program.
_PROGRAM_CACHE: dict = {}


def _bucketize(keys, values, dest, n_dev: int, cap: int, pad_key):
    """Group local records into a [n_dev, cap] send buffer (+mask) by
    destination, dropping per-bucket overflow. Runs under jit: the
    grouping is argsort + searchsorted, both static-shaped."""
    n = keys.shape[0]
    order = jnp.argsort(dest, stable=True)
    dest_s = dest[order]
    keys_s = keys[order]
    vals_s = values[order]
    # start offset of each destination's run in the sorted order
    starts = jnp.searchsorted(dest_s, jnp.arange(n_dev), side="left")
    slot = jnp.arange(n) - starts[dest_s]
    ok = slot < cap
    dropped = jnp.sum(~ok)
    # overflow records get an out-of-bounds index; mode="drop" discards
    # the write entirely (an in-bounds clamp would clobber a bucket's
    # slot 0 with a masked record)
    flat = jnp.where(ok, dest_s * cap + slot, n_dev * cap)
    send_k = jnp.full((n_dev * cap,), pad_key, keys.dtype)
    send_v = jnp.zeros((n_dev * cap,) + values.shape[1:], values.dtype)
    send_m = jnp.zeros((n_dev * cap,), jnp.bool_)
    send_k = send_k.at[flat].set(keys_s, mode="drop")
    send_v = send_v.at[flat].set(vals_s, mode="drop")
    send_m = send_m.at[flat].set(True, mode="drop")
    return (send_k.reshape(n_dev, cap),
            send_v.reshape((n_dev, cap) + values.shape[1:]),
            send_m.reshape(n_dev, cap), dropped)


def _exchange_local(keys, values, splits, partition, n_dev: int, cap: int,
                    pad_key, axis: str, sort_output: bool):
    """Per-device body (under shard_map): bucket → all_to_all → merge.
    ``splits`` is the traced range-partition operand (a dummy scalar for
    non-range partitioners)."""
    if splits.ndim:  # range partition: cut points are data, not code
        dest = jnp.searchsorted(splits, keys,
                                side="left").astype(jnp.int32)
    else:
        dest = partition(keys)
    dest = jnp.clip(dest, 0, n_dev - 1)
    send_k, send_v, send_m, dropped = _bucketize(
        keys, values, dest, n_dev, cap, pad_key)
    # [n_dev, cap,...] → peer p receives our row p; we end with row j
    # holding what peer j sent us.
    recv_k = lax.all_to_all(send_k, axis, split_axis=0, concat_axis=0,
                            tiled=False)
    recv_v = lax.all_to_all(send_v, axis, split_axis=0, concat_axis=0,
                            tiled=False)
    recv_m = lax.all_to_all(send_m, axis, split_axis=0, concat_axis=0,
                            tiled=False)
    out_k = recv_k.reshape(n_dev * cap)
    out_v = recv_v.reshape((n_dev * cap,) + values.shape[1:])
    out_m = recv_m.reshape(n_dev * cap)
    if sort_output:
        # pads carry pad_key = +max so they sort to the tail; the mask
        # travels with the permutation.
        order = jnp.argsort(out_k, stable=True)
        out_k, out_v, out_m = out_k[order], out_v[order], out_m[order]
    return out_k, out_v, out_m, dropped[None]


def device_shuffle(mesh: Mesh, axis: str, keys: jax.Array,
                   values: jax.Array,
                   partition: Optional[Callable] = None,
                   capacity_factor: float = 2.0,
                   sort_output: bool = True) -> ShuffleResult:
    """All-to-all hash-partition exchange of device-resident records.

    ``keys``/``values`` are globally-sharded arrays (leading dim sharded
    over ``axis``); each record goes to the device ``partition(key)``
    names, then each device optionally sorts its received run. This is
    the map-output → reduce-input movement of the MR shuffle executed
    as one compiled collective instead of N² HTTP fetches (ref:
    ShuffleHandler.java:145 / Fetcher.java:305).

    Returns a ShuffleResult of globally-sharded arrays; row counts are
    padded to ``n_dev * cap`` per device with ``valid`` marking real
    records and ``dropped[d]`` counting device d's send-side overflow
    (0 for well-sized capacity factors; callers retry bigger on >0).
    """
    n_dev = mesh.shape[axis]
    n_local = keys.shape[0] // n_dev
    cap = max(1, int(n_local * capacity_factor / n_dev))
    if not jnp.issubdtype(keys.dtype, jnp.integer):
        raise TypeError("device_shuffle keys must be integers (numeric "
                        "record exchange; host shuffle covers the rest)")
    pad_key = jnp.iinfo(keys.dtype).max
    if partition is None:
        partition = hash_partitioner(n_dev)
    part_key = getattr(partition, "cache_key", None)
    is_range = bool(part_key) and part_key[0] == "range"
    splits = partition.splits if is_range \
        else jnp.zeros((), jnp.int32)  # 0-d sentinel: "not range"

    spec = P(axis)
    vspec = P(axis, *([None] * (values.ndim - 1)))

    def build():
        return jax.jit(shard_map(
            partial(_exchange_local, partition=partition, n_dev=n_dev,
                    cap=cap, pad_key=pad_key, axis=axis,
                    sort_output=sort_output),
            mesh=mesh, in_specs=(spec, vspec, P()),
            out_specs=(spec, vspec, spec, spec)))

    if part_key is None:
        prog = build()  # custom partitioner: identity unknown, no cache
    else:
        ck = ("shuffle", mesh, axis, n_dev, cap, sort_output, part_key,
              keys.shape, str(keys.dtype), values.shape[1:],
              str(values.dtype))
        prog = _PROGRAM_CACHE.get(ck)
        if prog is None:
            prog = _PROGRAM_CACHE.setdefault(ck, build())
    out_k, out_v, out_m, dropped = prog(keys, values, splits)
    return ShuffleResult(out_k, out_v, out_m, dropped)


def sample_split_points(mesh: Mesh, axis: str, keys: jax.Array,
                        n_parts: int, n_samples: int = 1024) -> jax.Array:
    """Sampled range-partition cut points (ref: TeraInputFormat's
    client-side sampling feeding TotalOrderPartitioner): every device
    contributes an evenly-strided sample of its local keys; the merged,
    sorted sample's quantiles become the n_parts-1 split points."""
    n_dev = mesh.shape[axis]
    per_dev = max(1, n_samples // n_dev)

    def body(local):
        stride = max(1, local.shape[0] // per_dev)
        sample = jnp.sort(local[::stride][:per_dev])
        # gather-as-psum: scatter my sample into my row and sum — the
        # result is *statically known replicated*, which keeps
        # shard_map's vma checking on (an all_gather's replication
        # can't be inferred and would force check_vma=False).
        row = lax.axis_index(axis)
        buf = jnp.zeros((n_dev,) + sample.shape, sample.dtype)
        allsamp = lax.psum(buf.at[row].set(sample), axis).reshape(-1)
        allsamp = jnp.sort(allsamp)
        idx = (jnp.arange(1, n_parts) * allsamp.shape[0]) // n_parts
        return allsamp[idx]

    ck = ("sample", mesh, axis, n_parts, per_dev, keys.shape,
          str(keys.dtype))
    prog = _PROGRAM_CACHE.get(ck)
    if prog is None:
        prog = _PROGRAM_CACHE.setdefault(
            ck, jax.jit(shard_map(body, mesh=mesh, in_specs=(P(axis),),
                                  out_specs=P())))
    return prog(keys)


def device_sorted(mesh: Mesh, axis: str, keys: jax.Array,
                  values: jax.Array,
                  capacity_factor: float = 2.0) -> ShuffleResult:
    """Global sort of device-resident records — TeraSort as collectives:
    sample → range-partition all_to_all → local sort. After this, valid
    keys on device d are all ≤ valid keys on device d+1 and each
    device's run is internally sorted."""
    n_dev = mesh.shape[axis]
    splits = sample_split_points(mesh, axis, keys, n_dev)
    return device_shuffle(mesh, axis, keys, values,
                          partition=range_partitioner(splits),
                          capacity_factor=capacity_factor,
                          sort_output=True)
