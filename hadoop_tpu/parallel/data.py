"""Token dataloader streaming from the framework's FileSystem.

The trainer's input pipeline reads token shards straight off the DFS —
the counterpart of the reference feeding MapReduce from HDFS splits
(FileInputFormat.getSplits) and of the mmap'd GPT dataset the task
baseline names as a keep. Files are flat little-endian token arrays
(uint16 or int32); batches are cut deterministically so a resumed run
sees exactly the continuation of the stream.

Resume contract: ``state()`` is a tiny dict (file cursor) that travels
with the model checkpoint; ``restore(state)`` repositions the stream so
batch N+1 after restore equals batch N+1 of an uninterrupted run.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from hadoop_tpu.fs import FileSystem


class TokenDataset:
    """Sequential [batch, seq+1] int32 batches from DFS token files.

    Each batch row is ``seq + 1`` tokens so the caller can slice
    (inputs, targets) = (row[:-1], row[1:]). The stream walks files in
    sorted order, tiles the concatenated token stream into rows, and
    wraps around at the end (epochs are implicit).
    """

    def __init__(self, fs: FileSystem, path: str, batch: int, seq: int,
                 dtype: str = "uint16", read_mb: int = 8):
        self.fs = fs
        self.batch = batch
        self.seq = seq
        self.dtype = np.dtype(dtype)
        st = fs.get_file_status(path)
        if st.is_dir:
            self.files: List[str] = sorted(
                s.path for s in fs.list_status(path)
                if not s.is_dir and not s.path.rsplit("/", 1)[-1]
                .startswith(("_", ".")))
            sizes = {s.path: s.length for s in fs.list_status(path)}
            self.sizes = [sizes[f] for f in self.files]
        else:
            self.files = [path]
            self.sizes = [st.length]
        itemsize = self.dtype.itemsize
        self.tokens_per_file = [n // itemsize for n in self.sizes]
        self.total_tokens = sum(self.tokens_per_file)
        need = batch * (seq + 1)
        if self.total_tokens < need:
            raise ValueError(f"dataset {path} has {self.total_tokens} "
                             f"tokens < one batch ({need})")
        self._pos = 0          # global token cursor
        self._buf = np.empty(0, np.int32)
        self._read_tokens = max(need, (read_mb << 20) // itemsize)

    # ------------------------------------------------------------- cursor

    def state(self) -> Dict:
        """Resume state — save alongside the model checkpoint."""
        return {"pos": int(self._pos) - int(self._buf.size)}

    def restore(self, state: Dict) -> None:
        self._pos = int(state["pos"]) % max(self.total_tokens, 1)
        self._buf = np.empty(0, np.int32)

    # -------------------------------------------------------------- reads

    def _read_span(self, pos: int, n: int) -> np.ndarray:
        """Read n tokens at global token offset pos (wrapping)."""
        out = np.empty(n, np.int32)
        filled = 0
        pos %= self.total_tokens
        while filled < n:
            fi, in_file = self._locate(pos)
            take = min(n - filled, self.tokens_per_file[fi] - in_file)
            stream = self.fs.open(self.files[fi])
            try:
                stream.seek(in_file * self.dtype.itemsize)
                raw = stream.read(take * self.dtype.itemsize)
            finally:
                stream.close()
            got = len(raw) // self.dtype.itemsize
            out[filled:filled + got] = np.frombuffer(
                raw, self.dtype, count=got).astype(np.int32)
            filled += got
            pos = (pos + got) % self.total_tokens
            if got == 0:
                raise IOError(f"short read from {self.files[fi]}")
        return out

    def _locate(self, pos: int):
        for fi, n in enumerate(self.tokens_per_file):
            if pos < n:
                return fi, pos
            pos -= n
        raise IndexError(pos)

    def next_batch(self) -> np.ndarray:
        """[batch, seq+1] int32, advancing the cursor."""
        need = self.batch * (self.seq + 1)
        if self._buf.size < need:
            span = self._read_span(self._pos, self._read_tokens)
            self._pos = (self._pos + span.size) % self.total_tokens
            self._buf = np.concatenate([self._buf, span]) \
                if self._buf.size else span
        out = self._buf[:need].reshape(self.batch, self.seq + 1)
        self._buf = self._buf[need:]
        return out

    def __iter__(self):
        while True:
            yield self.next_batch()
