"""Elastic training plane: doctor-driven eviction + reshard-on-restore.

ISSUE 14 built the SENSING half of fleet-elastic training (per-rank
step anatomy at ``/ws/v1/trainer``, the doctor's ``trainer.step_wall``
median/MAD straggler detector, the trainer-job roster). This package is
the ACTUATION half:

- :mod:`hadoop_tpu.parallel.elastic.reshard` — checkpoints carry a
  plan-describing manifest, and a snapshot written under mesh plan A
  restores into a step built for plan B (ZeRO-1 optimizer slices and
  pp stage shards reassembled to global layout on the host, re-sliced
  for the target plan). Bit-identical when A == B; allclose across
  plan changes.
- :mod:`hadoop_tpu.parallel.elastic.controller` — a trainer-side loop
  that polls the doctor's trainer verdicts and, on a flagged or dead
  rank, fences the async checkpoint writer, picks the largest healthy
  sub-mesh, rebuilds the train step for the shrunken plan, and resumes
  from the last snapshot via reshard-on-restore — with hysteresis so
  one noisy window never thrashes the mesh.

Configuration keys (the ParityConfig/asdict self-describing precedent —
:class:`ElasticConfig` round-trips through ``dataclasses.asdict`` so
every decision event can embed the exact knobs that produced it):

==============================  =======  ==================================
key                             default  meaning
==============================  =======  ==================================
``elastic.enabled``             false    turn the controller on
``elastic.poll.steps``          20       trainer steps between doctor polls
``elastic.min-dp``              1        never shrink dp below this
``elastic.demote.windows``      2        consecutive flagged polls before a
                                         DEMOTE (protective checkpoint)
``elastic.evict.windows``       4        consecutive flagged polls before a
                                         slow rank is EVICTED
``elastic.dead.windows``        2        consecutive dead polls before a
                                         lost rank is evicted
``elastic.cooldown.polls``      3        polls ignored after a resume
                                         (hysteresis against thrash)
==============================  =======  ==================================

This module stays importable from jax-free processes (conf tooling, the
doctor); the controller and reshard machinery import jax lazily.
"""

from __future__ import annotations

import dataclasses

ELASTIC_KEY = "elastic.enabled"


@dataclasses.dataclass(frozen=True)
class ElasticConfig:
    """Static elastic-plane knobs, fixed at trainer build time."""
    enabled: bool = False
    poll_steps: int = 20          # elastic.poll.steps
    min_dp: int = 1               # elastic.min-dp
    demote_windows: int = 2       # elastic.demote.windows
    evict_windows: int = 4        # elastic.evict.windows
    dead_windows: int = 2         # elastic.dead.windows
    cooldown_polls: int = 3       # elastic.cooldown.polls

    def __post_init__(self):
        if self.poll_steps < 1:
            raise ValueError("elastic.poll.steps must be >= 1, got "
                             f"{self.poll_steps}")
        if self.min_dp < 1:
            raise ValueError(f"elastic.min-dp must be >= 1, got "
                             f"{self.min_dp}")
        if self.demote_windows < 1 or self.evict_windows < 1 or \
                self.dead_windows < 1:
            raise ValueError("elastic window thresholds must be >= 1")
        if self.evict_windows <= self.demote_windows:
            raise ValueError(
                "elastic.evict.windows must exceed elastic.demote.windows "
                "(a demote must get its protective checkpoint in before "
                f"the evict fires): demote={self.demote_windows} "
                f"evict={self.evict_windows}")
        if self.cooldown_polls < 0:
            raise ValueError("elastic.cooldown.polls must be >= 0")


DEFAULT_ELASTIC = ElasticConfig()


def elastic_from_conf(conf) -> ElasticConfig:
    """Build an ElasticConfig from a Configuration (defaults above)."""
    if conf is None:
        return DEFAULT_ELASTIC
    return ElasticConfig(
        enabled=conf.get_bool(ELASTIC_KEY, False),
        poll_steps=conf.get_int("elastic.poll.steps", 20),
        min_dp=conf.get_int("elastic.min-dp", 1),
        demote_windows=conf.get_int("elastic.demote.windows", 2),
        evict_windows=conf.get_int("elastic.evict.windows", 4),
        dead_windows=conf.get_int("elastic.dead.windows", 2),
        cooldown_polls=conf.get_int("elastic.cooldown.polls", 3))


def __getattr__(name):
    # lazy: the controller/reshard modules import jax; this package's
    # config surface must stay importable from jax-free processes
    if name in ("ElasticController", "doctor_http_poll"):
        from hadoop_tpu.parallel.elastic import controller as _c
        return getattr(_c, name)
    if name in ("manifest_meta", "plan_from_meta", "resolve_restore",
                "check_reshardable", "reshard_opt_state"):
        from hadoop_tpu.parallel.elastic import reshard as _r
        return getattr(_r, name)
    raise AttributeError(name)


__all__ = ["ElasticConfig", "DEFAULT_ELASTIC", "ELASTIC_KEY",
           "elastic_from_conf", "ElasticController", "doctor_http_poll",
           "manifest_meta", "plan_from_meta", "resolve_restore",
           "check_reshardable", "reshard_opt_state"]
