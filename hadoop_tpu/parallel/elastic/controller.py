"""Trainer-side elastic controller: doctor verdicts → mesh actuation.

The loop the sensing plane (ISSUE 14) was built for. Every
``elastic.poll.steps`` trainer steps the controller reads the doctor's
trainer verdicts (the ``trainers`` section of ``/ws/v1/fleet/doctor``
— flagged stragglers from the step_wall median/MAD detector, dead
ranks from the roster) and turns streaks into three decisions:

- **DEMOTE** — a rank flagged ``elastic.demote.windows`` polls in a
  row: write a protective checkpoint NOW, while the straggler is still
  alive, so an eventual eviction resumes from here instead of the last
  interval save. This is what makes the elastic plane lose strictly
  fewer steps than restart-from-checkpoint: the protective snapshot is
  always at least as fresh as the interval schedule's.
- **EVICT** — flagged ``elastic.evict.windows`` polls, or dead (roster
  ``ok=False``) ``elastic.dead.windows`` polls: fence the async
  checkpoint writer, pick the largest healthy sub-mesh (largest dp'
  ≤ healthy ranks that divides the global batch, ≥ ``elastic.min-dp``
  — non-power-of-two shrinks like 8→6 included), and hand the trainer
  the new plan. The trainer ends its step segment, rebuilds the train
  step, and resumes from the newest snapshot via reshard-on-restore.
- **RESUME** — the restore landed: record the lost-step count and wall
  time, then hold ``elastic.cooldown.polls`` polls of hysteresis so
  one noisy window after the reshard can't immediately thrash the
  mesh again.

Every decision is a structured event (the ElasticConfig that produced
it rides along via ``dataclasses.asdict``) on the ``htpu_elastic_*``
counter family and the trainer's ``/ws/v1/trainer`` elastic block.

The poll itself is HOST-side work on a step-count cadence, outside the
jitted step — the deliberate blocking the ``jit/blocking-in-step``
lint annotations in the trainer loop mark.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import time
from typing import Any, Callable, Dict, List, Optional

from hadoop_tpu.metrics import metrics_system
from hadoop_tpu.parallel.elastic import ElasticConfig
from hadoop_tpu.parallel.mesh import MeshPlan

log = logging.getLogger(__name__)

MAX_EVENTS = 256   # bounded event ring for /ws/v1/trainer


def doctor_http_poll(host: str, port: int,
                     timeout: float = 5.0) -> Callable[[], Dict]:
    """A poll_fn reading the fleet doctor's HTTP report — the
    deployment wiring (in-process tests/smokes script their own)."""
    from hadoop_tpu.http import http_get

    def poll() -> Dict:
        return json.loads(http_get(host, port, "/ws/v1/fleet/doctor",
                                   timeout).decode())
    return poll


def pick_shrunken_plan(plan: MeshPlan, healthy: int, batch: int,
                       min_dp: int) -> Optional[MeshPlan]:
    """Largest healthy sub-mesh: shrink ONLY dp (tp/pp/ep/sp shape the
    model math; dp is the replica axis eviction removes capacity from),
    to the largest dp' ≤ healthy ranks with ``batch % (dp'*ep) == 0``
    and dp' ≥ min_dp. Non-power-of-two shrinks (8→6, 4→3) are fine —
    the reshard path never assumes power-of-two. None if no feasible
    plan exists."""
    for d in range(min(plan.dp, healthy), min_dp - 1, -1):
        if d >= 1 and batch % (d * plan.ep) == 0:
            return dataclasses.replace(plan, dp=d)
    return None


class ElasticController:
    """Streak bookkeeping + decisions for one trainer.

    ``trainer`` needs: ``.plan``, ``.step``, ``.batch``,
    ``.save(wait=False)``, ``.apply_plan(plan) -> bool`` (the Trainer
    contract; tests duck-type it). ``poll_fn`` returns the doctor
    report dict (see :func:`doctor_http_poll`).
    """

    def __init__(self, trainer, cfg: ElasticConfig, *,
                 poll_fn: Callable[[], Dict]):
        if poll_fn is None:
            raise ValueError("ElasticController needs a poll_fn (use "
                             "doctor_http_poll for a live doctor)")
        self.trainer = trainer
        self.cfg = cfg
        self._poll_fn = poll_fn
        self._flagged_streak: Dict[str, int] = {}
        self._dead_streak: Dict[str, int] = {}
        self._demoted: set = set()
        # ranks already evicted from the mesh: their roster rows linger
        # (a dead rank's registry record only ages out) and must never
        # re-trigger an eviction of capacity that is already gone
        self._evicted_ranks: set = set()
        self._cooldown = 0
        self._pending_plan: Optional[MeshPlan] = None
        self._pending_ranks: List[str] = []
        self.events: List[Dict[str, Any]] = []
        reg = metrics_system().source("elastic")
        self._m_polls = reg.counter(
            "polls", "doctor polls taken by the elastic controller",
            prom_name="htpu_elastic_polls")
        self._m_demotes = reg.counter(
            "demotes", "protective checkpoints on flagged-rank streaks",
            prom_name="htpu_elastic_demotes")
        self._m_evictions = reg.counter(
            "evictions", "ranks evicted from the mesh",
            prom_name="htpu_elastic_evictions")
        self._m_resumes = reg.counter(
            "resumes", "reshard-on-restore resumes completed",
            prom_name="htpu_elastic_resumes")
        self._m_lost_steps = reg.counter(
            "lost_steps", "steps re-run after elastic resumes",
            prom_name="htpu_elastic_lost_steps")
        self._m_resume_seconds = reg.counter(
            "resume_seconds", "wall seconds spent in elastic resumes",
            prom_name="htpu_elastic_resume_seconds")

    # ------------------------------------------------------------ events

    def _event(self, decision: str, step: int, **detail) -> Dict:
        ev = {"decision": decision, "step": int(step),
              "time": time.time(),
              "config": dataclasses.asdict(self.cfg)}
        ev.update(detail)
        self.events.append(ev)
        del self.events[:-MAX_EVENTS]
        log.info("elastic %s at step %d: %s", decision, step,
                 {k: v for k, v in detail.items()})
        return ev

    # ------------------------------------------------------------- polls

    def on_step(self, step: int) -> bool:
        """One cadence-gated poll+decide. Returns True when an evict
        decision is pending — the trainer must end its step segment
        and call :meth:`resume`."""
        if self._pending_plan is not None:
            return True
        try:
            report = self._poll_fn()
        except Exception as e:  # noqa: BLE001 — an unreachable doctor
            # must not kill training; the next poll retries
            log.warning("elastic doctor poll failed: %s", e)
            return False
        self._m_polls.incr()
        trainers = (report or {}).get("trainers") or {}
        flagged = set(trainers.get("flagged") or ()) \
            - self._evicted_ranks
        roster = trainers.get("ranks") or {}
        dead = {name for name, row in roster.items()
                if not row.get("ok")} - self._evicted_ranks
        for name in list(self._flagged_streak):
            if name not in flagged:
                self._flagged_streak.pop(name)
                self._demoted.discard(name)
        for name in flagged:
            self._flagged_streak[name] = \
                self._flagged_streak.get(name, 0) + 1
        for name in list(self._dead_streak):
            if name not in dead:
                self._dead_streak.pop(name)
        for name in dead:
            self._dead_streak[name] = self._dead_streak.get(name, 0) + 1
        if self._cooldown > 0:
            self._cooldown -= 1
            return False

        evict = sorted(
            {n for n, s in self._dead_streak.items()
             if s >= self.cfg.dead_windows} |
            {n for n, s in self._flagged_streak.items()
             if s >= self.cfg.evict_windows})
        if evict:
            return self._decide_evict(step, evict, roster, dead)

        for name in sorted(flagged):
            if self._flagged_streak[name] >= self.cfg.demote_windows \
                    and name not in self._demoted:
                self._demoted.add(name)
                self._demote(step, name)
        return False

    # --------------------------------------------------------- decisions

    def _demote(self, step: int, rank: str) -> None:
        """Protective checkpoint while the straggler is still alive:
        the freshest possible resume point if the streak becomes an
        eviction."""
        self.trainer.save(wait=False)
        self._m_demotes.incr()
        self._event("demote", step, rank=rank,
                    streak=self._flagged_streak.get(rank, 0),
                    snapshot_step=int(step))

    def _decide_evict(self, step: int, ranks: List[str], roster: Dict,
                      dead: set) -> bool:
        plan = self.trainer.plan
        if roster:
            healthy = sum(1 for name, row in roster.items()
                          if row.get("ok") and name not in ranks)
        else:
            # static fleets may poll a doctor without a roster: assume
            # one rank per dp slice and count the survivors
            healthy = plan.dp - len(ranks)
        new_plan = pick_shrunken_plan(plan, healthy, self.trainer.batch,
                                      self.cfg.min_dp)
        if new_plan is None:
            self._event("evict-infeasible", step, ranks=ranks,
                        healthy=healthy, plan=dataclasses.asdict(plan))
            raise RuntimeError(
                f"elastic eviction of {ranks} leaves {healthy} healthy "
                f"ranks but no dp in [{self.cfg.min_dp}, {plan.dp}] "
                f"divides batch={self.trainer.batch} (ep={plan.ep})")
        self._m_evictions.incr(len(ranks))
        self._event("evict", step, ranks=ranks, healthy=healthy,
                    dead=sorted(dead),
                    plan_from=dataclasses.asdict(plan),
                    plan_to=dataclasses.asdict(new_plan))
        self._pending_plan = new_plan
        self._pending_ranks = list(ranks)
        return True

    def resume(self) -> bool:
        """Apply the pending evict decision: fence, rebuild the train
        step for the shrunken plan, reshard-on-restore from the newest
        snapshot. Called by the trainer BETWEEN step segments (never
        under a live prefetch thread). Returns whether a snapshot was
        restored."""
        plan = self._pending_plan
        if plan is None:
            return False
        self._pending_plan = None
        ranks, self._pending_ranks = self._pending_ranks, []
        self._evicted_ranks.update(ranks)
        step_before = int(self.trainer.step)
        t0 = time.monotonic()
        restored = self.trainer.apply_plan(plan)
        resume_s = time.monotonic() - t0
        lost = step_before - int(self.trainer.step) if restored \
            else step_before
        self._m_resumes.incr()
        self._m_lost_steps.incr(int(lost))
        self._m_resume_seconds.incr(int(round(resume_s)))
        self._event("resume", self.trainer.step, ranks=ranks,
                    restored=bool(restored), lost_steps=int(lost),
                    resume_seconds=round(resume_s, 3),
                    plan_to=dataclasses.asdict(plan))
        self._cooldown = self.cfg.cooldown_polls
        self._flagged_streak.clear()
        self._dead_streak.clear()
        self._demoted.clear()
        return bool(restored)

    @property
    def pending(self) -> bool:
        return self._pending_plan is not None

    # ------------------------------------------------------------ report

    def report(self) -> Dict[str, Any]:
        """The ``/ws/v1/trainer`` elastic block."""
        return {
            "enabled": self.cfg.enabled,
            "config": dataclasses.asdict(self.cfg),
            "plan": dataclasses.asdict(self.trainer.plan),
            "cooldown": self._cooldown,
            "flagged_streaks": dict(self._flagged_streak),
            "dead_streaks": dict(self._dead_streak),
            "evicted_ranks": sorted(self._evicted_ranks),
            "events": list(self.events[-32:]),
        }
