"""Reshard-on-restore: one checkpoint serves any dp×tp fleet shape.

Checkpoints already store PARAMS in global logical layout (shards are
reassembled on load and re-placed under the target mesh — see
``parallel/checkpoint.py``), so a param tree restores into any plan for
free. The plan-locked leaves are the ZeRO-1 optimizer moments: a state
leaf is a ``(*spec_axis_sizes, *data_axis_sizes, K)`` array whose very
SHAPE bakes in the plan that wrote it (the slice layout defined once by
``overlap.zero1_slice_meta``). This module converts those leaves
through the canonical intermediate form — the global param-shaped
moment array — so a snapshot written under plan A restores into a step
built for plan B:

    plan-A state ──(slice layout A)──▶ global moments
                 ──(slice layout B)──▶ plan-B state

The conversion is exact on the real (non-padding) region: every slice
segment lands at the flattened-param offset the mixed-radix rank index
(``overlap.zero1_slice_index``) assigns it, and the padding tail is
zeros by construction (grads are zero-padded, so moments never leave
zero there). Plain-AdamW moments ARE global moment arrays, so the same
two maps also convert zero1 ⇄ non-zero1 restores (one side is the
identity).

Refused loudly: pp/vpp stage-count changes. A pp resize re-stacks which
layers share a stage (and an interleaved checkpoint persists ZeRO-1
state in PHYSICAL layer order while params are logical — see
``Trainer._vpp_snapshot_reorder``), so there is no host-side relayout
that preserves the optimizer trajectory; restore under the saved pp,
re-save, then change plans.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Any, Dict, Optional, Tuple

import numpy as np

from hadoop_tpu.parallel.mesh import AXES, MeshPlan

# manifest["meta"]["format"] for plan-bearing checkpoints; bumping it is
# a layout break (readers refuse formats they don't know)
MANIFEST_FORMAT = "htpu-ckpt-plan-1"


# ------------------------------------------------------------- manifest

def manifest_meta(plan: MeshPlan, *, zero1: bool) -> Dict[str, Any]:
    """The plan-describing manifest block a checkpoint writer embeds."""
    return {"format": MANIFEST_FORMAT,
            "zero1": bool(zero1),
            "plan": dataclasses.asdict(plan)}


def plan_from_meta(meta: Dict[str, Any]) -> MeshPlan:
    if meta.get("format") != MANIFEST_FORMAT:
        raise ValueError(
            f"unknown checkpoint meta format {meta.get('format')!r} "
            f"(this reader understands {MANIFEST_FORMAT!r})")
    return MeshPlan(**meta["plan"])


def resolve_restore(manifest: Dict[str, Any], plan: MeshPlan,
                    zero1: bool) -> Tuple[str, Optional[MeshPlan], bool]:
    """Classify a restore against the manifest's plan block.

    Returns ``(mode, saved_plan, saved_zero1)`` with mode one of:

    - ``"same-plan"`` — saved and target plans match exactly; the
      restore takes the direct placement path (bit-identical).
    - ``"reshard"`` — plans differ; the restore goes through the
      host-side global relayout (allclose, not bitwise).
    - ``"legacy"`` — manifest predates the plan block; the restore
      proceeds as same-plan (all a legacy manifest can support) with a
      DeprecationWarning.
    """
    meta = manifest.get("meta")
    if not meta or "plan" not in meta:
        warnings.warn(
            "checkpoint manifest has no plan block (written before the "
            "elastic plane); restoring as same-plan — re-save to make "
            "this checkpoint reshardable", DeprecationWarning,
            stacklevel=2)
        return "legacy", None, zero1
    saved_plan = plan_from_meta(meta)
    saved_zero1 = bool(meta.get("zero1", False))
    if saved_plan == plan and saved_zero1 == zero1:
        return "same-plan", saved_plan, saved_zero1
    check_reshardable(saved_plan, plan)
    return "reshard", saved_plan, saved_zero1


def check_reshardable(plan_a: MeshPlan, plan_b: MeshPlan) -> None:
    """Refuse plan changes reshard-on-restore cannot express."""
    if plan_a.pp != plan_b.pp or plan_a.vpp != plan_b.vpp:
        raise ValueError(
            "reshard-on-restore cannot change the pipeline stage count: "
            f"checkpoint written under pp={plan_a.pp} vpp={plan_a.vpp}, "
            f"target plan has pp={plan_b.pp} vpp={plan_b.vpp}. A pp "
            "resize re-stacks which layers share a stage (and ZeRO-1 "
            "state under vpp is persisted in physical layer order), so "
            "no host relayout preserves the optimizer trajectory — "
            "restore under the saved pp, re-save, then change plans.")


# ---------------------------------------------------- slice-layout math

def _plan_sizes(plan: MeshPlan) -> Dict[str, int]:
    return dict(zip(AXES, (plan.dp, plan.pp, plan.tp, plan.ep, plan.sp)))


def _sharded_dims(spec):
    """``[(dim, [axes...]), ...]`` for a PartitionSpec's sharded dims,
    in order of appearance — matches ``train._spec_axes_ordered`` so
    state-leaf leading dims line up with coordinate enumeration."""
    out = []
    for d, part in enumerate(spec):
        if part is None:
            continue
        axes = list(part) if isinstance(part, tuple) else [part]
        out.append((d, axes))
    return out


def _block_slices(coords, sharded, shape, sizes):
    """Global-array slices selecting the shard at spec coords
    (``coords`` ordered like the state leaf's leading dims)."""
    sl = [slice(None)] * len(shape)
    it = iter(coords)
    for d, axes in sharded:
        idx, n = 0, 1
        for a in axes:
            idx = idx * sizes[a] + next(it)
            n *= sizes[a]
        bl = shape[d] // n
        sl[d] = slice(idx * bl, (idx + 1) * bl)
    return tuple(sl)


def _leaf_geometry(spec, shape, plan: MeshPlan):
    """(sharded dims, spec axis sizes, z axis sizes, Z, K, local size)
    for one leaf under one plan — the host-side mirror of
    ``train.zero1_layout`` / ``overlap.zero1_slice_meta``."""
    sizes = _plan_sizes(plan)
    sharded = _sharded_dims(spec)
    spec_ax = [a for _, axes in sharded for a in axes]
    for d, axes in sharded:
        n = int(np.prod([sizes[a] for a in axes]))
        if shape[d] % n:
            raise ValueError(
                f"leaf dim {d} of shape {shape} not divisible by its "
                f"mesh axes {axes} (sizes {sizes})")
    spec_sizes = tuple(sizes[a] for a in spec_ax)
    z_ax = tuple(a for a in plan.batch_axes if a not in spec_ax)
    z_sizes = tuple(sizes[a] for a in z_ax)
    z = int(np.prod(z_sizes)) if z_sizes else 1
    denom = int(np.prod(spec_sizes)) if spec_sizes else 1
    local = max(1, int(np.prod(shape)) // denom) if shape else 1
    k = (local + z - 1) // z
    return sharded, sizes, spec_sizes, z_sizes, z, k, local


def zero1_state_to_global(state, spec, global_shape,
                          plan: MeshPlan) -> np.ndarray:
    """One ZeRO-1 moment leaf (plan layout) → the global param-shaped
    f32 moment array. Exact: every slice segment is written back at
    the flattened offset the mixed-radix rank index assigned it."""
    state = np.asarray(state)
    global_shape = tuple(global_shape)
    sharded, sizes, spec_sizes, z_sizes, z, k, local = \
        _leaf_geometry(spec, global_shape, plan)
    want = spec_sizes + z_sizes + (k,)
    if tuple(state.shape) != want:
        raise ValueError(
            f"zero1 state leaf shape {tuple(state.shape)} does not "
            f"match plan layout {want} (global {global_shape})")
    out = np.empty(global_shape, np.float32)
    for coords in np.ndindex(*spec_sizes):
        sl = _block_slices(coords, sharded, global_shape, sizes)
        block_shape = out[sl].shape
        # (z..., K) segments concatenate, row-major over the data axes,
        # into the zero-padded flattened shard — drop the pad tail
        flat = state[coords].reshape(-1)[:local].astype(np.float32)
        out[sl] = flat.reshape(block_shape)
    return out


def global_to_zero1_state(garr, spec, plan: MeshPlan) -> np.ndarray:
    """The global param-shaped moment array → one ZeRO-1 moment leaf in
    ``plan``'s layout (inverse of :func:`zero1_state_to_global`; the
    padding tail is zero, matching what training writes there)."""
    garr = np.asarray(garr, np.float32)
    sharded, sizes, spec_sizes, z_sizes, z, k, local = \
        _leaf_geometry(spec, garr.shape, plan)
    out = np.zeros(spec_sizes + z_sizes + (k,), np.float32)
    for coords in np.ndindex(*spec_sizes):
        sl = _block_slices(coords, sharded, garr.shape, sizes)
        flat = garr[sl].reshape(-1)
        pad = z * k - flat.size
        if pad:
            flat = np.pad(flat, (0, pad))
        out[coords] = flat.reshape(z_sizes + (k,))
    return out


def reshard_zero1_leaf(state, spec, global_shape, plan_a: MeshPlan,
                       plan_b: MeshPlan) -> np.ndarray:
    """Plan-A moment leaf → plan-B moment leaf, through global layout."""
    return global_to_zero1_state(
        zero1_state_to_global(state, spec, global_shape, plan_a),
        spec, plan_b)


# --------------------------------------------------------- whole trees

def reshard_opt_state(opt, params_shapes, specs, plan_a: MeshPlan,
                      plan_b: MeshPlan, *, zero1_a: bool, zero1_b: bool):
    """Convert a host AdamWState between plan layouts.

    ``opt`` is the loaded host optimizer state (mu/nu trees in plan A's
    layout); ``params_shapes`` a matching pytree of GLOBAL param shapes
    (tuples or arrays — only ``np.shape`` is read); ``specs`` the
    ``mesh.param_specs`` tree (plan-independent). Same plan AND same
    zero1 flag returns ``opt`` untouched — the bit-identical path.
    """
    import jax

    check_reshardable(plan_a, plan_b)
    if plan_a == plan_b and zero1_a == zero1_b:
        return opt

    def leaf(m, shape_like, spec):
        gshape = tuple(np.shape(shape_like))
        if zero1_a:
            g = zero1_state_to_global(m, spec, gshape, plan_a)
        else:
            g = np.asarray(m, np.float32)
            if g.shape != gshape:
                raise ValueError(f"moment shape {g.shape} != param "
                                 f"shape {gshape}")
        if zero1_b:
            return global_to_zero1_state(g, spec, plan_b)
        return g

    mu = jax.tree_util.tree_map(leaf, opt.mu, params_shapes, specs)
    nu = jax.tree_util.tree_map(leaf, opt.nu, params_shapes, specs)
    return type(opt)(np.asarray(opt.count), mu, nu)
