"""Relaxed-parity plane: parity tiers for the communication stack.

Every transform in the overlap pass (parallel/overlap.py,
ops/collective_matmul.py) ships under a BIT-EXACT contract: same
per-element sums, same matmul shapes, byte-identical losses. That
contract is what made the pass safe to turn on by default — and what
put the best remaining levers off the table. Chunking the collective
matmul reassociates the weight-grad contraction (measured, PROFILE.md);
quantizing a gradient bucket to int8 moves every element. Neither can
ever pass a bitwise A-B.

This package is the second tier. ``parallel.parity`` names the
contract the train step is built under:

- ``bitwise`` (the default): exactly today's behavior. No lowp code
  executes, no quantizer is imported on the hot path, and every
  existing parity test stays byte-identical.
- ``relaxed``: collectives may trade bits for bytes and schedule.
  Correctness is guarded statistically instead of bit-wise — allclose
  guards on values (:mod:`guard`) and a loss-curve A-B acceptance
  (N training steps relaxed vs bitwise, bounded trajectory
  divergence) recorded in the bench JSON.

Under the relaxed tier three consumer families light up (Flash
Communication, arXiv:2412.04964; T3, arXiv:2401.16677):

1. **Quantized gradient buckets** — the overlap pass's bucketed
   psum / psum_scatter payloads ride the wire as int8 (or emulated
   fp8) with shared f32 scales; the ZeRO-1 param reassembly's
   psum-of-disjoint-scatters quantizes at full int8 range (exactly
   one rank contributes per element). ≥2× fewer collective payload
   bytes, proven by the trace-time comm ledger (:mod:`quant`).
2. **Quantized chunked TP reduces** — the row-parallel reduce in
   ops/collective_matmul.py quantizes each chunk's psum/psum_scatter
   with a per-tensor scale.
3. **True chunked collective matmul** — per-chunk matmul pipelined
   against per-chunk reduce (T3-style compute/collective
   interleaving). The forward is value-exact (disjoint row chunks);
   the backward's weight-grad reassociation is covered by the
   loss-curve guard instead of forbidden by the bitwise contract.

Conf keys (read by :func:`parity_from_conf`):

  parallel.parity                   bitwise | relaxed   (default bitwise)
  parallel.lowp.codec               int8 | fp8          (default int8)
  parallel.lowp.quant.buckets       default true  (consumer 1, grads)
  parallel.lowp.quant.zero1-gather  default true  (consumer 1, params)
  parallel.lowp.quant.tp            default true  (consumer 2)
  parallel.lowp.chunk-matmul        default true  (consumer 3)
  parallel.lowp.quant.group         default 1024  (scale granularity)
  parallel.lowp.sync.schedule       default full  (per-layer TP sync
                                    schedule: full | none | periodic:<k>
                                    | layers:<spec> — syncpolicy.py)
  parallel.lowp.sync.mode           default skip  (skip | stale: what a
                                    scheduled-off layer does)
  parallel.lowp.sync.guard.rel-tol  default 2.0   (loss-curve tolerance
                                    for sync-SCHEDULE rungs — see
                                    syncpolicy.py)
  parallel.lowp.guard.steps         default 50    (loss-curve A-B length)
  parallel.lowp.guard.rel-tol       default 0.25  (max per-step rel div)

tpulint's ``parity/relaxed-gated`` checker enforces the tiering
statically: any call to a quantized-collective or chunked-matmul entry
point outside this package must sit under a lexical guard that names
the relaxed tier, so the bitwise paths are provably untouched.
"""

from __future__ import annotations

import dataclasses

PARITY_KEY = "parallel.parity"
TIERS = ("bitwise", "relaxed")
WIRE_CODECS = ("int8", "fp8")


@dataclasses.dataclass(frozen=True)
class ParityConfig:
    """Static parity-tier knobs, fixed at train-step build time.

    ``tier == "bitwise"`` disables every consumer regardless of the
    per-consumer flags — the flags describe what the relaxed tier
    quantizes, not whether the tier is on.
    """
    tier: str = "bitwise"
    codec: str = "int8"               # int8 | fp8 (emulated)
    quant_buckets: bool = True        # grad bucket psum / psum_scatter
    quant_zero1_gather: bool = True   # ZeRO-1 param reassembly
    quant_tp: bool = True             # row-parallel tp reduces
    chunk_matmul: bool = True         # true chunked collective matmul
    group: int = 1024                 # elements per shared scale
    # per-layer TP activation-sync schedule (syncpolicy.py; partially
    # synchronized activations, arXiv:2506.19645). "full" (the
    # default) syncs every layer — the schedule machinery is
    # unreachable, and under the bitwise tier it is unreachable
    # regardless of this field (the lexical relaxed_* gating tpulint
    # enforces).
    relaxed_sync: str = "full"        # parallel.lowp.sync.schedule
    relaxed_sync_mode: str = "skip"   # parallel.lowp.sync.mode
    # loss-curve tolerance for SYNC-SCHEDULE rungs (a schedule shifts
    # the trajectory — the scheduled curve tracks the bitwise shape a
    # constant factor behind — so the per-step relative guard needs a
    # wider bar than quantization noise; the all-skipped falsifiability
    # arm still rejects >8x above this: see syncpolicy.py)
    sync_guard_rel_tol: float = 2.0   # parallel.lowp.sync.guard.rel-tol
    guard_steps: int = 50
    guard_rel_tol: float = 0.25

    def __post_init__(self):
        if self.tier not in TIERS:
            raise ValueError(f"{PARITY_KEY} must be one of {TIERS}, "
                             f"got {self.tier!r}")
        if self.codec not in WIRE_CODECS:
            raise ValueError(f"parallel.lowp.codec must be one of "
                             f"{WIRE_CODECS}, got {self.codec!r}")
        # grammar check at config time (jax-free; full resolution
        # against n_layers happens at train-step build)
        from hadoop_tpu.parallel.lowp.syncpolicy import validate_spec
        validate_spec(self.relaxed_sync, self.relaxed_sync_mode)

    @property
    def relaxed(self) -> bool:
        return self.tier == "relaxed"


BITWISE_PARITY = ParityConfig()
RELAXED_PARITY = ParityConfig(tier="relaxed")


def parity_from_conf(conf) -> ParityConfig:
    """Build a ParityConfig from a Configuration (defaults above)."""
    if conf is None:
        return BITWISE_PARITY
    return ParityConfig(
        tier=conf.get(PARITY_KEY, "bitwise"),
        codec=conf.get("parallel.lowp.codec", "int8"),
        quant_buckets=conf.get_bool("parallel.lowp.quant.buckets", True),
        quant_zero1_gather=conf.get_bool(
            "parallel.lowp.quant.zero1-gather", True),
        quant_tp=conf.get_bool("parallel.lowp.quant.tp", True),
        chunk_matmul=conf.get_bool("parallel.lowp.chunk-matmul", True),
        group=conf.get_int("parallel.lowp.quant.group", 1024),
        relaxed_sync=conf.get("parallel.lowp.sync.schedule", "full"),
        relaxed_sync_mode=conf.get("parallel.lowp.sync.mode", "skip"),
        sync_guard_rel_tol=conf.get_float(
            "parallel.lowp.sync.guard.rel-tol", 2.0),
        guard_steps=conf.get_int("parallel.lowp.guard.steps", 50),
        guard_rel_tol=conf.get_float("parallel.lowp.guard.rel-tol", 0.25))


# ---- public host-side per-group int8 codec (the kvstore codec.py
# precedent: ONE quantizer defines every int8 surface). Re-exported
# lazily — `quant` imports jax, and this package's config surface must
# stay importable from jax-free processes:
#
#   quantize_array(x, codec="int8", group)  -> (q, scales): symmetric
#       per-group quantization at full +/-127 range, groups of `group`
#       consecutive elements, one f32 scale per group (amax/qmax).
#   dequantize_array(q, scales, shape, dtype) -> the reconstruction.
#   encode_payload / decode_payload          -> the self-describing
#       wire form (loud failure on codec/shape/dtype mismatch).
#
# Consumers: the relaxed collectives here, the serving weight plane
# (serving/weightplane.py — weight groups ride the contraction dim),
# and any future int8 surface. Quantization behavior changes happen in
# quant.py or nowhere.
_QUANT_API = ("quantize_array", "dequantize_array", "encode_payload",
              "decode_payload")


def __getattr__(name: str):
    if name in _QUANT_API:
        from hadoop_tpu.parallel.lowp import quant
        return getattr(quant, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = ["ParityConfig", "parity_from_conf", "BITWISE_PARITY",
           "RELAXED_PARITY", "PARITY_KEY", "TIERS", "WIRE_CODECS",
           *_QUANT_API]
