"""A-B acceptance for the relaxed parity tier.

The bitwise tier's guard is trivial: ``losses_on == losses_off``, byte
for byte. The relaxed tier trades bits for bytes on purpose, so its
guard is statistical instead:

- **allclose guards** (:func:`allclose_guard`) replace bitwise asserts
  on values — with the max abs/rel divergence reported, so a failing
  guard says HOW far off, not just that it is.
- **loss-curve acceptance** (:func:`loss_curve_report`,
  :func:`run_loss_ab`): N tiny training steps with the relaxed tier vs
  the bitwise tier from identical init and data. The trajectories may
  drift — quantization noise compounds through the optimizer — but the
  drift must stay bounded (max per-step relative divergence ≤
  ``rel_tol``) and the relaxed run must still LEARN (final loss below
  its starting loss). The whole report is a plain dict so the bench
  rungs (profile_train, lowp_smoke, the MULTICHIP dryrun) record it
  in their JSON and the trajectory survives for the next reader.

``run_loss_ab`` is the one shared harness: tests, the smoke and the
dryrun all call it, so "passes the loss-curve guard" means the same
thing everywhere.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from hadoop_tpu.parallel.lowp import (BITWISE_PARITY, ParityConfig,
                                      RELAXED_PARITY)


class ParityGuardError(AssertionError):
    """A relaxed-tier guard rejected: values or trajectories diverged
    past the configured bound."""


def allclose_guard(name: str, ref, got, *, rtol: float = 1e-5,
                   atol: float = 1e-6) -> Dict:
    """The relaxed tier's replacement for a bitwise assert: compare two
    arrays/trees, raise :class:`ParityGuardError` with the measured
    divergence when out of tolerance, return the divergence report
    when within."""
    import jax

    ref_leaves = jax.tree_util.tree_leaves(ref)
    got_leaves = jax.tree_util.tree_leaves(got)
    if len(ref_leaves) != len(got_leaves):
        raise ParityGuardError(
            f"{name}: tree arity {len(got_leaves)} != {len(ref_leaves)}")
    max_abs = 0.0
    max_rel = 0.0
    ok = True
    for a, b in zip(ref_leaves, got_leaves):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        if a.shape != b.shape:
            raise ParityGuardError(f"{name}: shape {b.shape} != {a.shape}")
        d = np.abs(a - b)
        max_abs = max(max_abs, float(d.max(initial=0.0)))
        denom = np.maximum(np.abs(a), atol)
        max_rel = max(max_rel, float((d / denom).max(initial=0.0)))
        # one pass: the acceptance test IS np.allclose's criterion, so
        # a rejection's reported numbers agree with the stated rtol
        if not np.all(d <= atol + rtol * np.abs(a)):
            ok = False
    report = {"max_abs": max_abs, "max_rel": max_rel,
              "rtol": rtol, "atol": atol}
    if not ok:
        raise ParityGuardError(
            f"{name}: allclose guard rejected (max_abs={max_abs:.3e}, "
            f"max_rel={max_rel:.3e}, rtol={rtol}, atol={atol})")
    return report


def _smooth(curve: np.ndarray, window: int) -> np.ndarray:
    """Trailing moving average (head uses the running mean, so early
    steps — where both curves are steep and close — still judge)."""
    if window <= 1 or curve.size <= 1:
        return curve
    out = np.empty_like(curve)
    for i in range(curve.size):
        lo = max(0, i - window + 1)
        out[i] = curve[lo:i + 1].mean()
    return out


def loss_curve_report(bitwise: Sequence[float],
                      relaxed: Sequence[float], *,
                      rel_tol: float = 0.25,
                      abs_floor: float = 1e-6,
                      smooth_window: int = 5) -> Dict:
    """Bounded-trajectory acceptance of a relaxed loss curve vs its
    bitwise twin.

    Accepted iff (a) both curves are finite, (b) the max per-step
    relative divergence ``|r_t - b_t| / max(|b_t|, abs_floor)`` of the
    SMOOTHED curves (trailing mean over ``smooth_window`` steps) stays
    ≤ ``rel_tol``, and (c) the relaxed run still learns — its final
    loss is below its own starting loss (quantization noise must slow
    training at worst, never turn it into a random walk).

    Why smoothed: near convergence the optimizer itself jitters — a
    bitwise tiny run oscillates ±20% per step around its floor, so the
    RAW per-step divergence between two equally-good trajectories
    spikes on unlucky step pairs (measured: 37% on zero1-dp8 while the
    smoothed curves sat 7% apart). The raw max is still recorded
    (``raw_max_rel_div``) so a drift the smoothing hides stays visible
    in the bench JSON."""
    b = np.asarray(list(bitwise), np.float64)
    r = np.asarray(list(relaxed), np.float64)
    report: Dict = {"steps": int(min(b.size, r.size)),
                    "rel_tol": rel_tol,
                    "bitwise_first": float(b[0]) if b.size else None,
                    "bitwise_final": float(b[-1]) if b.size else None,
                    "relaxed_first": float(r[0]) if r.size else None,
                    "relaxed_final": float(r[-1]) if r.size else None}
    if b.size == 0 or b.size != r.size:
        report.update(accepted=False,
                      reason=f"curve length mismatch {r.size}!={b.size}")
        return report
    if not (np.isfinite(b).all() and np.isfinite(r).all()):
        report.update(accepted=False, reason="non-finite loss")
        return report
    raw_div = np.abs(r - b) / np.maximum(np.abs(b), abs_floor)
    bs, rs = _smooth(b, smooth_window), _smooth(r, smooth_window)
    div = np.abs(rs - bs) / np.maximum(np.abs(bs), abs_floor)
    report["max_rel_div"] = float(div.max())
    report["mean_rel_div"] = float(div.mean())
    report["final_rel_div"] = float(div[-1])
    report["raw_max_rel_div"] = float(raw_div.max())
    if div.max() > rel_tol:
        report.update(accepted=False,
                      reason=f"max_rel_div {div.max():.4f} > {rel_tol}")
        return report
    if r.size >= 10 and not r[-1] < r[0]:
        report.update(accepted=False,
                      reason=f"relaxed curve did not learn "
                             f"({r[0]:.4f} -> {r[-1]:.4f})")
        return report
    report["accepted"] = True
    return report


def guard_rel_tol_for(parity: ParityConfig, n_layers: int, *,
                      tp: int = 1) -> float:
    """Which loss-curve tolerance judges this parity config.

    Sync-SCHEDULE rungs judge at the schedule tier's tolerance: a
    schedule shifts the trajectory (constant-factor lag), which the
    per-step relative bar built for quantization noise reads as
    divergence — see syncpolicy.py for the measured separation from
    the all-skipped falsifiability arm. Decided on the RESOLVED
    schedule, never the spec string: ``periodic:1`` / ``layers:*=sync``
    resolve to the exact full graph (and tp=1 plans have no schedule
    at all), so they keep the strict quantization tolerance."""
    from hadoop_tpu.parallel.lowp.syncpolicy import resolve_schedule
    sched = resolve_schedule(
        parity.relaxed_sync, n_layers,
        off_mode=parity.relaxed_sync_mode) if tp > 1 else None
    if sched is not None and any(m != "sync" for m in sched):
        return parity.sync_guard_rel_tol
    return parity.guard_rel_tol


def run_loss_ab(plan, *, preset: str = "tiny", steps: int = 50,
                lr: float = 5e-3, batch: int = 8, seq: int = 32,
                zero1: bool = False, n_microbatches: int = 1,
                optimizer: str = "adamw",
                parity: Optional[ParityConfig] = None,
                rel_tol: Optional[float] = None,
                bitwise_losses: Optional[Sequence[float]] = None,
                seed: int = 0) -> Dict:
    """The loss-curve A-B: run ``steps`` training steps bitwise and
    relaxed from identical init/data on ``plan`` and judge the relaxed
    trajectory with :func:`loss_curve_report`. Captures the relaxed
    build's comm ledger so the report also carries the measured
    payload-byte reduction. Returns the report dict (never raises on
    rejection — callers assert ``report["accepted"]`` so benches can
    record a failing rung as data).

    ``bitwise_losses``: a previously-measured bitwise twin for the SAME
    plan/steps/seed/preset (e.g. another rung's
    ``report["bitwise_losses"]``) — skips re-running the bitwise arm,
    which otherwise dominates a multi-rung ladder's wall clock. A
    length mismatch with ``steps`` is rejected by the judge.

    The default ``lr`` keeps the tiny preset in its DESCENT regime for
    all 50 steps: a hotter rate parks both curves on the converged
    noise floor by mid-run, where per-step divergence measures the
    optimizer's jitter instead of the quantizer's drift (measured:
    lr=1e-2 ends with the bitwise zero1 curve oscillating ±20% around
    its own floor)."""
    import jax
    import jax.numpy as jnp

    from hadoop_tpu.models import get_config
    from hadoop_tpu.parallel.lowp.quant import capture_comm
    from hadoop_tpu.parallel.mesh import make_mesh
    from hadoop_tpu.parallel.train import (init_sharded,
                                           make_data_sharding,
                                           make_train_step)

    if parity is None:
        parity = RELAXED_PARITY
    cfg = get_config(preset, max_seq=max(seq, 32))
    if rel_tol is None:
        rel_tol = guard_rel_tol_for(parity, cfg.n_layers,
                                    tp=plan.tp)
    mesh = make_mesh(plan)
    ds = make_data_sharding(mesh)
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(seed + 1), (batch, seq),
                           0, cfg.vocab_size, dtype=jnp.int32), ds)
    targets = jax.device_put(jnp.roll(tokens, -1, axis=1), ds)

    def run(tier_parity) -> List[float]:
        step = make_train_step(cfg, plan, mesh, lr=lr, donate=False,
                               optimizer=optimizer, zero1=zero1,
                               n_microbatches=n_microbatches,
                               parity=tier_parity)
        params, opt = init_sharded(jax.random.PRNGKey(seed), cfg, plan,
                                   mesh, zero1=zero1)
        losses = []
        for _ in range(steps):
            params, opt, m = step(params, opt, tokens, targets)
            # deliberate per-step sync: the A-B judge needs BOTH full
            # trajectories on the host, and the harness is offline
            losses.append(float(m["loss"]))  # lint: disable=jit/blocking-in-step
        return losses

    bit = [float(x) for x in bitwise_losses] \
        if bitwise_losses is not None else run(BITWISE_PARITY)
    with capture_comm() as ledger:
        rel = run(parity)
    report = loss_curve_report(bit, rel, rel_tol=rel_tol)
    report["plan"] = repr(plan)
    report["codec"] = parity.codec
    # the active TP sync schedule (syncpolicy.py) — A-B rows must say
    # which schedule produced them, or two rungs' ledgers are
    # indistinguishable in the bench JSON
    report["sync_schedule"] = parity.relaxed_sync
    report["sync_mode"] = parity.relaxed_sync_mode
    report["comm"] = ledger.report()
    report["bitwise_losses"] = [round(x, 6) for x in bit]
    report["relaxed_losses"] = [round(x, 6) for x in rel]
    return report
