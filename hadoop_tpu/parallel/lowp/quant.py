"""Quantized collective payloads for the relaxed parity tier.

Comm volume is the bottleneck Flash Communication (arXiv:2412.04964)
attacks: a gradient bucket or row-parallel activation crossing ICI as
float32 spends 4 bytes per element on values whose useful information
is a few bits. Under ``parallel.parity=relaxed`` the collectives here
replace the float payload with:

- ``int8`` — symmetric quantization against SHARED scales: every
  participating rank computes the same scale via a tiny ``pmax``
  collective (one f32 per scale group), so the int8 payloads are
  summable without an all_to_all re-layout. Overflow headroom is
  carved out of the quantization range: with N summing ranks the
  per-rank range is ``127 // N``, so the int8 accumulator can never
  wrap; past 127 ranks the wire widens to int16 (``32767 // N``) —
  still 2× under f32 — rather than silently wrapping. Payload:
  1 byte/element + 4 bytes/group of scales.
- ``fp8`` (emulated via ``float8_e4m3fn``) — values are normalized by
  a shared per-group scale and cast to e4m3 for the wire; the sum runs
  as an all_gather of fp8 payloads reduced locally in f32 (an in-wire
  fp8 accumulation would cost more bits than it saves). On backends
  without native f8 this is exactly what the emulation costs on real
  hardware; the byte accounting is the same 1 byte/element.

Every quantized collective records its payload bytes — and the bytes
the float form would have moved — into the trace-time comm ledger
(:func:`capture_comm`), which is how the bench rungs and tests prove
the ≥2× reduction without instrumenting XLA.

These functions are RELAXED-TIER ENTRY POINTS: tpulint's
``parity/relaxed-gated`` checker requires every call site outside this
package to sit under a lexical guard naming the relaxed tier, so the
bitwise tier provably never reaches them.

Host-side payload codec: :func:`encode_payload` / :func:`decode_payload`
serialize a quantized array with a self-describing header (codec,
dtype, shape) and fail loudly on any mismatch — the same contract as
the serving KV block codec (serving/kvstore/codec.py).
"""

from __future__ import annotations

import dataclasses
import json
import struct
from contextlib import contextmanager
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hadoop_tpu.parallel.lowp import WIRE_CODECS

_TINY = 1e-30          # scale floor: an all-zeros group stays exactly 0
_F8_MAX = 240.0        # e4m3 headroom below the 448 format max
_F8 = jnp.float8_e4m3fn if hasattr(jnp, "float8_e4m3fn") else None


@dataclasses.dataclass(frozen=True)
class RelaxedQuant:
    """How a relaxed-tier collective quantizes its payload."""
    codec: str = "int8"
    group: int = 1024                     # elements per shared scale
    mesh_axis_sizes: Mapping[str, int] = dataclasses.field(
        default_factory=dict)

    def __post_init__(self):
        if self.codec not in WIRE_CODECS:
            raise ValueError(f"relaxed wire codec must be one of "
                             f"{WIRE_CODECS}, got {self.codec!r}")

    def ranks(self, axes: Sequence[str]) -> int:
        n = 1
        for a in axes:
            n *= self.mesh_axis_sizes.get(a, 1)
        return n


# ------------------------------------------------------------ comm ledger

class CommLedger:
    """Trace-time accounting of collective payload bytes.

    ``payload_bytes`` is what the quantized collectives put on the wire
    (int8/fp8 values + f32 scale exchanges); ``reference_bytes`` is
    what the same collectives would have moved unquantized. Both are
    static at trace time (shapes and dtypes are), so recording happens
    while jit TRACES the step — capture must wrap the first call of a
    freshly built step function (a jit cache hit records nothing).
    """

    def __init__(self):
        self.payload_bytes = 0
        self.reference_bytes = 0
        self.executions = 0
        self.sites: List[Tuple[str, int, int]] = []
        # per-site accumulation: site -> [payload, reference, executions]
        # (the sync-schedule proofs read executed-collective counts per
        # site off the trace — a scheduled-off site records 0)
        self.per_site: Dict[str, List[int]] = {}

    def add(self, site: str, payload: int, reference: int,
            executions: int = 1) -> None:
        self.payload_bytes += payload
        self.reference_bytes += reference
        self.executions += executions
        self.sites.append((site, payload, reference))
        tot = self.per_site.setdefault(site, [0, 0, 0])
        tot[0] += payload
        tot[1] += reference
        tot[2] += executions

    @property
    def ratio(self) -> float:
        """reference / payload — ≥2.0 is the relaxed tier's contract."""
        if self.payload_bytes == 0:
            return float("inf") if self.reference_bytes else 1.0
        return self.reference_bytes / self.payload_bytes

    def report(self) -> Dict:
        return {"payload_bytes": self.payload_bytes,
                "reference_bytes": self.reference_bytes,
                "executions": self.executions,
                "ratio": round(self.ratio, 3) if self.payload_bytes
                else None,
                "sites": len(self.sites),
                "per_site": {s: {"payload_bytes": t[0],
                                 "reference_bytes": t[1],
                                 "executions": t[2]}
                             for s, t in self.per_site.items()}}


_ACTIVE_LEDGERS: List[CommLedger] = []


@contextmanager
def capture_comm():
    """Collect quantized-collective byte counts recorded while tracing
    happens inside the ``with`` (build the step fn AND call it once
    inside — jit traces at the first call)."""
    led = CommLedger()
    _ACTIVE_LEDGERS.append(led)
    try:
        yield led
    finally:
        _ACTIVE_LEDGERS.remove(led)


def _nbytes(x) -> int:
    # THE static byte-count helper lives with the runtime ledger — two
    # copies of the byte-accounting primitive feeding one htpu_comm
    # surface would drift
    from hadoop_tpu.obs.comm import static_nbytes
    return static_nbytes(x)


def _record(site: str, payload: int, reference: int,
            executions: int = 1) -> None:
    # scan-fused layer bodies trace once for many executions: the layer
    # loop sets a comm_scale so the trace-time ledgers count what the
    # hardware runs per step (obs/comm.comm_scale)
    from hadoop_tpu.obs.comm import comm_scale_factor
    m = comm_scale_factor()
    for led in _ACTIVE_LEDGERS:
        led.add(site, payload * m, reference * m, executions * m)
    # the RUNTIME comm ledger (obs/comm.py) keeps the same trace-time
    # byte facts per bounded site label, bound to the dispatch seam
    # that traced them — that is how htpu_comm byte counters advance
    # per executed step at runtime. executions=0 marks a site the sync
    # schedule (syncpolicy.py) scheduled off.
    from hadoop_tpu.obs.comm import record_comm
    record_comm(site, payload, reference, executions)


# ------------------------------------------------------------- primitives

def _pad_to_group(flat, group: int):
    pad = (-flat.size) % group
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat, pad


def _shared_group_scales(flat2d, axes, qmax: float):
    """[G] shared scales: per-group amax agreed across ranks via pmax
    (the only float exchange the quantized path keeps)."""
    amax = jnp.max(jnp.abs(flat2d.astype(jnp.float32)), axis=1)
    # axes is a static tuple of mesh-axis NAMES, not a tracer
    if axes:  # lint: disable=jit/traced-branch
        amax = jax.lax.pmax(amax, tuple(axes))
    return jnp.maximum(amax, _TINY) / qmax


def _wire_for(n_ranks: int):  # lint: static-fn — mesh size is trace-time
    """(wire dtype, per-rank qmax) with overflow headroom for ``n``
    in-wire summands. Past 127 ranks an int8 range can't hold even
    ±1 per rank without wrapping, so the wire widens to int16 — still
    2× under f32, and the accumulator invariant stays true instead of
    silently failing at fleet scale."""
    # n_ranks is a static product of mesh-axis sizes, not a tracer
    if n_ranks <= 127:  # lint: disable=jit/traced-branch
        return jnp.int8, max(1, 127 // n_ranks)
    if n_ranks > 32767:  # lint: disable=jit/traced-branch
        raise ValueError(f"quantized collective over {n_ranks} ranks "
                         f"overflows the int16 wire — widen the codec")
    return jnp.int16, max(1, 32767 // n_ranks)


def _quant_rows(flat2d, scales, qmax: float, wire=jnp.int8):
    q = jnp.rint(flat2d.astype(jnp.float32) / scales[:, None])
    return jnp.clip(q, -qmax, qmax).astype(wire)


def _pvary_ct(ct, axes):
    """Re-stamp a cotangent as varying over ``axes`` — metadata only.

    The straight-through backwards implement the VMA transpose
    convention (psum of a varying value transposes to the identity-
    valued pvary). Pre-vma jax has no pcast AND transposes psum as
    psum(ct) — a ×N mismatch — but it also cannot trace the train
    step at all (out_specs replication inference fails, the seed
    parallel suite's gap), so the only pre-vma consumers are the
    verify harness's deliberately patched runs, whose valid-plan
    caveats live in .claude/skills/verify/SKILL.md."""
    if hasattr(jax, "typeof"):
        from hadoop_tpu.ops.vma import pvary_to
        return pvary_to(ct, axes)
    return ct


def _straight_through(fwd_impl, bwd_fn, x):
    """Quantized collective with the EXACT collective's backward.

    The quantizer's rounding has measure-zero gradients — naively
    differentiating through ``rint``/``clip`` returns zero cotangents
    and the relaxed tier silently stops training the moment a
    quantized collective sits inside the autodiff region (the tp
    reduces do). The straight-through estimator keeps the quantized
    wire in the forward and applies the transpose the exact collective
    would have applied in the backward — which for a psum is the free
    cotangent broadcast, so the backward costs exactly what the
    bitwise tier's backward costs."""
    f = jax.custom_vjp(fwd_impl)
    f.defvjp(lambda v: (fwd_impl(v), None),
             lambda _res, ct: (bwd_fn(ct),))
    return f(x)


def psum_quantized(x, axes, rq: RelaxedQuant, *, scale: str = "group",
                   site: str = "psum"):
    """Relaxed psum: int8 (or fp8) payload + shared scales.

    ``scale="group"`` uses one scale per ``rq.group`` elements (gradient
    buckets concatenate leaves whose magnitudes differ by orders);
    ``scale="tensor"`` uses one scalar (activations inside one layer are
    magnitude-homogeneous, and a scalar scale survives any downstream
    re-layout). Result has x's shape/dtype; values are allclose to the
    exact psum, never bitwise. Differentiable: the backward is the
    exact psum's transpose (straight-through), identical in cost and
    value to the bitwise tier's backward.
    """
    axes = tuple(axes)
    n = rq.ranks(axes)
    # static mesh-size / dtype facts decide the codec path at trace time
    if n == 1 or not jnp.issubdtype(  # lint: disable=jit/traced-branch
            jnp.dtype(x.dtype), jnp.floating):
        return jax.lax.psum(x, axes) if axes else x

    def bwd(ct):
        # transpose of psum: every rank receives the (replicated)
        # cotangent; pvary only re-stamps the varying-axes metadata
        return _pvary_ct(ct, axes)

    return _straight_through(
        lambda v: _psum_quantized_impl(v, axes, rq, scale, site),
        bwd, x)


def _psum_quantized_impl(x, axes, rq: RelaxedQuant, scale: str,
                         site: str):
    n = rq.ranks(axes)
    flat = x.reshape(-1)
    group = flat.size if scale == "tensor" else max(1, rq.group)
    flat, _pad = _pad_to_group(flat, group)
    rows = flat.reshape(-1, group)
    if rq.codec == "fp8" and _F8 is not None and len(axes) == 1:
        # in-wire fp8 accumulation would burn the saved bits: gather
        # the fp8 payloads and reduce locally in f32. Only single-axis
        # sums — a multi-axis sum would need an f32 second stage that
        # moves MORE bytes than the f8 leg saves, so those ride the
        # int8 wire below instead.
        scales = _shared_group_scales(rows, axes, _F8_MAX)
        f8 = (rows.astype(jnp.float32) / scales[:, None]).astype(_F8)
        gat = jax.lax.all_gather(f8, axes[0])
        acc = jnp.sum(gat.astype(jnp.float32), axis=0)
        out = acc * scales[:, None]
        _record(site, _nbytes(f8) + _nbytes(scales), _nbytes(x))
    else:
        wire, qmax = _wire_for(n)
        scales = _shared_group_scales(rows, axes, qmax)
        q = _quant_rows(rows, scales, qmax, wire)
        s = jax.lax.psum(q, axes)
        out = s.astype(jnp.float32) * scales[:, None]
        _record(site, _nbytes(q) + _nbytes(scales), _nbytes(x))
    return out.reshape(-1)[:x.size].reshape(x.shape).astype(x.dtype)


def psum_scatter_quantized(x, scatter_axis: str, rq: RelaxedQuant, *,
                           rest_axes: Sequence[str] = (),
                           scatter_dimension: int = 0,
                           scale: str = "group", site: str = "scatter"):
    """Relaxed psum(+rest) ∘ psum_scatter: the reduce-scatter form.

    ``scale="group"`` requires the ZeRO-1 bucket layout — a 2-D
    ``[Z, K]`` array tiled-scattered on dim 0 — and keeps one scale per
    (row, group-of-K) so the surviving slice dequantizes with exactly
    its own scales (selected by this rank's row index after the
    scatter). ``scale="tensor"`` works with any layout/dimension (the
    megatron-SP activation scatter) at scalar-scale granularity.

    The in-wire accumulation needs integer headroom, so the fp8 codec
    falls back to the int8 wire here (documented; the gather-based fp8
    form cannot express a scatter without re-materializing the full
    tensor it exists to avoid). The tensor-scale form is
    differentiable: its backward is the exact reduce-scatter's
    transpose (an all_gather of the cotangent — the same collective
    the bitwise tier's backward issues).
    """
    rest = tuple(rest_axes)
    all_axes = rest + (scatter_axis,)
    n = rq.ranks(all_axes)
    wire, qmax = _wire_for(n)
    if scale == "tensor":
        def impl(v):
            # one scalar scale, agreed across every participating rank
            # — survives any scatter layout (the megatron-SP scatter)
            amax = jax.lax.pmax(
                jnp.max(jnp.abs(v.astype(jnp.float32))), all_axes)
            s0 = jnp.maximum(amax, _TINY) / qmax
            q = jnp.clip(jnp.rint(v.astype(jnp.float32) / s0),
                         -qmax, qmax).astype(wire)
            if rest:
                q = jax.lax.psum(q, rest)
            sl = jax.lax.psum_scatter(
                q, scatter_axis, scatter_dimension=scatter_dimension,
                tiled=True)
            _record(site, _nbytes(q) + 4, _nbytes(v))
            return (sl.astype(jnp.float32) * s0).astype(v.dtype)

        def bwd(ct):
            full = jax.lax.all_gather(ct, scatter_axis,
                                      axis=scatter_dimension,
                                      tiled=True)
            return _pvary_ct(full, all_axes)

        return _straight_through(impl, bwd, x)
    if x.ndim != 2 or scatter_dimension != 0:
        raise ValueError("group-scaled quantized scatter needs the "
                         "[Z, K] bucket layout (scatter_dimension=0)")
    z, k = x.shape
    group = min(max(1, rq.group), k)
    pad = (-k) % group
    buf = jnp.pad(x, ((0, 0), (0, pad))) if pad else x
    g = buf.shape[1] // group
    rows = buf.reshape(z * g, group)
    scales = _shared_group_scales(rows, all_axes, qmax)   # [z*g]
    q = _quant_rows(rows, scales, qmax, wire).reshape(z, g * group)
    if rest:
        q = jax.lax.psum(q, rest)
    sl = jax.lax.psum_scatter(q, scatter_axis, scatter_dimension=0,
                              tiled=True).reshape(g, group)
    idx = jax.lax.axis_index(scatter_axis)
    my_scales = jax.lax.dynamic_slice(scales.reshape(z, g),
                                      (idx, jnp.zeros((), jnp.int32)),
                                      (1, g)).reshape(g)
    out = sl.astype(jnp.float32) * my_scales[:, None]
    _record(site, _nbytes(q) + _nbytes(scales), _nbytes(x))
    return out.reshape(-1)[:k].astype(x.dtype)


def psum_of_scatter_quantized(row, z: int, idx, axes,
                              rq: RelaxedQuant, *, site: str = "gather"):
    """Relaxed ZeRO-1 reassembly: the psum-of-disjoint-scatters gather
    with a quantized wire. Exactly ONE rank contributes each element,
    so there is no accumulation and the full ±127 int8 range (or a
    true fp8 value — f8 + 0 is exact) applies; scales are local to the
    contributing rank and ride a tiny parallel f32 scatter-psum.

    ``row``: this rank's (K,) updated slice; returns the dequantized
    ``[Z, K_padded]`` buffer (caller slices columns per leaf).
    """
    axes = tuple(axes)
    k = row.shape[0]
    group = min(max(1, rq.group), k)
    flat, _pad = _pad_to_group(row, group)
    rows = flat.reshape(-1, group)
    g = rows.shape[0]
    kp = g * group
    zero_i = jnp.zeros((), jnp.int32)
    if rq.codec == "fp8" and _F8 is not None:
        scales = _shared_group_scales(rows, (), _F8_MAX)   # local amax
        payload = (rows.astype(jnp.float32) /
                   scales[:, None]).astype(_F8).reshape(kp)
        buf = jnp.zeros((z, kp), _F8)
    else:
        scales = _shared_group_scales(rows, (), 127.0)
        payload = _quant_rows(rows, scales, 127.0).reshape(kp)
        buf = jnp.zeros((z, kp), jnp.int8)
    buf = jax.lax.dynamic_update_slice(buf, payload[None, :],
                                       (idx, zero_i))
    sbuf = jnp.zeros((z, g), jnp.float32)
    sbuf = jax.lax.dynamic_update_slice(sbuf, scales[None, :],
                                        (idx, zero_i))
    # int8/f8 + 0 sums exactly: the psum IS the all_gather here
    buf = jax.lax.psum(buf, axes)
    sbuf = jax.lax.psum(sbuf, axes)
    out = buf.astype(jnp.float32).reshape(z, g, group) * \
        sbuf[:, :, None]
    # the wire moves the whole [Z, Kp] buffer (as the bitwise psum-of-
    # scatters does in the leaf dtype) plus the [Z, G] scale plane
    _record(site, _nbytes(buf) + _nbytes(sbuf),
            z * kp * jnp.dtype(row.dtype).itemsize)
    return out.reshape(z, kp).astype(row.dtype)


# ------------------------------------------- MoE expert all2all payloads
# (RELAXED-TIER ENTRY POINTS: the expert-parallel dispatch/combine
# exchange of serving MoE — models/moe.py's all_to_all pair — carries
# its payload as int8 rows + per-(expert, slot) f32 scales under
# serving.parity=relaxed. Flash Communication (arXiv:2412.04964)
# applied to the a2a legs; every call site outside the lowp package
# must sit under a lexical relaxed-parity guard.)

def _expert_payload_quantized(x, site: str, axis_name, *,
                              split_axis: int, concat_axis: int):
    """Quantize an ``[E, C, D]`` expert payload to int8 with one f32
    scale per (expert, slot) row, exchange it over ``axis_name`` (the
    ``ep`` mesh axis; ``None`` = single-chip replica, the exchange is
    the identity), and dequantize on the far side. The trace-time
    record charges the WIRE form (int8 payload + scale plane) against
    the f32 reference at the bounded ``moe.*`` comm-ledger sites —
    that ledger is where the >=2x byte contract is asserted from."""
    flat = x.reshape(-1, x.shape[-1])
    amax = jnp.max(jnp.abs(flat.astype(jnp.float32)), axis=1)
    scales = jnp.maximum(amax, _TINY) / 127.0
    q = _quant_rows(flat, scales, 127.0).reshape(x.shape)
    s = scales.reshape(x.shape[:-1])
    _record(site, _nbytes(q) + _nbytes(s), _nbytes(x))
    # axis_name is a static mesh-axis name, never a tracer
    if axis_name is not None:  # lint: disable=jit/traced-branch
        # tiled=True form — the untiled form's transpose miscompiles
        # in current JAX (models/moe.py precedent); the scale plane
        # rides the same exchange one dim short
        q = jax.lax.all_to_all(q, axis_name, split_axis=split_axis,
                               concat_axis=concat_axis, tiled=True)
        s = jax.lax.all_to_all(s, axis_name, split_axis=split_axis,
                               concat_axis=concat_axis, tiled=True)
    return (q.astype(jnp.float32) * s[..., None]).astype(x.dtype)


def moe_dispatch_quantized(xe, axis_name=None):
    """The dispatch leg: every rank's ``[E, C, D]`` expert input
    batches cross to their expert owners ([E, C, D] -> [E/ep, ep*C, D]
    on a real ``ep`` mesh) as int8 + row scales. RELAXED-TIER ENTRY
    POINT — recorded at the bounded ``moe.dispatch`` site."""
    return _expert_payload_quantized(xe, "moe.dispatch", axis_name,
                                     split_axis=0, concat_axis=1)


def moe_combine_quantized(ye, axis_name=None):
    """The combine leg: expert outputs return to their token owners
    (the reverse exchange) as int8 + row scales. RELAXED-TIER ENTRY
    POINT — recorded at the bounded ``moe.combine`` site."""
    return _expert_payload_quantized(ye, "moe.combine", axis_name,
                                     split_axis=1, concat_axis=0)


# ------------------------------------------------- host-side payload codec

_PAYLOAD_VERSION = 1


def _np_dtype(name) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # bfloat16 and friends register through ml_dtypes, which numpy
        # cannot resolve from the string name alone
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def quantize_array(x: np.ndarray, codec: str = "int8",
                   group: int = 1024) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side symmetric per-group quantization — THE public
    per-group int8 codec (re-exported from ``hadoop_tpu.parallel.lowp``;
    the kvstore codec.py precedent: one quantizer defines every int8
    surface). Groups are ``group`` consecutive elements of the
    flattened array, one f32 scale per group (amax / qmax), full ±127
    range — no accumulation headroom, resident/payload data sums
    nothing in-wire. Contract pins consumers rely on: an all-zeros
    group decodes to exact zeros (the _TINY scale floor), and
    ``scales.size == ceil(x.size / group)``. Consumers: the relaxed
    collectives above, the serving weight plane
    (``serving/weightplane.py`` — weight groups ride the contraction
    dimension so scales dequantize next to the MXU), the payload codec
    below."""
    if codec not in WIRE_CODECS:
        raise ValueError(f"unknown wire codec {codec!r} "
                         f"(must be one of {WIRE_CODECS})")
    flat = np.asarray(x, np.float32).reshape(-1)
    pad = (-flat.size) % group
    if pad:
        flat = np.pad(flat, (0, pad))
    rows = flat.reshape(-1, group)
    qmax = _F8_MAX if codec == "fp8" else 127.0
    scales = np.maximum(np.max(np.abs(rows), axis=1), _TINY) / qmax
    if codec == "fp8":
        import ml_dtypes
        q = (rows / scales[:, None]).astype(ml_dtypes.float8_e4m3fn)
    else:
        q = np.clip(np.rint(rows / scales[:, None]), -127,
                    127).astype(np.int8)
    return q, scales.astype(np.float32)


def dequantize_array(q: np.ndarray, scales: np.ndarray, shape,
                     dtype) -> np.ndarray:
    rows = np.asarray(q, np.float32) * np.asarray(
        scales, np.float32)[:, None]
    n = int(np.prod(shape))
    return rows.reshape(-1)[:n].reshape(shape).astype(dtype)


def encode_payload(x: np.ndarray, codec: str = "int8",
                   group: int = 1024) -> bytes:
    """Serialize one quantized payload with a self-describing header
    (``u32 BE length || JSON || q bytes || scale bytes``). The header
    pins codec/dtype/shape so a reader configured differently fails
    loudly — mirroring the KV block codec contract."""
    q, scales = quantize_array(x, codec=codec, group=group)
    header = {"v": _PAYLOAD_VERSION, "codec": codec, "group": group,
              "dtype": str(np.dtype(x.dtype)), "shape": list(x.shape)}
    hj = json.dumps(header, separators=(",", ":")).encode()
    return struct.pack(">I", len(hj)) + hj + q.tobytes() + \
        scales.tobytes()


def decode_payload(data: bytes, *, codec: Optional[str] = None,
                   shape=None, dtype=None) -> Tuple[np.ndarray, dict]:
    """Inverse of :func:`encode_payload`; any pinned expectation
    (codec/shape/dtype) that disagrees with the header is a loud
    error, never a silent dequantization against the wrong scales."""
    if len(data) < 4:
        raise ValueError("truncated lowp payload (no header length)")
    (hlen,) = struct.unpack(">I", data[:4])
    header = json.loads(data[4:4 + hlen].decode())
    if header.get("v") != _PAYLOAD_VERSION:
        raise ValueError(f"lowp payload version {header.get('v')!r} "
                         f"(expected {_PAYLOAD_VERSION})")
    if codec is not None and header["codec"] != codec:
        raise ValueError(f"lowp payload codec {header['codec']!r} != "
                         f"expected {codec!r}")
    hshape = tuple(header["shape"])
    if shape is not None and hshape != tuple(shape):
        raise ValueError(f"lowp payload shape {hshape} != {tuple(shape)}")
    if dtype is not None and _np_dtype(header["dtype"]) != \
            _np_dtype(dtype):
        raise ValueError(f"lowp payload dtype {header['dtype']} != "
                         f"{_np_dtype(dtype)}")
    group = int(header["group"])
    n = int(np.prod(hshape))
    g = -(-n // group)
    body = data[4 + hlen:]
    if len(body) != g * group + g * 4:
        raise ValueError("truncated lowp payload body")
    if header["codec"] == "fp8":
        import ml_dtypes
        q = np.frombuffer(body[:g * group], ml_dtypes.float8_e4m3fn)
    else:
        q = np.frombuffer(body[:g * group], np.int8)
    scales = np.frombuffer(body[g * group:], np.float32)
    out = dequantize_array(q.reshape(g, group), scales, hshape,
                           _np_dtype(header["dtype"]))
    return out, header
