"""Partially synchronized activations — per-layer TP sync schedules.

Tensor-Parallelism with Partially Synchronized Activations (PST,
arXiv:2506.19645) observes that the row-parallel activation all-reduce
does not have to run on every layer of every step: a subset of the
syncs can be skipped (each rank proceeds on its local partial sum) or
staled (the reduced correction from the previous step stands in for
this step's collective) with negligible loss impact — the residual
stream dominates, and the remaining synced layers keep the ranks from
drifting apart. T3-style chunking (ops/collective_matmul.py) hides the
collective's *latency* and the lowp wire codecs (quant.py) cut its
*bytes*; this module is the third axis: the collective sometimes does
not EXECUTE at all.

The schedule is a per-layer mode assignment resolved once at
train-step build time (:func:`resolve_schedule`):

  parallel.lowp.sync.schedule   full | none | periodic:<k> | layers:<spec>
  parallel.lowp.sync.mode       skip | stale     (what an "off" layer does)

Grammar — clauses joined with ``+``, later clauses refine earlier:

- ``full``           every layer syncs (the default; the exact graph).
- ``none``           no layer syncs (the falsifiability arm — the
                     loss-curve guard must REJECT it).
- ``periodic:<k>``   layer ``i`` syncs iff ``i % k == 0``; the rest
                     take the off-mode. ``periodic:1`` ≡ ``full`` by
                     construction (collective count identical — pinned
                     against the trace-time ledger in tests).
- ``layers:<i>=<mode>[,<i>=<mode>...]``  explicit per-layer overrides
                     (``mode`` ∈ sync|skip|stale; ``*`` = every layer),
                     merged over the base clause — so
                     ``periodic:2+layers:0=sync,3=stale`` is legal.

Off-layer semantics, wired into the row-parallel reduce seam
(``ops/collective_matmul.row_parallel_project`` /
``reduce_row_parallel``) via :func:`scheduled_row_reduce`:

- **skip**: the psum is replaced by the rank's local partial; under
  megatron-SP the psum_scatter is replaced by the rank's own sequence
  block of its local partial (the wire moves nothing, the shape
  contract holds). Straight-through autodiff: the backward applies the
  EXACT collective's transpose (identity/pvary for psum, all_gather
  for the scatter) — the ISSUE-10 measure-zero-gradient lesson applies
  identically here: a skipped forward sync must NOT silently zero the
  backward cotangents.
- **stale**: the layer consumes ``local + corr`` where ``corr`` is the
  previous step's reduced residual correction (``exact - local``,
  stop-gradient) for this site, and emits this step's correction for
  the next step. The deferred collective still executes, but nothing
  in this step's critical path consumes it — XLA schedules it with
  total freedom against the remaining compute (the T3 interleave taken
  to its limit: one whole step of slack). Its bytes are accounted
  honestly under the dedicated ``tp.stale`` ledger site; the
  critical-path site records payload 0.

Every scheduled-off site still records to the runtime comm ledger
(obs/comm.py) under its bounded site label with ``payload_bytes=0``
and ``executions=0`` against the full reference bytes — so the ledger
IS the proof: per-step collective-execution counts and payload bytes
at the scheduled sites drop exactly on schedule, and the per-rank
``htpu_trainer_step_wall`` histograms show whether the win survives
where overlap has no compute left to hide behind.

These in-graph functions are RELAXED-TIER ENTRY POINTS: tpulint's
``parity/relaxed-gated`` checker requires every call site outside this
package to sit under a lexical guard naming the relaxed tier, so the
bitwise tier provably never reaches them. Acceptance is the shared
50-step loss-curve A-B (``guard.run_loss_ab``) like every other
relaxed transform — judged at the schedule tier's own tolerance,
``parallel.lowp.sync.guard.rel-tol`` (default 2.0): a schedule
perturbs the TRAJECTORY (the scheduled run tracks the bitwise curve's
shape a constant factor behind — measured 1.14 stale / 1.45 skip max
smoothed per-step relative divergence at periodic:2 on dp2×tp2+sp
over 50 steps, both ACCEPTED), which the 0.25 tolerance built for
quantization noise reads as failure; the all-layers-skipped
falsifiability arm still REJECTS >8× above this bar (measured
max_rel_div 16.9 with the tp gain, 589 without it), and the guard's
finite + still-learning criteria apply unchanged.

This module is importable from jax-free processes (config parsing);
jax is imported lazily inside the in-graph functions only.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Tuple

SYNC_SCHEDULE_KEY = "parallel.lowp.sync.schedule"
SYNC_MODE_KEY = "parallel.lowp.sync.mode"

MODES = ("sync", "skip", "stale")
OFF_MODES = ("skip", "stale")


# ------------------------------------------------------- schedule parsing

def _parse_clauses(spec: str) -> Tuple[str, int, List[Tuple[Any, str]]]:
    """Grammar check: returns (base, k, overrides) or raises ValueError.
    ``overrides`` is an ORDERED list of (layer index or "*", mode) — the
    documented merge semantics are "later clauses refine earlier", so
    application order must survive parsing; index range is the
    resolver's job (it knows n_layers)."""
    if not isinstance(spec, str) or not spec.strip():
        raise ValueError(
            f"{SYNC_SCHEDULE_KEY} must be a non-empty schedule spec, "
            f"got {spec!r}")
    base, k = "full", 1
    overrides: List[Tuple[Any, str]] = []
    seen_base = False
    for clause in spec.strip().split("+"):
        clause = clause.strip()
        if clause in ("full", "none"):
            if seen_base:
                raise ValueError(f"{SYNC_SCHEDULE_KEY}: more than one "
                                 f"base clause in {spec!r}")
            base, seen_base = clause, True
        elif clause.startswith("periodic:"):
            if seen_base:
                raise ValueError(f"{SYNC_SCHEDULE_KEY}: more than one "
                                 f"base clause in {spec!r}")
            try:
                k = int(clause[len("periodic:"):])
            except ValueError:
                raise ValueError(
                    f"{SYNC_SCHEDULE_KEY}: periodic:<k> needs an "
                    f"integer period, got {clause!r}") from None
            if k < 1:
                raise ValueError(f"{SYNC_SCHEDULE_KEY}: periodic "
                                 f"period must be >= 1, got {k}")
            base, seen_base = "periodic", True
        elif clause.startswith("layers:"):
            body = clause[len("layers:"):]
            if not body:
                raise ValueError(f"{SYNC_SCHEDULE_KEY}: empty layers: "
                                 f"override in {spec!r}")
            for item in body.split(","):
                item = item.strip()
                if "=" not in item:
                    raise ValueError(
                        f"{SYNC_SCHEDULE_KEY}: layers: overrides are "
                        f"<layer>=<mode>, got {item!r}")
                idx_s, mode = item.split("=", 1)
                mode = mode.strip()
                if mode not in MODES:
                    raise ValueError(
                        f"{SYNC_SCHEDULE_KEY}: mode must be one of "
                        f"{MODES}, got {mode!r} in {item!r}")
                idx_s = idx_s.strip()
                if idx_s == "*":
                    overrides.append(("*", mode))
                    continue
                try:
                    idx = int(idx_s)
                except ValueError:
                    raise ValueError(
                        f"{SYNC_SCHEDULE_KEY}: layer index must be an "
                        f"integer or '*', got {idx_s!r}") from None
                if idx < 0:
                    raise ValueError(f"{SYNC_SCHEDULE_KEY}: layer "
                                     f"index must be >= 0, got {idx}")
                overrides.append((idx, mode))
        else:
            raise ValueError(
                f"{SYNC_SCHEDULE_KEY}: unknown clause {clause!r} "
                f"(want full | none | periodic:<k> | layers:<spec>)")
    return base, k, overrides


def validate_spec(spec: str, off_mode: str = "skip") -> None:
    """Grammar-only validation (no n_layers): what ParityConfig's
    __post_init__ runs so a bad conf fails at config time, loudly."""
    _parse_clauses(spec)
    if off_mode not in OFF_MODES:
        raise ValueError(f"{SYNC_MODE_KEY} must be one of {OFF_MODES}, "
                         f"got {off_mode!r}")


def resolve_schedule(spec: str, n_layers: int,
                     off_mode: str = "skip") -> Tuple[str, ...]:
    """Resolve a schedule spec into the per-layer mode tuple the
    ParallelCtx carries (length ``n_layers``, each ``sync|skip|stale``).
    Layer indices out of range are a loud error. The caller is
    responsible for the tp=1 degeneracy (a plan without a tp axis has
    no sync to schedule — ``MeshPlan.ctx`` forces ``full`` by
    construction there)."""
    if off_mode not in OFF_MODES:
        raise ValueError(f"{SYNC_MODE_KEY} must be one of {OFF_MODES}, "
                         f"got {off_mode!r}")
    base, k, overrides = _parse_clauses(spec)
    if base == "full":
        modes = ["sync"] * n_layers
    elif base == "none":
        modes = [off_mode] * n_layers
    else:  # periodic
        modes = ["sync" if i % k == 0 else off_mode
                 for i in range(n_layers)]
    # overrides apply IN SPEC ORDER (later refines earlier — so a
    # trailing `layers:*=stale` really does force the whole stack, and
    # a per-layer override after it wins back its layer)
    for idx, mode in overrides:
        if idx == "*":
            modes = [mode] * n_layers
            continue
        if idx >= n_layers:
            raise ValueError(
                f"{SYNC_SCHEDULE_KEY}: layer index {idx} out of range "
                f"for {n_layers} layers")
        modes[idx] = mode
    return tuple(modes)


# ---------------------------------------------------- trace-time carrier

@dataclasses.dataclass(frozen=True)
class SiteSync:
    """One reduce site's scheduled behavior for the current layer.

    Built by the decoder's scheduled layer loop and consumed at the
    row-parallel reduce seam. ``corr`` is a tracer only in stale mode
    (the previous step's reduced residual correction for this site) —
    a SiteSync with a tracer must therefore be constructed INSIDE the
    traced function, never passed through a static argument.
    """
    mode: str                      # "sync" | "skip" | "stale"
    corr: Optional[Any] = None     # stale only


# --------------------------------------------------- in-graph primitives

def _site_and_ref(y, ctx):
    from hadoop_tpu.parallel.lowp.quant import _nbytes
    site = "tp.scatter" if ctx.megatron_sp else "tp.psum"
    return site, _nbytes(y)


def skip_row_reduce(y, ctx):
    """The scheduled-off reduce: forward keeps the rank's LOCAL partial
    scaled by ``tp_size`` (its own sequence block of it under
    megatron-SP), backward applies the EXACT collective's transpose —
    identity/pvary for the psum, all_gather for the scatter — so
    cotangents through a skipped layer are nonzero and bitwise-match
    the synced layer's backward (the straight-through contract,
    quant.py precedent). Records the site with payload 0 /
    executions 0 against the full reference bytes.

    Why the ``tp_size`` gain: the row-parallel sum has ``tp``
    contributions of comparable magnitude, so the bare local partial
    systematically understates the layer's residual contribution by
    ~1/tp — a bias (not noise) that compounds through the stack.
    Scaling the partial to the sum's expected magnitude is what makes
    the schedule a perturbation instead of a different network
    (measured on the dp2×tp2+sp 50-step A-B: max_rel_div 67.6 bare →
    1.45 with the gain at periodic:2)."""
    import jax

    from hadoop_tpu.parallel.lowp.quant import (_pvary_ct, _record,
                                                _straight_through)
    site, ref = _site_and_ref(y, ctx)
    _record(site, 0, ref, executions=0)
    gain = float(ctx.tp_size)
    if not ctx.megatron_sp:
        # skipped psum: scaled local partial forward, free-broadcast
        # backward
        return _straight_through(
            lambda v: v * gain,
            lambda ct: _pvary_ct(ct, (ctx.tp_axis,)), y)

    step = y.shape[1] // ctx.tp_size

    def fwd(v):
        # the rank's own sequence block of its local partial — the
        # psum_scatter's shape contract without the sum or the wire
        idx = jax.lax.axis_index(ctx.tp_axis)
        return jax.lax.dynamic_slice_in_dim(
            v, idx * step, step, axis=1) * gain

    def bwd(ct):
        full = jax.lax.all_gather(ct, ctx.tp_axis, axis=1, tiled=True)
        return _pvary_ct(full, (ctx.tp_axis,))

    return _straight_through(fwd, bwd, y)


def stale_row_reduce(y, ctx, corr):
    """The scheduled-stale reduce: this step consumes the PREVIOUS
    step's reduced residual correction (``out = local + corr``, no
    collective on the critical path — the tp site records payload 0 /
    executions 0 like a skip), and emits this step's correction
    (``exact - local`` on stop-gradient values) for the next step. The
    deferred exact collective is real and is accounted under the
    dedicated ``tp.stale`` site — but nothing in this step consumes
    its result, so XLA is free to run it beside ALL remaining compute
    (a full step of overlap slack). Returns ``(out, new_corr)``."""
    import jax

    from hadoop_tpu.parallel.lowp.quant import _nbytes, _record
    local = skip_row_reduce(y, ctx)
    if tuple(corr.shape) != tuple(local.shape):
        # a mis-sliced correction would broadcast silently and corrupt
        # every downstream activation — shapes are static, fail at trace
        raise ValueError(
            f"stale sync correction shape {tuple(corr.shape)} != reduce "
            f"output {tuple(local.shape)} (sync_state layout mismatch)")
    out = local + jax.lax.stop_gradient(corr).astype(local.dtype)
    # next step's correction: the exact collective on stop-gradient
    # values — off the autodiff tape AND off this step's critical path
    y_sg = jax.lax.stop_gradient(y)
    _record("tp.stale", _nbytes(y_sg), _nbytes(y_sg))
    if ctx.megatron_sp:
        exact = jax.lax.psum_scatter(y_sg, ctx.tp_axis,
                                     scatter_dimension=1, tiled=True)
    else:
        exact = jax.lax.psum(y_sg, ctx.tp_axis)
    new_corr = exact - jax.lax.stop_gradient(local)
    return out, new_corr


def scheduled_row_reduce(y, ctx, relaxed_sync: SiteSync):
    """Dispatch one row-parallel reduce on its scheduled mode — the
    seam ``ops/collective_matmul`` routes through for scheduled-off
    layers. skip returns the array; stale returns ``(out, new_corr)``."""
    if relaxed_sync.mode == "skip":
        return skip_row_reduce(y, ctx)
    if relaxed_sync.mode == "stale":
        if relaxed_sync.corr is None:
            raise ValueError("stale sync schedule reached the reduce "
                             "seam without a correction input")
        return stale_row_reduce(y, ctx, relaxed_sync.corr)
    raise ValueError(f"scheduled_row_reduce: unexpected mode "
                     f"{relaxed_sync.mode!r}")
