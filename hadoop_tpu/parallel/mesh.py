"""Mesh plans and parameter sharding rules.

A ``MeshPlan`` names the five parallel axes. All five always exist on the
mesh (size-1 axes are free), so one set of PartitionSpecs covers every
plan; the ``ParallelCtx`` handed to the model only names axes with size>1
so degenerate collectives are elided at trace time.

Axis roles:
- ``dp`` data parallelism (batch)
- ``pp`` pipeline parallelism (layer-stack leading dim)
- ``tp`` tensor parallelism (heads / ffn / vocab; Megatron sequence
  parallelism rides this axis when enabled)
- ``ep`` expert parallelism (MoE expert dim; also shards the batch,
  i.e. dp×ep ranks are all data-parallel for non-expert params)
- ``sp`` context parallelism (sequence dim end-to-end, ring attention)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from hadoop_tpu.models.config import ModelConfig
from hadoop_tpu.models.decoder import ParallelCtx
from hadoop_tpu.parallel.ulysses import supports as _ulysses_supports

AXES = ("dp", "pp", "tp", "ep", "sp")


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    dp: int = 1
    pp: int = 1
    tp: int = 1
    ep: int = 1
    sp: int = 1
    megatron_sp: bool = False   # sequence parallelism on the tp axis
    sp_mode: str = "ring"       # context-parallel attention: ring | ulysses
    vpp: int = 1                # virtual stages per pp rank (interleaved
    #                             1F1B model chunks, Megatron-style)

    def __post_init__(self):
        if self.megatron_sp and self.tp == 1:
            raise ValueError("megatron_sp requires tp > 1")
        if self.vpp > 1 and self.pp == 1:
            raise ValueError("vpp (interleaved virtual stages) requires "
                             "pp > 1")
        if self.sp > 1 and self.megatron_sp:
            raise ValueError("sp composes with plain tp, not megatron_sp "
                             "(two different sequence shardings would "
                             "fight over the same dimension)")
        if self.sp > 1 and self.ep > 1:
            raise ValueError("sp x ep (MoE) is not supported yet")

    @property
    def n_devices(self) -> int:
        return self.dp * self.pp * self.tp * self.ep * self.sp

    @property
    def batch_axes(self):
        """Mesh axes that shard the batch (grad-allreduce axes)."""
        axes = ["dp"]
        if self.ep > 1:
            axes.append("ep")
        return tuple(axes)

    def ctx(self, cfg: ModelConfig, tp_overlap_chunks: int = 1,
            relaxed_codec=None,
            relaxed_chunk_matmul: bool = False,
            relaxed_sync=None) -> ParallelCtx:
        return ParallelCtx(
            tp_axis="tp" if self.tp > 1 else None,
            tp_size=self.tp,
            megatron_sp=self.megatron_sp,
            ep_axis="ep" if (self.ep > 1 and cfg.is_moe) else None,
            ep_size=self.ep,
            ring_axis="sp" if self.sp > 1 else None,
            ring_size=self.sp,
            sp_mode=self.sp_mode,
            tp_overlap_chunks=tp_overlap_chunks if self.tp > 1 else 1,
            # the relaxed lowp knobs only change behaviour where a tp
            # collective exists; a tp=1 plan stays bitwise by shape —
            # including the sync schedule, which is forced to full
            # (None) by construction when there is no tp sync to skip
            relaxed_codec=relaxed_codec if self.tp > 1 else None,
            relaxed_chunk_matmul=(relaxed_chunk_matmul
                                  if self.tp > 1 else False),
            relaxed_sync=(tuple(relaxed_sync)
                          if relaxed_sync is not None and self.tp > 1
                          else None),
        )

    def validate(self, cfg: ModelConfig, batch: int, seq: int,
                 n_microbatches: int = 1) -> None:
        checks = [
            (cfg.n_layers % self.pp == 0, "n_layers %% pp"),
            (cfg.vocab_size % self.tp == 0, "vocab %% tp"),
            (cfg.n_heads % self.tp == 0, "heads %% tp"),
            (cfg.n_kv_heads % self.tp == 0, "kv heads %% tp"),
            (cfg.d_ff % self.tp == 0, "d_ff %% tp"),
            (batch % (self.dp * self.ep) == 0, "batch %% dp*ep"),
            (seq % self.sp == 0, "seq %% sp"),
            (self.sp_mode != "ulysses" or self.sp == 1 or
             _ulysses_supports(cfg.n_heads // self.tp,
                               cfg.n_kv_heads // self.tp, self.sp),
             "heads %% sp (ulysses; after tp head split)"),
            (not self.megatron_sp or seq % self.tp == 0, "seq %% tp (sp)"),
            (not cfg.is_moe or cfg.n_experts % self.ep == 0, "experts %% ep"),
            (self.ep == 1 or cfg.is_moe, "ep needs a MoE config"),
            ((batch // (self.dp * self.ep)) % n_microbatches == 0,
             "local batch %% microbatches"),
        ]
        for ok, what in checks:
            if not ok:
                raise ValueError(f"plan/config mismatch: {what} "
                                 f"(plan={self}, cfg={cfg.family})")


def make_mesh(plan: MeshPlan, devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if len(devices) < plan.n_devices:
        raise ValueError(
            f"plan needs {plan.n_devices} devices, have {len(devices)}")
    arr = np.array(devices[: plan.n_devices]).reshape(
        plan.dp, plan.pp, plan.tp, plan.ep, plan.sp)
    return Mesh(arr, AXES)


def param_specs(cfg: ModelConfig, plan: MeshPlan) -> Dict[str, Any]:
    """PartitionSpec pytree matching ``models.decoder.init_params``."""
    layers: Dict[str, P] = {
        "attn_norm_w": P("pp", None),
        "wq": P("pp", None, "tp"),
        "wk": P("pp", None, "tp"),
        "wv": P("pp", None, "tp"),
        "wo": P("pp", "tp", None),
        "mlp_norm_w": P("pp", None),
    }
    if not cfg.use_rmsnorm:
        layers["attn_norm_b"] = P("pp", None)
        layers["mlp_norm_b"] = P("pp", None)
    if cfg.is_moe:
        layers["router"] = P("pp", None, None)
        layers["w_gate"] = P("pp", "ep", None, "tp")
        layers["w_up"] = P("pp", "ep", None, "tp")
        layers["w_down"] = P("pp", "ep", "tp", None)
    elif cfg.use_swiglu:
        layers["w_gate"] = P("pp", None, "tp")
        layers["w_up"] = P("pp", None, "tp")
        layers["w_down"] = P("pp", "tp", None)
    else:
        layers["w_in"] = P("pp", None, "tp")
        layers["b_in"] = P("pp", "tp")
        layers["w_out"] = P("pp", "tp", None)
        layers["b_out"] = P("pp", None)

    specs: Dict[str, Any] = {
        "embed": P("tp", None),
        "layers": layers,
        "final_norm_w": P(),
    }
    if not cfg.use_rmsnorm:
        specs["final_norm_b"] = P()
    if not cfg.use_rope:
        specs["pos_embed"] = P()
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


def shard_params(params, mesh: Mesh, specs):
    """Place an (unsharded) param tree onto the mesh per the spec tree."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, jax.sharding.NamedSharding(mesh, s)),
        params, specs)
