"""Fused AdamW on local parameter shards, with optional ZeRO-1 sharding.

Two tiers of state distribution:

1. Model-parallel sharding (always): the update runs inside ``shard_map``
   on whatever slice of each parameter the rank owns, so moment state is
   sharded exactly like the parameters over tp/pp/ep.
2. ZeRO-1 over the DATA axes (``zero1=True`` in make_train_step): a
   parameter replicated across N data-parallel ranks keeps only 1/N of
   its moment state (and update work) per rank; the updated slices are
   reassembled with one ``all_gather`` per leaf. This is the TPU-native
   equivalent of Megatron's distributed optimizer (param/grad/state
   partitioning + gather), expressed as slice/gather inside the one
   shard_map instead of bespoke bucketing code.

ZeRO-1 state layout: each leaf's local shard is flattened and padded to
``Z*K`` (Z = product of that leaf's data-axis sizes); the state leaf is a
global array of shape ``(*spec_axis_sizes, *data_axis_sizes, K)`` whose
PartitionSpec names every one of those axes — local piece: just ``(K,)``.
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp


class AdamWState(NamedTuple):
    count: jnp.ndarray     # scalar int32
    mu: Any                # tree like params, float32
    nu: Any                # tree like params, float32


def adamw_init(params) -> AdamWState:
    zeros = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    return AdamWState(jnp.zeros((), jnp.int32), zeros,
                      jax.tree_util.tree_map(jnp.copy, zeros))


def adamw_update(params, grads, state: AdamWState, lr: float,
                 b1: float = 0.9, b2: float = 0.95, eps: float = 1e-8,
                 weight_decay: float = 0.1, grad_clip: float = 1.0,
                 gsq=None):
    """One AdamW step; master math in f32, params cast back to their dtype.

    ``gsq``: squared global grad norm. Inside shard_map the local tree is
    only a shard, so the caller must supply the correctly-reduced value
    (see parallel.train._global_grad_sq); default computes it locally.
    """
    count = state.count + 1
    cf = count.astype(jnp.float32)
    if gsq is None:
        gsq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                  for g in jax.tree_util.tree_leaves(grads))
    return _apply(params, grads, state, count, cf, gsq, lr, b1, b2, eps,
                  weight_decay, grad_clip)


def _apply(params, grads, state, count, cf, gsq, lr, b1, b2, eps,
           weight_decay, grad_clip):
    gnorm = jnp.sqrt(gsq)
    scale = jnp.minimum(1.0, grad_clip / jnp.maximum(gnorm, 1e-12))

    bc1 = 1.0 - b1 ** cf
    bc2 = 1.0 - b2 ** cf

    def leaf(p, g, m, n):
        g = g.astype(jnp.float32) * scale
        m = b1 * m + (1 - b1) * g
        n = b2 * n + (1 - b2) * jnp.square(g)
        update = (m / bc1) / (jnp.sqrt(n / bc2) + eps)
        # decoupled weight decay on matrices only (ndim >= 2), like the
        # usual no-decay-on-norms/bias convention
        if p.ndim >= 2:
            update = update + weight_decay * p.astype(jnp.float32)
        newp = p.astype(jnp.float32) - lr * update
        return newp.astype(p.dtype), m, n

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_m = treedef.flatten_up_to(state.mu)
    flat_n = treedef.flatten_up_to(state.nu)
    out = [leaf(p, g, m, n)
           for p, g, m, n in zip(flat_p, flat_g, flat_m, flat_n)]
    new_p = treedef.unflatten([o[0] for o in out])
    new_m = treedef.unflatten([o[1] for o in out])
    new_n = treedef.unflatten([o[2] for o in out])
    return new_p, AdamWState(count, new_m, new_n), gnorm


# ------------------------------------------------------------------ ZeRO-1

def _pad_len(local_size: int, z: int) -> int:
    """Per-data-rank slice length K (local shard padded to Z*K)."""
    return (local_size + z - 1) // z


def zero1_leaf_plan(spec_axes: Sequence[str], data_axes: Sequence[str]
                    ) -> Tuple[str, ...]:
    """Data axes a leaf's state is partitioned over = the data axes the
    leaf is NOT already sharded on (an expert weight sharded on ep keeps
    only dp)."""
    return tuple(a for a in data_axes if a not in spec_axes)


def zero1_init_local(local_shape, z: int):
    """Zeros for one leaf's per-rank moment slice."""
    k = _pad_len(int(jnp.prod(jnp.array(local_shape))) if local_shape
                 else 1, z)
    return jnp.zeros((k,), jnp.float32)


def zero1_update(params, grads, state: AdamWState, lr: float, *,
                 leaf_axes, mesh_axis_sizes: Dict[str, int],
                 b1: float = 0.9, b2: float = 0.95, eps: float = 1e-8,
                 weight_decay: float = 0.1, grad_clip: float = 1.0,
                 gsq=None, grads_sliced: bool = False,
                 gather_bucket_bytes: int = 0, gather_relaxed=None):
    """ZeRO-1 AdamW step (inside shard_map). ``leaf_axes``: pytree like
    params whose leaves are the tuple of data axes partitioning that
    leaf's state (see zero1_leaf_plan). State mu/nu leaves are the local
    (K,) slices. Ref intent: Megatron's DistributedOptimizer — param
    update computed on 1/Z of each replicated leaf, then gathered.

    ``grads_sliced``: the grad leaves are already this rank's reduced
    (K,) slices (the overlap pass reduce-scatters them straight into
    the state layout — parallel/overlap.py); the clip scale still
    applies here. ``gather_bucket_bytes`` > 0 reassembles the updated
    params through bucketed psum-of-scatters (one collective per
    bucket, bitwise identical to the per-leaf form) instead of one
    collective per leaf. ``gather_relaxed`` (relaxed parity tier only,
    parallel/lowp) quantizes that reassembly's wire payload; the
    master mu/nu/param slices this rank updates stay full precision —
    only the broadcast working copy is quantized."""
    count = state.count + 1
    cf = count.astype(jnp.float32)
    gnorm = jnp.sqrt(gsq)
    scale = jnp.minimum(1.0, grad_clip / jnp.maximum(gnorm, 1e-12))
    bc1 = 1.0 - b1 ** cf
    bc2 = 1.0 - b2 ** cf

    # the slice layout (Z, K, rank index) has ONE definition, shared
    # with the overlap pass's reduce-scatter/gather so the layouts can
    # never silently fork (parallel/overlap.py)
    from hadoop_tpu.parallel.overlap import (zero1_slice_index,
                                             zero1_slice_meta)

    def leaf_slice(p, g, m, n, axes):
        """(new_slice, m2, n2) for this rank's (K,) piece of one leaf."""
        z, k = zero1_slice_meta(p, axes, mesh_axis_sizes)
        flat = p.reshape(-1)
        if z == 1:
            idx = jnp.zeros((), jnp.int32)
        else:
            idx = zero1_slice_index(axes, mesh_axis_sizes)
        pad = z * k - flat.size
        if pad:
            flat = jnp.pad(flat, (0, pad))
        pslice = jax.lax.dynamic_slice(flat, (idx * k,), (k,))
        if grads_sliced:
            gslice = g.astype(jnp.float32) * scale
        else:
            gflat = g.reshape(-1).astype(jnp.float32) * scale
            if pad:
                gflat = jnp.pad(gflat, (0, pad))
            gslice = jax.lax.dynamic_slice(gflat, (idx * k,), (k,))
        m2 = b1 * m + (1 - b1) * gslice
        n2 = b2 * n + (1 - b2) * jnp.square(gslice)
        update = (m2 / bc1) / (jnp.sqrt(n2 / bc2) + eps)
        if p.ndim >= 2:  # decay matrices only, same rule as _apply
            update = update + weight_decay * pslice.astype(jnp.float32)
        new_slice = (pslice.astype(jnp.float32) - lr * update).astype(
            p.dtype)
        return new_slice, m2, n2, z, k, idx

    def gather_leaf(p, new_slice, z, k, idx, axes):
        if z == 1:
            return new_slice[:p.size].reshape(p.shape)
        # gather expressed as psum of disjoint scatters: numerically
        # identical to all_gather(tiled) over the slice layout, and
        # provably replication-invariant under shard_map's vma
        # checking (all_gather's output can't be statically shown
        # invariant; a psum's can).
        full = jnp.zeros((z * k,), new_slice.dtype)
        full = jax.lax.dynamic_update_slice(full, new_slice, (idx * k,))
        full = jax.lax.psum(full, axes)
        return full[:p.size].reshape(p.shape)

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_m = treedef.flatten_up_to(state.mu)
    flat_n = treedef.flatten_up_to(state.nu)
    flat_a = treedef.flatten_up_to(leaf_axes)
    out = [leaf_slice(p, g, m, n, a) for p, g, m, n, a in
           zip(flat_p, flat_g, flat_m, flat_n, flat_a)]
    new_m = treedef.unflatten([o[1] for o in out])
    new_n = treedef.unflatten([o[2] for o in out])
    if gather_bucket_bytes > 0:
        from hadoop_tpu.parallel.overlap import bucketed_gather_slices
        new_p = bucketed_gather_slices(
            treedef.unflatten([o[0] for o in out]), params, leaf_axes,
            mesh_axis_sizes, gather_bucket_bytes,
            relaxed=gather_relaxed)
    else:
        new_p = treedef.unflatten([
            gather_leaf(p, o[0], o[3], o[4], o[5], a)
            for p, o, a in zip(flat_p, out, flat_a)])
    return new_p, AdamWState(count, new_m, new_n), gnorm
