"""Fused AdamW on local parameter shards.

This *is* the distributed optimizer: because it runs inside ``shard_map``
on whatever slice of each parameter the rank owns, first/second-moment
state is sharded exactly like the parameters — the TPU-native equivalent
of Megatron's distributed optimizer (param/grad/state sharding), with the
sharding decided once by the PartitionSpec tree instead of bespoke
bucketing code.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp


class AdamWState(NamedTuple):
    count: jnp.ndarray     # scalar int32
    mu: Any                # tree like params, float32
    nu: Any                # tree like params, float32


def adamw_init(params) -> AdamWState:
    zeros = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    return AdamWState(jnp.zeros((), jnp.int32), zeros,
                      jax.tree_util.tree_map(jnp.copy, zeros))


def adamw_update(params, grads, state: AdamWState, lr: float,
                 b1: float = 0.9, b2: float = 0.95, eps: float = 1e-8,
                 weight_decay: float = 0.1, grad_clip: float = 1.0,
                 gsq=None):
    """One AdamW step; master math in f32, params cast back to their dtype.

    ``gsq``: squared global grad norm. Inside shard_map the local tree is
    only a shard, so the caller must supply the correctly-reduced value
    (see parallel.train._global_grad_sq); default computes it locally.
    """
    count = state.count + 1
    cf = count.astype(jnp.float32)
    if gsq is None:
        gsq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                  for g in jax.tree_util.tree_leaves(grads))
    return _apply(params, grads, state, count, cf, gsq, lr, b1, b2, eps,
                  weight_decay, grad_clip)


def _apply(params, grads, state, count, cf, gsq, lr, b1, b2, eps,
           weight_decay, grad_clip):
    gnorm = jnp.sqrt(gsq)
    scale = jnp.minimum(1.0, grad_clip / jnp.maximum(gnorm, 1e-12))

    bc1 = 1.0 - b1 ** cf
    bc2 = 1.0 - b2 ** cf

    def leaf(p, g, m, n):
        g = g.astype(jnp.float32) * scale
        m = b1 * m + (1 - b1) * g
        n = b2 * n + (1 - b2) * jnp.square(g)
        update = (m / bc1) / (jnp.sqrt(n / bc2) + eps)
        # decoupled weight decay on matrices only (ndim >= 2), like the
        # usual no-decay-on-norms/bias convention
        if p.ndim >= 2:
            update = update + weight_decay * p.astype(jnp.float32)
        newp = p.astype(jnp.float32) - lr * update
        return newp.astype(p.dtype), m, n

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_m = treedef.flatten_up_to(state.mu)
    flat_n = treedef.flatten_up_to(state.nu)
    out = [leaf(p, g, m, n)
           for p, g, m, n in zip(flat_p, flat_g, flat_m, flat_n)]
    new_p = treedef.unflatten([o[0] for o in out])
    new_m = treedef.unflatten([o[1] for o in out])
    new_n = treedef.unflatten([o[2] for o in out])
    return new_p, AdamWState(count, new_m, new_n), gnorm
