"""Communication overlap: bucketed gradient collectives + chunked TP reduce.

What serializes a multichip step once the compute itself is clean
(PROFILE.md round 5) is the communication that waits for it: the manual
pipeline schedules psum the whole gradient tree in one burst at the end
of backward, the ZeRO-1 optimizer reassembles parameters with one
collective per leaf, and every row-parallel matmul stalls on its psum
before the residual add can proceed. T3 (arxiv 2401.16677) and Flash
Communication (arxiv 2412.04964) both recover this by decomposing the
collectives so XLA's async-collective scheduler can run them beside the
remaining compute. This module is that decomposition, shaped for
bit-exact parity:

- **Bucketed reduction** (``bucketed_psum``): leaves are grouped by
  (reduce-axes, dtype) signature and packed — in deterministic tree
  flatten order — into buckets of at most ``bucket_bytes``; each bucket
  is one flattened psum. Per element the same ranks' values are summed
  by the same collective, so results are bitwise identical to the
  per-leaf form; what changes is the schedule: many small dependent
  collectives become few large independent ones XLA can overlap with
  the optimizer math that only consumes other buckets.
- **Scattered reduction** (``bucketed_psum_scatter``): the ZeRO-1 form.
  A rank about to update only its 1/Z slice never needs the other
  ranks' elements, so the bucket is reduce-scattered instead of
  all-reduced — half the traffic of psum + local slice. The slice
  VALUES are bitwise identical to psum-then-slice (verified on the CPU
  mesh); only the global grad-norm, now accumulated slice-wise, can
  differ in the last ulp (see make_train_step's zero1 notes).
- **Chunked TP collective-matmul** lives in
  :mod:`hadoop_tpu.ops.collective_matmul` and is driven by
  ``ParallelCtx.tp_overlap_chunks``.

Conf knobs (all ``parallel.overlap.*``; read by :func:`overlap_from_conf`):

  parallel.overlap.enabled              default true
  parallel.overlap.bucket.mb            default 4
  parallel.overlap.tp.chunks            default 4
  parallel.overlap.zero1.reduce-scatter default true
  parallel.ckpt.async                   default true (parallel/trainer.py)

Parity tiers: under ``parallel.parity=relaxed`` (parallel/lowp) the
bucketed collectives here accept a ``relaxed`` quant spec and ride the
wire as int8/fp8 payloads + shared f32 scales — allclose to the exact
sums, ≥2× fewer payload bytes, covered by the lowp loss-curve guard.
Under the default bitwise tier ``relaxed`` is None and this module
compiles exactly the graph documented above.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from hadoop_tpu.ops.vma import vma_of


def _vma_key(x) -> Tuple[str, ...]:
    """vma as a deterministic tuple. Bucket groups are keyed on it so a
    bucket only ever concatenates same-vma leaves: mixing would force a
    pvary up-cast, and a value CLAIMING to vary on an axis it is really
    invariant over turns any later psum over that axis into an
    over-count."""
    return tuple(sorted(vma_of(x)))


@dataclasses.dataclass(frozen=True)
class OverlapConfig:
    """Static overlap knobs, fixed at train-step build time."""
    enabled: bool = True
    bucket_mb: int = 4
    tp_chunks: int = 4
    zero1_reduce_scatter: bool = True

    @property
    def bucket_bytes(self) -> int:
        return max(1, self.bucket_mb) * (1 << 20)


DEFAULT_OVERLAP = OverlapConfig()
OVERLAP_OFF = OverlapConfig(enabled=False)


def overlap_from_conf(conf) -> OverlapConfig:
    """Build an OverlapConfig from a Configuration (defaults above)."""
    if conf is None:
        return DEFAULT_OVERLAP
    return OverlapConfig(
        enabled=conf.get_bool("parallel.overlap.enabled", True),
        bucket_mb=conf.get_int("parallel.overlap.bucket.mb", 4),
        tp_chunks=conf.get_int("parallel.overlap.tp.chunks", 4),
        zero1_reduce_scatter=conf.get_bool(
            "parallel.overlap.zero1.reduce-scatter", True))


# ---------------------------------------------------------------- bucketing

def _pack_buckets(sizes: Sequence[int], itemsize: int,  # lint: static-fn
                  bucket_bytes: int) -> List[List[int]]:
    """Greedy in-order packing of leaf positions into buckets.

    Deterministic: order is the caller's (tree flatten) order, a leaf
    larger than ``bucket_bytes`` gets its own bucket. Returns lists of
    indices into the caller's sequence."""
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i, n in enumerate(sizes):
        nb = n * itemsize
        if cur and cur_bytes + nb > bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nb
    if cur:
        buckets.append(cur)
    return buckets


def _bucket_psum(buf, axes, relaxed, site):
    """One bucket's reduction on the configured parity tier: exact
    psum under bitwise; int8/fp8 wire payload with shared per-group
    scales under relaxed (values allclose, never bitwise — covered by
    the lowp loss-curve guard). Integer buckets stay exact on either
    tier (quantizing an int payload would be a lie, not a codec)."""
    if relaxed is not None and \
            jnp.issubdtype(jnp.dtype(buf.dtype), jnp.floating):
        from hadoop_tpu.parallel.lowp.quant import psum_quantized
        return psum_quantized(buf, axes, relaxed, site=site)
    # runtime comm ledger: the bitwise wire moves exactly the payload
    # (payload == reference); bytes are static trace-time facts
    from hadoop_tpu.obs.comm import record_comm, static_nbytes
    record_comm(site, static_nbytes(buf), static_nbytes(buf))
    return jax.lax.psum(buf, axes)


def bucketed_psum(tree, reduce_axes_tree, bucket_bytes: int,
                  relaxed=None):
    """psum every leaf over its reduce axes, packing same-signature
    leaves into flattened buckets of at most ``bucket_bytes`` each.

    ``reduce_axes_tree``: pytree like ``tree`` whose leaves are tuples of
    mesh axis names to reduce over (empty tuple = leaf passes through).
    Bitwise identical to the per-leaf form — concatenation changes
    which collective an element rides in, never which values it sums.

    ``relaxed`` (a :class:`~hadoop_tpu.parallel.lowp.quant.RelaxedQuant`,
    relaxed parity tier only): each bucket's payload rides the wire
    quantized — allclose to the exact sums, ≥2× fewer payload bytes.
    """
    flat, treedef = jax.tree_util.tree_flatten(tree)
    axes_flat = treedef.flatten_up_to(reduce_axes_tree)
    out: List[Any] = list(flat)

    # group leaf positions by (axes, dtype, vma), preserving first-seen
    # order
    groups: Dict[Any, List[int]] = {}
    order: List[Any] = []
    for i, (g, axes) in enumerate(zip(flat, axes_flat)):
        axes = tuple(axes)
        if not axes:
            continue
        key = (axes, jnp.dtype(g.dtype), _vma_key(g))
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(i)

    for key in order:
        axes, dtype, _ = key
        idxs = groups[key]
        for bucket in _pack_buckets([flat[i].size for i in idxs],
                                    dtype.itemsize, bucket_bytes):
            members = [idxs[j] for j in bucket]
            if len(members) == 1:
                i = members[0]
                out[i] = _bucket_psum(flat[i], axes, relaxed,
                                      "bucket.psum")
                continue
            buf = jnp.concatenate([flat[i].reshape(-1) for i in members])
            buf = _bucket_psum(buf, axes, relaxed, "bucket.psum")
            off = 0
            for i in members:
                n = flat[i].size
                out[i] = buf[off:off + n].reshape(flat[i].shape)
                off += n
    return jax.tree_util.tree_unflatten(treedef, out)


# --------------------------------------------------------- ZeRO-1 scatter

def _axes_product(axes: Sequence[str],  # lint: static-fn
                  mesh_axis_sizes: Dict[str, int]) -> int:
    z = 1
    for a in axes:
        z *= mesh_axis_sizes.get(a, 1)
    return z


def zero1_slice_meta(leaf, axes: Sequence[str],  # lint: static-fn
                     mesh_axis_sizes: Dict[str, int]) -> Tuple[int, int]:
    """(Z, K) for one leaf's ZeRO-1 slice layout: the leaf's flattened
    size padded to Z*K, Z = product of its partitioning data axes.
    THE slice-layout definition — the optimizer's update/gather and
    this module's scatter/gather all import it so the layout cannot
    silently fork."""
    z = _axes_product(axes, mesh_axis_sizes)
    k = (leaf.size + z - 1) // z
    return z, k


def zero1_slice_index(axes: Sequence[str],
                      mesh_axis_sizes: Dict[str, int]):
    """This rank's slice position: mixed-radix (row-major) over the
    leaf's partitioning data axes — the companion of zero1_slice_meta,
    shared for the same single-definition reason."""
    idx = jnp.zeros((), jnp.int32)
    for a in axes:
        idx = idx * mesh_axis_sizes[a] + jax.lax.axis_index(a)
    return idx


def bucketed_psum_scatter(tree, reduce_axes_tree, scatter_axes_tree,
                          mesh_axis_sizes: Dict[str, int],
                          bucket_bytes: int, relaxed=None):
    """Reduce each leaf over its reduce axes AND hand back only this
    rank's ZeRO-1 slice: ``psum`` over the non-scatter axes composed with
    a ``psum_scatter`` over the (single) scatter axis, bucketed.

    Returns a pytree of ``(K,)`` slices in the zero1_layout order. Falls
    back to psum + local dynamic_slice for leaves partitioned over more
    than one data axis (the multi-axis scatter layout does not match a
    single tiled reduce-scatter) and for unpartitioned leaves (Z == 1,
    full psum, slice is the whole leaf). ``relaxed`` quantizes the
    bucketed scatter payloads (relaxed parity tier; the per-leaf
    fallback path stays exact — it carries the rare multi-axis leaves
    whose layout the quantized scatter cannot express).
    """
    flat, treedef = jax.tree_util.tree_flatten(tree)
    red_flat = treedef.flatten_up_to(reduce_axes_tree)
    sc_flat = treedef.flatten_up_to(scatter_axes_tree)
    out: List[Any] = [None] * len(flat)

    def _pad_flat(g, z, k):
        gf = g.reshape(-1)
        pad = z * k - gf.size
        return jnp.pad(gf, (0, pad)) if pad else gf

    # scatter-eligible: exactly one partitioning axis of size > 1
    groups: Dict[Any, List[int]] = {}
    order: List[Any] = []
    for i, (g, red0, sc0) in enumerate(zip(flat, red_flat, sc_flat)):
        red = tuple(red0)
        sc = tuple(a for a in sc0 if mesh_axis_sizes.get(a, 1) > 1)
        if len(sc) != 1 or sc[0] not in red:
            # fallback: full (possibly bucketed later by caller) psum,
            # then the local slice of the zero1 layout
            z, k = zero1_slice_meta(g, sc, mesh_axis_sizes)
            full = jax.lax.psum(g, red) if red else g
            if z == 1:
                out[i] = _pad_flat(full, 1, k)
            else:
                idx = zero1_slice_index(sc, mesh_axis_sizes)
                out[i] = jax.lax.dynamic_slice(
                    _pad_flat(full, z, k), (idx * k,), (k,))
            continue
        rest = tuple(a for a in red if a != sc[0])
        key = (rest, sc[0], jnp.dtype(g.dtype), _vma_key(g))
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(i)

    for key in order:
        rest, sc_axis, dtype, _ = key
        idxs = groups[key]
        z = mesh_axis_sizes[sc_axis]
        ks = [zero1_slice_meta(flat[i], (sc_axis,), mesh_axis_sizes)[1]
              for i in idxs]
        for bucket in _pack_buckets(ks, dtype.itemsize * z, bucket_bytes):
            members = [(idxs[j], ks[j]) for j in bucket]
            # [Z, K_total]: row r carries rank r's slices, concatenated
            buf = jnp.concatenate(
                [_pad_flat(flat[i], z, k).reshape(z, k)
                 for i, k in members], axis=1)
            if relaxed is not None and \
                    jnp.issubdtype(dtype, jnp.floating):
                from hadoop_tpu.parallel.lowp.quant import \
                    psum_scatter_quantized
                sl = psum_scatter_quantized(
                    buf, sc_axis, relaxed, rest_axes=rest,
                    site="bucket.scatter")
            else:
                from hadoop_tpu.obs.comm import (record_comm,
                                                 static_nbytes)
                record_comm("bucket.scatter", static_nbytes(buf),
                            static_nbytes(buf))
                if rest:
                    buf = jax.lax.psum(buf, rest)
                sl = jax.lax.psum_scatter(
                    buf, sc_axis, scatter_dimension=0,
                    tiled=True).reshape(-1)
            off = 0
            for i, k in members:
                out[i] = sl[off:off + k]
                off += k
    return jax.tree_util.tree_unflatten(treedef, out)


def bucketed_gather_slices(slices, params_like, leaf_axes,
                           mesh_axis_sizes: Dict[str, int],
                           bucket_bytes: int, relaxed=None):
    """Reassemble full leaves from per-rank ZeRO-1 slices with bucketed
    psum-of-disjoint-scatters (the vma-provable all_gather; see
    optimizer.zero1_update). One collective per bucket instead of one
    per leaf; bitwise identical — each element is still the psum of one
    rank's scatter against zeros.

    ``slices``: pytree of (K,) updated slices; ``params_like``: pytree of
    the full leaves (shape/dtype targets); ``leaf_axes``: the data axes
    partitioning each leaf. Leaves with Z == 1 pass through reshaped.
    ``relaxed`` (relaxed parity tier) quantizes the broadcast wire:
    each rank ships its slice as int8/fp8 + local scales at FULL range
    (exactly one rank contributes per element, so there is no
    accumulation headroom to pay) — the optimizer's master slices stay
    full precision, only the reassembled working copy is quantized.
    """
    flat_s, treedef = jax.tree_util.tree_flatten(slices)
    flat_p = treedef.flatten_up_to(params_like)
    flat_a = treedef.flatten_up_to(leaf_axes)
    out: List[Any] = [None] * len(flat_s)

    groups: Dict[Any, List[int]] = {}
    order: List[Any] = []
    for i, (sl, p, axes0) in enumerate(zip(flat_s, flat_p, flat_a)):
        axes = tuple(a for a in axes0 if mesh_axis_sizes.get(a, 1) > 1)
        if not axes:
            out[i] = sl[:flat_p[i].size].reshape(flat_p[i].shape)
            continue
        key = (axes, jnp.dtype(sl.dtype), _vma_key(sl))
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(i)

    for key in order:
        axes, dtype, _ = key
        idxs = groups[key]
        z = _axes_product(axes, mesh_axis_sizes)
        idx = zero1_slice_index(axes, mesh_axis_sizes)
        ks = [flat_s[i].shape[0] for i in idxs]
        for bucket in _pack_buckets(ks, dtype.itemsize * z, bucket_bytes):
            members = [(idxs[j], ks[j]) for j in bucket]
            k_total = sum(k for _, k in members)
            row = jnp.concatenate([flat_s[i] for i, _ in members])
            if relaxed is not None and \
                    jnp.issubdtype(dtype, jnp.floating):
                from hadoop_tpu.parallel.lowp.quant import \
                    psum_of_scatter_quantized
                buf = psum_of_scatter_quantized(
                    row, z, idx, axes, relaxed,
                    site="zero1.gather")[:, :k_total]
            else:
                from hadoop_tpu.obs.comm import (record_comm,
                                                 static_nbytes)
                buf = jnp.zeros((z, k_total), row.dtype)
                buf = jax.lax.dynamic_update_slice(
                    buf, row[None, :], (idx, jnp.zeros((), jnp.int32)))
                record_comm("zero1.gather", static_nbytes(buf),
                            static_nbytes(buf))
                buf = jax.lax.psum(buf, axes)
            off = 0
            for i, k in members:
                p = flat_p[i]
                # [Z, k] block, rows = rank slices → flatten row-major
                full = buf[:, off:off + k].reshape(-1)
                out[i] = full[:p.size].reshape(p.shape)
                off += k
    return jax.tree_util.tree_unflatten(treedef, out)
