"""1F1B pipeline schedule: manual fwd/bwd interleave with bounded buffers.

The GPipe schedule in ``parallel.train`` runs all M microbatch forwards,
then lets autodiff replay them backwards — activation liveness grows with
M. This module implements the 1F1B (one-forward-one-backward) schedule the
Megatron-class north star names (BASELINE.json): after a P-deep warmup
each stage alternates one microbatch forward with one backward, so at most
``2P-1`` microbatch stage-inputs are ever live per stage — activation
memory is bounded by the pipeline depth, not the microbatch count.

Because the backward order is interleaved with forwards, autodiff of the
whole schedule cannot produce it; the schedule is written out explicitly:

- One ``lax.scan`` over the global clock (M + 2P - 2 ticks). Every tick,
  every stage (SPMD over the ``pp`` mesh axis) runs one *forward half*
  (microbatch ``t - s``) and one *backward half* (microbatch
  ``t - (2P-2-s)``), each masked out while invalid.
- Forward half: receive the upstream activation (``ppermute`` +1), run
  this stage's layer slice, stash the stage INPUT in a ``2P-1``-slot ring
  buffer (activation checkpointing at stage boundaries: the backward
  recomputes the stage body, Megatron's selective-recompute trade).
- Backward half: receive the downstream cotangent (``ppermute`` -1),
  ``jax.vjp``-recompute the stage for the saved input, apply the
  cotangent — plus a unit cotangent on the per-microbatch loss at the
  last stage, which is where the head/loss gradient enters — accumulate
  parameter grads, send the input-cotangent upstream.

Gradient reduction happens in the caller (train.make_train_step) by the
same vma-driven rule both schedules share: psum each leaf over the axes
its gradient varies on minus the axes it is sharded on.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from hadoop_tpu.models.config import ModelConfig
from hadoop_tpu.models.decoder import embed_tokens, run_layers
from hadoop_tpu.ops import rope_frequencies
from hadoop_tpu.ops.vma import pvary_to, tree_vma, vma_of


def stage_body(params, tok, tgt, x_in, stage, cfg, ctx, cos, sin,
               remat, loss_from_h):
    """One pipeline stage's work on one microbatch — shared by the GPipe
    and 1F1B schedules so they cannot diverge: embed (used at stage 0),
    this rank's layer slice, the loss head (used at the last stage). The
    unused halves are masked by ``jnp.where`` so their cotangents vanish."""
    x0 = embed_tokens(params, tok, cfg, ctx)
    x = jnp.where(stage == 0, x0, x_in)
    y = run_layers(x, params["layers"], cfg, ctx, cos, sin, remat=remat)
    return y, loss_from_h(params, y, tgt, cfg, ctx)


def pipeline_1f1b_loss_and_grad(params, tokens, targets, *,
                                cfg: ModelConfig, plan, ctx,
                                n_microbatches: int, remat: bool,
                                loss_from_h) -> Tuple[jnp.ndarray, Any]:
    """Runs inside shard_map. tokens/targets: [B_local, S] on this rank.

    Returns (sum of per-microbatch mean losses on the last stage — psum
    over 'pp' and divide by M in the caller —, local parameter grads).
    """
    M = n_microbatches
    Pp = plan.pp
    B_l, S = tokens.shape
    tok_mb = tokens.reshape(M, B_l // M, S)
    tgt_mb = targets.reshape(M, B_l // M, S)
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    stage = jax.lax.axis_index("pp")
    s_act = S // plan.tp if plan.megatron_sp else S
    K = 2 * Pp - 1                       # ring-buffer depth (max in-flight)
    fwd_perm = [(i, (i + 1) % Pp) for i in range(Pp)]
    bwd_perm = [((i + 1) % Pp, i) for i in range(Pp)]

    def stage_fn(params, tok, tgt, x_in):
        return stage_body(params, tok, tgt, x_in, stage, cfg, ctx,
                          cos, sin, remat, loss_from_h)

    act_shape = (B_l // M, s_act, cfg.d_model)

    # ---- abstract vma discovery -----------------------------------------
    # shard_map's varying-manual-axes checking requires scan carries and
    # vjp cotangents to carry EXACTLY the vma of the values they stand in
    # for. Find the circulating activation's vma as a fixed point of one
    # stage application, then the cotangent avals from an abstract vjp.
    def _apply(p, x):
        return stage_fn(p, tok_mb[0], tgt_mb[0], x)

    act_vma = frozenset()
    for _ in range(4):
        x_probe = pvary_to(jnp.zeros(act_shape, cfg.jax_dtype), act_vma)
        y_av, loss_av = jax.eval_shape(_apply, params, x_probe)
        new = act_vma | frozenset(y_av.vma)
        if new == act_vma:
            break
        act_vma = new
    loss_vma = frozenset(loss_av.vma) | {"pp"}
    x_probe = pvary_to(jnp.zeros(act_shape, cfg.jax_dtype), act_vma)

    def _cotangent_avals(p, x):
        (y, loss), vjp = jax.vjp(_apply, p, x)
        return vjp((y, loss))

    dparams_av, dx_av = jax.eval_shape(_cotangent_avals, params, x_probe)
    zero_grads = jax.tree_util.tree_map(
        lambda av: pvary_to(jnp.zeros(av.shape, jnp.float32),
                            frozenset(av.vma)),
        dparams_av)

    def tick(carry, t):
        recv_f, recv_b, buf, gacc, loss_acc = carry

        # ---------------- forward half: microbatch mf = t - stage
        mf = t - stage
        f_valid = (mf >= 0) & (mf < M)
        mf_c = jnp.clip(mf, 0, M - 1)
        tok_f = jnp.take(tok_mb, mf_c, axis=0)
        tgt_f = jnp.take(tgt_mb, mf_c, axis=0)
        y, loss_f = stage_fn(params, tok_f, tgt_f, recv_f)
        is_last = stage == Pp - 1
        loss_acc = loss_acc + jnp.where(
            f_valid & is_last, loss_f, 0.0)
        # Checkpoint the stage input (recv_f; embed recomputed at stage 0).
        buf = jax.lax.dynamic_update_index_in_dim(
            buf, recv_f, mf_c % K, axis=0)

        # ---------------- backward half: microbatch mb = t - (2P-2-stage)
        mb = t - (2 * Pp - 2 - stage)
        b_valid = (mb >= 0) & (mb < M)
        mb_c = jnp.clip(mb, 0, M - 1)
        tok_b = jnp.take(tok_mb, mb_c, axis=0)
        tgt_b = jnp.take(tgt_mb, mb_c, axis=0)
        x_saved = jax.lax.dynamic_index_in_dim(
            buf, mb_c % K, axis=0, keepdims=False)
        _, vjp = jax.vjp(lambda p, x: stage_fn(p, tok_b, tgt_b, x),
                         params, x_saved)
        # Cotangents: downstream dy (zero at the last stage — its y feeds
        # nothing), unit loss cotangent at the last stage only. Each must
        # carry exactly the primal output's vma.
        dy = pvary_to(jnp.where(b_valid & ~is_last, 1.0, 0.0).astype(
            recv_b.dtype) * recv_b, act_vma)
        dloss = pvary_to(
            jnp.where(b_valid & is_last, 1.0, 0.0), loss_vma)
        dparams, dx = vjp((dy, dloss))
        gacc = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(jnp.float32), gacc, dparams)

        # ---------------- rotate: activations down, cotangents up
        recv_f2 = jax.lax.ppermute(y, "pp", fwd_perm)
        recv_b2 = jax.lax.ppermute(dx, "pp", bwd_perm)
        return (recv_f2, recv_b2, buf, gacc, loss_acc), None

    # Carries start with exactly the vma the tick outputs will have
    # (scan requires a fixed-point vma).
    recv_f0 = pvary_to(jnp.zeros(act_shape, cfg.jax_dtype), act_vma)
    recv_b0 = pvary_to(jnp.zeros(act_shape, cfg.jax_dtype),
                       frozenset(dx_av.vma))
    buf0 = pvary_to(jnp.zeros((K,) + act_shape, cfg.jax_dtype), act_vma)
    loss0 = pvary_to(jnp.zeros((), jnp.float32), loss_vma)

    (_, _, _, grads, loss_sum), _ = jax.lax.scan(
        tick, (recv_f0, recv_b0, buf0, zero_grads, loss0),
        jnp.arange(M + 2 * Pp - 2))
    # float32 accumulators; the caller reduces across ranks, then casts.
    return loss_sum, grads


def interleaved_layer_permutation(n_layers: int, pp: int, v: int):
    """Physical→logical layer order for the interleaved schedule.

    Megatron's interleaved layout (ref: BASELINE.json north star "1F1B
    interleaved pipeline schedule"; Megatron-LM's virtual pipeline
    model chunks) gives rank ``s`` the v chunks {c·pp + s}: virtual
    stage q = c·pp + s covers logical layers [q·Lc, (q+1)·Lc). The
    framework shards the stacked layer axis contiguously over 'pp', so
    the stacked order must be permuted: physical position
    (s·v + c)·Lc + i  ←  logical layer (c·pp + s)·Lc + i.

    Returns ``perm`` such that ``stacked.take(perm, axis=0)`` converts
    logically-ordered layers to the physical interleaved layout
    (and ``argsort(perm)`` inverts it, e.g. for gradients).
    """
    if n_layers % (pp * v):
        raise ValueError(f"n_layers={n_layers} not divisible by "
                         f"pp*v={pp * v}")
    lc = n_layers // (pp * v)
    perm = []
    for s in range(pp):
        for c in range(v):
            q = c * pp + s
            perm.extend(range(q * lc, (q + 1) * lc))
    return perm


def pipeline_interleaved_loss_and_grad(params, tokens, targets, *,
                                       cfg: ModelConfig, plan, ctx,
                                       n_microbatches: int, remat: bool,
                                       loss_from_h
                                       ) -> Tuple[jnp.ndarray, Any]:
    """Interleaved 1F1B: v virtual stages (model chunks) per rank.

    Ref: the Megatron-LM interleaved schedule (BASELINE.json's north
    star) — splitting each rank's layers into v chunks multiplies the
    pipeline's virtual depth by v while each hop stays one rank, which
    divides the warmup/cooldown bubble per unit of work by ~2 at the
    cost of v× the in-flight activation slots and v× the p2p hops.

    Same masked-global-clock construction as the plain schedule, with
    the clock remapped: virtual stage q = c·P + s (chunk c of rank s);
    per tick each rank runs ONE chunk-forward and ONE chunk-backward.
    Microbatches advance in groups of P (M must divide by P — the
    reference imposes the same constraint). All transfers remain
    single-tick ppermute(+1 fwd / −1 bwd) ring hops: for s<P−1 the
    activation moves to (c, s+1); off the ring's seam (s=P−1→0) it
    arrives as chunk c+1 — the clock arithmetic, not a data shuffle,
    realizes the seam.

    Forward of (m, q=cP+s) fires at  t = (m÷P)·V + cP + (m mod P) + s,
    backward at                      t = (m÷P)·V + (m mod P) + 2V−1−q,
    (V = vP), giving an input lifetime of 2V−1−2q ticks → a 2V-slot
    ring buffer indexed by forward tick never collides.
    """
    M = n_microbatches
    Pp = plan.pp
    v = getattr(plan, "vpp", 1)
    V = v * Pp
    if M % Pp:
        raise ValueError(f"interleaved schedule needs n_microbatches "
                         f"({M}) divisible by pp ({Pp})")
    B_l, S = tokens.shape
    tok_mb = tokens.reshape(M, B_l // M, S)
    tgt_mb = targets.reshape(M, B_l // M, S)
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    stage = jax.lax.axis_index("pp")
    s_act = S // plan.tp if plan.megatron_sp else S
    K = 2 * V
    fwd_perm = [(i, (i + 1) % Pp) for i in range(Pp)]
    bwd_perm = [((i + 1) % Pp, i) for i in range(Pp)]

    def chunk_params(p, c):
        """Chunk c's slice of this rank's stacked layer leaves. Must
        slice the CALLER's params (the vjp primal), not a closure —
        a closed-over copy would be constant under differentiation and
        the layer grads would silently vanish."""
        def slice_leaf(a):
            lc = a.shape[0] // v
            folded = a.reshape((v, lc) + a.shape[1:])
            return jax.lax.dynamic_index_in_dim(folded, c, axis=0,
                                                keepdims=False)
        return jax.tree_util.tree_map(slice_leaf, p["layers"])

    def stage_fn(params, tok, tgt, x_in, q, c):
        x0 = embed_tokens(params, tok, cfg, ctx)
        x = jnp.where(q == 0, x0, x_in)
        y = run_layers(x, chunk_params(params, c), cfg, ctx, cos, sin,
                       remat=remat)
        return y, loss_from_h(params, y, tgt, cfg, ctx)

    act_shape = (B_l // M, s_act, cfg.d_model)

    # vma fixed point + cotangent avals (same dance as the plain
    # schedule — scan carries must hold exactly the right vma).
    def _apply(p, x):
        return stage_fn(p, tok_mb[0], tgt_mb[0], x, jnp.int32(1),
                        jnp.int32(0))

    act_vma = frozenset()
    for _ in range(4):
        x_probe = pvary_to(jnp.zeros(act_shape, cfg.jax_dtype), act_vma)
        y_av, loss_av = jax.eval_shape(_apply, params, x_probe)
        new = act_vma | frozenset(y_av.vma)
        if new == act_vma:
            break
        act_vma = new
    loss_vma = frozenset(loss_av.vma) | {"pp"}
    x_probe = pvary_to(jnp.zeros(act_shape, cfg.jax_dtype), act_vma)

    def _cotangent_avals(p, x):
        (y, loss), vjp = jax.vjp(_apply, p, x)
        return vjp((y, loss))

    dparams_av, dx_av = jax.eval_shape(_cotangent_avals, params, x_probe)
    zero_grads = jax.tree_util.tree_map(
        lambda av: pvary_to(jnp.zeros(av.shape, jnp.float32),
                            frozenset(av.vma)),
        dparams_av)

    def fwd_coords(t):
        """(m, c, q, valid) whose forward this rank runs at tick t."""
        u = t - stage
        uc = jnp.maximum(u, 0)
        w = uc % V
        c = w // Pp
        m = (uc // V) * Pp + (w % Pp)
        valid = (u >= 0) & (m < M)
        return jnp.clip(m, 0, M - 1), c, c * Pp + stage, valid

    def bwd_coords(t):
        """(m, c, q, valid) whose backward this rank runs at tick t."""
        z = t + stage - (V - 1)
        zc = jnp.maximum(z, 0)
        k = zc // V
        w = zc % V
        cc = w // Pp
        c = jnp.where(cc == 0, 0, v - cc)
        m = (k - jnp.where(cc == 0, 1, 0)) * Pp + (w % Pp)
        valid = (z >= 0) & (m >= 0) & (m < M)
        return jnp.clip(m, 0, M - 1), c, c * Pp + stage, valid

    def tick(carry, t):
        recv_f, recv_b, buf, gacc, loss_acc = carry

        # ---------------- forward half
        mf, cf, qf, f_valid = fwd_coords(t)
        tok_f = jnp.take(tok_mb, mf, axis=0)
        tgt_f = jnp.take(tgt_mb, mf, axis=0)
        y, loss_f = stage_fn(params, tok_f, tgt_f, recv_f, qf, cf)
        loss_acc = loss_acc + jnp.where(f_valid & (qf == V - 1),
                                        loss_f, 0.0)
        buf = jax.lax.dynamic_update_index_in_dim(
            buf, recv_f, t % K, axis=0)

        # ---------------- backward half
        mb, cb, qb, b_valid = bwd_coords(t)
        tok_b = jnp.take(tok_mb, mb, axis=0)
        tgt_b = jnp.take(tgt_mb, mb, axis=0)
        x_saved = jax.lax.dynamic_index_in_dim(
            buf, (t + 2 * qb + 1) % K, axis=0, keepdims=False)
        _, vjp = jax.vjp(
            lambda p, x: stage_fn(p, tok_b, tgt_b, x, qb, cb),
            params, x_saved)
        dy = pvary_to(jnp.where(b_valid & (qb != V - 1), 1.0, 0.0)
                      .astype(recv_b.dtype) * recv_b, act_vma)
        dloss = pvary_to(
            jnp.where(b_valid & (qb == V - 1), 1.0, 0.0), loss_vma)
        dparams, dx = vjp((dy, dloss))
        gacc = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(jnp.float32), gacc, dparams)

        recv_f2 = jax.lax.ppermute(y, "pp", fwd_perm)
        recv_b2 = jax.lax.ppermute(dx, "pp", bwd_perm)
        return (recv_f2, recv_b2, buf, gacc, loss_acc), None

    recv_f0 = pvary_to(jnp.zeros(act_shape, cfg.jax_dtype), act_vma)
    recv_b0 = pvary_to(jnp.zeros(act_shape, cfg.jax_dtype),
                       frozenset(dx_av.vma))
    buf0 = pvary_to(jnp.zeros((K,) + act_shape, cfg.jax_dtype), act_vma)
    loss0 = pvary_to(jnp.zeros((), jnp.float32), loss_vma)

    n_ticks = (M // Pp + 2) * V + Pp - 1
    (_, _, _, grads, loss_sum), _ = jax.lax.scan(
        tick, (recv_f0, recv_b0, buf0, zero_grads, loss0),
        jnp.arange(n_ticks))
    return loss_sum, grads
