"""1F1B pipeline schedule: manual fwd/bwd interleave with bounded buffers.

The GPipe schedule in ``parallel.train`` runs all M microbatch forwards,
then lets autodiff replay them backwards — activation liveness grows with
M. This module implements the 1F1B (one-forward-one-backward) schedule the
Megatron-class north star names (BASELINE.json): after a P-deep warmup
each stage alternates one microbatch forward with one backward, so at most
``2P-1`` microbatch stage-inputs are ever live per stage — activation
memory is bounded by the pipeline depth, not the microbatch count.

Because the backward order is interleaved with forwards, autodiff of the
whole schedule cannot produce it; the schedule is written out explicitly:

- One ``lax.scan`` over the global clock (M + 2P - 2 ticks). Every tick,
  every stage (SPMD over the ``pp`` mesh axis) runs one *forward half*
  (microbatch ``t - s``) and one *backward half* (microbatch
  ``t - (2P-2-s)``), each masked out while invalid.
- Forward half: receive the upstream activation (``ppermute`` +1), run
  this stage's layer slice, stash the stage INPUT in a ``2P-1``-slot ring
  buffer (activation checkpointing at stage boundaries: the backward
  recomputes the stage body, Megatron's selective-recompute trade).
- Backward half: receive the downstream cotangent (``ppermute`` -1),
  ``jax.vjp``-recompute the stage for the saved input, apply the
  cotangent — plus a unit cotangent on the per-microbatch loss at the
  last stage, which is where the head/loss gradient enters — accumulate
  parameter grads, send the input-cotangent upstream.

Gradient reduction happens in the caller (train.make_train_step) by the
same vma-driven rule both schedules share: psum each leaf over the axes
its gradient varies on minus the axes it is sharded on.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from hadoop_tpu.models.config import ModelConfig
from hadoop_tpu.models.decoder import embed_tokens, run_layers
from hadoop_tpu.ops import rope_frequencies
from hadoop_tpu.ops.vma import pvary_to, tree_vma, vma_of


def stage_body(params, tok, tgt, x_in, stage, cfg, ctx, cos, sin,
               remat, loss_from_h):
    """One pipeline stage's work on one microbatch — shared by the GPipe
    and 1F1B schedules so they cannot diverge: embed (used at stage 0),
    this rank's layer slice, the loss head (used at the last stage). The
    unused halves are masked by ``jnp.where`` so their cotangents vanish."""
    x0 = embed_tokens(params, tok, cfg, ctx)
    x = jnp.where(stage == 0, x0, x_in)
    y = run_layers(x, params["layers"], cfg, ctx, cos, sin, remat=remat)
    return y, loss_from_h(params, y, tgt, cfg, ctx)


def pipeline_1f1b_loss_and_grad(params, tokens, targets, *,
                                cfg: ModelConfig, plan, ctx,
                                n_microbatches: int, remat: bool,
                                loss_from_h) -> Tuple[jnp.ndarray, Any]:
    """Runs inside shard_map. tokens/targets: [B_local, S] on this rank.

    Returns (sum of per-microbatch mean losses on the last stage — psum
    over 'pp' and divide by M in the caller —, local parameter grads).
    """
    M = n_microbatches
    Pp = plan.pp
    B_l, S = tokens.shape
    tok_mb = tokens.reshape(M, B_l // M, S)
    tgt_mb = targets.reshape(M, B_l // M, S)
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    stage = jax.lax.axis_index("pp")
    s_act = S // plan.tp if plan.megatron_sp else S
    K = 2 * Pp - 1                       # ring-buffer depth (max in-flight)
    fwd_perm = [(i, (i + 1) % Pp) for i in range(Pp)]
    bwd_perm = [((i + 1) % Pp, i) for i in range(Pp)]

    def stage_fn(params, tok, tgt, x_in):
        return stage_body(params, tok, tgt, x_in, stage, cfg, ctx,
                          cos, sin, remat, loss_from_h)

    act_shape = (B_l // M, s_act, cfg.d_model)

    # ---- abstract vma discovery -----------------------------------------
    # shard_map's varying-manual-axes checking requires scan carries and
    # vjp cotangents to carry EXACTLY the vma of the values they stand in
    # for. Find the circulating activation's vma as a fixed point of one
    # stage application, then the cotangent avals from an abstract vjp.
    def _apply(p, x):
        return stage_fn(p, tok_mb[0], tgt_mb[0], x)

    act_vma = frozenset()
    for _ in range(4):
        x_probe = pvary_to(jnp.zeros(act_shape, cfg.jax_dtype), act_vma)
        y_av, loss_av = jax.eval_shape(_apply, params, x_probe)
        new = act_vma | frozenset(y_av.vma)
        if new == act_vma:
            break
        act_vma = new
    loss_vma = frozenset(loss_av.vma) | {"pp"}
    x_probe = pvary_to(jnp.zeros(act_shape, cfg.jax_dtype), act_vma)

    def _cotangent_avals(p, x):
        (y, loss), vjp = jax.vjp(_apply, p, x)
        return vjp((y, loss))

    dparams_av, dx_av = jax.eval_shape(_cotangent_avals, params, x_probe)
    zero_grads = jax.tree_util.tree_map(
        lambda av: pvary_to(jnp.zeros(av.shape, jnp.float32),
                            frozenset(av.vma)),
        dparams_av)

    def tick(carry, t):
        recv_f, recv_b, buf, gacc, loss_acc = carry

        # ---------------- forward half: microbatch mf = t - stage
        mf = t - stage
        f_valid = (mf >= 0) & (mf < M)
        mf_c = jnp.clip(mf, 0, M - 1)
        tok_f = jnp.take(tok_mb, mf_c, axis=0)
        tgt_f = jnp.take(tgt_mb, mf_c, axis=0)
        y, loss_f = stage_fn(params, tok_f, tgt_f, recv_f)
        is_last = stage == Pp - 1
        loss_acc = loss_acc + jnp.where(
            f_valid & is_last, loss_f, 0.0)
        # Checkpoint the stage input (recv_f; embed recomputed at stage 0).
        buf = jax.lax.dynamic_update_index_in_dim(
            buf, recv_f, mf_c % K, axis=0)

        # ---------------- backward half: microbatch mb = t - (2P-2-stage)
        mb = t - (2 * Pp - 2 - stage)
        b_valid = (mb >= 0) & (mb < M)
        mb_c = jnp.clip(mb, 0, M - 1)
        tok_b = jnp.take(tok_mb, mb_c, axis=0)
        tgt_b = jnp.take(tgt_mb, mb_c, axis=0)
        x_saved = jax.lax.dynamic_index_in_dim(
            buf, mb_c % K, axis=0, keepdims=False)
        _, vjp = jax.vjp(lambda p, x: stage_fn(p, tok_b, tgt_b, x),
                         params, x_saved)
        # Cotangents: downstream dy (zero at the last stage — its y feeds
        # nothing), unit loss cotangent at the last stage only. Each must
        # carry exactly the primal output's vma.
        dy = pvary_to(jnp.where(b_valid & ~is_last, 1.0, 0.0).astype(
            recv_b.dtype) * recv_b, act_vma)
        dloss = pvary_to(
            jnp.where(b_valid & is_last, 1.0, 0.0), loss_vma)
        dparams, dx = vjp((dy, dloss))
        gacc = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(jnp.float32), gacc, dparams)

        # ---------------- rotate: activations down, cotangents up
        recv_f2 = jax.lax.ppermute(y, "pp", fwd_perm)
        recv_b2 = jax.lax.ppermute(dx, "pp", bwd_perm)
        return (recv_f2, recv_b2, buf, gacc, loss_acc), None

    # Carries start with exactly the vma the tick outputs will have
    # (scan requires a fixed-point vma).
    recv_f0 = pvary_to(jnp.zeros(act_shape, cfg.jax_dtype), act_vma)
    recv_b0 = pvary_to(jnp.zeros(act_shape, cfg.jax_dtype),
                       frozenset(dx_av.vma))
    buf0 = pvary_to(jnp.zeros((K,) + act_shape, cfg.jax_dtype), act_vma)
    loss0 = pvary_to(jnp.zeros((), jnp.float32), loss_vma)

    (_, _, _, grads, loss_sum), _ = jax.lax.scan(
        tick, (recv_f0, recv_b0, buf0, zero_grads, loss0),
        jnp.arange(M + 2 * Pp - 2))
    # float32 accumulators; the caller reduces across ranks, then casts.
    return loss_sum, grads
