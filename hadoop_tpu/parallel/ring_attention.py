"""Ring attention: causal attention over a sequence-sharded mesh axis.

Each rank owns a contiguous sequence shard of Q/K/V. K/V shards rotate
around the ring with ``ppermute`` (ICI neighbor exchange — the device
analogue of the reference's chained block pipeline, ref:
DataStreamer.java:1656 store-and-forward chain) while every rank
accumulates its queries' attention with the online-softmax merge from
``hadoop_tpu.ops.attention``. Causality is preserved globally because
each chunk is masked with absolute positions; fully-masked chunks merge
as the identity.

Implemented with ``lax.scan`` (not fori_loop) so the whole ring is
reverse-differentiable for training.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from hadoop_tpu.ops.attention import (_repeat_kv, chunk_attention,
                                      merge_attention)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis_name: str, axis_size: int,
                   impl: str = "auto") -> jnp.ndarray:
    """q,k,v: [B, S_local, H(q|kv), D] local shards. Returns [B,S_local,Hq,D].

    Must run inside shard_map with ``axis_name`` bound. ``impl="auto"``
    runs each ring step through the fused Pallas partial
    (ops.flash.flash_attention_partial) on TPU for qualifying shapes:
    the step-0 diagonal is the CAUSAL partial; later chunks run the
    non-causal partial and fold in through the merge weight (an
    invisible chunk's lse is forced to -inf, the merge identity — same
    compute shape every step, so one compiled kernel serves the whole
    ring)."""
    b, sl, hq, d = q.shape
    scale = 1.0 / (d ** 0.5)
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    # runtime comm ledger (obs/comm.py): each ring hop rotates the raw
    # K/V shards; the per-step total is hops x (K+V shard bytes) — a
    # static trace-time fact recorded OUTSIDE the scan (the scan body
    # traces once, but executes per hop). The flash path skips the
    # step-0 diagonal, so it pays one hop fewer.
    from hadoop_tpu.obs.comm import record_comm, static_nbytes
    kv_bytes = static_nbytes(k) + static_nbytes(v)

    from hadoop_tpu.ops import flash
    use_flash = impl == "flash" or (
        impl == "auto" and jax.default_backend() not in ("cpu", "gpu")
        and flash.partial_supported(q.shape, k.shape))

    from hadoop_tpu.ops.vma import pvary_to, vma_of
    target = vma_of(q) | vma_of(k) | vma_of(v) | {axis_name}

    if use_flash:
        record_comm("cp.ring", (axis_size - 1) * kv_bytes,
                    (axis_size - 1) * kv_bytes)
        # step 0: the causal diagonal, fused
        out, lse = flash.flash_attention_partial(q, k, v, scale, True)
        out = pvary_to(out, target)
        lse = pvary_to(lse, target)

        def step(carry, i):
            o_acc, l_acc, kc, vc = carry
            kc = jax.lax.ppermute(kc, axis_name, perm)
            vc = jax.lax.ppermute(vc, axis_name, perm)
            src = (my - i) % axis_size
            o_i, l_i = flash.flash_attention_partial(q, kc, vc, scale,
                                                     False)
            # visibility by merge weight: chunks from LATER ranks are
            # entirely in this rank's future → identity
            visible = src < my
            l_i = jnp.where(visible, l_i, -jnp.inf)
            o_acc, l_acc = merge_attention(o_acc, l_acc, o_i, l_i)
            return (o_acc, l_acc, kc, vc), None

        (out, _, _, _), _ = jax.lax.scan(
            step, (out, lse, k, v), jnp.arange(1, axis_size))
        return out.astype(q.dtype)

    record_comm("cp.ring", axis_size * kv_bytes, axis_size * kv_bytes)
    n_rep = hq // k.shape[2]
    q_pos = my * sl + jnp.arange(sl)
    out0 = pvary_to(jnp.zeros((b, sl, hq, d), jnp.float32), target)
    lse0 = pvary_to(jnp.full((b, sl, hq), -jnp.inf, jnp.float32), target)

    def step(carry, i):
        out, lse, kc, vc = carry
        src = (my - i) % axis_size          # which shard this K/V chunk is
        kv_pos = src * sl + jnp.arange(sl)
        # GQA expansion + f32 promotion happen HERE, per step: the ring
        # rotates the raw [B,S,Hkv,D] bf16 shard, so each ppermute hop
        # moves n_rep*2x fewer bytes over ICI than rotating expanded
        # float32 copies (the replication and cast are pure local
        # compute the VPU redoes for free each step)
        o_i, l_i = chunk_attention(
            q, _repeat_kv(kc, n_rep).astype(jnp.float32),
            _repeat_kv(vc, n_rep).astype(jnp.float32),
            scale, q_pos, kv_pos)
        out, lse = merge_attention(out, lse, o_i, l_i)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (out, lse, kc, vc), None

    (out, _, _, _), _ = jax.lax.scan(
        step, (out0, lse0, k, v), jnp.arange(axis_size))
    return out.astype(q.dtype)
