"""The sharded training step: one shard_map over the whole mesh.

Megatron-style *manual* SPMD: the body sees local shards, and every
cross-device exchange is an explicit XLA collective over ICI —

- tp   : psum after row-parallel matmuls, vocab-parallel CE psums
- sp(tp): all_gather / psum_scatter of sequence-sharded activations
- pp   : ppermute microbatch rotation (GPipe schedule; autodiff produces
         the backward interleave)
- ep   : all_to_all expert dispatch
- sp   : ppermute K/V ring (ring attention)
- dp/ep: psum of gradients
- grads + fused AdamW run on local shards (distributed optimizer)

Gradient reduction rule: a leaf's gradient is psum'd over every *data*
axis (dp, ep, sp, plus pp always and tp only under sequence parallelism —
the cases where ranks see different tokens or stages) that does NOT
appear in the leaf's PartitionSpec; axes in the spec mean the leaf is
sharded there and its gradient is already local-complete.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from hadoop_tpu.models.config import ModelConfig
from hadoop_tpu.models.decoder import (embed_tokens, final_hidden,
                                       forward_hidden, head_matrix,
                                       run_layers)
from hadoop_tpu.models.decoder import init_params as _init_params
from hadoop_tpu.ops import rope_frequencies
from hadoop_tpu.ops.cross_entropy import chunked_lm_cross_entropy
from hadoop_tpu.parallel.mesh import AXES, MeshPlan, param_specs, \
    shard_params
from hadoop_tpu.parallel.optimizer import (AdamWState, adamw_init,
                                           adamw_update, zero1_update)
from hadoop_tpu.parallel.lowp import BITWISE_PARITY, ParityConfig
from hadoop_tpu.parallel.overlap import (DEFAULT_OVERLAP, OverlapConfig,
                                         bucketed_psum,
                                         bucketed_psum_scatter)

try:  # stable name first, experimental fallback
    _shard_map_fn = jax.shard_map  # type: ignore[attr-defined]
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_fn


def _smap(f, mesh, in_specs, out_specs):
    # check_vma=True (the default) is load-bearing for correctness: the
    # varying-manual-axes tracking is what makes collective TRANSPOSES
    # insert the cotangent psums for replicated values used in
    # rank-divergent pathways (residual streams feeding vocab-sliced
    # heads, embeddings feeding only stage 0, ...). With it, gradients
    # of replicated params come out fully reduced over every axis whose
    # ranks see different data — the only manual step left is the
    # mean-vs-sum scaling (see make_train_step).
    return _shard_map_fn(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs)


def _spec_axes(spec) -> set:
    names = set()
    for part in spec:
        if part is None:
            continue
        if isinstance(part, tuple):
            names.update(part)
        else:
            names.add(part)
    return names


def _spec_axes_ordered(spec) -> list:
    names = []
    for part in spec:
        if part is None:
            continue
        for a in (part if isinstance(part, tuple) else (part,)):
            names.append(a)
    return names


def zero1_layout(cfg: ModelConfig, plan: MeshPlan):
    """Per-leaf ZeRO-1 state layout: (data axes partitioning the state,
    global state shape, state PartitionSpec). State leaves are
    ``(*spec_axis_sizes, *data_axis_sizes, K)`` arrays whose spec names
    every leading axis, so the per-rank piece is one (K,) slice —
    optimizer memory ÷ (dp·ep) for replicated leaves."""
    import numpy as np
    shapes = jax.eval_shape(
        lambda: _init_params(jax.random.PRNGKey(0), cfg))
    specs = param_specs(cfg, plan)
    sizes = dict(zip(AXES, (plan.dp, plan.pp, plan.tp, plan.ep, plan.sp)))
    data_axes = plan.batch_axes

    class _Leaf:  # opaque (not a pytree) so tree_map treats it atomically
        __slots__ = ("z_ax", "shape", "spec")

        def __init__(self, z_ax, shape, spec):
            self.z_ax, self.shape, self.spec = z_ax, shape, spec

    def leaf(sh, spec):
        spec_ax = _spec_axes_ordered(spec)
        z_ax = tuple(a for a in data_axes if a not in spec_ax)
        denom = int(np.prod([sizes[a] for a in spec_ax])) if spec_ax else 1
        local = max(1, int(np.prod(sh.shape)) // denom)
        z = int(np.prod([sizes[a] for a in z_ax])) if z_ax else 1
        k = (local + z - 1) // z
        state_shape = tuple(sizes[a] for a in spec_ax) + \
            tuple(sizes[a] for a in z_ax) + (k,)
        return _Leaf(z_ax, state_shape, P(*spec_ax, *z_ax, None))

    layout = jax.tree_util.tree_map(leaf, shapes, specs)
    axes_tree = jax.tree_util.tree_map(lambda lo: lo.z_ax, layout)
    shape_tree = jax.tree_util.tree_map(lambda lo: lo.shape, layout)
    spec_tree = jax.tree_util.tree_map(lambda lo: lo.spec, layout)
    return axes_tree, shape_tree, spec_tree, sizes


def _loss_from_h(params, h, targets, cfg: ModelConfig, ctx,
                 chunk: int = 256):
    """LM loss from pre-head hidden states, chunked over the sequence so
    the full [B,S,V] logits never materialize (the batch-size ceiling on
    large-vocab models — see chunked_lm_cross_entropy)."""
    h = final_hidden(params, h, cfg, ctx)
    head = head_matrix(params, cfg, h.dtype)
    if ctx.tp_axis is not None:
        return chunked_lm_cross_entropy(
            h, head, targets, chunk, axis_name=ctx.tp_axis,
            vocab_shard_size=cfg.vocab_size // ctx.tp_size)
    return chunked_lm_cross_entropy(h, head, targets, chunk)


def make_train_step(cfg: ModelConfig, plan: MeshPlan, mesh: Mesh, *,
                    lr: float = 3e-4, n_microbatches: int = 1,
                    remat: bool = False, donate: bool = True,
                    optimizer: str = "adamw", zero1: bool = False,
                    pipeline_schedule: str = "1f1b",
                    overlap: Optional[OverlapConfig] = None,
                    parity: Optional[ParityConfig] = None):
    """Build the jitted sharded train step.

    Returns fn(params, opt_state, tokens, targets) ->
    (params, opt_state, metrics) where tokens/targets are global
    [batch, seq] int32 arrays (batch sharded over dp×ep, sequence over sp).

    ``pipeline_schedule`` (used when plan.pp > 1): "1f1b" — the manual
    one-forward-one-backward interleave with pipeline-depth-bounded
    activation memory (parallel.pipeline); "gpipe" — all-forwards scan
    with autodiff-generated backwards (activation liveness grows with
    n_microbatches).

    ``overlap`` (default ON, parallel.overlap.* conf): communication
    overlap — chunked row-parallel tp collectives, bucketed manual-
    schedule gradient reduction (reduce-scattered into the ZeRO-1 slice
    layout when ``zero1``), bucketed ZeRO-1 param reassembly. All of it
    is loss-bit-exact against overlap-off except the zero1 manual-
    schedule (pp>1) grad-norm, whose slice-wise accumulation can move
    the clip scale by an ulp (see parallel/overlap.py).

    ``parity`` (default BITWISE, ``parallel.parity`` conf): the parity
    tier (parallel/lowp). Bitwise builds exactly the graph this
    function always built — no lowp code executes. Relaxed quantizes
    the bucketed gradient/reassembly collectives and the tp reduces
    to int8/fp8 wire payloads and unlocks the true chunked collective
    matmul; correctness is covered by the lowp loss-curve A-B guard
    instead of bit-parity. The relaxed consumers ride the overlap
    pass's bucketed collectives, so they require ``overlap.enabled``
    (the default).
    """
    if overlap is None:
        overlap = DEFAULT_OVERLAP
    if parity is None:
        parity = BITWISE_PARITY
    if parity.relaxed and not overlap.enabled:
        # silently degrading to bitwise would label bench rows and
        # A-B arms "relaxed" while measuring the bitwise tier
        raise ValueError(
            "parallel.parity=relaxed requires the overlap pass "
            "(parallel.overlap.enabled=true): every relaxed consumer "
            "rides its bucketed/chunked collectives")
    if parity.relaxed:
        # the relaxed consumers live on the overlap pass's bucketed /
        # chunked collectives; build the quant spec once (guarded —
        # under bitwise no lowp module is touched)
        from hadoop_tpu.parallel.lowp.quant import RelaxedQuant
        from hadoop_tpu.parallel.lowp.syncpolicy import resolve_schedule
        _sizes = dict(zip(AXES,
                          (plan.dp, plan.pp, plan.tp, plan.ep, plan.sp)))
        rq_buckets = RelaxedQuant(
            codec=parity.codec, group=parity.group,
            mesh_axis_sizes=_sizes) if parity.quant_buckets else None
        rq_gather = RelaxedQuant(
            codec=parity.codec, group=parity.group,
            mesh_axis_sizes=_sizes) if parity.quant_zero1_gather \
            else None
        relaxed_codec = parity.codec if parity.quant_tp else None
        relaxed_chunk = parity.chunk_matmul
        # per-layer TP sync schedule (syncpolicy.py): resolved once
        # against the layer count; tp=1 plans have no sync to schedule
        # (plan.ctx forces None there too — by construction)
        relaxed_sched = resolve_schedule(
            parity.relaxed_sync, cfg.n_layers,
            off_mode=parity.relaxed_sync_mode) if plan.tp > 1 else None
        if relaxed_sched is not None and \
                all(m == "sync" for m in relaxed_sched):
            relaxed_sched = None
        if relaxed_sched is not None and plan.pp > 1:
            # each pp stage traces only its local layer slice and the
            # schedule indexes GLOBAL layers — refusing loudly beats a
            # schedule that silently applies per-stage
            raise ValueError(
                "parallel.lowp.sync.schedule requires a flat layer "
                "stack (pp=1); pipeline plans trace per-stage layer "
                "slices the global schedule cannot index")
    else:
        rq_buckets = rq_gather = relaxed_codec = None
        relaxed_chunk = False
        relaxed_sched = None
    ctx = plan.ctx(cfg, tp_overlap_chunks=(
        overlap.tp_chunks if overlap.enabled else 1),
        relaxed_codec=relaxed_codec,
        relaxed_chunk_matmul=relaxed_chunk,
        relaxed_sync=relaxed_sched)
    n_stale = sum(m == "stale" for m in (relaxed_sched or ()))
    specs = param_specs(cfg, plan)
    data_spec = P(("dp", "ep"), "sp")

    # Data axes: each rank's local loss covers 1/data_ranks of the global
    # batch. The autodiff objective is effectively sum-over-data-ranks (the
    # vma transpose machinery psums cotangents of replicated params), so
    # gradients of the global *mean* loss need one uniform scale.
    loss_div = plan.dp * plan.ep * plan.sp

    def _reduce_grads(grads):
        if loss_div == 1:
            return grads
        return jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) / loss_div).astype(g.dtype),
            grads)

    def _global_grad_sq(grads):
        def leaf(g, s):
            local = jnp.sum(jnp.square(g.astype(jnp.float32)))
            shard_axes = tuple(sorted(_spec_axes(s)))
            return jax.lax.psum(local, shard_axes) if shard_axes else local
        parts = jax.tree_util.tree_map(leaf, grads, specs)
        return functools.reduce(
            jnp.add, jax.tree_util.tree_leaves(parts))

    # ------------------------------------------------------------ losses

    def flat_loss(params, tokens, targets):
        h = forward_hidden(params, tokens, cfg, ctx, remat=remat)
        return _loss_from_h(params, h, targets, cfg, ctx)

    def pipelined_loss(params, tokens, targets):
        M = n_microbatches
        Pp = plan.pp
        B_l, S = tokens.shape
        tok_mb = tokens.reshape(M, B_l // M, S)
        tgt_mb = targets.reshape(M, B_l // M, S)
        cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq,
                                    cfg.rope_theta)
        stage = jax.lax.axis_index("pp")
        s_act = S // plan.tp if plan.megatron_sp else S
        perm = [(i, i + 1) for i in range(Pp - 1)]

        def step(recv, t):
            mb_in = jnp.clip(t, 0, M - 1)
            x0 = embed_tokens(params, jnp.take(tok_mb, mb_in, axis=0),
                              cfg, ctx)
            x_in = jnp.where(stage == 0, x0, recv)
            y = run_layers(x_in, params["layers"], cfg, ctx, cos, sin,
                           remat=remat)
            out_i = t - (Pp - 1)
            mb_out = jnp.clip(out_i, 0, M - 1)
            loss_mb = _loss_from_h(
                params, y, jnp.take(tgt_mb, mb_out, axis=0), cfg, ctx)
            take = (stage == Pp - 1) & (out_i >= 0) & (out_i < M)
            loss_t = jnp.where(take, loss_mb, 0.0)
            recv2 = jax.lax.ppermute(y, "pp", perm)
            return recv2, loss_t

        from hadoop_tpu.ops.vma import pvary_to
        from hadoop_tpu.parallel.mesh import AXES
        # activations vary over every mesh axis: dp/ep/sp from the data,
        # pp/tp from the weights (vma is tracked even on size-1 axes)
        recv0 = pvary_to(
            jnp.zeros((B_l // M, s_act, cfg.d_model), cfg.jax_dtype), AXES)
        _, losses = jax.lax.scan(step, recv0, jnp.arange(M + Pp - 1))
        return jax.lax.psum(jnp.sum(losses), "pp") / M

    loss_fn = pipelined_loss if plan.pp > 1 else flat_loss
    if pipeline_schedule == "interleaved" or \
            (plan.pp > 1 and plan.vpp > 1 and pipeline_schedule == "1f1b"):
        pipeline_schedule = "interleaved"
    use_1f1b = plan.pp > 1 and pipeline_schedule in ("1f1b",
                                                     "interleaved")

    # Manual-schedule gradient reduction (the vma transpose machinery does
    # this automatically inside value_and_grad for the autodiff paths):
    # psum each leaf over every axis its accumulated gradient actually
    # varies on and the leaf is not sharded on — those are exactly the
    # axes whose ranks contributed partial sums (different tokens or
    # stages); anything the grad does not vary on is already complete.
    # With overlap on the per-leaf psums pack into deterministic-order
    # buckets (parallel/overlap.py) — same sums per element, but few
    # large independent collectives XLA can run beside remaining compute.
    def _manual_reduce_axes(grads):
        from hadoop_tpu.ops.vma import vma_of
        return jax.tree_util.tree_map(
            lambda g, s: tuple(sorted(vma_of(g) - _spec_axes(s))),
            grads, specs)

    def _reduce_manual(grads):
        axes_tree = _manual_reduce_axes(grads)
        if overlap.enabled:
            return bucketed_psum(grads, axes_tree, overlap.bucket_bytes,
                                 relaxed=rq_buckets)
        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat_a = treedef.flatten_up_to(axes_tree)
        return treedef.unflatten([
            jax.lax.psum(g, a) if a else g
            for g, a in zip(flat_g, flat_a)])

    # -------------------------------------------------------------- body

    from hadoop_tpu.ops.vma import vma_of

    # ZeRO-1 under a manual schedule: reduce-scatter the accumulated
    # grads straight into the slice layout (a rank about to update 1/Z
    # of each leaf never needs the rest) — half the grad traffic of
    # psum + local slice, bitwise-identical slice values. Only the
    # grad-norm accumulates slice-wise (± an ulp on the clip scale).
    z1_scatter = (zero1 and optimizer == "adamw" and use_1f1b and
                  overlap.enabled and overlap.zero1_reduce_scatter)

    def _global_grad_sq_sliced(slices):
        """Squared global grad norm from per-rank ZeRO-1 slices: each
        slice's local sum-of-squares psummed over every axis it still
        varies on (its scatter + shard axes)."""
        def leaf(g):
            local = jnp.sum(jnp.square(g.astype(jnp.float32)))
            axes = tuple(sorted(vma_of(local)))
            return jax.lax.psum(local, axes) if axes else local
        parts = jax.tree_util.tree_map(leaf, slices)
        return functools.reduce(
            jnp.add, jax.tree_util.tree_leaves(parts))

    def body(params, opt_state, tokens, targets):
        if use_1f1b:
            from hadoop_tpu.parallel.pipeline import (
                pipeline_1f1b_loss_and_grad,
                pipeline_interleaved_loss_and_grad)
            sched = pipeline_interleaved_loss_and_grad \
                if pipeline_schedule == "interleaved" \
                else pipeline_1f1b_loss_and_grad
            loss, grads = sched(
                params, tokens, targets, cfg=cfg, plan=plan, ctx=ctx,
                n_microbatches=n_microbatches, remat=remat,
                loss_from_h=_loss_from_h)
            if z1_scatter:
                grads = bucketed_psum_scatter(
                    grads, _manual_reduce_axes(grads), z1_axes,
                    z1_sizes, overlap.bucket_bytes, relaxed=rq_buckets)
            else:
                grads = _reduce_manual(grads)
            # Accumulators summed M per-microbatch mean-losses; the
            # objective (like the gpipe path's psum(...)/M) is their mean.
            grads = jax.tree_util.tree_map(
                lambda g, p: (g / n_microbatches).astype(p.dtype),
                grads, params)
            rem = tuple(sorted(vma_of(loss)))
            if rem:
                loss = jax.lax.psum(loss, rem)
            loss = loss / n_microbatches
        else:
            loss, grads = jax.value_and_grad(loss_fn)(
                params, tokens, targets)
            # sum the per-data-rank losses over whatever axes the loss
            # still varies on (real data axes, plus identity-psums on
            # size-1 axes) and turn the sum into the global batch mean
            rem = tuple(sorted(vma_of(loss)))
            if rem:
                loss = jax.lax.psum(loss, rem)
        return _tail(params, opt_state, loss, grads)

    def body_sync(params, opt_state, tokens, targets, sync_state):
        # stale sync schedule (parallel/lowp/syncpolicy.py): the step
        # additionally carries the [pp, tp, n_stale, 2, B, S, D]
        # correction state — the previous step's reduced residual
        # corrections in, this step's out (stop-gradient: state is soft
        # numerics, never part of the autodiff objective). Flat path
        # only (pp plans are refused above).
        st = sync_state.reshape(sync_state.shape[2:])

        def loss_sync(p):
            h, ns = forward_hidden(p, tokens, cfg, ctx, remat=remat,
                                   sync_state=st)
            return _loss_from_h(p, h, targets, cfg, ctx), ns

        (loss, new_st), grads = jax.value_and_grad(
            loss_sync, has_aux=True)(params)
        rem = tuple(sorted(vma_of(loss)))
        if rem:
            loss = jax.lax.psum(loss, rem)
        new_params, new_opt, metrics = _tail(params, opt_state, loss,
                                             grads)
        new_sync = jax.lax.stop_gradient(new_st).reshape(
            sync_state.shape)
        return new_params, new_opt, metrics, new_sync

    def _tail(params, opt_state, loss, grads):
        grads = _reduce_grads(grads)
        loss = loss / loss_div
        gsq = _global_grad_sq_sliced(grads) if z1_scatter \
            else _global_grad_sq(grads)
        if zero1 and optimizer == "adamw":
            mu_l = jax.tree_util.tree_map(
                lambda m: m.reshape(-1), opt_state.mu)
            nu_l = jax.tree_util.tree_map(
                lambda n: n.reshape(-1), opt_state.nu)
            new_params, new_opt_l, gnorm = zero1_update(
                params, grads,
                AdamWState(opt_state.count, mu_l, nu_l), lr,
                leaf_axes=z1_axes, mesh_axis_sizes=z1_sizes, gsq=gsq,
                grads_sliced=z1_scatter,
                gather_bucket_bytes=(overlap.bucket_bytes
                                     if overlap.enabled else 0),
                gather_relaxed=rq_gather)
            # restore the (1,...,1,K) local state layout for out_specs
            new_opt = AdamWState(
                new_opt_l.count,
                jax.tree_util.tree_map(
                    lambda n2, old: n2.reshape(old.shape),
                    new_opt_l.mu, opt_state.mu),
                jax.tree_util.tree_map(
                    lambda n2, old: n2.reshape(old.shape),
                    new_opt_l.nu, opt_state.nu))
            metrics = {"loss": loss, "grad_norm": gnorm}
            return new_params, new_opt, metrics
        if optimizer == "sgd":
            # plain SGD: exact-parity testing mode (no adaptive-state
            # amplification of float accumulation noise)
            new_params = jax.tree_util.tree_map(
                lambda p, g: (p.astype(jnp.float32)
                              - lr * g.astype(jnp.float32)).astype(p.dtype),
                params, grads)
            new_opt = AdamWState(opt_state.count + 1, opt_state.mu,
                                 opt_state.nu)
            gnorm = jnp.sqrt(gsq)
        else:
            new_params, new_opt, gnorm = adamw_update(
                params, grads, opt_state, lr, gsq=gsq)
        metrics = {"loss": loss, "grad_norm": gnorm}
        return new_params, new_opt, metrics

    if zero1 and optimizer == "adamw":
        z1_axes, _, z1_specs, z1_sizes = zero1_layout(cfg, plan)
        opt_specs = AdamWState(count=P(), mu=z1_specs, nu=z1_specs)
    else:
        z1_axes = z1_sizes = None
        opt_specs = AdamWState(count=P(), mu=specs, nu=specs)
    metric_specs = {"loss": P(), "grad_norm": P()}
    if n_stale:
        # stale sync schedules carry the correction state through the
        # step as an explicit donated operand: global layout
        # [pp, tp, n_stale, 2(attn,mlp), B, S_eff, D] — the leading
        # axes hold each rank's distinct partial-sum corrections, the
        # batch/seq dims shard exactly like the data. The wrapper owns
        # the buffer so every existing caller keeps the 4-arg step
        # signature; a restart (or a batch-shape change) reinitializes
        # it to zeros, which makes the next step behave as skip for
        # exactly one step — soft state, deliberately not checkpointed.
        state_spec = P("pp", "tp", None, None, ("dp", "ep"), "sp", None)
        mapped = _smap(
            body_sync, mesh,
            in_specs=(specs, opt_specs, data_spec, data_spec,
                      state_spec),
            out_specs=(specs, opt_specs, metric_specs, state_spec))
        jitted = jax.jit(mapped,
                         donate_argnums=(0, 1, 4) if donate else ())
        holder = {"shape": None, "state": None}

        def step_with_sync_state(params, opt_state, tokens, targets):
            if holder["shape"] != tuple(tokens.shape):
                b, s = tokens.shape
                s_eff = s // plan.tp if plan.megatron_sp else s
                shp = (plan.pp, plan.tp, n_stale, 2, b, s_eff,
                       cfg.d_model)
                holder["state"] = jax.device_put(
                    jnp.zeros(shp, cfg.jax_dtype),
                    jax.sharding.NamedSharding(mesh, state_spec))
                holder["shape"] = tuple(tokens.shape)
            new_p, new_o, metrics, holder["state"] = jitted(
                params, opt_state, tokens, targets, holder["state"])
            return new_p, new_o, metrics

        return step_with_sync_state
    mapped = _smap(
        body, mesh,
        in_specs=(specs, opt_specs, data_spec, data_spec),
        out_specs=(specs, opt_specs, metric_specs))
    return jax.jit(mapped, donate_argnums=(0, 1) if donate else ())


def physical_layer_order(params, cfg: ModelConfig, plan: MeshPlan):
    """Interleaved-1F1B placement: permute the stacked layer axis so the
    contiguous 'pp' shard hands each rank its v model chunks (virtual
    stages {c·pp + rank}). Identity when vpp == 1."""
    if getattr(plan, "vpp", 1) <= 1:
        return params
    from hadoop_tpu.parallel.pipeline import interleaved_layer_permutation
    perm = jnp.asarray(interleaved_layer_permutation(
        cfg.n_layers, plan.pp, plan.vpp))
    out = dict(params)
    out["layers"] = jax.tree_util.tree_map(
        lambda a: jnp.take(a, perm, axis=0), params["layers"])
    return out


def logical_layer_order(params, cfg: ModelConfig, plan: MeshPlan):
    """Inverse of :func:`physical_layer_order` — back to checkpoint /
    single-device layer order."""
    if getattr(plan, "vpp", 1) <= 1:
        return params
    import numpy as _np

    from hadoop_tpu.parallel.pipeline import interleaved_layer_permutation
    inv = jnp.asarray(_np.argsort(interleaved_layer_permutation(
        cfg.n_layers, plan.pp, plan.vpp)))
    out = dict(params)
    out["layers"] = jax.tree_util.tree_map(
        lambda a: jnp.take(a, inv, axis=0), params["layers"])
    return out


def init_sharded(rng, cfg: ModelConfig, plan: MeshPlan, mesh: Mesh,
                 zero1: bool = False):
    """Initialize params + optimizer state and place them on the mesh.
    ``zero1``: moment state in the ZeRO-1 slice layout (must match the
    train step's flag)."""
    params = _init_params(rng, cfg)
    params = physical_layer_order(params, cfg, plan)
    specs = param_specs(cfg, plan)
    params = shard_params(params, mesh, specs)
    if zero1:
        _, z1_shapes, z1_specs, _ = zero1_layout(cfg, plan)
        def mk(shape, spec):
            return jax.device_put(
                jnp.zeros(shape, jnp.float32),
                jax.sharding.NamedSharding(mesh, spec))
        mu = jax.tree_util.tree_map(
            mk, z1_shapes, z1_specs,
            is_leaf=lambda x: isinstance(x, tuple))
        nu = jax.tree_util.tree_map(
            mk, z1_shapes, z1_specs,
            is_leaf=lambda x: isinstance(x, tuple))
        return params, AdamWState(
            count=jax.device_put(
                jnp.zeros((), jnp.int32),
                jax.sharding.NamedSharding(mesh, P())),
            mu=mu, nu=nu)
    opt = adamw_init(params)
    opt = AdamWState(
        count=jax.device_put(
            opt.count, jax.sharding.NamedSharding(mesh, P())),
        mu=shard_params(opt.mu, mesh, specs),
        nu=shard_params(opt.nu, mesh, specs))
    return params, opt


def make_data_sharding(mesh: Mesh):
    return jax.sharding.NamedSharding(mesh, P(("dp", "ep"), "sp"))
