"""Training driver: sharded step + DFS dataloader + DFS checkpoints.

The integration layer the reference spreads across its AM/history/state-
store machinery: run the jitted sharded train step over a DFS-resident
token stream, checkpoint params + optimizer + data cursor to the DFS on
an interval, and resume exactly after a crash (same loss curve as an
uninterrupted run — the test asserts this bit-for-bit on CPU).
"""

from __future__ import annotations

import logging
import queue
import threading
from collections import deque
from typing import Dict, Optional

import time

import jax
import jax.numpy as jnp

from hadoop_tpu.fs import FileSystem
from hadoop_tpu.metrics import metrics_system
from hadoop_tpu.models.config import ModelConfig
from hadoop_tpu.tracing.tracer import global_tracer
from hadoop_tpu.parallel.checkpoint import (AsyncCheckpointWriter,
                                            latest_step, load_checkpoint,
                                            read_manifest,
                                            reorder_snapshot_axis0,
                                            snapshot_tree, write_snapshot)
from hadoop_tpu.parallel.data import TokenDataset
from hadoop_tpu.parallel.elastic import ElasticConfig
from hadoop_tpu.parallel.mesh import MeshPlan, make_mesh, param_specs
from hadoop_tpu.parallel.lowp import ParityConfig
from hadoop_tpu.parallel.overlap import DEFAULT_OVERLAP, OverlapConfig
from hadoop_tpu.parallel.train import (init_sharded, make_data_sharding,
                                       make_train_step, zero1_layout)
from hadoop_tpu.parallel.optimizer import AdamWState

log = logging.getLogger(__name__)


class Trainer:
    def __init__(self, cfg: ModelConfig, plan: MeshPlan, fs: FileSystem,
                 data_path: str, ckpt_dir: str, *, batch: int,
                 lr: float = 3e-4, optimizer: str = "adamw",
                 zero1: bool = False, remat=False,
                 ckpt_interval: int = 100, keep: int = 3,
                 data_dtype: str = "uint16",
                 n_microbatches: Optional[int] = None,
                 pipeline_schedule: str = "1f1b",
                 overlap: Optional[OverlapConfig] = None,
                 parity: Optional[ParityConfig] = None,
                 async_ckpt: bool = True, rank: int = 0,
                 elastic: Optional[ElasticConfig] = None,
                 doctor_poll=None):
        self.cfg, self.plan, self.fs = cfg, plan, fs
        self.ckpt_dir = ckpt_dir
        self.ckpt_interval = ckpt_interval
        self.keep = keep
        self.batch = batch
        self.zero1 = zero1 and optimizer == "adamw"
        # everything the train step's build needs, kept so apply_plan
        # (the elastic reshard seam) can rebuild for a different plan
        self._n_microbatches_arg = n_microbatches
        self._build_kwargs = dict(
            lr=lr, optimizer=optimizer, zero1=zero1, remat=remat,
            pipeline_schedule=pipeline_schedule, overlap=overlap,
            parity=parity)
        # parallel.ckpt.async: save() blocks only for the host snapshot;
        # the DFS write (and the vpp logical reorder) runs on a
        # background writer fenced at the next save / restore /
        # train-exit (checkpoint.AsyncCheckpointWriter).
        self.async_ckpt = async_ckpt
        self._ckpt_writer = AsyncCheckpointWriter()
        self.data = TokenDataset(fs, data_path, batch=batch,
                                 seq=cfg.max_seq, dtype=data_dtype)
        self._build_for_plan(plan)
        self.step = 0
        self.losses: list = []
        # latest loss per ABSOLUTE step index: under the elastic plane
        # a resume rewinds and re-runs steps, so self.losses alone can
        # carry duplicates; this map always holds one (the newest)
        # loss per step — what the loss-curve A-B guard compares.
        self.loss_by_step: Dict[int, float] = {}
        # elastic controller (parallel/elastic): polls the doctor's
        # trainer verdicts every elastic.poll.steps steps and, on a
        # flagged/dead rank, hands train() a shrunken plan to resume
        # under via apply_plan + reshard-on-restore.
        self.elastic = None
        if elastic is not None and elastic.enabled:
            from hadoop_tpu.parallel.elastic.controller import \
                ElasticController
            self.elastic = ElasticController(self, elastic,
                                             poll_fn=doctor_poll)
        # Step anatomy as a LIVE surface (profile_train's one-shot
        # accounting, always on): /jmx and /prom see exactly where a
        # step's wall time goes — data wait vs dispatched step vs the
        # checkpoint snapshot/fence the async writer still charges the
        # loop for. The metric set is THE shared definition in
        # obs/trainer.py (rank-labeled /prom families the fleet doctor
        # windows per rank); a dryrun subprocess worker builds the same
        # set, so the families can never fork.
        from hadoop_tpu.obs.trainer import TrainerStepMetrics
        self.rank = int(rank)
        m = TrainerStepMetrics(rank=self.rank)
        self.step_metrics = m
        self._m_steps = m.steps
        self._m_data_wait = m.data_wait
        self._m_data_wait_hist = m.data_wait_hist
        self._m_step_wall = m.step_wall
        self._m_step_wall_hist = m.step_wall_hist
        self._m_ckpt_snapshot = m.ckpt_snapshot
        self._m_ckpt_write = m.ckpt_write
        self._m_ckpt_fence = m.ckpt_fence
        # Live HBM ledger: this trainer's resident state, alongside the
        # serving components (obs/hbm.py). grad_buckets is the overlap
        # pass's transient packing buffer bound — the concat each
        # bucketed collective materializes at peak.
        from hadoop_tpu.obs.comm import comm_runtime
        from hadoop_tpu.obs.hbm import hbm_ledger, tree_nbytes
        self._comm = comm_runtime()
        ov = overlap if overlap is not None else DEFAULT_OVERLAP
        led = hbm_ledger()
        self._hbm_owner = f"trainer@{id(self)}."
        # providers hold a WEAK ref: a replaced trainer that was never
        # close()d must not pin its whole params+opt state in the
        # process-global ledger forever (a dead ref reports 0 bytes —
        # truthfully: that state is collectable)
        import weakref
        ref = weakref.ref(self)

        def _tree(attr):
            t = ref()
            return tree_nbytes(getattr(t, attr)) if t is not None else 0

        led.register(f"{self._hbm_owner}params", "params",
                     lambda: _tree("params"))
        led.register(f"{self._hbm_owner}opt", "opt_state",
                     lambda: _tree("opt"))
        led.register(f"{self._hbm_owner}buckets", "grad_buckets",
                     lambda: (ov.bucket_bytes if ov.enabled else 0)
                     if ref() is not None else 0)
        self._tracer = global_tracer()
        # Cursor of the last batch a completed step CONSUMED — set only
        # while train() runs (the prefetch thread advances the dataset
        # ahead of consumption, so the dataset's own cursor overstates
        # progress mid-run). None outside train(); save() then reads
        # the dataset directly.
        self._inflight_cursor: Optional[Dict] = None

    def _build_for_plan(self, plan: MeshPlan) -> None:
        """Mesh + step_fn + data sharding + fresh sharded state for one
        plan — the slice of construction ``apply_plan`` re-runs when
        the elastic controller shrinks the mesh."""
        kw = self._build_kwargs
        n_microbatches = self._n_microbatches_arg
        if n_microbatches is None:
            # pipeline plans need M > 1 (interleaved REQUIRES pp | M;
            # plain 1F1B with M=1 is a full bubble); single-stage plans
            # run unsplit
            n_microbatches = max(1, plan.pp * getattr(plan, "vpp", 1))
        plan.validate(self.cfg, self.batch, self.cfg.max_seq,
                      n_microbatches=n_microbatches)
        self.plan = plan
        self.mesh = make_mesh(plan)
        self.step_fn = make_train_step(
            self.cfg, plan, self.mesh, lr=kw["lr"],
            optimizer=kw["optimizer"], zero1=kw["zero1"],
            remat=kw["remat"], donate=False,
            n_microbatches=n_microbatches,
            pipeline_schedule=kw["pipeline_schedule"],
            overlap=kw["overlap"], parity=kw["parity"])
        self.data_sharding = make_data_sharding(self.mesh)
        self.params, self.opt = init_sharded(
            jax.random.PRNGKey(0), self.cfg, plan, self.mesh,
            zero1=self.zero1)

    def apply_plan(self, new_plan: MeshPlan) -> bool:
        """Rebuild this trainer for a new mesh plan and resume from the
        newest snapshot via reshard-on-restore (the elastic
        controller's actuation seam; callable directly for a manual
        reshard). Must not run under a live train() segment — the
        prefetch thread shares the dataset. Returns whether a
        checkpoint was restored; without one the state is freshly
        initialized and the step count restarts at 0."""
        self._ckpt_writer.wait()   # fence: an in-flight write lands
        #                            before the plan that wrote it dies
        old_step = self.step
        self._build_for_plan(new_plan)
        restored = self.try_restore()
        if not restored:
            self.step = 0
            log.warning("apply_plan(%s): no checkpoint to restore; "
                        "reinitialized from step 0 (was step %d)",
                        new_plan, old_step)
        return restored

    # -------------------------------------------------------- persistence

    def _state_tree(self):
        return {"params": self.params, "opt": self.opt}

    def save(self, wait: Optional[bool] = None) -> str:
        """Checkpoint the current state.

        ``wait=False`` (what the step loop's interval saves pass): block
        only for the host snapshot (device→host copies of the unique
        shards) plus a fence on any PREVIOUS in-flight write; the DFS
        write itself — and the vpp logical-reorder, which permutes
        whole layer stacks — runs on the background writer, fenced at
        the next save / restore / train-exit. The data cursor is
        captured at call time, so in-flight prefetched batches are
        accounted exactly as before. A crash (or writer failure)
        mid-write leaves a manifest-less directory the next retention
        sweep removes — the previous complete checkpoint keeps winning.

        Default (``wait=None`` → True): an EXPLICIT save is durable on
        return, exactly like the old synchronous path — only saves
        issued from inside the training loop ride the background
        writer. ``async_ckpt=False`` forces every save synchronous.
        """
        if wait is None:
            wait = True
        t_fence = time.monotonic()
        self._ckpt_writer.wait()  # fence: surfaces a prior write failure
        self._m_ckpt_fence.add(time.monotonic() - t_fence)
        tree = self._state_tree()
        # The data cursor rides as an extra leaf, split into two int32
        # halves: datasets beyond 2**31 tokens are ordinary LM scale and
        # a single int32 would overflow (or wrap negative) and resume
        # the stream at the wrong position.
        cursor = (self._inflight_cursor if self._inflight_cursor
                  is not None else self.data.state())
        pos = cursor["pos"] % max(self.data.total_tokens, 1)
        tree = dict(tree, data_pos=jnp.asarray(
            [pos >> 31, pos & 0x7FFFFFFF], jnp.int32))
        with self._tracer.span("trainer.ckpt.snapshot") as ssp:
            t_snap = time.monotonic()
            snap = snapshot_tree(tree)
            self._m_ckpt_snapshot.add(time.monotonic() - t_snap)
            ssp.add_kv("step", str(self.step))
        step, fs, ckpt_dir, keep = self.step, self.fs, self.ckpt_dir, \
            self.keep
        reorder = self._vpp_snapshot_reorder()
        m_write, tracer = self._m_ckpt_write, self._tracer
        # the manifest carries the writing plan (captured NOW — the
        # elastic controller may swap self.plan before the background
        # write lands) so a restore under any other plan knows to go
        # through the host-side reshard
        from hadoop_tpu.parallel.elastic.reshard import manifest_meta
        meta = manifest_meta(self.plan, zero1=self.zero1)

        def write():
            # the writer thread carries the submitter's context
            # (AsyncCheckpointWriter wraps with carry_context), so this
            # span lands in the same trace as the snapshot above
            with tracer.span("trainer.ckpt.write") as wsp:
                t_w = time.monotonic()
                path = write_snapshot(fs, ckpt_dir, step,
                                      reorder(snap) if reorder else snap,
                                      keep=keep, meta=meta)
                m_write.add(time.monotonic() - t_w)
                wsp.add_kv("step", str(step))
            log.info("checkpoint step %d -> %s", step, path)

        if self.async_ckpt:
            self._ckpt_writer.submit(write)
            if wait:
                t_fence = time.monotonic()
                self._ckpt_writer.wait()
                self._m_ckpt_fence.add(time.monotonic() - t_fence)
        else:
            write()
        return f"{self.ckpt_dir}/step_{step:012d}"

    def _vpp_snapshot_reorder(self):
        """Host-side logical-reorder closure for interleaved plans.

        Checkpoints persist the LOGICAL layer order so they stay
        portable across plans (interleaved placement permutes the
        stacked layer axis on device; see train.physical_layer_order).
        Adam moments mirror the params tree, so they permute the same
        way. ZeRO-1 state is flat slices — plan-locked either way —
        left as stored. Running the permutation on the host snapshot
        keeps the device free of the full permuted copy the old
        device-side ``logical_layer_order`` materialized."""
        if getattr(self.plan, "vpp", 1) <= 1:
            return None
        import numpy as _np

        from hadoop_tpu.parallel.pipeline import \
            interleaved_layer_permutation
        inv = _np.argsort(interleaved_layer_permutation(
            self.cfg.n_layers, self.plan.pp, self.plan.vpp))
        prefixes = ["['params']['layers']"]
        if not self.zero1:
            prefixes += ["['opt'].mu['layers']", "['opt'].nu['layers']"]

        def match(name: str) -> bool:
            return any(name.startswith(p) for p in prefixes)

        return lambda snap: reorder_snapshot_axis0(snap, inv, match)

    def wait_for_checkpoint(self) -> None:
        """Block until any in-flight async checkpoint write completes
        (re-raising its failure, if it failed)."""
        self._ckpt_writer.wait()

    def close(self) -> None:
        """Retire this trainer from the process-global ledgers. Without
        this, a replaced trainer (elastic restart, a bench loop) keeps
        its params/opt providers registered — the HBM report double-
        counts AND the ledger's provider closures pin the dead
        trainer's whole state in memory."""
        from hadoop_tpu.obs.hbm import hbm_ledger
        hbm_ledger().unregister_prefix(self._hbm_owner)

    def _target_spec_tree(self):
        """Placement specs for the CURRENT plan's state tree."""
        specs = param_specs(self.cfg, self.plan)
        if self.zero1:
            _, _, z1_specs, _ = zero1_layout(self.cfg, self.plan)
            opt_specs = AdamWState(
                count=jax.sharding.PartitionSpec(), mu=z1_specs,
                nu=z1_specs)
        else:
            opt_specs = AdamWState(
                count=jax.sharding.PartitionSpec(), mu=specs, nu=specs)
        return {"params": specs, "opt": opt_specs,
                "data_pos": jax.sharding.PartitionSpec()}

    def try_restore(self) -> bool:
        """Resume from the newest complete checkpoint, if any.

        Reads the manifest's plan block first: a snapshot written
        under a DIFFERENT mesh plan restores through the host-side
        reshard (parallel/elastic/reshard.py — ZeRO-1 slices
        reassembled to global moments and re-sliced for this plan); a
        matching plan takes the direct placement path, bit-identical
        to what was saved; a legacy manifest (no plan block) restores
        as same-plan with a DeprecationWarning."""
        self._ckpt_writer.wait()  # a restore must see the newest save
        step = latest_step(self.fs, self.ckpt_dir)
        if step is None:
            return False
        from hadoop_tpu.parallel.elastic.reshard import resolve_restore
        manifest = read_manifest(self.fs, self.ckpt_dir, step)
        mode, saved_plan, saved_zero1 = resolve_restore(
            manifest, self.plan, self.zero1)
        spec_tree = self._target_spec_tree()
        if mode == "reshard":
            tree, got = self._load_resharded(step, saved_plan,
                                             saved_zero1, spec_tree)
        else:
            like = dict(self._state_tree(),
                        data_pos=jnp.zeros((2,), jnp.int32))
            tree, got = load_checkpoint(self.fs, self.ckpt_dir, like,
                                        step=step, mesh=self.mesh,
                                        specs=spec_tree)
        self.params, self.opt = tree["params"], tree["opt"]
        if getattr(self.plan, "vpp", 1) > 1:
            from hadoop_tpu.parallel.train import physical_layer_order
            self.params = physical_layer_order(self.params, self.cfg,
                                               self.plan)
            if not self.zero1:
                self.opt = type(self.opt)(
                    self.opt.count,
                    physical_layer_order(self.opt.mu, self.cfg,
                                         self.plan),
                    physical_layer_order(self.opt.nu, self.cfg,
                                         self.plan))
        hi, lo = (int(x) for x in tree["data_pos"])
        self.data.restore({"pos": (hi << 31) | lo})
        self.step = got
        log.info("restored step %d from %s", got, self.ckpt_dir)
        return True

    def _load_resharded(self, step: int, saved_plan: MeshPlan,
                        saved_zero1: bool, spec_tree):
        """Cross-plan restore: assemble the snapshot to HOST arrays in
        the saved plan's layout (params and pp stage shards come back
        global for free — the manifest stores global logical shapes),
        convert the optimizer moments through global layout for this
        plan (elastic/reshard.py), then place everything under the
        target mesh. Returns ``(tree, step)`` like load_checkpoint."""
        from jax.sharding import NamedSharding
        from hadoop_tpu.parallel.elastic.reshard import reshard_opt_state
        sds = jax.ShapeDtypeStruct
        pshapes = jax.tree_util.tree_map(
            lambda p: sds(p.shape, p.dtype), self.params)
        if saved_zero1:
            _, shape_tree, _, _ = zero1_layout(self.cfg, saved_plan)
            # shape_tree's leaves are shape TUPLES — without is_leaf,
            # tree_map would descend into them int by int
            moments = jax.tree_util.tree_map(
                lambda s: sds(tuple(s), jnp.float32), shape_tree,
                is_leaf=lambda s: isinstance(s, tuple))
        else:
            moments = jax.tree_util.tree_map(
                lambda p: sds(p.shape, jnp.float32), self.params)
        like = {"params": pshapes,
                "opt": AdamWState(count=sds((), jnp.int32), mu=moments,
                                  nu=moments),
                "data_pos": sds((2,), jnp.int32)}
        tree, got = load_checkpoint(self.fs, self.ckpt_dir, like,
                                    step=step)
        opt = reshard_opt_state(
            tree["opt"], self.params, param_specs(self.cfg, self.plan),
            saved_plan, self.plan, zero1_a=saved_zero1,
            zero1_b=self.zero1)

        def place(x, s):
            return jax.device_put(x, NamedSharding(self.mesh, s))

        return {"params": jax.tree_util.tree_map(
                    place, tree["params"], spec_tree["params"]),
                "opt": jax.tree_util.tree_map(
                    place, opt, spec_tree["opt"]),
                "data_pos": tree["data_pos"]}, got

    # -------------------------------------------------------------- train

    # In-flight step bound: losses older than this are forced to host,
    # which (a) backpressures async dispatch so the host can't run
    # unboundedly ahead of the device and (b) keeps the host busy with
    # the NEXT batch's DFS read while the device works. The old loop
    # float()ed every step — a full sync serializing read → transfer →
    # step (the "host input pipeline" item of VERDICT r4 weak #7).
    MAX_INFLIGHT = 16

    def train(self, n_steps: int) -> list:
        """Run ``n_steps`` more steps; returns the losses of every step
        executed.

        The dataloader runs in a background prefetch thread (DFS read +
        host→device transfer overlap the device step); each prefetched
        batch carries the dataset cursor as of ITS production, and the
        checkpoint cursor tracks the last batch a completed step
        consumed — so a mid-run save resumes bit-exactly even with
        batches in flight.

        Under the elastic plane the target is ABSOLUTE: an eviction
        ends the running step segment (the prefetch thread drains and
        the dataset cursor rewinds first), the controller reshards onto
        the shrunken plan, and the loop re-runs the steps lost since
        the restored snapshot — the call still returns with
        ``self.step == start + n_steps``. The returned list includes
        re-run steps; ``self.loss_by_step`` keeps exactly one (the
        newest) loss per step index."""
        if self.elastic is None:
            return self._train_segment(n_steps)
        target = self.step + n_steps
        out: list = []
        while self.step < target:
            out.extend(self._train_segment(target - self.step))
            if self.elastic.pending:
                self.elastic.resume()
        return out

    def _train_segment(self, n_steps: int) -> list:
        """One uninterrupted run of the step loop (train() without the
        elastic replan seam). Ends early only when the elastic
        controller marks an eviction pending."""
        zombie = getattr(self, "_zombie_producer", None)
        if zombie is not None:
            if zombie.is_alive():
                raise RuntimeError(
                    "a previous train()'s prefetch thread is still "
                    "stuck in a dataset read; the dataset cannot be "
                    "shared with a new run")
            self._zombie_producer = None
            if self._inflight_cursor is not None:
                # the stuck thread has since died: rewind to the
                # consumed position it left unrestored
                if self.data.state() != self._inflight_cursor:
                    self.data.restore(self._inflight_cursor)
                self._inflight_cursor = None
        out: list = []
        pending: deque = deque()   # device-side loss scalars, oldest first
        q: queue.Queue = queue.Queue(maxsize=2)
        abort = threading.Event()

        def produce():
            try:
                for _ in range(n_steps):
                    rows = self.data.next_batch()
                    item = (
                        jax.device_put(jnp.asarray(rows[:, :-1], jnp.int32),
                                       self.data_sharding),
                        jax.device_put(jnp.asarray(rows[:, 1:], jnp.int32),
                                       self.data_sharding),
                        self.data.state())
                    while not abort.is_set():
                        try:
                            q.put(item, timeout=0.5)
                            break
                        except queue.Full:
                            continue
                    if abort.is_set():
                        return
            except BaseException as e:  # surfaced from the consumer loop
                while not abort.is_set():
                    try:
                        q.put(e, timeout=0.5)
                        break
                    except queue.Full:
                        continue

        producer = threading.Thread(target=produce, daemon=True,
                                    name="trainer-prefetch")
        producer.start()
        step_failed = False
        try:
            for _ in range(n_steps):
                t_step = time.monotonic()
                item = q.get()
                data_wait = time.monotonic() - t_step
                if isinstance(item, BaseException):
                    raise item
                tokens, targets, cursor = item
                # always-on step anatomy: one span per step (the root
                # of that step's trace — an interval save's snapshot/
                # write spans join it) + the live data-wait/step-wall
                # split. step_fn dispatches asynchronously, so
                # "step wall" is dispatch-to-dispatch time; the
                # MAX_INFLIGHT float() below is where a device stall
                # would surface in it.
                with self._tracer.span("trainer.step") as stsp:
                    stsp.add_kv("step", str(self.step + 1))
                    stsp.add_kv("data_wait_ms",
                                f"{data_wait * 1e3:.2f}")
                    # runtime comm ledger dispatch seam: the first call
                    # traces the step INSIDE this window (binding every
                    # collective site's static bytes to "trainer.step");
                    # every call advances the per-site byte counters and
                    # records this window's host wall — with this span's
                    # trace id as the bucket exemplar — into the
                    # htpu_comm histograms. Nothing enters the graph.
                    with self._comm.step("trainer.step"):
                        self.params, self.opt, metrics = self.step_fn(
                            self.params, self.opt, tokens, targets)
                        self.step += 1
                        self._inflight_cursor = cursor
                        pending.append((self.step, metrics["loss"]))
                        # materialize as they age out so self.losses
                        # stays current even if a later step raises;
                        # this float() is the DELIBERATE bounded-in-
                        # flight backpressure sync (see MAX_INFLIGHT
                        # above), not a stray stall
                        while len(pending) > self.MAX_INFLIGHT:
                            s, dev = pending.popleft()
                            val = float(  # lint: disable=jit/blocking-in-step
                                dev)
                            out.append(val)
                            self.losses.append(val)
                            self.loss_by_step[s] = val
                    if self.ckpt_interval and \
                            self.step % self.ckpt_interval == 0:
                        # interval saves ride the background writer:
                        # the step loop pays only the host-snapshot
                        # time (the train-exit fence below guarantees
                        # durability); the save's snapshot/write spans
                        # join this step's trace
                        self.save(wait=False)
                self._m_steps.incr()
                self._m_data_wait.add(data_wait)
                self._m_data_wait_hist.add(data_wait)
                step_wall = time.monotonic() - t_step
                self._m_step_wall.add(step_wall)
                self._m_step_wall_hist.add(step_wall)
                if self.elastic is not None and \
                        self.step % self.elastic.cfg.poll_steps == 0:
                    # DELIBERATE host-side doctor poll, cadence-gated
                    # and outside the jitted step: the elastic plane's
                    # sensing seam (an HTTP read of
                    # /ws/v1/fleet/doctor, never per-step)
                    if self.elastic.on_step(self.step):
                        # evict pending: end this segment so the
                        # prefetch thread drains and the cursor
                        # rewinds before the mesh is rebuilt
                        break
        except BaseException:
            step_failed = True
            raise
        finally:
            abort.set()
            # Drain completed steps' losses even when a step raised —
            # self.losses must not end up behind self.step by up to
            # MAX_INFLIGHT entries.
            while pending:
                s, dev = pending.popleft()
                try:
                    val = float(dev)
                except Exception:  # noqa: BLE001 — a failed step's loss
                    break
                out.append(val)
                self.losses.append(val)
                self.loss_by_step[s] = val
            producer.join(timeout=10.0)
            if producer.is_alive():
                # Pathological: producer stuck (e.g. a hung DFS read)
                # past its abort checks. It still owns self.data, so
                # don't rewind under it — keep the in-flight cursor so
                # a later save() records the consumed position, and
                # make the next train() refuse until the thread dies.
                log.warning("prefetch thread did not exit within 10s; "
                            "keeping the in-flight data cursor")
                self._zombie_producer = producer
            elif self._inflight_cursor is not None:
                # Rewind the dataset's own cursor to the consumed
                # position so save()/state() outside train() agree with
                # what actually trained — but only when the producer
                # really read ahead (restore() drops the read buffer,
                # which would force a pointless DFS re-read on the
                # common all-consumed exit).
                if self.data.state() != self._inflight_cursor:
                    self.data.restore(self._inflight_cursor)
                self._inflight_cursor = None
            # Completion fence at train-exit, AFTER the drain/join/
            # rewind so a failed write never skips the loss and cursor
            # bookkeeping above: a caller returning from train() must
            # find its interval checkpoints durable (and learn about a
            # failed write here, not at some later save). When a STEP
            # exception is propagating (tracked explicitly — exc_info()
            # lies both inside except blocks and when train() is called
            # from a caller's handler), the write failure is logged
            # instead of masking it.
            try:
                self._ckpt_writer.wait()
            except Exception:
                if not step_failed:
                    raise
                log.exception("async checkpoint write failed during "
                              "train()")
        return out
