"""Ulysses-style context parallelism: all-to-all head↔sequence exchange.

The second of the two sequence-parallel attention strategies SURVEY §5.7
names ("ring attention or all-to-all sequence/context parallelism").
Where ring attention (parallel/ring_attention.py) keeps activations
sequence-sharded and ROTATES K/V around the mesh (P-1 neighbor
exchanges, O(S/P) memory per rank, arbitrary head counts), the
all-to-all strategy (the DeepSpeed-Ulysses shape, rebuilt here on
``lax.all_to_all`` — the same collective the MoE dispatch and the
device shuffle ride) TRANSPOSES the sharding for the attention op:

    [B, S/P, H, D]  --all_to_all-->  [B, S, H/P, D]
    full-sequence attention on local heads (one fused flash call —
    no per-step merge state, no P-step scan)
    [B, S, H/P, D]  --all_to_all-->  [B, S/P, H, D]

Two collectives per attention instead of P-1 permutes: cheaper on a
fat-ICI pod when heads divide evenly; ring remains the fallback for
GQA ratios the head split cannot express and for S too large to hold
one rank's full-sequence K/V.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def supports(n_q_heads: int, n_kv_heads: int, axis_size: int) -> bool:
    """The head transpose needs both head counts divisible by the axis."""
    return n_q_heads % axis_size == 0 and n_kv_heads % axis_size == 0


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      axis_name: str, axis_size: int) -> jnp.ndarray:
    """q,k,v: [B, S_local, H(q|kv), D] sequence-sharded over axis_name.
    Returns [B, S_local, Hq, D]. Must run inside shard_map with the
    axis bound; RoPE must already be applied with GLOBAL positions
    (the caller's ring-path offsets serve both strategies)."""
    from hadoop_tpu.ops.attention import causal_attention
    from hadoop_tpu.ops.vma import pvary_to, vma_of

    target = vma_of(q) | vma_of(k) | vma_of(v) | {axis_name}
    q, k, v = (pvary_to(t, target) for t in (q, k, v))

    # runtime comm ledger (obs/comm.py): four all_to_alls per attention
    # (q/k/v in, attn out) — static trace-time byte facts
    from hadoop_tpu.obs.comm import record_comm, static_nbytes
    a2a = (2 * static_nbytes(q) + static_nbytes(k) + static_nbytes(v))
    record_comm("cp.all2all", a2a, a2a)

    # seq-sharded → head-sharded: split heads P ways, gather the
    # sequence (tiled: received chunks concatenate along seq)
    q = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1,
                       tiled=True)
    k = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1,
                       tiled=True)
    v = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1,
                       tiled=True)

    # full sequence, H/P local heads: plain fused causal attention —
    # global causality needs no masks beyond the standard one because
    # the whole sequence is present
    attn = causal_attention(q, k, v)

    # head-sharded → seq-sharded (inverse transpose)
    return lax.all_to_all(attn, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)
