"""Service registry (ref: hadoop-common-project/hadoop-registry)."""

from hadoop_tpu.registry.registry import (RegistryClient, RegistryServer,
                                          ServiceRecord)

__all__ = ["RegistryClient", "RegistryServer", "ServiceRecord"]
