"""Service registry (ref: hadoop-common-project/hadoop-registry)."""

from hadoop_tpu.registry.registry import (HEARTBEAT_ATTR, RegistryClient,
                                          RegistryServer, ServiceRecord,
                                          record_is_stale)

__all__ = ["RegistryClient", "RegistryServer", "ServiceRecord",
           "HEARTBEAT_ATTR", "record_is_stale"]
