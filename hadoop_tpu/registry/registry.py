"""Service registry — named service records with liveness TTLs.

Parity with the reference registry (ref: hadoop-common-project/
hadoop-registry — RegistryOperations over a ZooKeeper tree of
ServiceRecords with ephemeral liveness; the DNS frontend is out of
scope): services register a record (endpoints + attributes) under a
slash path; EPHEMERAL records disappear when their owner stops
renewing (the ZK-ephemeral analog on a lease, consistent with how this
framework replaced ZK everywhere else — cf. the QJM lease elector).
Served over the framework RPC plane.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from hadoop_tpu.conf import Configuration
from hadoop_tpu.ipc import Client, Server, get_proxy
from hadoop_tpu.service import AbstractService

log = logging.getLogger(__name__)

# services that publish liveness heartbeats stamp this attribute with
# time.time() on every refresh; consumers (router, autoscaler) treat a
# record whose stamp has aged past the record TTL as dead even while it
# still sits in the registry (a wedged sweeper, or a consumer serving
# its stale cache through a registry outage, must not route into a
# corpse)
HEARTBEAT_ATTR = "hb"

RECORD_TTL_KEY = "serving.registry.record.ttl"


def record_ttl(conf) -> float:
    """THE record-TTL resolution, shared by publisher (replica
    heartbeat cadence), router, and autoscaler — three consumers
    resolving it differently would disagree on what 'stale' means.
    Falls back to the older ``serving.registry.ttl`` key."""
    return conf.get_time_seconds(
        RECORD_TTL_KEY, conf.get_time_seconds("serving.registry.ttl",
                                              10.0))


def record_is_stale(record: "ServiceRecord", ttl_s: float,
                    now: Optional[float] = None) -> bool:
    """Client-side staleness: the record's owner stopped heartbeating.
    Records without the attribute (hand-registered, pre-heartbeat
    publishers) are never stale — the registry's own TTL sweep is
    their only eviction.

    The stamp is the publisher's wall clock compared against the
    consumer's: the check assumes NTP-disciplined hosts (skew well
    under the TTL, 10s by default — the same assumption Kerberos and
    every lease in the reference make). A consumer whose clock runs a
    full TTL ahead would see the whole fleet as stale; keep the TTL
    comfortably above your clock-sync error budget."""
    hb = record.attributes.get(HEARTBEAT_ATTR)
    if not hb:
        return False
    try:
        stamp = float(hb)
    except (TypeError, ValueError):
        return True     # a malformed stamp means a broken publisher
    return (time.time() if now is None else now) - stamp > ttl_s


class ServiceRecord:
    """Ref: registry/client/types/ServiceRecord.java."""

    def __init__(self, path: str, endpoints: Dict[str, str],
                 attributes: Optional[Dict[str, str]] = None,
                 ephemeral: bool = True):
        self.path = path
        self.endpoints = endpoints
        self.attributes = attributes or {}
        self.ephemeral = ephemeral

    def to_wire(self) -> Dict:
        return {"p": self.path, "e": self.endpoints,
                "a": self.attributes, "eph": self.ephemeral}

    @classmethod
    def from_wire(cls, d: Dict) -> "ServiceRecord":
        return cls(d["p"], d["e"], d.get("a", {}), d.get("eph", True))


class _Entry:
    __slots__ = ("record", "deadline")

    def __init__(self, record: ServiceRecord, deadline: float):
        self.record = record
        self.deadline = deadline


class RegistryProtocol:
    def __init__(self, server: "RegistryServer"):
        self.srv = server

    def register(self, record_wire: Dict, ttl_s: float) -> bool:
        return self.srv.put(ServiceRecord.from_wire(record_wire), ttl_s)

    def renew(self, path: str, ttl_s: float) -> bool:
        return self.srv.renew(path, ttl_s)

    def unregister(self, path: str) -> bool:
        return self.srv.remove(path)

    def resolve(self, path: str) -> Optional[Dict]:
        rec = self.srv.get(path)
        return rec.to_wire() if rec else None

    def list(self, prefix: str) -> List[Dict]:
        return [r.to_wire() for r in self.srv.list(prefix)]


class RegistryServer(AbstractService):
    def __init__(self, conf: Configuration):
        super().__init__("RegistryServer")
        self._entries: Dict[str, _Entry] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self.rpc: Optional[Server] = None
        self._stop = threading.Event()

    def service_init(self, conf: Configuration) -> None:
        self.rpc = Server(conf, bind=("127.0.0.1", conf.get_int(
            "registry.port", 0)), num_handlers=2, name="registry")
        self.rpc.register_protocol("RegistryProtocol",
                                   RegistryProtocol(self))
        self._sweep_interval = conf.get_time_seconds(
            "registry.sweep.interval", 1.0)

    def service_start(self) -> None:
        self.rpc.start()
        from hadoop_tpu.util.misc import Daemon
        Daemon(self._sweep_loop, "registry-sweeper").start()
        log.info("RegistryServer on :%d", self.rpc.port)

    def service_stop(self) -> None:
        self._stop.set()
        if self.rpc:
            self.rpc.stop()

    @property
    def port(self) -> int:
        return self.rpc.port

    # ------------------------------------------------------------ storage

    def put(self, record: ServiceRecord, ttl_s: float) -> bool:
        deadline = time.monotonic() + ttl_s if record.ephemeral \
            else float("inf")
        with self._lock:
            self._entries[record.path] = _Entry(record, deadline)
        return True

    def renew(self, path: str, ttl_s: float) -> bool:
        with self._lock:
            e = self._entries.get(path)
            if e is None:
                return False
            # a persistent record stays persistent: arming a TTL here
            # would let a generic keepalive loop convert it into an
            # expiring one, and the sweeper would delete it the moment
            # the caller stopped renewing
            if e.record.ephemeral:
                e.deadline = time.monotonic() + ttl_s
            return True

    def remove(self, path: str) -> bool:
        with self._lock:
            return self._entries.pop(path, None) is not None

    def get(self, path: str) -> Optional[ServiceRecord]:
        with self._lock:
            e = self._entries.get(path)
            return e.record if e else None

    def list(self, prefix: str) -> List[ServiceRecord]:
        prefix = prefix.rstrip("/")
        with self._lock:
            return [e.record for p, e in sorted(self._entries.items())
                    if p == prefix or p.startswith(prefix + "/")]

    def _sweep_loop(self) -> None:
        while not self._stop.wait(self._sweep_interval):
            now = time.monotonic()
            with self._lock:
                dead = [p for p, e in self._entries.items()
                        if e.deadline < now]
                for p in dead:
                    del self._entries[p]
            for p in dead:
                log.info("registry record %s expired", p)


class RegistryClient:
    """Ref: registry/client/impl — register + background renewal."""

    def __init__(self, addr, conf: Optional[Configuration] = None):
        self.conf = conf or Configuration()
        self._client = Client(self.conf)
        self._proxy = get_proxy("RegistryProtocol", addr,
                                client=self._client)
        # path → (record, ttl): the record is kept so a renewal that
        # finds it GONE (registry restarted and lost its ephemeral
        # state) can re-register it — the analog of ZK clients
        # recreating ephemeral znodes on session re-establishment.
        self._renewals: Dict[str, tuple] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def register(self, record: ServiceRecord, ttl_s: float = 10.0,
                 auto_renew: bool = True) -> None:
        self._proxy.register(record.to_wire(), ttl_s)
        if auto_renew and record.ephemeral:
            self._renewals[record.path] = (record, ttl_s)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._renew_loop, daemon=True,
                    name="registry-renewer")
                self._thread.start()

    def unregister(self, path: str) -> None:
        self._renewals.pop(path, None)
        self._proxy.unregister(path)

    def resolve(self, path: str) -> Optional[ServiceRecord]:
        d = self._proxy.resolve(path)
        return ServiceRecord.from_wire(d) if d else None

    def list(self, prefix: str) -> List[ServiceRecord]:
        return [ServiceRecord.from_wire(d)
                for d in self._proxy.list(prefix)]

    def close(self) -> None:
        self._stop.set()
        self._client.stop()

    def _renew_loop(self) -> None:
        while not self._stop.wait(min(
                [t / 3 for _, t in self._renewals.values()] or [1.0])):
            self._renew_once()

    def _renew_once(self) -> None:
        for path, (record, ttl) in list(self._renewals.items()):
            try:
                if not self._proxy.renew(path, ttl):
                    if path not in self._renewals:
                        continue  # unregistered while we renewed
                    # Record vanished server-side (registry restart, or
                    # an expiry that beat this renewal): recreate it so
                    # the service stays resolvable.
                    log.info("registry record %s lost; re-registering",
                             path)
                    self._proxy.register(record.to_wire(), ttl)
                    if path not in self._renewals:
                        # lost a race with unregister() mid-recreate —
                        # compensate so the deliberate removal wins
                        self._proxy.unregister(path)
            except Exception as e:  # noqa: BLE001
                log.debug("registry renewal of %s failed: %s", path, e)
