from hadoop_tpu.security.ugi import (
    UserGroupInformation, current_user, AccessControlError, Token, SecretManager,
)

__all__ = [
    "UserGroupInformation", "current_user", "AccessControlError", "Token",
    "SecretManager",
]
