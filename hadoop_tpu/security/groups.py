"""Server-side user→groups resolution.

Parity with the reference's group mapping layer (ref: hadoop-common
security/Groups.java + GroupMappingServiceProvider /
ShellBasedUnixGroupsMapping / StaticUserWebFilter's static mapping):
group membership is resolved ON THE SERVER from a trusted source —
never taken from the client's asserted UGI, which would let any caller
claim membership in the superuser group.

Sources, in order:
  1. ``hadoop.security.group.mapping.static.mapping`` — inline
     ``user1=g1,g2;user2=g3`` pairs (ref: the static mapping config
     used throughout the reference's tests).
  2. OS account database (``grp``/``pwd``) for users that exist
     locally — the ShellBasedUnixGroupsMapping analog.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List

STATIC_MAPPING_KEY = "hadoop.security.group.mapping.static.mapping"
CACHE_TTL_S = 300.0  # ref: hadoop.security.groups.cache.secs default


class Groups:
    def __init__(self, conf=None):
        self._static: Dict[str, List[str]] = {}
        raw = conf.get(STATIC_MAPPING_KEY, "") if conf is not None else ""
        for pair in raw.split(";"):
            user, _, gl = pair.strip().partition("=")
            if user and gl:
                self._static[user.strip()] = [
                    g.strip() for g in gl.split(",") if g.strip()]
        self._cache: Dict[str, tuple] = {}
        self._lock = threading.Lock()

    def groups_for(self, user: str) -> List[str]:
        static = self._static.get(user)
        if static is not None:
            return list(static)
        now = time.monotonic()
        with self._lock:
            hit = self._cache.get(user)
            if hit and now - hit[1] < CACHE_TTL_S:
                return list(hit[0])
        groups = self._os_groups(user)
        with self._lock:
            self._cache[user] = (groups, now)
        return list(groups)

    @staticmethod
    def _os_groups(user: str) -> List[str]:
        try:
            import grp
            import pwd
            pw = pwd.getpwnam(user)
            primary = grp.getgrgid(pw.pw_gid).gr_name
            out = [primary]
            for g in grp.getgrall():
                if user in g.gr_mem and g.gr_name != primary:
                    out.append(g.gr_name)
            return out
        except (KeyError, ImportError, OSError):
            return []
