"""HTTP authentication filter — the hadoop-auth analog.

Parity with the reference auth library (ref: hadoop-common-project/
hadoop-auth — AuthenticationFilter.java fronting every web endpoint,
PseudoAuthenticationHandler.java (?user.name=), the signed
``hadoop.auth`` cookie issued by AuthenticationToken/Signer.java;
KerberosAuthenticationHandler remains a named seam exactly as in the
RPC layer — SIMPLE/TOKEN are the implemented mechanisms): the filter
wraps an HttpServer handler; an unauthenticated request either presents
``?user.name=`` (pseudo) and receives a signed token cookie, or replays
a previously-issued cookie; tampered or expired cookies are rejected
401."""

from __future__ import annotations

import base64
import hmac
import hashlib
import json
import logging
import time
from typing import Callable, Dict, Optional, Tuple

log = logging.getLogger(__name__)

COOKIE_NAME = "hadoop.auth"


class AuthenticationToken:
    """Signed (user, expiry) token. Ref: hadoop-auth
    AuthenticationToken.java + util/Signer.java."""

    def __init__(self, user: str, expires: float):
        self.user = user
        self.expires = expires

    def sign(self, secret: bytes) -> str:
        body = json.dumps({"u": self.user, "e": self.expires}).encode()
        mac = hmac.new(secret, body, hashlib.sha256).hexdigest()
        return base64.urlsafe_b64encode(body).decode() + "." + mac

    @classmethod
    def verify(cls, signed: str, secret: bytes
               ) -> Optional["AuthenticationToken"]:
        try:
            b64, _, mac = signed.partition(".")
            body = base64.urlsafe_b64decode(b64)
            want = hmac.new(secret, body, hashlib.sha256).hexdigest()
            if not hmac.compare_digest(mac, want):
                return None
            d = json.loads(body)
            tok = cls(d["u"], float(d["e"]))
            if tok.expires < time.time():
                return None
            return tok
        except (ValueError, KeyError, TypeError):
            return None


class AuthFilter:
    """Wraps HttpServer handlers with pseudo/token authentication.
    Ref: AuthenticationFilter.doFilter. Usage:

        filt = AuthFilter(secret)
        http.add_handler("/prot", filt.wrap(handler))

    The wrapped handler receives ``query["__user__"]``. Anonymous
    access is allowed iff ``allow_anonymous`` (the reference's
    simple.anonymous.allowed)."""

    def __init__(self, secret: bytes, token_validity_s: float = 36000.0,
                 allow_anonymous: bool = False):
        self.secret = secret
        self.validity = token_validity_s
        self.allow_anonymous = allow_anonymous

    def authenticate(self, query: Dict) -> Tuple[Optional[str],
                                                 Optional[str]]:
        """(user, fresh-cookie-or-None); user None = unauthenticated."""
        cookie = query.get("__cookie__", "")
        for part in cookie.split(";"):
            name, _, value = part.strip().partition("=")
            if name == COOKIE_NAME:
                tok = AuthenticationToken.verify(value, self.secret)
                if tok is not None:
                    return tok.user, None
        user = query.get("user.name")
        if user:
            fresh = AuthenticationToken(
                user, time.time() + self.validity).sign(self.secret)
            return user, fresh
        if self.allow_anonymous:
            return "anonymous", None
        return None, None

    def wrap(self, handler: Callable) -> Callable:
        def wrapped(query: Dict, body: bytes):
            user, fresh = self.authenticate(query)
            if user is None:
                return 401, {"RemoteException": {
                    "exception": "AuthenticationException",
                    "message": "authentication required "
                               "(?user.name= or hadoop.auth cookie)"}}
            query["__user__"] = user
            out = handler(query, body)
            if fresh is not None:
                status, payload = out[0], out[1]
                headers = dict(out[2]) if len(out) == 3 else {}
                headers["Set-Cookie"] = \
                    f"{COOKIE_NAME}={fresh}; HttpOnly"
                return status, payload, headers
            return out
        return wrapped


def ugi_for_query(query) -> "UserGroupInformation":
    """Resolve the UGI a REST handler should doAs (ref:
    NamenodeWebHdfsMethods/HttpFSServer user resolution): the
    AuthFilter-authenticated principal (``__user__``) outranks the
    pseudo-auth ``user.name`` parameter — a caller must not execute as
    someone other than who they authenticated as — and an anonymous or
    absent identity falls to the reference's unprivileged default
    "dr.who"."""
    from hadoop_tpu.security.ugi import UserGroupInformation
    user = query.get("__user__")
    if user in (None, "", "anonymous"):
        user = query.get("user.name") or "dr.who"
    return UserGroupInformation.create_remote_user(user)
