"""Impersonation (proxy-user) authorization.

The reference never lets an authenticated principal claim an arbitrary
effective user: every ``real != effective`` connection must pass
``ProxyUsers.authorize`` against a conf-driven ACL (ref:
security/authorize/ProxyUsers.java:96,
security/authorize/DefaultImpersonationProvider.java:118 — keys
``hadoop.proxyuser.<real>.users|groups|hosts``). This module is that
check for the TPU framework: servers call :meth:`ProxyUsers.authorize`
whenever a proven real identity asks to act as someone else, in every
auth mode (SIMPLE ``real=`` headers, TOKEN, SASL).

Semantics (matching DefaultImpersonationProvider):

- ``hadoop.proxyuser.<real>.users``: comma list of effective users the
  real user may impersonate, or ``*`` for any.
- ``hadoop.proxyuser.<real>.groups``: comma list of groups the
  *effective* user may belong to, or ``*``.
- ``hadoop.proxyuser.<real>.hosts``: comma list of client IPs/hostnames
  the proxying is allowed from, or ``*``. Unset means no hosts — the
  reference denies when the superuser has no proxy conf at all.

A real user is authorized iff (users ∪ groups) matches the effective
user AND hosts matches the remote address. Absent any
``hadoop.proxyuser.<real>.*`` keys, impersonation by that user is
denied outright.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, Optional

from hadoop_tpu.security.ugi import AccessControlError, UserGroupInformation


def _split(val: Optional[str]) -> Optional[set]:
    """None → key unset; '*' → wildcard (returned as None-sentinel set)."""
    if val is None:
        return None
    items = {v.strip() for v in val.split(",") if v.strip()}
    return items


class ProxyUsers:
    """Conf-driven impersonation ACL with hot ``refresh`` (the reference
    exposes ``-refreshSuperUserGroupsConfiguration``)."""

    PREFIX = "hadoop.proxyuser."

    def __init__(self, conf=None):
        self._lock = threading.Lock()
        self._acl: Dict[str, Dict[str, Optional[set]]] = {}
        if conf is not None:
            self.refresh(conf)

    def refresh(self, conf) -> None:
        acl: Dict[str, Dict[str, Optional[set]]] = {}
        for rest, val in conf.get_by_prefix(self.PREFIX).items():
            # get_by_prefix strips the prefix: rest is "<real>.<attr>"
            if "." not in rest:
                continue
            real, attr = rest.rsplit(".", 1)
            if attr not in ("users", "groups", "hosts"):
                continue
            acl.setdefault(real, {})[attr] = _split(val)
        with self._lock:
            self._acl = acl

    @staticmethod
    def _matches(allowed: Optional[set], candidates: Iterable[str]) -> bool:
        if allowed is None:
            return False
        if "*" in allowed:
            return True
        return any(c in allowed for c in candidates)

    def authorize(self, effective: "UserGroupInformation",
                  remote_addr: Optional[str] = None) -> None:
        """Raise AccessControlError unless ``effective.real_user`` may act
        as ``effective`` from ``remote_addr``. No-op when there is no
        proxy chain (effective == real)."""
        real = effective.real_user
        if real is None or real.user_name == effective.user_name:
            return
        with self._lock:
            entry = self._acl.get(real.user_name)
        if not entry:
            raise AccessControlError(
                f"user {real.user_name} is not configured as a proxy user "
                f"(no hadoop.proxyuser.{real.user_name}.* ACL)")
        user_ok = self._matches(entry.get("users"), [effective.user_name])
        group_ok = self._matches(entry.get("groups"), effective.groups)
        if not (user_ok or group_ok):
            raise AccessControlError(
                f"user {real.user_name} is not allowed to impersonate "
                f"{effective.user_name}")
        hosts = entry.get("hosts")
        if hosts is None or ("*" not in hosts and
                             (remote_addr is None or
                              remote_addr not in hosts)):
            raise AccessControlError(
                f"proxying by {real.user_name} not allowed from host "
                f"{remote_addr}")
