"""SASL-analog mutual authentication + optional wire privacy.

Parity with the reference's SASL layer (ref:
security/SaslRpcServer.java, SaslRpcClient.java — SASL negotiation on
the RPC plane; hadoop-hdfs-client/.../protocol/datatransfer/sasl/
SaslDataTransferClient.java + SaslDataTransferServer.java — the data
plane; hadoop-common/.../security/SaslPropertiesResolver.java — QoP
selection). The reference negotiates GSSAPI (Kerberos) or DIGEST-MD5
(tokens) through javax.security.sasl; this framework implements the
same *contract* — mutual authentication from a never-transmitted shared
secret, with optional per-connection encryption — using a SCRAM-style
challenge/response (RFC 5802 shape, SHA-256) and AES-GCM wraps, both
from the same OpenSSL-backed primitives the at-rest crypto uses.

Mechanisms:
- ``SCRAM-HTPU``: secret = a principal's password provisioned by the
  KDC-analog (``testing/minikdc.py`` in tests; any credential store in
  production). Fills the GSSAPI/Kerberos slot.
- ``TOKEN``: secret = the HMAC password of a delegation/block token,
  identity = the token's verified owner. Fills the DIGEST-MD5 slot
  (ref: SaslRpcServer.AuthMethod.TOKEN).

QoP (``hadoop.rpc.protection``): ``authentication`` authenticates and
leaves the channel plaintext; ``integrity`` MACs every frame
(HMAC-SHA256 with per-direction session keys — tamper-evident,
readable); ``privacy`` encrypts every frame with per-direction
AES-256-GCM keys bound to both nonces (so neither side can replay the
other's traffic).

Handshake (both mechanisms; 2 round trips, mutual):
  C→S  initiate: mech, user/token-identifier, client nonce, wanted QoP
  S→C  challenge: server nonce, salt, iterations, granted QoP
  C→S  response: client proof = ClientKey XOR HMAC(StoredKey, transcript)
  S→C  success: server proof = HMAC(ServerKey, transcript)
The server recovers ClientKey from the proof (SCRAM property), so both
sides can derive session keys from it without the secret itself ever
crossing the wire; a server that cannot produce the server proof never
knew the verifier — that is the mutual leg.
"""

from __future__ import annotations

import hashlib
import hmac
import secrets
import struct
import threading
from typing import Dict, Optional, Tuple

from cryptography.hazmat.primitives.ciphers.aead import AESGCM

from hadoop_tpu.security.ugi import AccessControlError, Token

MECH_SCRAM = "SCRAM-HTPU"
MECH_TOKEN = "TOKEN"

QOP_AUTH = "authentication"
QOP_INTEGRITY = "integrity"
QOP_PRIVACY = "privacy"

_DEFAULT_ITERS = 4096


def _hmac(key: bytes, msg: bytes) -> bytes:
    return hmac.new(key, msg, hashlib.sha256).digest()


def _xor(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b))


def salted_password(password: bytes, salt: bytes, iters: int) -> bytes:
    return hashlib.pbkdf2_hmac("sha256", password, salt, iters)


def scram_verifier(password: bytes, salt: Optional[bytes] = None,
                   iters: int = _DEFAULT_ITERS) -> Dict:
    """Server-side credential record: the server never needs (and with a
    provisioning path that pre-hashes, never sees) the password itself —
    ref: the keytab holds keys, not passwords."""
    salt = salt or secrets.token_bytes(16)
    sp = salted_password(password, salt, iters)
    client_key = _hmac(sp, b"Client Key")
    return {
        "salt": salt,
        "iters": iters,
        "stored_key": hashlib.sha256(client_key).digest(),
        "server_key": _hmac(sp, b"Server Key"),
    }


def _auth_message(user: str, cnonce: bytes, snonce: bytes, salt: bytes,
                  iters: int, qop: str) -> bytes:
    return b"|".join([user.encode(), cnonce, snonce, salt,
                      str(iters).encode(), qop.encode()])


def _derive_wire_keys(client_key: bytes, cnonce: bytes,
                      snonce: bytes) -> Tuple[bytes, bytes]:
    """(client→server key, server→client key), 32 bytes each, bound to
    both nonces so a session key never repeats across connections."""
    base = _hmac(client_key, b"htpu-wire|" + cnonce + snonce)
    return _hmac(base, b"c2s"), _hmac(base, b"s2c")


class WireCipher:
    """Per-connection AES-256-GCM frame protection.

    Each wrapped record is ``12-byte nonce || ciphertext+tag``. Nonces
    are direction-scoped counters; the receiver enforces that each
    record's nonce counter is exactly the next expected value, so a
    captured record cannot be replayed or reordered within the
    connection (GCM's tag alone only binds content, not position).
    ``is_client`` picks which derived key encrypts outbound.
    """

    def __init__(self, c2s_key: bytes, s2c_key: bytes, is_client: bool):
        out_key, in_key = (c2s_key, s2c_key) if is_client \
            else (s2c_key, c2s_key)
        self._out = AESGCM(out_key)
        self._in = AESGCM(in_key)
        self._out_ctr = 0
        self._in_ctr = 0
        self._in_lock = threading.Lock()
        self._out_lock = threading.Lock()

    def wrap(self, payload: bytes) -> bytes:
        with self._out_lock:
            nonce = struct.pack(">4xQ", self._out_ctr)
            self._out_ctr += 1
        return nonce + self._out.encrypt(nonce, payload, b"")

    def unwrap(self, record: bytes) -> bytes:
        if len(record) < 12 + 16:
            raise AccessControlError("truncated encrypted frame")
        with self._in_lock:
            expect = struct.pack(">4xQ", self._in_ctr)
            if record[:12] != expect:
                raise AccessControlError(
                    "frame decryption failed: out-of-order nonce "
                    "(replayed or reordered record)")
            try:
                out = self._in.decrypt(record[:12], record[12:], b"")
            except Exception as e:  # InvalidTag
                raise AccessControlError(
                    f"frame decryption failed: {e}") from e
            self._in_ctr += 1
            return out


class IntegrityWrapper:
    """auth-int QoP: per-frame HMAC-SHA256 with direction-scoped
    counters (ref: SASL auth-int wrap/unwrap). Same wrap/unwrap surface
    as WireCipher so the transports don't care which QoP won."""

    MACLEN = 32

    def __init__(self, c2s_key: bytes, s2c_key: bytes, is_client: bool):
        self._out_key, self._in_key = (c2s_key, s2c_key) if is_client \
            else (s2c_key, c2s_key)
        self._out_ctr = 0
        self._in_ctr = 0
        self._out_lock = threading.Lock()
        self._in_lock = threading.Lock()

    def wrap(self, payload: bytes) -> bytes:
        with self._out_lock:
            ctr = struct.pack(">Q", self._out_ctr)
            self._out_ctr += 1
        return ctr + _hmac(self._out_key, ctr + payload) + payload

    def unwrap(self, record: bytes) -> bytes:
        if len(record) < 8 + self.MACLEN:
            raise AccessControlError("truncated integrity frame")
        ctr, mac = record[:8], record[8:8 + self.MACLEN]
        payload = record[8 + self.MACLEN:]
        with self._in_lock:
            expect = struct.pack(">Q", self._in_ctr)
            self._in_ctr += 1
        if ctr != expect or not hmac.compare_digest(
                mac, _hmac(self._in_key, ctr + payload)):
            raise AccessControlError(
                "frame integrity check failed (tampered or replayed)")
        return payload


class CipherSocket:
    """Stream-transparent encrypted socket for the bulk data plane.

    Exposes ``sendall``/``recv``/``close``/``settimeout`` so
    ``io.wire.read_frame`` and ``datatransfer.send_frame`` work
    unchanged: every ``sendall`` payload becomes one encrypted record
    (u32 length || nonce || ct+tag) and ``recv`` serves decrypted bytes
    from an internal buffer. Ref: the reference wraps data-transfer
    streams in SaslInputStream/SaslOutputStream the same way.
    """

    def __init__(self, sock, cipher: WireCipher):
        self._sock = sock
        self._cipher = cipher
        self._rbuf = bytearray()

    def sendall(self, data) -> None:
        record = self._cipher.wrap(bytes(data))
        self._sock.sendall(struct.pack(">I", len(record)) + record)

    def recv(self, n: int) -> bytes:
        while not self._rbuf:
            hdr = self._read_exact(4)
            if hdr is None:
                return b""
            (rlen,) = struct.unpack(">I", hdr)
            if rlen > 256 * 1024 * 1024:
                raise AccessControlError("oversized encrypted record")
            rec = self._read_exact(rlen)
            if rec is None:
                return b""
            self._rbuf += self._cipher.unwrap(rec)
        out = bytes(self._rbuf[:n])
        del self._rbuf[:n]
        return out

    def _read_exact(self, n: int) -> Optional[bytes]:
        chunks = bytearray()
        while len(chunks) < n:
            chunk = self._sock.recv(n - len(chunks))
            if not chunk:
                if not chunks:
                    return None          # clean EOF at a record boundary
                # EOF mid-record: surface socket semantics (the frame
                # readers handle OSError), not a struct.error from a
                # partial length header leaking to the caller
                raise ConnectionError(
                    f"connection closed mid-record ({len(chunks)}/{n}B)")
            chunks += chunk
        return bytes(chunks)

    # pass-throughs the data plane uses
    def settimeout(self, t) -> None:
        self._sock.settimeout(t)

    def close(self) -> None:
        self._sock.close()

    def fileno(self) -> int:
        return self._sock.fileno()

    def getpeername(self):
        return self._sock.getpeername()


class SaslServerSession:
    """Server half of the handshake; message-in/message-out so any
    transport (RPC header frames, data-transfer frames) can carry it."""

    def __init__(self, credentials, secret_manager=None,
                 required_qop: str = QOP_AUTH):
        """``credentials(user) -> verifier dict`` (scram_verifier output)
        for SCRAM; ``secret_manager`` verifies TOKEN-mechanism tokens."""
        self.credentials = credentials
        self.secret_manager = secret_manager
        self.required_qop = required_qop
        self.user: Optional[str] = None
        self.token_ident: Optional[Dict] = None
        self.cipher: Optional[WireCipher] = None
        self.qop: Optional[str] = None   # granted QoP once complete
        self.complete = False
        self._state: Optional[Dict] = None

    def step(self, msg: Dict) -> Dict:
        state = msg.get("state")
        if state == "initiate":
            return self._challenge(msg)
        if state == "response":
            return self._verify(msg)
        raise AccessControlError(f"unexpected SASL state {state!r}")

    def _challenge(self, msg: Dict) -> Dict:
        mech = msg.get("mech")
        cnonce = msg.get("cnonce", b"")
        if not isinstance(cnonce, bytes) or len(cnonce) < 8:
            raise AccessControlError("bad client nonce")
        wanted = (msg.get("qop"), self.required_qop)
        if QOP_PRIVACY in wanted:
            qop = QOP_PRIVACY
        elif QOP_INTEGRITY in wanted:
            qop = QOP_INTEGRITY
        else:
            qop = QOP_AUTH
        if mech == MECH_SCRAM:
            user = msg.get("user")
            if not user:
                raise AccessControlError("SCRAM initiate without user")
            ver = self.credentials(user) if self.credentials else None
            if ver is None:
                raise AccessControlError(f"unknown principal {user!r}")
            token_ident = None
        elif mech == MECH_TOKEN:
            if self.secret_manager is None:
                raise AccessControlError("server does not accept tokens")
            ident_bytes = msg.get("token_ident")
            if not isinstance(ident_bytes, bytes):
                raise AccessControlError("TOKEN initiate without an "
                                         "identifier")
            # The recomputed password is the SCRAM shared secret; the
            # identifier's CLAIMS become trusted only when the client's
            # proof (which requires knowing that password) verifies.
            password = self.secret_manager.password_for(ident_bytes)
            from hadoop_tpu.io import unpack as _unpack
            token_ident = _unpack(ident_bytes)
            user = token_ident["owner"]
            ver = scram_verifier(password)
        else:
            raise AccessControlError(f"unsupported mechanism {mech!r}")
        snonce = secrets.token_bytes(16)
        self._state = {"mech": mech, "user": user, "ver": ver,
                       "cnonce": cnonce, "snonce": snonce, "qop": qop,
                       "token_ident": token_ident}
        return {"state": "challenge", "snonce": snonce,
                "salt": ver["salt"], "iters": ver["iters"], "qop": qop}

    def _verify(self, msg: Dict) -> Dict:
        st = self._state
        if st is None:
            raise AccessControlError("SASL response before initiate")
        ver = st["ver"]
        auth_msg = _auth_message(st["user"], st["cnonce"], st["snonce"],
                                 ver["salt"], ver["iters"], st["qop"])
        proof = msg.get("proof", b"")
        client_sig = _hmac(ver["stored_key"], auth_msg)
        client_key = _xor(proof, client_sig)
        if hashlib.sha256(client_key).digest() != ver["stored_key"]:
            raise AccessControlError(
                f"authentication failed for {st['user']!r}")
        self.user = st["user"]
        self.token_ident = st["token_ident"]
        self.qop = st["qop"]
        self.complete = True
        if st["qop"] in (QOP_PRIVACY, QOP_INTEGRITY):
            c2s, s2c = _derive_wire_keys(client_key, st["cnonce"],
                                         st["snonce"])
            cls = WireCipher if st["qop"] == QOP_PRIVACY \
                else IntegrityWrapper
            self.cipher = cls(c2s, s2c, is_client=False)
        return {"state": "success", "qop": st["qop"],
                "server_proof": _hmac(ver["server_key"], auth_msg)}


class SaslClientSession:
    """Client half. Drive with initiate() → step(challenge) →
    step(success); ``complete``/``cipher`` mirror the server side."""

    def __init__(self, mech: str, user: str = "",
                 password: Optional[bytes] = None,
                 token: Optional[Token] = None, qop: str = QOP_AUTH):
        self.mech = mech
        self.user = user
        if mech == MECH_TOKEN:
            if token is None:
                raise AccessControlError("TOKEN mechanism without a token")
            self.password = token.password
        else:
            if password is None:
                raise AccessControlError(
                    f"no credentials for principal {user!r}")
            self.password = password
        self.token = token
        self.qop = qop
        self.cnonce = secrets.token_bytes(16)
        self.cipher: Optional[WireCipher] = None
        self.complete = False
        self._expect_proof: Optional[bytes] = None
        self._client_key: Optional[bytes] = None
        self._granted_qop = qop

    def initiate(self) -> Dict:
        msg: Dict = {"state": "initiate", "mech": self.mech,
                     "cnonce": self.cnonce, "qop": self.qop}
        if self.mech == MECH_TOKEN:
            # ONLY the identifier crosses the wire — the password is the
            # SCRAM shared secret the server recomputes from its master
            # key (transmitting it would hand the credential to any
            # eavesdropper before a cipher exists; ref: DIGEST-MD5 over
            # tokens sends the identifier, the server retrievePassword's)
            msg["token_ident"] = self.token.identifier
            msg["token_kind"] = self.token.kind
        else:
            msg["user"] = self.user
        return msg

    def step(self, msg: Dict) -> Optional[Dict]:
        state = msg.get("state")
        if self.complete:
            # a replayed/duplicate terminal message must not re-derive
            # wire ciphers (their counters would reset — a replay window)
            raise AccessControlError("SASL message after completion")
        if state == "challenge":
            salt, iters = msg["salt"], msg["iters"]
            self._granted_qop = msg.get("qop", QOP_AUTH)
            sp = salted_password(self.password, salt, iters)
            client_key = _hmac(sp, b"Client Key")
            stored_key = hashlib.sha256(client_key).digest()
            user = self.user if self.mech == MECH_SCRAM else \
                self._token_owner()
            auth_msg = _auth_message(user, self.cnonce, msg["snonce"],
                                     salt, iters, self._granted_qop)
            self._client_key = client_key
            self._nonces = (self.cnonce, msg["snonce"])
            self._expect_proof = _hmac(_hmac(sp, b"Server Key"), auth_msg)
            return {"state": "response",
                    "proof": _xor(client_key,
                                  _hmac(stored_key, auth_msg))}
        if state == "success":
            if self._expect_proof is None:
                # success before any challenge was processed: nothing to
                # verify against — accepting would let an impostor that
                # knows no credential complete the "mutual" handshake
                # with a guessable placeholder proof
                raise AccessControlError(
                    "SASL success before challenge — impostor endpoint")
            if not hmac.compare_digest(msg.get("server_proof", b""),
                                       self._expect_proof):
                raise AccessControlError(
                    "server failed mutual authentication (bad server "
                    "proof) — possible impostor endpoint")
            # QoP floor: the client never accepts LESS protection than
            # it asked for — a stripped initiate frame must not
            # downgrade integrity/privacy to plaintext (the granted QoP
            # is bound into both proofs, so a tampered challenge fails
            # the handshake; this guards the honest-server-lower-config
            # case too).
            rank = {QOP_AUTH: 0, QOP_INTEGRITY: 1, QOP_PRIVACY: 2}
            if rank.get(self._granted_qop, 0) < rank.get(self.qop, 0):
                raise AccessControlError(
                    f"server granted qop {self._granted_qop!r} below "
                    f"the required {self.qop!r}")
            self.complete = True
            if self._granted_qop in (QOP_PRIVACY, QOP_INTEGRITY):
                c2s, s2c = _derive_wire_keys(self._client_key,
                                             *self._nonces)
                cls = WireCipher if self._granted_qop == QOP_PRIVACY \
                    else IntegrityWrapper
                self.cipher = cls(c2s, s2c, is_client=True)
            return None
        raise AccessControlError(f"unexpected SASL state {state!r}")

    def _token_owner(self) -> str:
        from hadoop_tpu.io import unpack
        return unpack(self.token.identifier)["owner"]


class CredentialStore:
    """Principal → SCRAM verifier map, loadable from a MiniKdc keytab
    directory or fed programmatically (ref: the server-side keytab of
    SaslRpcServer; MiniKdc.java:71 provisions it for tests)."""

    def __init__(self):
        self._verifiers: Dict[str, Dict] = {}
        self._lock = threading.Lock()

    def add_principal(self, user: str, password: bytes) -> None:
        with self._lock:
            self._verifiers[user] = scram_verifier(password)

    def add_verifier(self, user: str, verifier: Dict) -> None:
        with self._lock:
            self._verifiers[user] = dict(verifier)

    def load_keytab(self, path: str) -> "CredentialStore":
        from hadoop_tpu.io import unpack
        with open(path, "rb") as f:
            entries = unpack(f.read())
        for user, pw in entries.items():
            self.add_principal(user, pw)
        return self

    def __call__(self, user: str) -> Optional[Dict]:
        with self._lock:
            v = self._verifiers.get(user)
            return dict(v) if v else None


def password_from_keytab(path: str, principal: str) -> bytes:
    """Client-side credential load (ref: UGI.loginUserFromKeytab)."""
    from hadoop_tpu.io import unpack
    with open(path, "rb") as f:
        entries = unpack(f.read())
    user = principal.split("/")[0].split("@")[0]
    if user not in entries:
        raise AccessControlError(
            f"principal {principal!r} not in keytab {path}")
    return entries[user]
