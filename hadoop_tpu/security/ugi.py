"""User/auth context threaded through every call path.

Capability parity with the reference's security layer (ref:
security/UserGroupInformation.java:104, :1107 loginUserFromKeytab, :1839 doAs;
security/token/ secret managers; security/SaslRpcServer.java). The reference's
hardest retrofit lesson (SURVEY.md §7) is that the auth seam must exist from
day one even when the first implementation is simple-auth-only — so:

- Every RPC carries an effective user + real user (impersonation-aware).
- Servers resolve the caller via ``current_user()`` inside handlers
  (the doAs propagation; a contextvar here instead of a JAAS Subject).
- ``Token``/``SecretManager`` implement HMAC-signed delegation tokens — the
  real mechanism (ref: security/token/SecretManager.java,
  delegation/AbstractDelegationTokenSecretManager.java), usable for block
  tokens and job tokens. Kerberos/SASL negotiation is a pluggable
  ``AuthMethod`` with SIMPLE and TOKEN implemented; KERBEROS is a stub seam.
"""

from __future__ import annotations

import contextvars
import getpass
import hashlib
import hmac
import os
import secrets
import threading
import time
from typing import Dict, List, Optional

from hadoop_tpu.io import pack, unpack


class AccessControlError(PermissionError):
    pass


_current: contextvars.ContextVar[Optional["UserGroupInformation"]] = \
    contextvars.ContextVar("htpu_current_ugi", default=None)


class UserGroupInformation:
    AUTH_SIMPLE = "SIMPLE"
    AUTH_TOKEN = "TOKEN"
    AUTH_KERBEROS = "KERBEROS"  # seam: negotiation not implemented, shape is

    _login_user: Optional["UserGroupInformation"] = None
    _lock = threading.Lock()

    def __init__(self, user_name: str, groups: Optional[List[str]] = None,
                 auth_method: str = AUTH_SIMPLE,
                 real_user: Optional["UserGroupInformation"] = None):
        self.user_name = user_name
        self.groups = list(groups or [])
        self.auth_method = auth_method
        self.real_user = real_user  # impersonation: proxy-user chains
        self.tokens: Dict[str, "Token"] = {}
        self.sasl_password: Optional[bytes] = None  # set by keytab login

    # ------------------------------------------------------------- factories

    @classmethod
    def get_login_user(cls) -> "UserGroupInformation":
        """Ref: UGI.getLoginUser — the OS process user."""
        with cls._lock:
            if cls._login_user is None:
                cls._login_user = cls(getpass.getuser())
            return cls._login_user

    @classmethod
    def create_remote_user(cls, name: str,
                           auth: str = AUTH_SIMPLE) -> "UserGroupInformation":
        return cls(name, auth_method=auth)

    @classmethod
    def create_proxy_user(cls, name: str,
                          real: "UserGroupInformation") -> "UserGroupInformation":
        return cls(name, auth_method=real.auth_method, real_user=real)

    @classmethod
    def login_from_keytab(cls, principal: str, keytab_path: str) -> "UserGroupInformation":
        """Load credentials for SASL auth (ref: UGI.loginUserFromKeytab
        :1107). The keytab (MiniKdc-written in tests) holds the
        principal's secret; the SASL client proves possession of it
        without ever transmitting it (security/sasl.py)."""
        if not os.path.exists(keytab_path):
            raise AccessControlError(f"keytab not found: {keytab_path}")
        from hadoop_tpu.security.sasl import password_from_keytab
        user = principal.split("/")[0].split("@")[0]
        ugi = cls(user, auth_method=cls.AUTH_KERBEROS)
        ugi.sasl_password = password_from_keytab(keytab_path, principal)
        with cls._lock:
            cls._login_user = ugi
        return ugi

    # ----------------------------------------------------------------- doAs

    def do_as(self, fn, *args, **kwargs):
        """Run fn with this UGI as the current caller. Ref: UGI.doAs:1839."""
        token = _current.set(self)
        try:
            return fn(*args, **kwargs)
        finally:
            _current.reset(token)

    def add_token(self, token: "Token") -> None:
        self.tokens[token.kind] = token

    def short_name(self) -> str:
        return self.user_name

    def effective_and_real(self) -> Dict[str, Optional[str]]:
        return {
            "user": self.user_name,
            "real": self.real_user.user_name if self.real_user else None,
        }

    def __repr__(self) -> str:
        via = f" via {self.real_user.user_name}" if self.real_user else ""
        return f"{self.user_name}{via} (auth:{self.auth_method})"


def current_user() -> "UserGroupInformation":
    ugi = _current.get()
    return ugi if ugi is not None else UserGroupInformation.get_login_user()


class Token:
    """Signed delegation token: identifier + HMAC(password) derived from a
    SecretManager key. Ref: security/token/Token.java."""

    def __init__(self, kind: str, identifier: bytes, password: bytes,
                 service: str = ""):
        self.kind = kind
        self.identifier = identifier
        self.password = password
        self.service = service

    def to_wire(self) -> Dict:
        return {"k": self.kind, "i": self.identifier, "p": self.password,
                "s": self.service}

    @classmethod
    def from_wire(cls, d: Dict) -> "Token":
        return cls(d["k"], d["i"], d["p"], d.get("s", ""))


class SecretManager:
    """HMAC secret manager with rolling master keys.
    Ref: security/token/SecretManager.java,
    delegation/AbstractDelegationTokenSecretManager.java."""

    def __init__(self, kind: str, key_rotation_s: float = 24 * 3600.0,
                 token_ttl_s: float = 7 * 24 * 3600.0):
        self.kind = kind
        self.key_rotation_s = key_rotation_s
        self.token_ttl_s = token_ttl_s
        self._keys: Dict[int, bytes] = {}
        self._key_id = 0
        self._lock = threading.Lock()
        self._roll_key()

    def _roll_key(self) -> None:
        with self._lock:
            self._key_id += 1
            self._keys[self._key_id] = secrets.token_bytes(32)
            # Retain last 3 keys so in-flight tokens survive a rotation.
            for kid in list(self._keys):
                if kid < self._key_id - 2:
                    del self._keys[kid]

    def _sign(self, key: bytes, ident: bytes) -> bytes:
        return hmac.new(key, ident, hashlib.sha256).digest()

    def create_token(self, owner: str, renewer: str = "",
                     extra: Optional[Dict] = None) -> Token:
        with self._lock:
            kid = self._key_id
            key = self._keys.get(kid)
        if key is None:
            # A verification-only instance whose keys were never imported
            # (or were cleared) must fail like an auth error, not KeyError.
            raise AccessControlError(
                f"no current master key (id {kid}) to mint {self.kind}")
        ident = pack({
            "owner": owner, "renewer": renewer, "issue": time.time(),
            "expiry": time.time() + self.token_ttl_s, "key_id": kid,
            "extra": extra or {},
        })
        return Token(self.kind, ident, self._sign(key, ident))

    def password_for(self, identifier: bytes) -> bytes:
        """Recompute a token's password from its identifier — the SASL
        TOKEN mechanism's server side (ref: the DIGEST-MD5 path where
        the server derives the password via retrievePassword and only
        the identifier crosses the wire; transmitting the password
        itself would hand the credential to any eavesdropper)."""
        ident = unpack(identifier)
        kid = ident.get("key_id")
        with self._lock:
            key = self._keys.get(kid)
        if key is None:
            raise AccessControlError(f"unknown/expired master key {kid}")
        if ident.get("expiry", 0) < time.time():
            raise AccessControlError("token expired")
        return self._sign(key, identifier)

    def verify_token(self, token: Token) -> Dict:
        """Returns the decoded identifier; raises AccessControlError on
        bad signature or expiry."""
        if token.kind != self.kind:
            raise AccessControlError(
                f"token kind {token.kind!r} != expected {self.kind!r}")
        ident = unpack(token.identifier)
        kid = ident.get("key_id")
        with self._lock:
            key = self._keys.get(kid)
        if key is None:
            raise AccessControlError(f"unknown/expired master key {kid}")
        if not hmac.compare_digest(self._sign(key, token.identifier),
                                   token.password):
            raise AccessControlError("token signature mismatch")
        if ident["expiry"] < time.time():
            raise AccessControlError("token expired")
        return ident
