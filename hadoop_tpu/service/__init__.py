from hadoop_tpu.service.service import (
    Service, ServiceState, AbstractService, CompositeService,
    ServiceStateException, LifecycleEvent,
)

__all__ = [
    "Service", "ServiceState", "AbstractService", "CompositeService",
    "ServiceStateException", "LifecycleEvent",
]
