"""Service lifecycle state machine.

Capability parity with the reference's service framework
(ref: service/AbstractService.java (490 LoC), service/CompositeService.java,
service/ServiceStateModel.java): NOTINITED → INITED → STARTED → STOPPED with a
validated transition matrix, idempotent stop, failure capture, lifecycle
listeners, and composite services that init/start children in order and stop
them in reverse.

Every daemon in this framework (NameNode, BlockServer, ResourceManager,
NodeAgent, AppMaster) is a CompositeService tree, exactly as in the reference.
"""

from __future__ import annotations

import enum
import logging
import threading
import time
from typing import Callable, List, Optional

from hadoop_tpu.conf import Configuration

log = logging.getLogger(__name__)


class ServiceState(enum.IntEnum):
    NOTINITED = 0
    INITED = 1
    STARTED = 2
    STOPPED = 3


# Valid transitions (ref: ServiceStateModel.statemap). stop() is legal from any
# state (idempotent teardown).
_VALID = {
    ServiceState.NOTINITED: {ServiceState.INITED, ServiceState.STOPPED},
    ServiceState.INITED: {ServiceState.STARTED, ServiceState.STOPPED},
    ServiceState.STARTED: {ServiceState.STOPPED},
    ServiceState.STOPPED: {ServiceState.STOPPED},
}


class ServiceStateException(RuntimeError):
    pass


class LifecycleEvent:
    def __init__(self, state: ServiceState):
        self.state = state
        self.time = time.time()


class Service:
    """Interface — see AbstractService for the standard implementation."""

    def init(self, conf: Configuration) -> None: ...
    def start(self) -> None: ...
    def stop(self) -> None: ...
    @property
    def state(self) -> ServiceState: ...
    @property
    def name(self) -> str: ...


class AbstractService(Service):
    """Subclasses override service_init / service_start / service_stop.

    Ref: AbstractService.serviceInit/serviceStart/serviceStop — public
    init/start/stop do the state checking and exception capture, the
    ``service_*`` hooks do the work.
    """

    def __init__(self, name: Optional[str] = None):
        self._name = name or type(self).__name__
        self._state = ServiceState.NOTINITED
        self._state_lock = threading.RLock()
        self._conf: Optional[Configuration] = None
        self._failure: Optional[BaseException] = None
        self._failure_state: Optional[ServiceState] = None
        self._listeners: List[Callable[["AbstractService", ServiceState], None]] = []
        self._lifecycle_history: List[LifecycleEvent] = []
        self._start_time = 0.0

    # ------------------------------------------------------------- properties

    @property
    def name(self) -> str:
        return self._name

    @property
    def state(self) -> ServiceState:
        return self._state

    @property
    def config(self) -> Optional[Configuration]:
        return self._conf

    @property
    def failure_cause(self) -> Optional[BaseException]:
        return self._failure

    def is_in_state(self, s: ServiceState) -> bool:
        return self._state == s

    # -------------------------------------------------------------- lifecycle

    def _enter(self, new_state: ServiceState) -> bool:
        """Returns False when already in new_state (no-op re-entry)."""
        with self._state_lock:
            if self._state == new_state:
                return False
            if new_state not in _VALID[self._state]:
                raise ServiceStateException(
                    f"{self._name}: cannot enter {new_state.name} from {self._state.name}")
            self._state = new_state
            self._lifecycle_history.append(LifecycleEvent(new_state))
            return True

    def init(self, conf: Configuration) -> None:
        if self._state == ServiceState.INITED:
            return
        self._conf = conf
        if not self._enter(ServiceState.INITED):
            return
        try:
            self.service_init(conf)
        except BaseException as e:
            self._note_failure(e)
            self.stop()
            raise
        self._notify(ServiceState.INITED)

    def start(self) -> None:
        if self._state == ServiceState.STARTED:
            return
        if not self._enter(ServiceState.STARTED):
            return
        self._start_time = time.time()
        try:
            self.service_start()
        except BaseException as e:
            self._note_failure(e)
            self.stop()
            raise
        log.debug("Service %s started", self._name)
        self._notify(ServiceState.STARTED)

    def stop(self) -> None:
        with self._state_lock:
            if self._state == ServiceState.STOPPED:
                return
            self._state = ServiceState.STOPPED
            self._lifecycle_history.append(LifecycleEvent(ServiceState.STOPPED))
        try:
            self.service_stop()
        except BaseException as e:
            self._note_failure(e)
            raise
        finally:
            self._notify(ServiceState.STOPPED)

    def close(self) -> None:
        self.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def _note_failure(self, e: BaseException) -> None:
        if self._failure is None:
            self._failure = e
            self._failure_state = self._state
        log.error("Service %s failed in state %s: %s", self._name,
                  self._state.name, e)

    # -------------------------------------------------------------- listeners

    def register_listener(self, cb: Callable[["AbstractService", ServiceState], None]) -> None:
        self._listeners.append(cb)

    def _notify(self, state: ServiceState) -> None:
        for cb in list(self._listeners):
            try:
                cb(self, state)
            except Exception:
                log.exception("Listener failure on %s", self._name)

    # ------------------------------------------------------------------ hooks

    def service_init(self, conf: Configuration) -> None:
        pass

    def service_start(self) -> None:
        pass

    def service_stop(self) -> None:
        pass

    def __repr__(self) -> str:
        return f"{self._name}[{self._state.name}]"


class CompositeService(AbstractService):
    """Parent service managing an ordered list of children.

    Ref: CompositeService.java — children are inited/started in add order and
    stopped in reverse; a child failure during start triggers a full stop.
    """

    def __init__(self, name: Optional[str] = None):
        super().__init__(name)
        self._services: List[Service] = []

    def add_service(self, svc: Service) -> Service:
        self._services.append(svc)
        return svc

    def add_if_service(self, obj) -> bool:
        if isinstance(obj, Service):
            self.add_service(obj)
            return True
        return False

    def get_services(self) -> List[Service]:
        return list(self._services)

    def service_init(self, conf: Configuration) -> None:
        for s in list(self._services):
            s.init(conf)

    def service_start(self) -> None:
        for s in list(self._services):
            s.start()

    def service_stop(self) -> None:
        first_error: Optional[BaseException] = None
        for s in reversed(list(self._services)):
            try:
                s.stop()
            except BaseException as e:
                log.exception("Error stopping child %s", getattr(s, "name", s))
                if first_error is None:
                    first_error = e
        if first_error is not None:
            raise first_error
