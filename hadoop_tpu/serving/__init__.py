"""Decode-serving plane: continuous-batching TPU inference on YARN.

The compute plane inherited from the reference is batch-only (PAPER.md
§5.7/§5.8); this package opens the online workload. A serving replica is

    loader.py   checkpoint straight from DFS (hedged reads for stragglers)
    engine.py   continuous-batching decode engine over the paged KV pool
                (device-resident step state, in-graph stop scan, and a
                speculation lane verified in the same fused step)
    speculate.py  n-gram / prompt-lookup draft proposer per request
    weightplane.py  resident-weight dtype/layout policy behind
                serving.parity: int8 + per-group scales at load,
                dequantized in-register, freed HBM sized into lanes
    kvstore/    tiered fleet-wide KV cache: HBM radix -> host-RAM ring
                -> DFS prefix store (+ raw/int8 block codecs)
    longctx/    long-context plane (serving.parity=relaxed only):
                context-parallel prefill across the replica's mesh,
                KV streamed into the cold tiers, working-set decode
    server.py   /v1/generate (streaming) + /v1/prefill + /v1/health
                + /v1/admin/drain (autoscaler-initiated retirement)
    router.py   registry discovery, role- and prefix-affinity-aware
                balancing, prefill/decode disaggregation handoff
    qos.py      door QoS: per-tenant decay-cost fairness + load
                shedding (FairCallQueue ported to admission)
    autoscale/  the SLO control loop: scrape /prom + registry, grow
                and shrink the fleet, drain-aware scale-in
    service.py  the replica packaged as a YARN long-running service
    metrics.py  queue depth / occupancy / TTFT / per-tier KV wiring

Everything runs on the CPU mesh in tests and shards over ``tp`` via
``parallel.mesh`` on real hardware.
"""

from hadoop_tpu.serving.engine import (BlockPool, DecodeEngine, GenRequest,
                                       SamplingParams)
from hadoop_tpu.serving.loader import load_serving_params
from hadoop_tpu.serving.metrics import ServingMetrics

__all__ = [
    "BlockPool", "DecodeEngine", "GenRequest", "SamplingParams",
    "load_serving_params", "ServingMetrics",
]
