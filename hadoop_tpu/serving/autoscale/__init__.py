"""SLO-driven elastic serving fleet — the autoscaler control plane.

The last loop of the serving story closed: the registry provides
membership (with heartbeat staleness), every replica's ``/v1/health`` +
``/prom`` provide the signals (TTFT p99 over a sliding window, queue
depth, prefill backlog, utilization, QoS sheds), and a ``FleetActuator``
provides the muscle (YARN ``flex``, or a local fleet in benchmarks).

    signals.py     prom parsing, windowed histogram quantiles, the
                   per-poll FleetSnapshot
    controller.py  the Autoscaler: hysteresis + cooldown, cold-start-
                   aware growth, role-aware pools, drain-aware shrink
    __main__.py    standalone daemon (`hadoop-tpu autoscale`) and the
                   YARN-packaged controller component
"""

from hadoop_tpu.serving.autoscale.controller import (AdviseOnlyActuator,
                                                     Autoscaler,
                                                     FleetActuator,
                                                     ScaleDecision,
                                                     YarnServiceActuator)
from hadoop_tpu.serving.autoscale.signals import (FleetScraper,
                                                  FleetSnapshot,
                                                  ReplicaSample,
                                                  histogram_p99,
                                                  parse_prom)

__all__ = [
    "Autoscaler", "FleetActuator", "AdviseOnlyActuator",
    "YarnServiceActuator", "ScaleDecision",
    "FleetScraper", "FleetSnapshot", "ReplicaSample",
    "parse_prom", "histogram_p99",
]
