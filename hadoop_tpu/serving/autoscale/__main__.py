"""Standalone autoscaler daemon — `hadoop-tpu autoscale` and the YARN
service component both land here.

    python -m hadoop_tpu.serving.autoscale \
        --registry HOST:PORT --service NAME \
        [--rm HOST:PORT --app APP_ID [--component replica]] \
        [--http-port N]

Without ``--rm/--app`` the controller runs in **advise** mode: it
scrapes, decides, and publishes its would-have-done decisions on
``/ws/v1/autoscaler`` and ``/prom`` — the dry-run an operator watches
before handing it the flex lever. The HTTP chassis is the same one
every daemon rides, so ``/prom``, ``/jmx`` and the trace endpoints come
for free next to the status door.
"""

from __future__ import annotations

import logging
import signal
import sys
import threading
from typing import List, Optional

from hadoop_tpu.conf import Configuration

log = logging.getLogger(__name__)


def autoscaler_main(argv: List[str],
                    conf: Optional[Configuration] = None) -> int:
    from hadoop_tpu.cli.main import parse_generic_options
    from hadoop_tpu.http.server import HttpServer
    from hadoop_tpu.serving.autoscale.controller import (
        Autoscaler, YarnServiceActuator)
    from hadoop_tpu.util.misc import parse_addr_list
    from hadoop_tpu.yarn.records import ApplicationId

    conf = conf or Configuration()
    argv = parse_generic_options(conf, list(argv))
    args = dict(registry=None, service="serving", rm=None, app=None,
                component="replica", http_port="0", host="127.0.0.1")
    i = 0
    while i < len(argv):
        key = argv[i].lstrip("-").replace("-", "_")
        if key in args and i + 1 < len(argv):
            args[key] = argv[i + 1]
            i += 2
        else:
            print(f"unknown autoscale option {argv[i]}", file=sys.stderr)
            return 2
    if not args["registry"]:
        print("usage: autoscale --registry HOST:PORT --service NAME "
              "[--rm HOST:PORT --app APP_ID [--component NAME]] "
              "[--http-port N]", file=sys.stderr)
        return 2
    registry_addr = parse_addr_list(args["registry"])[0]
    actuator = None
    if args["rm"] and args["app"]:
        try:  # application_<cluster_ts>_<seq>
            _, ts, seq = str(args["app"]).split("_")
            app_id = ApplicationId(int(ts), int(seq))
        except ValueError:
            print(f"bad --app {args['app']!r} (want "
                  f"application_<ts>_<seq>)", file=sys.stderr)
            return 2
        actuator = YarnServiceActuator(
            parse_addr_list(args["rm"])[0], app_id,
            component=str(args["component"]), conf=conf)
    scaler = Autoscaler(conf, registry_addr, str(args["service"]),
                        actuator=actuator)
    http = HttpServer(conf, (str(args["host"]), int(args["http_port"])),
                      daemon_name="autoscaler")
    http.add_handler("/ws/v1/autoscaler",
                     lambda q, b: (200, scaler.status()))
    http.start()
    scaler.start()
    log.info("autoscaler for %s up on :%d (%s mode)", args["service"],
             http.port, "flex" if actuator else "advise")
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    try:
        while not stop.wait(0.5):
            pass
    finally:
        scaler.stop()
        http.stop()
    return 0


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    sys.exit(autoscaler_main(sys.argv[1:]))
