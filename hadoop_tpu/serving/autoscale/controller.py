"""The SLO control loop: scrape → decide → act.

Closes the last open serving-plane loop: the registry knows the
members, ``/prom`` knows whether users are feeling it (TTFT p99,
backlog, QoS sheds), YARN can flex a component count at runtime — this
controller is the piece that reads the first two and drives the third.

Decision rules (all conf-keyed, ``serving.autoscale.*``):

- **Grow before saturation, not at it.** A breach is TTFT p99 over the
  SLO, mean queue depth over ``queue.high``, any QoS shed in the
  window, or utilization over a **cold-start-adjusted** high-water
  mark: the measured checkpoint-pull latency each replica publishes
  (``load_seconds``) is divided by the planning ``horizon`` and
  subtracted from ``util.high`` — a fleet whose replicas take 5 minutes
  to come up starts growing proportionally earlier, because capacity
  ordered at saturation arrives after the queue has already melted.

- **Hysteresis + cooldown, never flap.** Growth needs ``breach.polls``
  consecutive breaching polls; shrink needs ``idle.polls`` consecutive
  quiet polls (TTFT under ``scalein.ttft.frac`` of the SLO, near-empty
  queues, utilization under ``util.low``, zero sheds); every action
  arms a ``cooldown`` during which the pool holds.

- **Role-aware.** The ``prefill`` pool (strict ``role=prefill``
  replicas) is sized independently off prefill backlog; everything
  else is the ``decode`` pool, sized off the latency SLOs. A fleet
  without prefill replicas is just a decode pool.

- **Drain-aware scale-in.** The victim (least loaded, then least cache
  resident — retiring the replica whose loss costs the fleet's
  hit-rate least) is told to retire through ``POST /v1/admin/drain``:
  it leaves the registry, force-persists its resident prefixes into
  the DFS tier, finishes every in-flight generation, and exits; only
  then does the actuator release its capacity. Shrinking the fleet
  never torches the cache and never fails a request.
"""

from __future__ import annotations

import http.client
import json
import logging
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from hadoop_tpu.conf import Configuration
from hadoop_tpu.metrics import metrics_system
from hadoop_tpu.registry.registry import (RegistryClient,
                                          record_is_stale, record_ttl)
from hadoop_tpu.serving.autoscale.signals import (FleetScraper,
                                                  FleetSnapshot,
                                                  ReplicaSample, http_get)
from hadoop_tpu.serving.router import REGISTRY_PREFIX
from hadoop_tpu.util.misc import backoff_delay

log = logging.getLogger(__name__)

INTERVAL_KEY = "serving.autoscale.interval"
TTFT_SLO_KEY = "serving.autoscale.ttft.p99.slo"
QUEUE_HIGH_KEY = "serving.autoscale.queue.high"
UTIL_HIGH_KEY = "serving.autoscale.util.high"
UTIL_LOW_KEY = "serving.autoscale.util.low"
HORIZON_KEY = "serving.autoscale.horizon"
LEAD_MAX_KEY = "serving.autoscale.lead.max"
BREACH_POLLS_KEY = "serving.autoscale.breach.polls"
IDLE_POLLS_KEY = "serving.autoscale.idle.polls"
COOLDOWN_KEY = "serving.autoscale.cooldown"
MIN_KEY = "serving.autoscale.min"
MAX_KEY = "serving.autoscale.max"
PREFILL_MIN_KEY = "serving.autoscale.prefill.min"
PREFILL_MAX_KEY = "serving.autoscale.prefill.max"
BACKLOG_HIGH_KEY = "serving.autoscale.backlog.high"
DRAIN_TIMEOUT_KEY = "serving.autoscale.drain.timeout"
SCALEIN_TTFT_FRAC_KEY = "serving.autoscale.scalein.ttft.frac"
# fleet doctor door (host:port): sick replicas become preferred
# scale-in victims — retiring the statistical outlier heals the fleet
DOCTOR_KEY = "serving.autoscale.doctor"
# guarded grow signal off the doctor's SLO scoreboard: when enabled, a
# tenant class burning its error budget (multi-window verdict at
# /ws/v1/fleet/slo) breaches like a shed. Default OFF — the scoreboard
# observes a fleet for free; acting on it is an operator's call.
SLO_BURN_KEY = "serving.autoscale.slo.burn"

METRICS_SOURCE = "serving.autoscale"


@dataclass
class ScaleDecision:
    at: float
    role: str
    action: str            # "grow" | "shrink" | "hold"
    current: int
    target: int
    reason: str
    victim: Optional[str] = None


class FleetActuator:
    """What the controller drives. ``scale_out`` must eventually make
    ``target`` members of ``role`` register; ``retire`` releases the
    drained victim's capacity (kill the container / flex the count).
    ``drains_via_platform=True`` actuators (YARN: the NM's SIGTERM IS
    the drain — the replica's signal handler runs the same persist +
    finish path) skip the controller's HTTP drain."""

    drains_via_platform = False

    def scale_out(self, role: str, target: int) -> None:
        raise NotImplementedError

    def retire(self, sample: ReplicaSample, target: int) -> None:
        raise NotImplementedError


class AdviseOnlyActuator(FleetActuator):
    """Observe mode: decisions are logged and recorded, nothing moves.
    The standalone controller runs this when no flex target is
    configured — dashboards still get the would-have-done trail."""

    def scale_out(self, role: str, target: int) -> None:
        log.info("autoscale (advise): would grow %s pool to %d",
                 role, target)

    def retire(self, sample: ReplicaSample, target: int) -> None:
        log.info("autoscale (advise): would retire %s (pool -> %d)",
                 sample.path, target)


class YarnServiceActuator(FleetActuator):
    """Flex the replica component of a YARN long-running service. The
    service AM stops the newest surplus container on flex-down and its
    SIGTERM runs the replica's own drain path (registry flip → persist
    → finish in-flight → exit), so the platform drain is the same
    protocol — minus the controller's victim choice, which YARN does
    not expose."""

    drains_via_platform = True

    def __init__(self, rm_addr: Tuple[str, int], app_id,
                 component: str = "replica",
                 conf: Optional[Configuration] = None,
                 prefill_component: Optional[str] = None):
        from hadoop_tpu.yarn.services import ServiceClient
        self.client = ServiceClient(rm_addr, conf)
        self.app_id = app_id
        self.components = {"decode": component,
                           "prefill": prefill_component or
                           f"{component}-prefill"}

    def scale_out(self, role: str, target: int) -> None:
        self.client.flex(self.app_id, self.components[role], target)

    def retire(self, sample: ReplicaSample, target: int) -> None:
        self.client.flex(self.app_id, self.components[sample.role
                         if sample.role == "prefill" else "decode"],
                         target)


class _PoolState:
    def __init__(self):
        self.breach = 0
        self.idle = 0
        self.last_action = 0.0      # monotonic; 0 = never


class Autoscaler:
    """One control loop over one serving service's fleet."""

    def __init__(self, conf: Configuration,
                 registry_addr: Tuple[str, int], service: str,
                 actuator: Optional[FleetActuator] = None):
        self.conf = conf
        self.service = service
        self.actuator = actuator or AdviseOnlyActuator()
        self.reg = RegistryClient(registry_addr, conf)
        self.scraper = FleetScraper(conf)
        self.interval = conf.get_time_seconds(INTERVAL_KEY, 10.0)
        self.ttft_slo = conf.get_time_seconds(TTFT_SLO_KEY, 2.0)
        self.queue_high = conf.get_float(QUEUE_HIGH_KEY, 2.0)
        self.util_high = conf.get_float(UTIL_HIGH_KEY, 0.85)
        self.util_low = conf.get_float(UTIL_LOW_KEY, 0.3)
        self.horizon = conf.get_time_seconds(HORIZON_KEY, 60.0)
        self.lead_max = conf.get_float(LEAD_MAX_KEY, 0.3)
        self.breach_polls = max(1, conf.get_int(BREACH_POLLS_KEY, 2))
        self.idle_polls = max(1, conf.get_int(IDLE_POLLS_KEY, 5))
        self.cooldown = conf.get_time_seconds(COOLDOWN_KEY, 30.0)
        self.bounds = {
            "decode": (max(1, conf.get_int(MIN_KEY, 1)),
                       conf.get_int(MAX_KEY, 8)),
            "prefill": (conf.get_int(PREFILL_MIN_KEY, 0),
                        conf.get_int(PREFILL_MAX_KEY, 4)),
        }
        self.backlog_high = conf.get_float(BACKLOG_HIGH_KEY, 512.0)
        self.drain_timeout = conf.get_time_seconds(DRAIN_TIMEOUT_KEY,
                                                   120.0)
        self.scalein_ttft_frac = conf.get_float(SCALEIN_TTFT_FRAC_KEY,
                                                0.5)
        self.record_ttl = record_ttl(conf)
        self._doctor_addr: Optional[Tuple[str, int]] = None
        doctor = conf.get(DOCTOR_KEY, "")
        if doctor:
            host, _, port = doctor.rpartition(":")
            self._doctor_addr = (host or "127.0.0.1", int(port))
        self._sick: set = set()     # doctor-flagged replica paths
        self.slo_burn_enabled = conf.get_bool(SLO_BURN_KEY, False)
        # last per-class burn verdict off the doctor report's "slo"
        # section (kept on doctor outage, like _sick)
        self._slo_burn: Dict[str, dict] = {}
        self._pools: Dict[str, _PoolState] = {
            "decode": _PoolState(), "prefill": _PoolState()}
        self._draining: set = set()     # guarded-by: _lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.decisions: List[ScaleDecision] = []
        self.last_snapshot: Optional[FleetSnapshot] = None
        reg = metrics_system().source(METRICS_SOURCE)
        self.m_scale_out = reg.counter(
            "autoscale_scale_out", "pool growth actions issued")
        self.m_scale_in = reg.counter(
            "autoscale_scale_in", "drain-and-retire actions completed")
        self.m_drain_failures = reg.counter(
            "autoscale_drain_failures",
            "victims that did not finish draining inside the timeout")
        self.m_decode_replicas = reg.gauge(
            "autoscale_decode_replicas", "live decode-pool replicas")
        self.m_prefill_replicas = reg.gauge(
            "autoscale_prefill_replicas", "live prefill-pool replicas")
        self.m_ttft_p99 = reg.gauge(
            "autoscale_ttft_p99_seconds",
            "fleet TTFT p99 over the last poll window")

    # --------------------------------------------------------- one poll

    def poll(self) -> List[ScaleDecision]:
        """One scrape-decide-act cycle (the loop calls this; tests call
        it directly for deterministic stepping)."""
        try:
            recs = [r for r in self.reg.list(
                        f"{REGISTRY_PREFIX}/{self.service}")
                    if "http" in r.endpoints
                    and not record_is_stale(r, self.record_ttl)]
        except (OSError, IOError) as e:
            log.warning("autoscale: registry list failed: %s", e)
            return []
        snap = self.scraper.scrape(recs)
        with self._lock:
            for s in snap.samples:
                if s.path in self._draining:
                    s.draining = True
        self._refresh_sick()
        self.last_snapshot = snap
        self.m_decode_replicas.set(len(snap.pool("decode")))
        self.m_prefill_replicas.set(len(snap.pool("prefill")))
        if snap.ttft_p99_s is not None:
            self.m_ttft_p99.set(round(snap.ttft_p99_s, 6))
        out: List[ScaleDecision] = []
        for role in ("decode", "prefill"):
            d = self._decide(role, snap)
            if d is not None:
                out.append(d)
                self.decisions.append(d)
                del self.decisions[:-256]          # bounded history
                self._act(d, snap)
        return out

    def _refresh_sick(self) -> None:
        """Pull the doctor's sick-replica verdict (bounded timeout; a
        dead doctor keeps the last-known set — a transient doctor
        outage must not flip victim preference every poll)."""
        if self._doctor_addr is None:
            return
        try:
            rep = json.loads(http_get(self._doctor_addr[0],
                                      self._doctor_addr[1],
                                      "/ws/v1/fleet/doctor",
                                      self.scraper.timeout))
            self._sick = set((rep.get("replicas") or {})
                             .get("flagged", {}).keys())
            # the SLO burn verdicts ride the same pull — one doctor
            # scrape feeds both victim preference and the grow signal
            classes = (rep.get("slo") or {}).get("classes") or {}
            self._slo_burn = {
                cls: {"burning": bool(row.get("burning")),
                      "burn_fast": row.get("burn_fast"),
                      "burn_slow": row.get("burn_slow"),
                      "availability": row.get("availability")}
                for cls, row in classes.items()
                if isinstance(row, dict)}
        except (OSError, ValueError) as e:
            log.debug("doctor scrape failed: %s", e)

    # ---------------------------------------------------------- policy

    def _grow_reason(self, role: str, snap: FleetSnapshot
                     ) -> Optional[str]:
        if role == "prefill":
            backlog = snap.mean_prefill_backlog("prefill")
            if snap.pool("prefill") and backlog > self.backlog_high:
                return (f"prefill backlog {backlog:.0f} tokens/replica "
                        f"> {self.backlog_high:.0f}")
            return None
        if snap.ttft_p99_s is not None and \
                snap.ttft_p99_s > self.ttft_slo:
            return (f"ttft p99 {snap.ttft_p99_s * 1e3:.0f}ms > SLO "
                    f"{self.ttft_slo * 1e3:.0f}ms")
        if snap.shed_delta > 0:
            return f"{snap.shed_delta} requests shed (429) this window"
        if self.slo_burn_enabled:
            burning = sorted(cls for cls, row in self._slo_burn.items()
                             if row.get("burning"))
            if burning:
                return (f"error-budget burn in class"
                        f"{'es' if len(burning) > 1 else ''} "
                        f"{', '.join(burning)} (doctor SLO scoreboard)")
        q = snap.mean_queue_depth(role)
        if q > self.queue_high:
            return f"queue depth {q:.1f}/replica > {self.queue_high:g}"
        # cold-start-aware saturation guard: the slower a replacement
        # replica comes up, the earlier the pool must order one
        lead = min(self.lead_max,
                   snap.max_load_seconds(role) / max(1.0, self.horizon))
        util = snap.utilization(role)
        if util >= self.util_high - lead:
            return (f"utilization {util:.2f} >= "
                    f"{self.util_high:g} - cold-start lead {lead:.2f}")
        return None

    def _quiet(self, role: str, snap: FleetSnapshot) -> bool:
        if role == "prefill":
            return snap.mean_prefill_backlog("prefill") <= 0
        ttft_ok = (snap.ttft_p99_s is None or
                   snap.ttft_p99_s < self.ttft_slo *
                   self.scalein_ttft_frac)
        return (ttft_ok and snap.shed_delta == 0
                and snap.mean_queue_depth(role) < 0.5
                and snap.utilization(role) < self.util_low)

    def _decide(self, role: str, snap: FleetSnapshot
                ) -> Optional[ScaleDecision]:
        pool = snap.pool(role)
        n = len(pool)
        lo, hi = self.bounds[role]
        st = self._pools[role]
        if role == "prefill" and n == 0 and lo == 0:
            return None       # no prefill pool configured: decode-only
        if n < lo and self._cooled(st):
            # below the configured floor (a crashed replica whose
            # record TTL-expired): restore capacity without waiting for
            # a breach — an empty quiet pool never breaches anything
            st.breach = st.idle = 0
            st.last_action = time.monotonic()
            return ScaleDecision(snap.at, role, "grow", n, n + 1,
                                 f"pool below min floor {lo}")
        reason = self._grow_reason(role, snap)
        if reason is not None:
            st.idle = 0
            st.breach += 1
            if st.breach >= self.breach_polls and n < hi and \
                    self._cooled(st):
                st.breach = 0
                st.last_action = time.monotonic()
                return ScaleDecision(snap.at, role, "grow", n, n + 1,
                                     reason)
            return None
        st.breach = 0
        if self._quiet(role, snap):
            st.idle += 1
            # the floor counts only HEALTHY members: with one working
            # and one wedged replica, n=2 > min=1 must not retire the
            # working one and leave a fleet of corpses
            healthy = sum(1 for s in pool if s.ok)
            if st.idle >= self.idle_polls and n > lo and \
                    healthy > lo and \
                    self._cooled(st):
                victim = self._pick_victim(pool)
                if victim is None:
                    return None
                st.idle = 0
                st.last_action = time.monotonic()
                return ScaleDecision(
                    snap.at, role, "shrink", n, n - 1,
                    f"quiet for {self.idle_polls} polls", victim.path)
        else:
            st.idle = 0
        return None

    def _cooled(self, st: _PoolState) -> bool:
        return time.monotonic() - st.last_action >= self.cooldown

    def _pick_victim(self, pool: List[ReplicaSample]
                     ) -> Optional[ReplicaSample]:
        """Affinity-aware victim choice: a doctor-flagged SICK replica
        first (retiring the statistical outlier removes the fleet's
        tail), then the least-loaded, then the fewest resident cached
        blocks — retire the member whose drain persists the least and
        whose loss moves the fewest rendezvous keys."""
        cands = [s for s in pool if s.ok]
        if not cands:
            return None
        sick = self._sick
        return min(cands, key=lambda s: (s.path not in sick,
                                         s.active + s.queue_depth,
                                         s.cached_blocks, s.path))

    # ---------------------------------------------------------- actions

    def _act(self, d: ScaleDecision, snap: FleetSnapshot) -> None:
        if d.action == "grow":
            self.m_scale_out.incr()
            log.info("autoscale: grow %s pool %d -> %d (%s)",
                     d.role, d.current, d.target, d.reason)
            try:
                self.actuator.scale_out(d.role, d.target)
            except Exception as e:  # noqa: BLE001 — a failed flex must
                # not kill the loop; the breach re-arms next poll
                log.warning("autoscale: scale_out failed: %s", e)
            return
        victim = next((s for s in snap.samples if s.path == d.victim),
                      None)
        if victim is None:
            return
        with self._lock:
            if victim.path in self._draining:
                return
            self._draining.add(victim.path)
        log.info("autoscale: shrink %s pool %d -> %d, draining %s (%s)",
                 d.role, d.current, d.target, victim.path, d.reason)
        threading.Thread(target=self._drain_and_retire,
                         args=(victim, d.target),
                         name="autoscale-drain", daemon=True).start()

    def _drain_and_retire(self, victim: ReplicaSample,
                          target: int) -> None:
        try:
            if not self.actuator.drains_via_platform:
                self._drain_via_door(victim)
            self.actuator.retire(victim, target)
            self.m_scale_in.incr()
        except Exception as e:  # noqa: BLE001 — a wedged victim is
            # logged and counted; the pool re-decides next poll
            self.m_drain_failures.incr()
            log.warning("autoscale: drain of %s failed: %s",
                        victim.path, e)
        finally:
            with self._lock:
                self._draining.discard(victim.path)

    def _drain_via_door(self, victim: ReplicaSample) -> None:
        """POST /v1/admin/drain, then watch the door until the drain
        completes (active and queue both zero) or the replica's door
        vanishes (it exited — the strongest completion signal)."""
        conn_timeout = self.scraper.timeout
        conn = http.client.HTTPConnection(victim.host, victim.port,
                                          timeout=conn_timeout)
        try:
            conn.request("POST", "/v1/admin/drain")
            resp = conn.getresponse()
            resp.read()
            if resp.status not in (200, 202):
                raise IOError(f"admin drain -> HTTP {resp.status}")
        finally:
            conn.close()
        deadline = time.monotonic() + self.drain_timeout
        attempt = 0
        misses = 0
        while time.monotonic() < deadline:
            try:
                h = json.loads(http_get(victim.host, victim.port,
                                        "/v1/health", conn_timeout))
                misses = 0
            except ConnectionRefusedError:
                return      # door socket closed: the replica exited
            except (OSError, IOError, ValueError):
                # a timeout or blip is NOT "exited" — a GIL-bound
                # persist can miss one poll, and retiring on it would
                # kill the replica mid-drain; only a persistent
                # silence reads as gone
                misses += 1
                if misses >= 3:
                    return
                time.sleep(backoff_delay(0.1, min(attempt, 4),
                                         max_s=2.0))
                attempt += 1
                continue
            if h.get("status") == "draining" and \
                    int(h.get("active", 0)) == 0 and \
                    int(h.get("queue_depth", 0)) == 0 and \
                    h.get("drain_complete", True):
                # drain_complete distinguishes "in-flight done" from
                # "cache persist flushed" — retiring between the two
                # would strand half-written DFS blocks (missing on a
                # pre-drain_complete door: assume the weaker signal)
                return
            time.sleep(backoff_delay(0.1, min(attempt, 4), max_s=2.0))
            attempt += 1
        raise TimeoutError(
            f"{victim.path} still draining after {self.drain_timeout}s")

    # --------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run,
                                        name="autoscaler", daemon=True)
        self._thread.start()

    def run(self) -> None:
        """The control loop: jittered cadence (a fleet of controllers
        restarted together must not scrape in lockstep — same law as
        every retry in this tree, via ``util.misc.backoff_delay``)."""
        while not self._stop.wait(backoff_delay(
                self.interval, 0, max_s=self.interval * 1.5)):
            try:
                self.poll()
            except Exception as e:  # noqa: BLE001 — one bad poll
                # (replica mid-exit, registry restart) must not kill
                # the controller
                log.warning("autoscale poll failed: %s", e)

    def stop(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.reg.close()

    def status(self) -> dict:
        snap = self.last_snapshot
        with self._lock:
            draining = sorted(self._draining)
        return {
            "service": self.service,
            "interval_s": self.interval,
            "ttft_p99_slo_s": self.ttft_slo,
            "pools": {
                role: {
                    "live": len(snap.pool(role)) if snap else 0,
                    "min": self.bounds[role][0],
                    "max": self.bounds[role][1],
                    "breach_polls": self._pools[role].breach,
                    "idle_polls": self._pools[role].idle,
                } for role in ("decode", "prefill")},
            "ttft_p99_s": snap.ttft_p99_s if snap else None,
            "shed_delta": snap.shed_delta if snap else 0,
            "draining": draining,
            "sick": sorted(self._sick),
            # last per-class SLO burn verdict (doctor scoreboard) next
            # to the decision history it can justify
            "slo_burn": {"enabled": self.slo_burn_enabled,
                         "classes": dict(self._slo_burn)},
            "decisions": [
                {"at": d.at, "role": d.role, "action": d.action,
                 "current": d.current, "target": d.target,
                 "reason": d.reason, "victim": d.victim}
                for d in self.decisions[-20:]],
        }
