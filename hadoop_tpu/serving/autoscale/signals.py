"""Fleet signal plane of the SLO autoscaler.

One poll = one ``FleetSnapshot``: the registry names the members, every
member's door is scraped for per-replica load (``/v1/health`` — queue
depth, active slots, prefill backlog, cache residency), and ``/prom``
supplies the SLO histograms. TTFT p99 is computed over the **window
since the previous poll** by differencing the cumulative histogram
buckets per endpoint and merging the deltas across the fleet — the
autoscaler must react to the last few seconds, not the lifetime average
a counter-since-boot would give it (a fleet that was slow an hour ago
and is fine now must not keep growing).

Every scrape carries a bounded timeout (``serving.autoscale.scrape
.timeout``): a wedged replica is itself a signal (``ok=False``), never a
stall in the control loop.
"""

from __future__ import annotations

import json
import logging
import math
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from hadoop_tpu.conf import Configuration
# THE bounded fleet probe lives in the light http package (the
# doctor's obs/ plane reuses it without dragging serving imports into
# a DataNode process); re-exported here for existing callers
from hadoop_tpu.http import http_get  # noqa: F401

log = logging.getLogger(__name__)

SCRAPE_TIMEOUT_KEY = "serving.autoscale.scrape.timeout"

TTFT_FAMILY = "htpu_time_to_first_token_seconds"
SHED_FAMILY = "htpu_qos_shed_total"


def parse_prom(text: str) -> Dict[str, List[Tuple[Dict[str, str], float]]]:
    """Minimal Prometheus text-exposition parser: sample name →
    [(labels, value)]. Enough for the families the autoscaler reads;
    unparseable lines are skipped (a scraper must never die on one
    daemon's odd metric)."""
    out: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        # histogram _bucket lines may carry an OpenMetrics exemplar
        # suffix ('value # {trace_id="..."} ex_value ts') — strip it, or
        # float(valstr) below rejects the line and the autoscaler loses
        # exactly the TTFT buckets it scales on
        if " # " in line:
            line = line.split(" # ", 1)[0].rstrip()
        try:
            if "{" in line:
                name, rest = line.split("{", 1)
                labelstr, valstr = rest.rsplit("}", 1)
                labels = {}
                for part in labelstr.split(","):
                    if not part:
                        continue
                    k, _, v = part.partition("=")
                    labels[k.strip()] = v.strip().strip('"')
            else:
                name, _, valstr = line.rpartition(" ")
                labels = {}
            out.setdefault(name.strip(), []).append(
                (labels, float(valstr.strip())))
        except ValueError:
            continue
    return out


def histogram_p99(buckets: Dict[float, float], q: float = 0.99
                  ) -> Optional[float]:
    """Quantile estimate from cumulative ``{le_bound: count}`` buckets
    (linear interpolation inside the winning bucket — the standard
    ``histogram_quantile`` estimator). None when the window saw no
    samples."""
    if not buckets:
        return None
    bounds = sorted(buckets)
    total = buckets[bounds[-1]]
    if total <= 0:
        return None
    target = q * total
    prev_bound, prev_cum = 0.0, 0.0
    for b in bounds:
        cum = buckets[b]
        if cum >= target:
            if math.isinf(b):
                return prev_bound        # the overflow bucket has no
                #                          upper edge to interpolate to
            span = cum - prev_cum
            frac = (target - prev_cum) / span if span > 0 else 1.0
            return prev_bound + (b - prev_bound) * frac
        prev_bound, prev_cum = b, cum
    return bounds[-1] if not math.isinf(bounds[-1]) else prev_bound


@dataclass
class ReplicaSample:
    """One replica's registry record + door scrape for one poll."""
    path: str
    host: str
    port: int
    role: str = "mixed"
    load_seconds: float = 0.0
    ok: bool = False
    error: str = ""
    draining: bool = False
    queue_depth: int = 0
    active: int = 0
    slots: int = 0
    prefilling: int = 0
    prefill_backlog: int = 0
    cached_blocks: int = 0
    hits_dfs: int = 0
    qos_sheds: int = 0


@dataclass
class FleetSnapshot:
    """Everything one control-loop iteration decides from."""
    at: float
    samples: List[ReplicaSample] = field(default_factory=list)
    ttft_p99_s: Optional[float] = None   # over the inter-poll window
    ttft_samples: int = 0
    shed_delta: int = 0                  # 429s since the previous poll
    scrape_failures: int = 0

    def pool(self, role: str) -> List[ReplicaSample]:
        """Live members of one scaling pool: ``prefill`` is the strict
        prefill role; ``decode`` is everything else (mixed replicas
        decode). Draining replicas are mid-retirement — they belong to
        no pool, or scale-in would count its own victim and shrink
        twice."""
        if role == "prefill":
            mine = [s for s in self.samples if s.role == "prefill"]
        else:
            mine = [s for s in self.samples if s.role != "prefill"]
        return [s for s in mine if not s.draining]

    def utilization(self, role: str) -> float:
        pool = [s for s in self.pool(role) if s.ok]
        slots = sum(s.slots for s in pool)
        if not slots:
            return 0.0
        return sum(s.active for s in pool) / slots

    def mean_queue_depth(self, role: str) -> float:
        pool = [s for s in self.pool(role) if s.ok]
        if not pool:
            return 0.0
        return sum(s.queue_depth for s in pool) / len(pool)

    def mean_prefill_backlog(self, role: str) -> float:
        pool = [s for s in self.pool(role) if s.ok]
        if not pool:
            return 0.0
        return sum(s.prefill_backlog for s in pool) / len(pool)

    def max_load_seconds(self, role: str) -> float:
        pool = self.pool(role)
        if not pool:
            return 0.0
        return max(s.load_seconds for s in pool)




class FleetScraper:
    """Scrapes the fleet and carries the inter-poll histogram state
    (previous cumulative buckets per endpoint) that turns lifetime
    counters into windowed signals."""

    def __init__(self, conf: Optional[Configuration] = None):
        conf = conf or Configuration(load_defaults=False)
        self.timeout = conf.get_time_seconds(SCRAPE_TIMEOUT_KEY, 2.0)
        # endpoint → (ttft {le: cum}, ttft count, shed total)
        self._prev: Dict[str, Tuple[Dict[float, float], float, float]] = {}

    @staticmethod
    def _endpoint(record) -> Tuple[str, int]:
        host, _, port = record.endpoints["http"].rpartition(":")
        return host or "127.0.0.1", int(port)

    def _scrape_health(self, s: ReplicaSample) -> None:
        h = json.loads(http_get(s.host, s.port, "/v1/health",
                                self.timeout))
        s.draining = h.get("status") == "draining"
        s.queue_depth = int(h.get("queue_depth", 0))
        s.active = int(h.get("active", 0))
        s.slots = int(h.get("slots", 0))
        s.prefilling = int(h.get("prefilling", 0))
        s.prefill_backlog = int(h.get("prefill_backlog", 0))
        cache = h.get("prefix_cache") or {}
        s.cached_blocks = int(cache.get("cached_blocks", 0))
        tiers = cache.get("tiers") or {}
        s.hits_dfs = int(tiers.get("hits_dfs", 0))
        qos = h.get("qos") or {}
        s.qos_sheds = int(qos.get("sheds", 0))

    def _scrape_prom(self, s: ReplicaSample
                     ) -> Tuple[Dict[float, float], float, float]:
        fams = parse_prom(http_get(s.host, s.port, "/prom",
                                   self.timeout).decode())
        buckets: Dict[float, float] = {}
        count = 0.0
        for labels, value in fams.get(f"{TTFT_FAMILY}_bucket", []):
            le = labels.get("le", "")
            bound = math.inf if le == "+Inf" else float(le)
            buckets[bound] = buckets.get(bound, 0.0) + value
        for _, value in fams.get(f"{TTFT_FAMILY}_count", []):
            count += value
        shed = sum(v for _, v in fams.get(SHED_FAMILY, []))
        return buckets, count, shed

    def scrape(self, records) -> FleetSnapshot:
        snap = FleetSnapshot(at=time.time())
        merged: Dict[float, float] = {}
        merged_count = 0.0
        shed_delta = 0.0
        seen: set = set()
        for rec in records:
            try:
                host, port = self._endpoint(rec)
            except (KeyError, ValueError):
                continue
            # still a member (even if this scrape fails): its window
            # state must survive a transient scrape failure, or the
            # next success reads its whole lifetime as one window
            seen.add(f"{host}:{port}")
            attrs = rec.attributes
            s = ReplicaSample(
                path=rec.path, host=host, port=port,
                role=attrs.get("role", "mixed"),
                load_seconds=float(attrs.get("load_seconds", 0) or 0),
                draining=attrs.get("state") == "draining")
            try:
                self._scrape_health(s)
                buckets, count, shed = self._scrape_prom(s)
                s.ok = True
            except (OSError, IOError, ValueError) as e:
                s.error = str(e)
                snap.scrape_failures += 1
                snap.samples.append(s)
                continue
            key = f"{host}:{port}"
            prev_b, prev_c, prev_shed = self._prev.get(
                key, ({}, 0.0, 0.0))
            if count < prev_c or shed < prev_shed:
                # counter reset: the replica restarted behind the same
                # endpoint — its whole history is this window
                prev_b, prev_c, prev_shed = {}, 0.0, 0.0
            for bound, cum in buckets.items():
                d = cum - prev_b.get(bound, 0.0)
                if d > 0:
                    merged[bound] = merged.get(bound, 0.0) + d
            merged_count += count - prev_c
            shed_delta += shed - prev_shed
            self._prev[key] = (buckets, count, shed)
            snap.samples.append(s)
        # drop inter-poll state for endpoints that left the fleet —
        # elastic fleets mint a fresh port per replica, and keeping
        # every dead endpoint's bucket dict would grow without bound
        for key in list(self._prev):
            if key not in seen:
                del self._prev[key]
        # the merged per-bucket deltas stay cumulative (they arrive
        # cumulative per endpoint, so the diffs are cumulative per
        # bound; merging sums preserve that)
        snap.ttft_p99_s = histogram_p99(merged)
        snap.ttft_samples = int(merged_count)
        snap.shed_delta = int(shed_delta)
        return snap
