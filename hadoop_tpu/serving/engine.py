"""Continuous-batching decode engine over ``models.decoder`` weights.

Design (TPU-first, same rules as the trainer):

- **Fixed shapes, compile once per shape.** ONE step function covers
  the whole lifetime of a replica: every row of the step is "one token
  at one position, scattered into and gathered through a block table" —
  the first ``max_batch`` rows are the running decode lanes, the last
  ``prefill_chunk`` rows are a chunk of some request's prompt. It
  compiles at exactly TWO shapes: decode-only (``[max_batch]`` rows —
  steady-state decode pays nothing for an idle chunk lane) and fused
  (``[max_batch + prefill_chunk]`` rows when a prompt chunk rides
  along). Prompts of any length, any admission order, and any sampling
  mix ride those two executables — no per-request retracing, ever.
  ``decode_compiles`` / ``prefill_compiles`` count the two shape
  families' traces so tests and the bench can assert exactly-once
  compilation of each.

- **Paged KV cache.** K/V live in a block pool of shape
  ``[L, num_blocks, block_size, Hkv, Dh]``; each running request owns a
  block table (list of pool indices). Each step scatters the new
  tokens' K/V into ``table[pos // bs], pos % bs`` and gathers each
  row's context back through its table — requests share one pool with
  no per-request padding waste (the vLLM PagedAttention layout,
  expressed as jnp scatter/gather so XLA keeps it fused). Block 0 is a
  write-off scratch page: inactive rows and chunk padding scatter
  there, so masking never needs dynamic shapes.

- **Prefix-reuse KV cache.** The pool is refcounted and a radix index
  (block-granular trie keyed by token chunks) remembers fully-filled
  prompt blocks after prefill. A new request whose token prefix walks a
  cached path maps those blocks into its table (incref — shared,
  read-only: full blocks are never rewritten, so sharing needs no copy)
  and prefills only the tail; at least the last prompt token is always
  recomputed so the first output token has fresh logits. Blocks whose
  refcount drops to zero stay resident as cache and are evicted LRU
  (leaves first) when the pool runs dry — eviction composes with the
  recompute-preemption path: evict cold cache first, preempt the
  youngest request only when the cache is already dry.

- **Tiered fleet-wide cache.** The pool + radix moved into
  ``serving/kvstore`` and grew two cold tiers behind them: zero-ref
  blocks demote to a host-RAM ring (``serving.kv.host.bytes``) when the
  HBM tier evicts them, and hot shared prefixes persist as blocks on
  the DataNodes (``serving.kv.dfs.enable``) via the DFS write pipeline
  so ANY replica — including one that just restarted — maps them back
  with hedged reads instead of re-prefilling. A radix miss at admission
  consults host, then DFS, before falling back to prefill; promotions
  ride fixed-shape jitted page movers (no new compiles). See
  ``kvstore/tiered.py`` for the policy.

- **Chunked prefill, fused into the step.** A prompt is prefilled
  ``prefill_chunk`` tokens per engine step in the SAME compiled step
  that advances every running decode — a long prompt can no longer
  head-of-line-block the batch for a whole monolithic prefill call, so
  admitted requests keep streaming while a new prompt fills in.

- **Continuous batching.** New requests are admitted at any step
  boundary into free slots (their prefill chunks interleave with
  running decodes); finished requests free their slot and decref their
  blocks immediately. When pool + cache run dry the youngest request is
  preempted — its refs drop and it re-queues for recompute-style
  re-admission (warm: its own prompt blocks usually survive as cache).

- **Device-resident step state.** Block tables, positions, last
  tokens, active mask, sampling params, token budgets and the PRNG
  seed live ON DEVICE and are carried through the donated step — the
  host no longer rebuilds eight numpy arrays into device arrays every
  step. State changes ride small event scatters (``_SET_SLOT`` /
  ``_SET_TABLE``) on admission, prefill completion, page growth,
  preemption and release — events, not steps. The stop-condition scan
  (max_new budget, stop_token) runs INSIDE the compiled step, and the
  host reads back one packed ``[B, k+4]`` bundle per step
  (sampled tokens, emit counts, finished mask, verifier accept
  lengths) instead of scanning per-slot Python. In steady-state decode the hot loop transfers
  nothing host→device (tests pin this with a ``jax.transfer_guard``).

- **Speculative decoding.** A third lane in the SAME compiled step:
  a host-side n-gram / prompt-lookup index over each request's prompt
  + generated tokens (``serving/speculate.py``) proposes up to
  ``serving.speculate.k`` draft tokens per decode lane; each lane
  becomes a group of ``k+1`` rows (last accepted token + k drafts at
  consecutive positions) and the single batched forward verifies all
  of them against the paged KV cache at once. The longest agreeing
  prefix is accepted — greedy lanes by argmax equality (token-for-token
  identical to speculation-off, the serve_bench A-B contract), sampled
  lanes by rejection sampling against the verifier distribution (the
  draft is a point mass: accept ``u < p(draft)``, re-sample from the
  draft-removed renormalized target on rejection — output distribution
  exactly the target's). Rejected drafts waste only the row: their KV
  lands beyond the accepted tip and is rewritten by the next step's
  contiguous window before anything can attend to it, and the radix
  prefix cache only ever sees accepted, block-aligned tokens. The two
  compiled shapes stay two: ``[B*(k+1)]`` and ``[B*(k+1) + chunk]``.

- **Weight plane.** Resident weights follow the per-tensor policy of
  ``serving/weightplane.py``: under ``serving.parity=relaxed`` the
  matmul weights live in HBM as int8 + per-group f32 scales and every
  serving matmul dequantizes them in-register (weight-only int8 —
  decode is bandwidth-bound, so ~4x fewer weight-read bytes is decode
  speed AND freed HBM). ``hbm_bytes`` turns the freed memory into
  capacity: the KV pool and the decode-lane count are sized against
  the MEASURED resident-weight bytes, so the int8 plane admits 2-4x
  the lanes x context of the f32 plane at the same budget. Bitwise
  (the default) compiles the exact pre-weight-plane graph — zero
  quantized code reachable, enforced by tpulint's
  ``parity/relaxed-gated`` checker on the qdot/qrows/qhead call sites.

- **Long-context lane.** With a ``serving/longctx`` plane attached
  (``attach_longctx`` — ``serving.parity=relaxed`` only, the CP
  softmax reassociation is not bitwise), prompts of at least
  ``serving.longctx.min.tokens`` bypass the fused step entirely:
  prefill runs as a context-parallel job across the replica's mesh,
  the finished KV streams into the host/DFS tiers
  (``kvstore.ingest_chain``, digest-chained), and decode pages a
  working set back through a fixed device window — the prompt never
  has to fit this engine's pool, and the two step shapes here stay
  exactly two.

- **Sharding.** Pass a ``MeshPlan`` (tp only) and the engine places the
  weights with ``parallel.mesh.param_specs`` and the KV pool with heads
  sharded over ``tp``; jit's SPMD partitioner inserts the decode
  collectives. Under ``JAX_PLATFORMS=cpu`` the same code runs on the
  virtual device mesh (tests) or a single device.
"""

from __future__ import annotations

import itertools
import logging
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field, replace as _dc_replace
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from hadoop_tpu.models.config import ModelConfig
from hadoop_tpu.models.decoder import _norm, head_matrix
# MoE serving shares models/moe.py's dispatch math verbatim — the
# capacity padding there is what keeps the fused step's shapes static
from hadoop_tpu.models.moe import _expert_ffn, route
from hadoop_tpu.models.moe import capacity as moe_capacity
from hadoop_tpu.ops import gelu, rope_frequencies, swiglu
from hadoop_tpu.ops.attention import _repeat_kv
# BlockPool/PrefixCache live in the kvstore package now (the tiered
# fleet-wide cache); re-exported here so `from serving.engine import
# BlockPool` keeps working for every existing consumer
from hadoop_tpu.serving.kvstore import (BlockPool, PrefixCache,
                                        TieredKVCache)
from hadoop_tpu.serving.speculate import NgramProposer
# the weight plane (serving/weightplane.py): qdot/qrows/qhead/qedot and
# the lowp a2a codecs below are RELAXED-TIER entry points — every call
# sits under an `if self._relaxed_weights ...` guard, so
# serving.parity=bitwise (the default) compiles zero quantized code
# (tpulint-enforced)
from hadoop_tpu.parallel.lowp.quant import (moe_combine_quantized,
                                            moe_dispatch_quantized)
from hadoop_tpu.serving.weightplane import (EXPERT_STACKS, describe_tree,
                                            expert_shard_count,
                                            expert_weight_bytes,
                                            is_qtensor, is_quantized_tree,
                                            qdot, qedot, qhead, qrows)
from hadoop_tpu.tracing.tracer import global_tracer

log = logging.getLogger(__name__)

_NEG_INF = -1e30


def _shard_expert_stacks(params, shards: int):
    """Place the expert FFN stacks expert-split across the replica's
    local chips: the leading layout is ``[L, E, ...]`` (f32 stacks) or
    ``[L, E, N, G, gs]``/``[L, E, N, G]`` (qtensor payload/scales), so
    a ``P(None, "ep")`` spec over a 1-axis local mesh splits the expert
    dim and replicates everything else — payload and scales split
    together, scales can never land off their expert's shard. Dense
    leaves (attention, norms, router) are untouched: they stay
    replicated, exactly the dense engine's placement."""
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P
    mesh = Mesh(np.asarray(jax.local_devices()[:shards]), ("ep",))
    spec = NamedSharding(mesh, P(None, "ep"))
    layers = dict(params["layers"])
    for k in EXPERT_STACKS:
        if k not in layers:
            continue
        leaf = layers[k]
        if is_qtensor(leaf):
            layers[k] = {"q": jax.device_put(leaf["q"], spec),
                         "s": jax.device_put(leaf["s"], spec)}
        else:
            layers[k] = jax.device_put(leaf, spec)
    out = dict(params)
    out["layers"] = layers
    return out


# fixed-shape page movers for the cold tiers: one trace each for the
# replica's lifetime (the block index is a traced scalar, the payload
# shape is pinned by the engine config), shared across engine instances
# through jit's module-level cache — tier promotions and demotions ride
# these, never a fresh compile
def _inject_impl(kp, vp, blk, k, v):
    return kp.at[:, blk].set(k), vp.at[:, blk].set(v)


def _extract_impl(kp, vp, blk):
    return kp[:, blk], vp[:, blk]


_INJECT = jax.jit(_inject_impl, donate_argnums=(0, 1))
_EXTRACT = jax.jit(_extract_impl)


# device-resident step-state event movers: the ONLY host→device traffic
# of the steady-state decode loop is these two scatters, and they fire
# on slot lifecycle events (admission, prefill completion, page growth,
# preemption, release) — never per step. Module-level jits like
# _INJECT/_EXTRACT: one trace per state layout for the process
# lifetime, outside the engine's two step-shape counters.
def _set_slot_impl(state, ints, table_row, temp):
    """Scatter one slot's full lane state. ``ints`` packs
    [slot, pos, last_token, active, top_k, out_count, max_new,
    stop_token] so one small upload carries the whole event."""
    slot = ints[0]
    return {
        "tables": state["tables"].at[slot].set(table_row),
        "positions": state["positions"].at[slot].set(ints[1]),
        "last": state["last"].at[slot].set(ints[2]),
        "active": state["active"].at[slot].set(ints[3] != 0),
        "temps": state["temps"].at[slot].set(temp),
        "topks": state["topks"].at[slot].set(ints[4]),
        "outc": state["outc"].at[slot].set(ints[5]),
        "maxn": state["maxn"].at[slot].set(ints[6]),
        "stopt": state["stopt"].at[slot].set(ints[7]),
        "seed": state["seed"],
    }


def _set_table_impl(state, ints):
    """Scatter one new page into a slot's block table:
    ``ints`` = [slot, index, block]."""
    out = dict(state)
    out["tables"] = state["tables"].at[ints[0], ints[1]].set(ints[2])
    return out


_SET_SLOT = jax.jit(_set_slot_impl, donate_argnums=(0,))
_SET_TABLE = jax.jit(_set_table_impl, donate_argnums=(0,))


# --------------------------------------------------------------- requests

@dataclass
class SamplingParams:
    """Per-request decode controls. ``temperature <= 0`` is greedy;
    ``top_k <= 0`` disables the top-k filter."""
    max_new_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0
    stop_token: Optional[int] = None


_req_ids = itertools.count(1)

QUEUED, RUNNING, FINISHED, FAILED = "QUEUED", "RUNNING", "FINISHED", "FAILED"


@dataclass
class GenRequest:
    """One generation request. Tokens stream into ``tokens_out`` (a
    Queue terminated by ``None``); ``done`` fires at completion."""
    prompt: List[int]
    sampling: SamplingParams
    id: int = field(default_factory=lambda: next(_req_ids))
    state: str = QUEUED
    out_tokens: List[int] = field(default_factory=list)
    tokens_out: "queue.Queue" = field(default_factory=queue.Queue)
    done: threading.Event = field(default_factory=threading.Event)
    error: Optional[str] = None
    submitted_at: float = field(default_factory=time.monotonic)
    first_token_at: Optional[float] = None
    # auth identity for door QoS: the fair admission queue orders
    # pending requests by the tenant's decayed usage share
    tenant: str = ""
    preemptions: int = 0
    prefix_tokens_reused: int = 0     # cached tokens mapped at admission
    # trace context of the request's door span: engine-side spans
    # (admit/preempt/first-token) run on the scheduler thread where no
    # contextvar survives, so the context rides the request itself
    trace_ctx: Optional[Any] = None
    # engine-private placement
    _slot: Optional[int] = None
    _proposer: Optional[Any] = None   # n-gram draft index (speculation)
    _blocks: List[int] = field(default_factory=list)
    _shared_blocks: int = 0           # leading blocks mapped from cache
    _ctx: List[int] = field(default_factory=list)
    _prefill_pos: Optional[int] = None  # next position to prefill
    _admit_seq: int = 0

    def _deliver(self, token: int) -> None:
        if self.first_token_at is None:
            self.first_token_at = time.monotonic()
        self.out_tokens.append(token)
        self.tokens_out.put(token)

    def _finish(self, state: str = FINISHED, error: str = None) -> None:
        self.state = state
        self.error = error
        self.tokens_out.put(None)
        self.done.set()

    def wait(self, timeout: Optional[float] = None) -> List[int]:
        if not self.done.wait(timeout):
            raise TimeoutError(f"request {self.id} not done")
        if self.state == FAILED:
            raise RuntimeError(self.error or "generation failed")
        return list(self.out_tokens)


# ----------------------------------------------------------------- engine
# (_norm and head_matrix come from models.decoder — the engine must
# apply EXACTLY the trained model's norm/head rules or served logits
# silently diverge from training)

def _rope_at(x, cos, sin, pos):
    """Rotate one token per row: x [T, H, Dh], pos [T]."""
    c = cos[pos][:, None, :]
    s = sin[pos][:, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = jnp.split(xf, 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
    return out.astype(x.dtype)


def _mask_and_scale(logits, temps, topks):
    """The exact top-k mask + temperature transform ``_sample`` draws
    from, rank-polymorphic over leading axes — the speculation
    verifier shares it so the acceptance distribution can never drift
    from the sampler's."""
    v = logits.shape[-1]
    srt = jnp.sort(logits, axis=-1)                       # ascending
    kidx = jnp.clip(v - topks, 0, v - 1)
    kth = jnp.take_along_axis(srt, kidx[..., None], axis=-1)[..., 0]
    masked = jnp.where((topks > 0)[..., None] & (logits < kth[..., None]),
                       _NEG_INF, logits)
    return masked / jnp.maximum(temps, 1e-6)[..., None]


def _sample(logits, temps, topks, key):
    """logits [T, V] float32; per-row temperature/top-k; greedy when
    temperature <= 0 (the fused decode+sampling step of arxiv
    2502.17728 — sampling stays inside the compiled program so no
    [T, V] logits tensor crosses to the host)."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = _mask_and_scale(logits, temps, topks)
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temps <= 0, greedy, sampled)


class DecodeEngine:
    """Continuous-batching decode over a fixed slot batch and a paged KV
    pool, with prefix reuse and step-fused chunked prefill. Drive it
    either with the background scheduler thread
    (``start``/``submit``/``stop`` — the serving replica) or by calling
    ``step()`` directly (tests, offline bench)."""

    def __init__(self, params, cfg: ModelConfig, *,
                 max_batch: Optional[int] = None, block_size: int = 8,
                 num_blocks: Optional[int] = None,
                 max_context: Optional[int] = None,
                 prefill_chunk: int = 16,
                 prefix_cache: bool = True,
                 kv_host_bytes: int = 0,
                 kv_store_fs=None, kv_store_dir: str = "/kvcache",
                 kv_dfs_min_refs: int = 1, kv_codec: str = "raw",
                 kv_fetch_window: int = 4,
                 speculate_k: int = 0, speculate_ngram: int = 3,
                 admission_queue=None, drain_persist: bool = True,
                 hbm_bytes: int = 0, max_lanes: int = 16,
                 quantize_seconds: float = 0.0,
                 moe_capacity_factor: float = 0.0, moe_shards: int = 0,
                 moe_a2a_codec: str = "int8",
                 plan=None, metrics=None, tracer=None):
        self.cfg = cfg
        # ---- expert plane (MoE checkpoints): the fused step routes
        # every row through models/moe.py's capacity-padded one-hot
        # dispatch, so the static row count pins the capacity and the
        # compile count stays at the same two shapes as dense
        if moe_a2a_codec not in ("int8", "none"):
            raise ValueError(f"serving.moe.a2a.codec={moe_a2a_codec!r} "
                             "(choices: int8, none)")
        self._moe_a2a_codec = moe_a2a_codec
        self._moe_cfg = cfg
        if cfg.is_moe and moe_capacity_factor:
            self._moe_cfg = _dc_replace(
                cfg, capacity_factor=float(moe_capacity_factor))
        self.block_size = block_size
        self.prefill_chunk = max(1, int(prefill_chunk))
        self.max_context = min(max_context or cfg.max_seq, cfg.max_seq)
        self.blocks_per_seq = -(-self.max_context // block_size)
        self.s_max = self.blocks_per_seq * block_size
        if self.s_max > cfg.max_seq:
            # never round past the rope/pos-embed tables: positions
            # beyond max_seq would silently clamp (wrong logits)
            self.blocks_per_seq = cfg.max_seq // block_size
            if self.blocks_per_seq == 0:
                raise ValueError(f"block_size {block_size} exceeds the "
                                 f"model's max_seq {cfg.max_seq}")
            self.s_max = self.blocks_per_seq * block_size
        # ---- the weight plane: MEASURED resident bytes decide the KV
        # budget. serving.parity=relaxed loads int8 weights + per-group
        # scales (serving/weightplane.py); the freed HBM converts into
        # more decode lanes x context below, at the same hbm_bytes.
        self._relaxed_weights = is_quantized_tree(params)
        self._q_embed = is_qtensor(params.get("embed"))
        self._q_head = is_qtensor(params["embed"]) if cfg.tie_embeddings \
            else is_qtensor(params.get("lm_head"))
        if self._relaxed_weights and plan is not None:
            raise NotImplementedError(
                "tp sharding of int8 resident weights is not wired yet "
                "(serving.parity=relaxed serves single-chip replicas)")
        # cached once: the params tree never changes after construction,
        # and /v1/health scrapes weight_plane() every autoscaler poll
        self._weight_desc = describe_tree(params)
        self.weight_bytes = self._weight_desc["weight_bytes"]
        self.quantize_seconds = quantize_seconds
        # expert stacks: measured resident bytes (ledgered as the
        # moe_experts component beside, not inside, the dense remainder)
        # and the expert-dim shard count across the replica's chips
        self.expert_bytes = expert_weight_bytes(params, cfg)
        self.expert_shards = expert_shard_count(
            cfg.n_experts, int(moe_shards),
            jax.local_device_count()) if cfg.is_moe else 0
        if cfg.is_moe and self.expert_shards > 1:
            params = _shard_expert_stacks(params, self.expert_shards)
        self.hbm_bytes = int(hbm_bytes or 0)
        kv_itemsize = jnp.dtype(cfg.jax_dtype).itemsize
        self.block_nbytes = (2 * cfg.n_layers * block_size *
                             cfg.n_kv_heads * cfg.head_dim * kv_itemsize)
        if self.hbm_bytes:
            # capacity = budget minus what the weights measurably
            # occupy; lanes sized so each can hold a full context
            kv_budget = self.hbm_bytes - self.weight_bytes
            min_blocks = self.blocks_per_seq + 2  # one lane + scratch
            if kv_budget < min_blocks * self.block_nbytes:
                raise ValueError(
                    f"serving.kv.hbm.bytes={self.hbm_bytes} leaves "
                    f"{kv_budget} bytes of KV after {self.weight_bytes} "
                    f"bytes of resident weights — below one "
                    f"{self.s_max}-token lane "
                    f"({min_blocks * self.block_nbytes} bytes)")
            budget_blocks = kv_budget // self.block_nbytes
            if num_blocks is None:
                num_blocks = int(budget_blocks)
            if max_batch is None:
                max_batch = max(1, min(int(max_lanes),
                                       (num_blocks - 1)
                                       // self.blocks_per_seq))
        if max_batch is None:
            max_batch = 4
        self.max_batch = max_batch
        if num_blocks is None:
            num_blocks = max_batch * self.blocks_per_seq + 1
        self.pool = BlockPool(num_blocks, block_size)
        self.metrics = metrics
        if metrics:
            metrics.weight_bytes.set(self.weight_bytes)
        self.tracer = tracer or global_tracer()
        # the tier manager owns the radix index and the cold tiers;
        # the engine stays the device owner (extract/inject below)
        self.kvstore = TieredKVCache(
            self.pool, layers=cfg.n_layers, kv_heads=cfg.n_kv_heads,
            head_dim=cfg.head_dim, dtype=cfg.jax_dtype,
            enabled=prefix_cache, host_bytes=kv_host_bytes,
            fs=kv_store_fs, dfs_dir=kv_store_dir,
            dfs_min_refs=kv_dfs_min_refs, codec=kv_codec,
            fetch_window=kv_fetch_window,
            metrics=metrics, tracer=self.tracer,
            extract=self._extract_block)
        self.prefix_cache = self.kvstore.radix

        self._mesh = None
        if plan is not None:
            from hadoop_tpu.parallel.mesh import (make_mesh, param_specs,
                                                  shard_params)
            if plan.pp != 1 or plan.sp != 1 or plan.ep != 1:
                raise ValueError("serving shards over tp (and dp) only; "
                                 f"got plan={plan}")
            self._mesh = make_mesh(plan)
            params = shard_params(params, self._mesh, param_specs(cfg, plan))
        self.params = params

        L, hkv, dh = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
        self._pool_shape = (L, num_blocks, block_size, hkv, dh)
        self._kv_sharding = None
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            self._kv_sharding = NamedSharding(
                self._mesh, P(None, None, None, "tp", None))
        self._kp, self._vp = self._fresh_kv_pools()

        # live HBM ledger (obs/hbm.py): this engine's resident bytes —
        # measured weights + the K/V pool it sized against them —
        # published as htpu_hbm_bytes{component=...} beside the trainer
        # and longctx components; torn down in stop()
        from hadoop_tpu.obs.hbm import hbm_ledger
        # trailing separator: unregister_prefix("engine@123") must not
        # also match a coexisting "engine@1234..." owner
        self._hbm_owner = f"engine@{id(self)}."
        kv_pool_bytes = num_blocks * self.block_nbytes
        led = hbm_ledger()
        led.register(f"{self._hbm_owner}weights", "weights",
                     lambda: self.weight_bytes - self.expert_bytes)
        if cfg.is_moe:
            # expert stacks get their own component so the autoscaler
            # sees where an MoE replica's HBM actually went
            led.register(f"{self._hbm_owner}experts", "moe_experts",
                         lambda: self.expert_bytes)
        led.register(f"{self._hbm_owner}kv", "kv_pool",
                     lambda: kv_pool_bytes)

        # speculation lane: k draft tokens per decode lane, verified by
        # the same fused step (0 = off; every lane is then one row,
        # exactly the pre-speculation layout)
        self.spec_k = max(0, int(speculate_k))
        self.spec_ngram = max(1, int(speculate_ngram))
        self.spec_proposed = 0
        self.spec_accepted = 0

        # host MIRRORS of the slot state (management reads: page
        # allocation, occupancy, tests). The device copy below is the
        # one the compiled step consumes and advances.
        self._tables = np.zeros((max_batch, self.blocks_per_seq), np.int32)
        self._seq_lens = np.zeros((max_batch,), np.int32)
        self._last_tokens = np.zeros((max_batch,), np.int32)
        self._active = np.zeros((max_batch,), bool)
        self._slots: List[Optional[GenRequest]] = [None] * max_batch
        # device-resident step state: carried (donated) through every
        # step, mutated from the host ONLY by slot lifecycle events via
        # _SET_SLOT/_SET_TABLE. "seed" replaces the per-step host
        # PRNGKey upload — the key is derived in-graph.
        self._dstate = self._fresh_dstate()
        # per-step draft proposals (host-filled when speculating); the
        # device-resident zero twins are dispatched on steps with no
        # proposals so an idle speculation lane uploads nothing
        self._draft_tokens = np.zeros((max_batch, self.spec_k), np.int32)
        self._draft_lens = np.zeros((max_batch,), np.int32)
        self._dz_drafts = jnp.zeros((max_batch, self.spec_k), jnp.int32)
        self._dz_lens = jnp.zeros((max_batch,), jnp.int32)

        # the admission seam: a deque by default, or any deque-shaped
        # queue (append/appendleft/popleft/len/[0]) — the door's QoS
        # layer installs a per-tenant weighted-round-robin queue here
        self._pending = admission_queue if admission_queue is not None \
            else deque()                # guarded-by: _cond
        self.drain_persist = drain_persist
        self._admit_counter = itertools.count()
        self._cond = threading.Condition()
        self._sched_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.steps = 0
        self.tokens_generated = 0
        self.occupancy_log: List[int] = []      # active slots per step
        self._fused_compiles = 0                # [B + chunk]-row traces
        self._decode_only_compiles = 0          # [B]-row traces
        self._chunk_fill = 0                    # chunk rows used last step
        # prefix-cache lifetime stats (cold-start zeros)
        self.prefix_tokens_seen = 0
        self.prefix_tokens_matched = 0
        self.prefix_evictions = 0
        self.prefix_inserted_blocks = 0
        # the long-context plane (serving/longctx): attached after
        # construction (it reads this engine's kvstore) and ONLY under
        # serving.parity=relaxed — the CP softmax reassociation is not
        # bitwise, so the bitwise default must keep it unreachable
        self._relaxed_longctx = None
        self._step_fn = jax.jit(self._step_impl, donate_argnums=(1, 2, 3))

    def attach_longctx(self, plane) -> None:
        """Wire the long-context serving plane (``serving/longctx``):
        prompts at least ``plane.min_tokens`` long route to it from
        ``submit`` instead of the fused-step path. Caller is the
        relaxed-tier gate (``longctx_plane_from_conf`` re-validates)."""
        self._relaxed_longctx = plane

        def wake() -> None:
            # a drain parked on `idle` in stop() waits on the scheduler
            # condition; without this, a completion on the plane's own
            # worker thread would only be seen at the drain deadline
            with self._cond:
                self._cond.notify_all()

        plane.on_done = wake

    @property
    def decode_compiles(self) -> int:
        """Traces of the decode-only shape of the step ([B] rows —
        dispatched when nothing is prefilling, so pure decode never
        pays for idle chunk rows). At most 1 or shapes are retracing."""
        return self._decode_only_compiles

    @property
    def prefill_compiles(self) -> int:
        """Traces of the fused shape of the step ([B + chunk] rows —
        dispatched when a prompt chunk rides along). At most 1."""
        return self._fused_compiles

    # ------------------------------------------------- tier page movers

    def _extract_block(self, blk: int):
        """One page's (K, V) payload to host numpy — the demotion /
        persistence copy. Fixed-shape jit, compiled once per layout."""
        k, v = _EXTRACT(self._kp, self._vp, jnp.int32(blk))
        return np.asarray(k), np.asarray(v)

    def _inject_block(self, blk: int, k, v) -> None:
        """Scatter a cold-tier payload into pool page ``blk`` (donated
        buffers — no pool-sized copy, no new compile)."""
        self._kp, self._vp = _INJECT(
            self._kp, self._vp, jnp.int32(blk),
            jnp.asarray(k, self._kp.dtype),
            jnp.asarray(v, self._vp.dtype))

    # ----------------------------------------------------- compiled body

    def _rope_tables(self):
        if not self.cfg.use_rope:
            return None, None
        return rope_frequencies(self.cfg.head_dim, self.cfg.max_seq,
                                self.cfg.rope_theta)

    def _wdot(self, x, w):
        """One serving matmul, weight-plane aware: under
        ``serving.parity=relaxed`` the weight arrives as int8 + scale
        groups and dequantizes in-register inside the contraction
        (weightplane.qdot); bitwise (the default) is the plain matmul,
        byte-identical to the pre-weight-plane engine."""
        if self._relaxed_weights:
            return qdot(x, w)
        return x @ w

    def _mlp(self, x, lp):
        if self.cfg.is_moe:
            return self._moe_mlp(x, lp)
        if self.cfg.use_swiglu:
            return self._wdot(swiglu(self._wdot(x, lp["w_gate"]),
                                     self._wdot(x, lp["w_up"])),
                              lp["w_down"])
        return self._wdot(gelu(self._wdot(x, lp["w_in"]) + lp["b_in"]),
                          lp["w_out"]) + lp["b_out"]

    def _moe_mlp(self, x, lp):
        """Routed expert MLP inside the ONE fused step. The full row
        batch ``x [T, D]`` (decode lanes + any riding prefill chunk)
        goes through models/moe.py's capacity-padded one-hot dispatch —
        T is static per shape family, so the capacity C is static and
        the compile count stays at the same two shapes as dense.
        Tokens past an expert's capacity (and inactive draft rows) get
        an all-zero combine row: the combine einsum yields exact 0.0
        and the residual passes through, bit-for-bit ``moe_mlp``'s
        dropped-token semantics. Under ``serving.parity=relaxed`` the
        expert contractions run against the int8 stacks
        (weightplane.qedot) and both all2all legs ride the lowp codec,
        recorded at the bounded ``moe.dispatch``/``moe.combine`` comm
        sites (Flash Communication, arXiv:2412.04964)."""
        mcfg = self._moe_cfg
        dispatch, combine = route(x, lp["router"], mcfg)
        xe = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
        if self._relaxed_weights and self._moe_a2a_codec != "none":
            xe = moe_dispatch_quantized(xe)
        if self._relaxed_weights:
            ye = qedot(swiglu(qedot(xe, lp["w_gate"]),
                              qedot(xe, lp["w_up"])),
                       lp["w_down"])
        else:
            ye = _expert_ffn(xe, lp, mcfg)
        if self._relaxed_weights and self._moe_a2a_codec != "none":
            ye = moe_combine_quantized(ye)
        y2d = jnp.einsum("tec,ecd->td", combine.astype(jnp.float32),
                         ye.astype(jnp.float32))
        return y2d.astype(x.dtype)

    def _step_impl(self, params, kp, vp, state, drafts, draft_lens,
                   chunk):
        """The ONE compiled function: every row is one token at one
        position. The first ``max_batch * (spec_k + 1)`` rows are the
        decode lanes — each lane a GROUP of ``spec_k + 1`` rows (its
        last accepted token plus up to ``spec_k`` draft tokens at
        consecutive positions, sharing the lane's block table row);
        when ``chunk`` rides along, the last ``prefill_chunk`` rows are
        consecutive positions of one request's prompt chunk.
        Scatter-all-then-gather makes earlier rows' K/V visible to
        later positions within the same step; the causal mask
        ``kpos <= position`` does the rest — a draft row attends to the
        drafts before it exactly as it would have sequentially.

        All lane state arrives in (and leaves through) the donated
        ``state`` dict: positions advance by the accepted length, the
        stop-condition scan (max_new budget, stop_token) retires lanes
        in-graph, and the PRNG key derives from the carried seed — the
        host uploads nothing per steady-state decode step and reads
        back one packed ``[B, spec_k + 4]`` bundle
        (tokens | emit_count | finished | accept_len).

        Compiled at exactly TWO shapes for the replica's lifetime:
        ``[B*(spec_k+1)]`` rows (decode-only) and
        ``[B*(spec_k+1) + prefill_chunk]`` rows (a prompt chunk riding
        along). Any further trace is a retracing bug the counters
        expose."""
        cfg = self.cfg
        B, S = self.max_batch, self.spec_k
        G = S + 1
        # python side effect at trace time only: shape-family counters
        if chunk is None:
            self._decode_only_compiles += 1
        else:
            self._fused_compiles += 1
        tables_s = state["tables"]
        positions_s = state["positions"]
        active_s = state["active"]
        temps_s, topks_s = state["temps"], state["topks"]
        outc, maxn, stopt = state["outc"], state["maxn"], state["stopt"]
        drafts = drafts.astype(jnp.int32)
        gj = jnp.arange(G)

        # ---- build the decode rows from the carried state
        if S:
            row_tok = jnp.concatenate([state["last"][:, None], drafts],
                                      axis=1)
        else:
            row_tok = state["last"][:, None]
        row_pos = positions_s[:, None] + gj[None, :]
        row_act = active_s[:, None] & (gj[None, :] <=
                                       draft_lens[:, None])
        bps = tables_s.shape[1]
        tokens = row_tok.reshape(B * G)
        positions = row_pos.reshape(B * G)
        active = row_act.reshape(B * G)
        tables = jnp.broadcast_to(tables_s[:, None, :],
                                  (B, G, bps)).reshape(B * G, bps)
        temps = jnp.broadcast_to(temps_s[:, None], (B, G)).reshape(B * G)
        topks = jnp.broadcast_to(topks_s[:, None], (B, G)).reshape(B * G)
        if chunk is not None:
            # chunk rows: tokens uploaded, everything else derived from
            # the prefilling slot's carried state (table row, sampling
            # params) — ints = [slot, start, n_valid]
            c_tok, c_ints = chunk
            c_slot, c_start, c_n = c_ints[0], c_ints[1], c_ints[2]
            C = self.prefill_chunk
            cj = jnp.arange(C)
            tokens = jnp.concatenate([tokens, c_tok.astype(jnp.int32)])
            positions = jnp.concatenate([positions, c_start + cj])
            active = jnp.concatenate([active, cj < c_n])
            tables = jnp.concatenate(
                [tables, jnp.broadcast_to(tables_s[c_slot][None, :],
                                          (C, bps))], axis=0)
            temps = jnp.concatenate(
                [temps, jnp.broadcast_to(temps_s[c_slot], (C,))])
            topks = jnp.concatenate(
                [topks, jnp.broadcast_to(topks_s[c_slot], (C,))])
        t = tokens.shape[0]
        # inactive draft rows can sit past the end of the table/rope
        # range; clip (identity for every live row) and let the active
        # mask discard their output
        pos = jnp.minimum(positions, self.s_max - 1)

        hq, hkv, dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
        cos, sin = self._rope_tables()
        if self._relaxed_weights and self._q_embed:
            # quantized embedding gather (policy-selectable; norms and
            # pos_embed never quantize)
            h = qrows(params["embed"], tokens, cfg.jax_dtype)
        else:
            h = params["embed"][tokens]
        if not cfg.use_rope:
            h = h + params["pos_embed"][
                jnp.clip(pos, 0, cfg.max_seq - 1)]
        blk = jnp.take_along_axis(
            tables, (pos // self.block_size)[:, None], axis=1)[:, 0]
        blk = jnp.where(active, blk, BlockPool.SCRATCH)
        off = pos % self.block_size
        scale = 1.0 / (dh ** 0.5)
        kpos = jnp.arange(self.s_max)

        def layer(h, xs):
            lp, kc, vc = xs
            x = _norm(h, lp["attn_norm_w"], lp.get("attn_norm_b"), cfg)
            q = self._wdot(x, lp["wq"]).reshape(t, hq, dh)
            k = self._wdot(x, lp["wk"]).reshape(t, hkv, dh)
            v = self._wdot(x, lp["wv"]).reshape(t, hkv, dh)
            if cfg.use_rope:
                q = _rope_at(q, cos, sin, pos)
                k = _rope_at(k, cos, sin, pos)
            kc = kc.at[blk, off].set(k.astype(kc.dtype))
            vc = vc.at[blk, off].set(v.astype(vc.dtype))
            # paged gather: each row pulls its own pages back into a
            # contiguous [S_max] context view through the block table
            kctx = kc[tables].reshape(t, self.s_max, hkv, dh)
            vctx = vc[tables].reshape(t, self.s_max, hkv, dh)
            kr = _repeat_kv(kctx, hq // hkv)
            vr = _repeat_kv(vctx, hq // hkv)
            logits = jnp.einsum(
                "bhd,bkhd->bhk", q, kr,
                preferred_element_type=jnp.float32) * scale
            mask = kpos[None, :] <= pos[:, None]
            logits = jnp.where(mask[:, None, :], logits, _NEG_INF)
            probs = jax.nn.softmax(logits, axis=-1).astype(vr.dtype)
            attn = jnp.einsum("bhk,bkhd->bhd", probs, vr)
            h2 = h + self._wdot(attn.reshape(t, hq * dh),
                                lp["wo"]).astype(h.dtype)
            x2 = _norm(h2, lp["mlp_norm_w"], lp.get("mlp_norm_b"), cfg)
            return h2 + self._mlp(x2, lp).astype(h.dtype), (kc, vc)

        # comm_scale: the trace-time comm ledgers see one body trace of
        # the scan; the hardware runs it n_layers times per step — the
        # MoE a2a sites record honest per-step executions/bytes
        from hadoop_tpu.obs.comm import comm_scale
        with comm_scale(cfg.n_layers):
            h, (kp, vp) = jax.lax.scan(layer, h,
                                       (params["layers"], kp, vp))
        h = _norm(h, params["final_norm_w"], params.get("final_norm_b"),
                  cfg)
        if self._relaxed_weights and self._q_head:
            logits = qhead(params, h, cfg).astype(jnp.float32)
        else:
            logits = (h @ head_matrix(params, cfg, h.dtype)).astype(
                jnp.float32)

        # ---- sample + verify (the key derives from the carried seed:
        # identical to the old host-side PRNGKey(step_counter))
        key = jax.random.PRNGKey(state["seed"])
        c_first = None
        if S == 0:
            # no speculation: one sample per row, bitwise the
            # pre-speculation engine (same _sample over the same rows
            # with the same key)
            sampled = _sample(logits, temps, topks, key)
            out = sampled[:B][:, None]                      # [B, 1]
            accept = jnp.zeros((B,), jnp.int32)
            if chunk is not None:
                c_first = sampled[B * G + c_n - 1]
        else:
            ku, kr_, kc_ = jax.random.split(key, 3)
            dec_logits = logits[:B * G].reshape(B, G, -1)
            V = dec_logits.shape[-1]
            greedy_tok = jnp.argmax(dec_logits, axis=-1).astype(
                jnp.int32)                                  # [B, G]
            # target distribution per row: the exact _sample transform
            # (top-k mask, temperature) in probability space
            row_top = jnp.broadcast_to(topks_s[:, None], (B, G))
            row_tmp = jnp.broadcast_to(temps_s[:, None], (B, G))
            scaled = _mask_and_scale(dec_logits, row_tmp, row_top)
            probs = jax.nn.softmax(scaled, axis=-1)         # [B, G, V]
            # acceptance: greedy lanes by argmax equality; sampled
            # lanes by rejection sampling — the n-gram draft is a point
            # mass, so accept iff u < p_target(draft)
            u = jax.random.uniform(ku, (B, S))
            p_draft = jnp.take_along_axis(
                probs[:, :S], drafts[..., None], axis=2)[..., 0]
            greedy_lane = temps_s <= 0
            ok = jnp.where(greedy_lane[:, None],
                           drafts == greedy_tok[:, :S], u < p_draft)
            ok = ok & (jnp.arange(S)[None, :] < draft_lens[:, None])
            accept = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1),
                             axis=1)                        # [B] 0..S
            # the bonus token at group index `accept`: greedy lanes
            # take the argmax; sampled lanes draw from the target with
            # a rejected draft token removed and renormalized (exact
            # speculative sampling — all-accepted lanes sample the
            # unmodified target)
            p_a = jnp.take_along_axis(
                probs, jnp.broadcast_to(accept[:, None, None],
                                        (B, 1, V)), axis=1)[:, 0]
            g_a = jnp.take_along_axis(greedy_tok, accept[:, None],
                                      axis=1)[:, 0]
            rejected = accept < draft_lens
            d_a = jnp.take_along_axis(
                drafts, jnp.minimum(accept, S - 1)[:, None],
                axis=1)[:, 0]
            adj = jnp.where(rejected[:, None] &
                            (jnp.arange(V)[None, :] == d_a[:, None]),
                            0.0, p_a)
            adj = adj / jnp.maximum(adj.sum(-1, keepdims=True), 1e-30)
            samp_a = jax.random.categorical(
                kr_, jnp.log(jnp.maximum(adj, 1e-38)),
                axis=-1).astype(jnp.int32)
            final = jnp.where(greedy_lane, g_a, samp_a)     # [B]
            draft_pad = jnp.concatenate(
                [drafts, jnp.zeros((B, 1), jnp.int32)], axis=1)
            out = jnp.where(gj[None, :] < accept[:, None],
                            draft_pad, final[:, None])      # [B, G]
            if chunk is not None:
                c_sampled = _sample(logits[B * G:], temps[B * G:],
                                    topks[B * G:], kc_)
                c_first = c_sampled[c_n - 1]

        # ---- in-graph stop-condition scan: budget clamp, stop_token
        # cut, lane retirement — the host reads the verdict, it does
        # not compute it
        remaining = jnp.maximum(maxn - outc, 0)
        n_emit = jnp.minimum(accept + 1, remaining)
        has_stop = stopt >= 0
        stop_hits = (out == stopt[:, None]) & has_stop[:, None]
        first_stop = jnp.min(
            jnp.where(stop_hits, gj[None, :], G + 1), axis=1)
        n_emit = jnp.minimum(n_emit, first_stop + 1)
        n_emit = jnp.where(active_s, n_emit, 0)
        stop_hit = first_stop < n_emit
        finished = active_s & ((outc + n_emit >= maxn) | stop_hit)
        last_idx = jnp.maximum(n_emit - 1, 0)
        new_last = jnp.where(
            active_s,
            jnp.take_along_axis(out, last_idx[:, None], axis=1)[:, 0],
            state["last"])
        new_state = {
            "tables": tables_s,
            "positions": positions_s + n_emit,
            "last": new_last,
            "active": active_s & ~finished,
            "temps": temps_s,
            "topks": topks_s,
            "outc": outc + n_emit,
            "maxn": maxn,
            "stopt": stopt,
            "seed": state["seed"] + 1,
        }
        packed = jnp.concatenate(
            [out, n_emit[:, None], finished.astype(jnp.int32)[:, None],
             accept[:, None]],
            axis=1)                                         # [B, G + 3]
        if chunk is None:
            return kp, vp, new_state, packed
        return kp, vp, new_state, packed, c_first

    # -------------------------------------------------------- public face

    def submit(self, prompt: List[int],
               sampling: Optional[SamplingParams] = None,
               trace_ctx=None, tenant: str = "") -> GenRequest:
        sampling = sampling or SamplingParams()
        if not prompt:
            raise ValueError("empty prompt")
        if sampling.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1 (prefill "
                             "always emits the first token)")
        if self._relaxed_longctx is not None and \
                len(prompt) >= self._relaxed_longctx.min_tokens:
            # the long-context lane: CP prefill across the mesh, KV
            # streamed into the cold tiers, working-set decode — the
            # prompt never has to fit this engine's pool or s_max
            from hadoop_tpu.tracing.tracer import current_context
            return self._relaxed_longctx.longctx_submit(
                prompt, sampling,
                trace_ctx=trace_ctx or current_context(), tenant=tenant)
        if len(prompt) + sampling.max_new_tokens > self.s_max:
            raise ValueError(
                f"prompt({len(prompt)}) + max_new({sampling.max_new_tokens})"
                f" exceeds engine max_context {self.s_max}")
        # fail fast on requests the pool can NEVER satisfy — parking
        # them in the admission queue would wedge the queue forever
        # (prefix hits could shrink the footprint, but cache contents
        # are transient and must not admit what can't run cold)
        pages = -(-(len(prompt) + sampling.max_new_tokens)
                  // self.block_size)
        if pages > self.pool.num_usable:
            raise ValueError(
                f"request needs {pages} KV pages but the pool holds only "
                f"{self.pool.num_usable} — it could never run alone")
        from hadoop_tpu.tracing.tracer import current_context
        req = GenRequest(prompt=list(prompt), sampling=sampling,
                         trace_ctx=trace_ctx or current_context(),
                         tenant=tenant)
        with self._cond:
            self._pending.append(req)
            depth = len(self._pending)
            self._cond.notify_all()
        if self.metrics:
            self.metrics.requests.incr()
            self.metrics.queue_depth.set(depth)
        return req

    @property
    def num_active(self) -> int:
        return int(self._active.sum())

    @property
    def num_prefilling(self) -> int:
        return sum(1 for r in self._slots
                   if r is not None and r._prefill_pos is not None)

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._pending)

    @property
    def prefill_backlog(self) -> int:
        """Prompt tokens still awaiting prefill across admitted
        requests — the stall gauge the autoscaler sizes prefill
        capacity against. Read lock-free from the health thread: each
        slot's fields are snapshotted once, so a prefill completing
        mid-scan reads as 0, never as a TypeError."""
        total = 0
        for r in list(self._slots):
            if r is None:
                continue
            pos = r._prefill_pos
            if pos is not None:
                total += max(0, len(r._ctx) - pos)
        return total

    @property
    def _local_idle(self) -> bool:
        """No fused-step work: the RUN LOOP's wait predicate. It must
        NOT consult the longctx plane — the plane serves on its own
        worker thread, and parking the scheduler on its busyness would
        hot-spin no-op step() calls against the very CP prefill it is
        waiting for."""
        with self._cond:
            has_pending = bool(self._pending)
        return not has_pending and all(r is None for r in self._slots)

    @property
    def idle(self) -> bool:
        """Nothing in flight ANYWHERE (fused step + longctx plane) —
        the drain/stop predicate."""
        lc = self._relaxed_longctx
        return self._local_idle and (lc is None or lc.idle)

    def longctx_stats(self) -> Dict[str, Any]:
        """The long-context plane's observability face (health, bench):
        ``{"enabled": False}`` when no plane is attached."""
        lc = self._relaxed_longctx
        return lc.stats() if lc is not None else {"enabled": False}

    def weight_plane(self) -> Dict[str, Any]:
        """The resident-weight policy and the capacity it bought —
        /v1/health, the registry record and the bench all read this:
        dtype, MEASURED weight bytes, quantize-at-load seconds, and the
        lanes x context the KV budget admits at those bytes."""
        desc = self._weight_desc
        plane = {
            "parity": "relaxed" if self._relaxed_weights else "bitwise",
            "dtype": desc["dtype"],
            "weight_bytes": self.weight_bytes,
            "quantize_seconds": self.quantize_seconds,
            "quantized_leaves": desc["int8_leaves"],
            "hbm_bytes": self.hbm_bytes,
            "lanes": self.max_batch,
            "max_context": self.s_max,
            "kv_capacity_tokens": self.pool.num_usable * self.block_size,
            "lanes_x_context": self.max_batch * self.s_max,
            # expert placement, beside weight_dtype for the autoscaler
            # and the registry record (0s on a dense checkpoint)
            "experts": self.cfg.n_experts,
            "expert_shards": self.expert_shards,
            "expert_bytes": self.expert_bytes,
        }
        if self.cfg.is_moe:
            plane["expert_capacity"] = moe_capacity(
                self.max_batch * (self.spec_k + 1), self._moe_cfg)
            plane["a2a_codec"] = self._moe_a2a_codec
        return plane

    def cache_stats(self) -> Dict[str, Any]:
        """Prefix-cache + chunked-prefill observability (health, bench)."""
        seen = self.prefix_tokens_seen
        return {
            "enabled": self.prefix_cache is not None,
            "cached_blocks": len(self.prefix_cache)
                             if self.prefix_cache is not None else 0,
            "tokens_seen": seen,
            "tokens_matched": self.prefix_tokens_matched,
            "hit_rate": (self.prefix_tokens_matched / seen) if seen
                        else 0.0,
            "evictions": self.prefix_evictions,
            "inserted_blocks": self.prefix_inserted_blocks,
            "prefill_chunk": self.prefill_chunk,
            # per-tier traffic: HBM radix hits vs host-ring and DFS
            # recoveries, demotions/promotions/persists
            "tiers": self.kvstore.stats(),
            # speculation lane: draft tokens proposed vs accepted
            # (engine-local — bench A-B runs must not bleed into each
            # other through the process-global metrics source)
            "speculate": {
                "k": self.spec_k,
                "proposed": self.spec_proposed,
                "accepted": self.spec_accepted,
                "accept_rate": (self.spec_accepted / self.spec_proposed)
                               if self.spec_proposed else 0.0,
            },
        }

    # ------------------------------------------------------ the scheduler

    def step(self) -> int:
        """One scheduler iteration: admit waiting requests into free
        slots (mapping any cached prefix), propose draft tokens for
        the speculation lane, ensure every decoding request has pages
        for this step's tokens, run the fused decode+prefill-chunk
        step, retire finished requests. Returns the number of tokens
        emitted."""
        with self._sched_lock:
            self._admit()
            self._propose_drafts()
            self._ensure_blocks()
            emitted = self._run_step()
            self._publish_metrics()
            return emitted

    def _propose_drafts(self) -> None:
        """Fill the per-lane draft buffers from each running request's
        n-gram index, clamped so speculation can never out-emit the
        request's remaining token budget (each step emits at most
        draft_len + 1 tokens; the last budgeted token must come from a
        verified sample, so a lane with 1 token left proposes none)."""
        if self.spec_k == 0:
            return
        self._draft_lens[:] = 0
        for slot, req in enumerate(self._slots):
            if req is None or req._prefill_pos is not None or \
                    not self._active[slot]:
                continue
            budget = min(self.spec_k,
                         req.sampling.max_new_tokens
                         - len(req.out_tokens) - 1)
            if budget <= 0:
                continue
            toks = req._proposer.propose(budget)
            if toks:
                self._draft_tokens[slot, :len(toks)] = toks
                self._draft_lens[slot] = len(toks)

    def _admit(self) -> None:
        while True:
            with self._cond:
                if not self._pending:
                    return
                req = self._pending[0]
            slot = next((i for i, r in enumerate(self._slots)
                         if r is None), None)
            if slot is None:
                return
            # prompt plus already-generated tokens (preempted requests
            # resume by recompute — often warm, off their own cached
            # prompt blocks); the first decode step after prefill needs
            # one more page slot for its token
            ctx = req.prompt + req.out_tokens
            shared: List[int] = []
            nodes = []
            cold = []
            limit = 0
            if self.prefix_cache is not None:
                # cap the match below the full context: the last token
                # must always be prefilled so its logits exist to
                # sample the first output token from
                limit = (len(ctx) - 1) // self.block_size
                nodes = self.prefix_cache.match_nodes(ctx)[:limit]
                if nodes:
                    shared = [n.block for n in nodes]
                    # pin before any eviction this admission might do
                    self.pool.incref(shared)
            need = -(-(len(ctx) + 1) // self.block_size) - len(shared)
            private = self._try_alloc(need)
            if private is None:
                # running requests outrank waiting ones (preemption only
                # keeps the running set going, never feeds admission) —
                # wait for retirements to return pages. The cold-tier
                # walk hasn't run yet, so a saturated pool never burns
                # DataNode reads on an admission it can't complete
                if shared:
                    # unpin; zero-ref pages stay resident in the index
                    self.pool.decref(shared)
                return
            if self.prefix_cache is not None:
                # a radix miss consults host RAM, then the DFS store,
                # for the next chunks of the chain — only the still-
                # uncached tail falls back to prefill. The matched
                # node's chain digest seeds the walk, so nothing is
                # rehashed from the root
                cold = self.kvstore.fetch_cold(
                    ctx, len(nodes), limit, parent_ctx=req.trace_ctx,
                    start_digest=nodes[-1].digest if nodes else None)
            with self._cond:
                self._pending.popleft()
            if cold:
                # cold payloads land in the first of the freshly
                # allocated pages (ref 1, owned by this request) and
                # re-register in the radix so siblings share them from
                # HBM; a mid-admission eviction above could only have
                # taken OTHER zero-ref pages — the shared span is
                # pinned and these pages are already allocated
                cold_pages = private[:len(cold)]
                for page, hit in zip(cold_pages, cold):
                    self._inject_block(page, hit.k, hit.v)
                span = shared + cold_pages
                self.prefix_cache.insert(
                    ctx[:len(span) * self.block_size], span)
                self.kvstore.mark_promoted(cold, cold_pages)
            self.kvstore.note_match(nodes, parent_ctx=req.trace_ctx,
                                    count=req.preemptions == 0)
            reused = (len(shared) + len(cold)) * self.block_size
            req.prefix_tokens_reused = reused
            if req.preemptions == 0:
                # hit-rate counts cross-request reuse only: a preempted
                # request re-matching its OWN surviving blocks is warm
                # resume, and counting it would inflate the gauge
                # exactly when the pool is thrashing
                self.prefix_tokens_seen += len(ctx)
                self.prefix_tokens_matched += reused
                if self.metrics and reused:
                    self.metrics.prefix_tokens_reused.incr(reused)
            self._place(req, slot, shared + private, ctx,
                        len(shared) + len(cold))

    def _try_alloc(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` pages, evicting LRU zero-ref cached blocks to
        make room before giving up (cold cache yields to live work).
        Victims demote to the host-RAM ring on their way out (the
        ``on_evict`` hook copies the payload while the page is still
        valid), so "evicted" means "one memcpy away", not "gone"."""
        if n <= 0:
            return []
        got = self.pool.alloc(n)
        if got is not None or self.prefix_cache is None:
            return got
        evicted = self.prefix_cache.evict(n - self.pool.num_free,
                                          self.pool.refcount,
                                          on_evict=self.kvstore.demote)
        if not evicted:
            return None
        self.pool.free(evicted)
        self.prefix_evictions += len(evicted)
        if self.metrics:
            self.metrics.prefix_cache_evictions.incr(len(evicted))
        return self.pool.alloc(n)

    def _place(self, req: GenRequest, slot: int, blocks: List[int],
               ctx: List[int], shared_blocks: int) -> None:
        req.state = RUNNING
        req._slot = slot
        req._blocks = blocks
        req._shared_blocks = shared_blocks
        req._ctx = ctx
        req._prefill_pos = shared_blocks * self.block_size
        req._admit_seq = next(self._admit_counter)
        if self.spec_k:
            req._proposer = NgramProposer(ctx, max_n=self.spec_ngram)
        self._slots[slot] = req
        row = np.zeros((self.blocks_per_seq,), np.int32)
        row[:len(blocks)] = blocks
        self._tables[slot] = row
        self._seq_lens[slot] = 0
        self._active[slot] = False
        self._last_tokens[slot] = 0
        # the admission-event scatter: the slot's whole lane state
        # (table row, sampling params, budget, stop token) lands on
        # device ONCE here; the compiled step carries it from now on
        self._push_slot(slot, req)
        sp = self.tracer.span("serving.admit", parent=req.trace_ctx)
        sp.add_kv("request", str(req.id))
        sp.add_kv("prompt_tokens", str(len(ctx)))
        sp.add_kv("prefix_tokens_reused", str(req.prefix_tokens_reused))
        sp.finish()

    def _ensure_blocks(self) -> None:
        """Every decoding slot must own the page its next token lands
        in; allocate at block boundaries (evicting cold cache first),
        preempting the youngest request when everything is dry. Draft
        rows scatter K/V too, so a speculating lane best-effort
        allocates through its furthest draft position — and on a dry
        pool the drafts are CLAMPED to the owned pages rather than
        preempting anyone: speculation degrades before it evicts."""
        for slot, req in enumerate(self._slots):
            if req is None or req._prefill_pos is not None:
                continue     # prefilling slots pre-allocated at admit
            # this step scatters K/V at position seq_lens[slot]; that
            # page must be owned or the write would land in scratch and
            # silently corrupt the request's context
            need = int(self._seq_lens[slot]) // self.block_size + 1
            while req._slot is not None and len(req._blocks) < need:
                got = self._try_alloc(1)
                if got is not None:
                    self._append_block(slot, req, got[0])
                    continue
                # pool and cache dry: evict the youngest running
                # request — which may be this one (then its slot
                # empties and the loop ends; it resumes by recompute
                # once pages free up). Preempting a sharer only drops
                # its refs — pages still mapped by a sibling survive.
                victim = max((r for r in self._slots if r is not None),
                             key=lambda r: r._admit_seq)
                self._preempt(victim)
            lens = int(self._draft_lens[slot]) if self.spec_k else 0
            if req._slot is None or not lens:
                continue
            want = (int(self._seq_lens[slot]) + lens) \
                // self.block_size + 1
            while len(req._blocks) < want:
                # pool.alloc, NOT _try_alloc: a possibly-rejected
                # draft page must never evict a cached prefix either —
                # the clamp below degrades speculation instead
                got = self.pool.alloc(1)
                if got is None:
                    break
                self._append_block(slot, req, got[0])
            self._draft_lens[slot] = min(
                lens, len(req._blocks) * self.block_size
                - int(self._seq_lens[slot]) - 1)

    def _append_block(self, slot: int, req: GenRequest,
                      block: int) -> None:
        """One new page for a decoding slot: host mirror + the
        device-side table scatter (a page-growth event — once per
        block_size tokens per lane, never per step)."""
        idx = len(req._blocks)
        self._tables[slot][idx] = block
        req._blocks.append(block)
        self._dstate = _SET_TABLE(
            self._dstate, np.asarray([slot, idx, block], np.int32))

    def _preempt(self, victim: GenRequest) -> None:
        """vLLM-style recompute preemption: drop the request's page
        refs and requeue it at the front; re-admission prefills prompt
        + tokens generated so far (warm when its prompt blocks survive
        in the prefix index)."""
        self._release_slot(victim)
        victim.state = QUEUED
        victim.preemptions += 1
        with self._cond:
            self._pending.appendleft(victim)
        if self.metrics:
            self.metrics.preemptions.incr()
        psp = self.tracer.span("serving.preempt", parent=victim.trace_ctx)
        psp.add_kv("request", str(victim.id))
        psp.finish()

    def _fresh_kv_pools(self):
        """Zeroed paged K/V pools, sharded when the engine owns a mesh
        — construction and the failed-step recovery path share it."""
        kp = jnp.zeros(self._pool_shape, self.cfg.jax_dtype)
        vp = jnp.zeros(self._pool_shape, self.cfg.jax_dtype)
        if self._kv_sharding is not None:
            kp = jax.device_put(kp, self._kv_sharding)
            vp = jax.device_put(vp, self._kv_sharding)
        return kp, vp

    def _fresh_dstate(self) -> dict:
        """Zeroed device-resident step state, every lane cleared. Used
        at construction and to REPLACE a state dict whose buffers a
        failed (donated) step call consumed — the seed resumes at the
        step count so the sampled-lane key stream never replays."""
        mb = self.max_batch
        return {
            "tables": jnp.zeros((mb, self.blocks_per_seq), jnp.int32),
            "positions": jnp.zeros((mb,), jnp.int32),
            "last": jnp.zeros((mb,), jnp.int32),
            "active": jnp.zeros((mb,), bool),
            "temps": jnp.zeros((mb,), jnp.float32),
            "topks": jnp.zeros((mb,), jnp.int32),
            "outc": jnp.zeros((mb,), jnp.int32),
            "maxn": jnp.zeros((mb,), jnp.int32),
            "stopt": jnp.full((mb,), -1, jnp.int32),
            "seed": jnp.int32(getattr(self, "steps", 0)),
        }

    def _push_slot(self, slot: int, req: Optional[GenRequest]) -> None:
        """One event scatter carrying a slot's whole lane state to the
        device copy (``req=None`` clears the lane)."""
        if req is None:
            ints = np.zeros((8,), np.int32)
            ints[0] = slot
            ints[7] = -1
            row = np.zeros((self.blocks_per_seq,), np.int32)
            temp = np.float32(0.0)
        else:
            sp = req.sampling
            stop = -1 if sp.stop_token is None else int(sp.stop_token)
            ints = np.asarray(
                [slot, int(self._seq_lens[slot]),
                 int(self._last_tokens[slot]),
                 int(self._active[slot]), sp.top_k,
                 len(req.out_tokens), sp.max_new_tokens, stop],
                np.int32)
            row = self._tables[slot]
            temp = np.float32(sp.temperature)
        self._dstate = _SET_SLOT(self._dstate, ints, row, temp)

    def _finish_request(self, req: GenRequest, state: str = FINISHED,
                        error: str = None) -> None:
        """Complete a request and wake anyone waiting on the scheduler
        condition (``stop(drain=True)`` parks there)."""
        req._finish(state, error)
        with self._cond:
            self._cond.notify_all()

    def _release_slot(self, req: GenRequest) -> None:
        slot = req._slot
        if slot is None:
            return
        released = self.pool.decref(req._blocks)
        if self.prefix_cache is not None:
            # zero-ref pages registered in the radix index stay
            # resident as reusable cache; the rest return to the pool
            drop = [b for b in released
                    if not self.prefix_cache.contains_block(b)]
        else:
            drop = released
        self.pool.free(drop)
        req._blocks = []
        req._shared_blocks = 0
        req._ctx = []
        req._prefill_pos = None
        req._slot = None
        self._slots[slot] = None
        self._active[slot] = False
        self._seq_lens[slot] = 0
        self._tables[slot] = 0
        self._last_tokens[slot] = 0
        self._draft_lens[slot] = 0     # stale drafts must not dispatch
        self._push_slot(slot, None)    # release event: clear the lane

    def _run_step(self) -> int:
        # oldest still-prefilling request gets this step's chunk budget
        pre: Optional[GenRequest] = None
        for r in self._slots:
            if r is not None and r._prefill_pos is not None:
                if pre is None or r._admit_seq < pre._admit_seq:
                    pre = r
        if pre is None and not self._active.any():
            return 0
        G = self.spec_k + 1
        proposed = int(self._draft_lens.sum()) if self.spec_k else 0
        if proposed:
            drafts_in, lens_in = self._draft_tokens, self._draft_lens
        else:
            # nothing proposed this step: dispatch the device-resident
            # zero twins so an idle speculation lane uploads nothing
            drafts_in, lens_in = self._dz_drafts, self._dz_lens
        n_valid = 0
        t0 = time.monotonic()
        if pre is None:
            # decode-only shape: no idle chunk rows to pay for — and
            # with the state device-resident, NOTHING crosses
            # host→device on this path (the steady-state contract the
            # transfer-guard test pins)
            self._kp, self._vp, self._dstate, packed = self._step_fn(
                self.params, self._kp, self._vp, self._dstate,
                drafts_in, lens_in, None)
            c_first = None
        else:
            c = self.prefill_chunk
            start = pre._prefill_pos
            n_valid = min(c, len(pre._ctx) - start)
            c_tokens = np.zeros((c,), np.int32)
            c_tokens[:n_valid] = pre._ctx[start:start + n_valid]
            c_ints = np.asarray([pre._slot, start, n_valid], np.int32)
            self._kp, self._vp, self._dstate, packed, c_first = \
                self._step_fn(self.params, self._kp, self._vp,
                              self._dstate, drafts_in, lens_in,
                              (c_tokens, c_ints))
        # the ONE device→host read of the step: [B, G+3] =
        # tokens | emit_count | finished | accept_len
        packed = np.asarray(packed)
        self.steps += 1
        self._chunk_fill = n_valid
        emitted = 0
        self.occupancy_log.append(self.num_active)
        if len(self.occupancy_log) > 100_000:
            del self.occupancy_log[:50_000]
        accepted = 0
        spec_parent = None
        step_exemplar = None   # any sampled request names this step
        for slot, req in enumerate(self._slots):
            if req is None or not self._active[slot]:
                continue
            if step_exemplar is None and req.trace_ctx is not None \
                    and req.trace_ctx.sampled:
                step_exemplar = req.trace_ctx.trace_id
            n = int(packed[slot, G])
            if n <= 0:
                continue
            toks = packed[slot, :n]
            if self.spec_k:
                # the VERIFIER's accept count, not the delivered n-1:
                # a stop-token or budget clamp truncates the burst but
                # must not read as the proposer guessing wrong
                acc = int(packed[slot, G + 2])
                accepted += acc
                if self._draft_lens[slot]:
                    if self.metrics:
                        self.metrics.spec_accept_len.add(acc)
                    if spec_parent is None:
                        spec_parent = req.trace_ctx
            # mirrors advance with the device state (the device already
            # committed these positions)
            self._seq_lens[slot] += n
            self._last_tokens[slot] = int(toks[-1])
            emitted += self._deliver_burst(req, toks)
            if packed[slot, G + 1] or self._exhausted(req):
                self._release_slot(req)
                self._finish_request(req, FINISHED)
        if self.spec_k and proposed:
            self.spec_proposed += proposed
            self.spec_accepted += accepted
            if self.metrics:
                self.metrics.spec_proposed.incr(proposed)
                if accepted:
                    self.metrics.spec_accepted.incr(accepted)
            # join a speculating request's trace (root spans at
            # decode-step rate would flood the bounded collector ring
            # with single-span traces and evict real request traces)
            ssp = self.tracer.span("serving.speculate",
                                   parent=spec_parent)
            ssp.add_kv("proposed", str(proposed))
            ssp.add_kv("accepted", str(accepted))
            ssp.finish()
        if pre is not None:
            pre._prefill_pos += n_valid
            if pre._prefill_pos >= len(pre._ctx):
                # the chunk's last valid row sat at the final context
                # position — its sample is the first output token
                self._finish_prefill(pre, int(c_first))
                emitted += 1
        self.tokens_generated += emitted
        if self.metrics:
            self.metrics.tokens_out.incr(emitted)
            step_s = time.monotonic() - t0
            self.metrics.decode_step.add(step_s)
            # exemplar: a slow decode_step bucket on /prom names a
            # trace riding this step, resolvable at the fleet doctor
            self.metrics.decode_step_hist.add(
                step_s, exemplar_trace=step_exemplar)
        return emitted

    def _deliver_burst(self, req: GenRequest, toks) -> int:
        """Deliver a step's accepted tokens in order, guarded against
        multi-token overshoot: never past ``max_new_tokens``, nothing
        past a ``stop_token`` hit mid-burst. The compiled step already
        truncates — this is the host-side belt to its braces."""
        sp = req.sampling
        n = 0
        for t in toks:
            if len(req.out_tokens) >= sp.max_new_tokens:
                break
            tok = int(t)
            req._deliver(tok)
            if req._proposer is not None:
                req._proposer.append(tok)
            n += 1
            if sp.stop_token is not None and tok == sp.stop_token:
                break
        return n

    @staticmethod
    def _exhausted(req: GenRequest) -> bool:
        sp = req.sampling
        return len(req.out_tokens) >= sp.max_new_tokens or \
            (sp.stop_token is not None and req.out_tokens and
             req.out_tokens[-1] == sp.stop_token)

    def _finish_prefill(self, req: GenRequest, tok: int) -> None:
        """Prompt fully cached: flip the slot to a decode lane, publish
        the fully-filled prompt blocks into the prefix index, deliver
        the first token, and scatter the armed lane state to the
        device (a prefill-completion event)."""
        slot = req._slot
        ctx_len = len(req._ctx)
        req._prefill_pos = None
        self._seq_lens[slot] = ctx_len
        self._last_tokens[slot] = tok
        self._active[slot] = True
        if self.prefix_cache is not None:
            full = ctx_len // self.block_size
            if full:
                self.prefix_inserted_blocks += self.prefix_cache.insert(
                    req._ctx[:full * self.block_size], req._blocks[:full])
        first = req.first_token_at is None
        req._deliver(tok)
        if req._proposer is not None:
            req._proposer.append(tok)
        if first:
            ttft = req.first_token_at - req.submitted_at
            if self.metrics:
                self.metrics.ttft.add(ttft)
                # a slow TTFT bucket's exemplar IS this request's trace
                self.metrics.ttft_hist.add(
                    ttft,
                    exemplar_trace=req.trace_ctx.trace_id
                    if req.trace_ctx is not None and
                    req.trace_ctx.sampled else None)
            fsp = self.tracer.span("serving.first_token",
                                   parent=req.trace_ctx)
            fsp.add_kv("request", str(req.id))
            fsp.add_kv("ttft_s", f"{ttft:.6f}")
            fsp.finish()
        self._maybe_finish(req, tok)
        if req._slot is not None:
            # still running: arm the device lane (active, position at
            # the context tip, budget counters) in one scatter
            self._push_slot(slot, req)

    def _maybe_finish(self, req: GenRequest, tok: int) -> None:
        sp = req.sampling
        if len(req.out_tokens) >= sp.max_new_tokens or \
                (sp.stop_token is not None and tok == sp.stop_token):
            self._release_slot(req)
            self._finish_request(req, FINISHED)

    def _publish_metrics(self) -> None:
        if not self.metrics:
            return
        m = self.metrics
        with self._cond:
            depth = len(self._pending)
        m.queue_depth.set(depth)
        m.batch_occupancy.set(self.num_active)
        used = self.pool.num_usable - self.pool.num_free
        m.kv_blocks_in_use.set(used)
        m.kv_block_utilization.set(used / max(1, self.pool.num_usable))
        stats = self.cache_stats()
        m.prefix_cache_hit_rate.set(round(stats["hit_rate"], 4))
        m.prefix_cached_blocks.set(stats["cached_blocks"])
        m.chunk_occupancy.set(self._chunk_fill / self.prefill_chunk)
        m.prefill_backlog.set(self.prefill_backlog)

    # --------------------------------------------------- replica lifecycle

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._run_loop,
                                        name="decode-engine", daemon=True)
        self._thread.start()

    def stop(self, drain: bool = False, timeout: float = 30.0) -> None:
        """``drain=True``: keep decoding until every queued and running
        request completes (graceful replica shutdown), then stop. The
        wait parks on the scheduler condition — request completions
        notify it — instead of a sleep-poll, so the drain turns around
        the moment the last request finishes."""
        if drain and self._thread is not None:
            deadline = time.monotonic() + timeout
            with self._cond:
                # self.idle re-enters _cond (Condition() wraps an
                # RLock); completions and submits both notify
                while not self.idle:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
            if self.drain_persist and self.kvstore.dfs_enabled:
                # affinity-aware drain: ship every resident cached
                # prefix to the DFS tier BEFORE the pools die with this
                # process, so a surviving replica maps the departed
                # replica's hot prefixes back instead of re-prefilling
                # — scale-in must never torch the fleet's cache
                self.persist_cache(
                    timeout=max(1.0, deadline - time.monotonic()))
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        # a stopped engine's pool must not haunt the HBM ledger
        from hadoop_tpu.obs.hbm import hbm_ledger
        hbm_ledger().unregister_prefix(self._hbm_owner)
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        # only touch slot/pool state under the scheduler lock — a step
        # still stuck in compilation past the join timeout must not race
        # a double-free of its KV pages; if the lock can't be had the
        # pages stay allocated (the process is going down anyway)
        locked = self._sched_lock.acquire(timeout=5.0)
        try:
            for req in [r for r in self._slots if r]:
                if not req.done.is_set():
                    if locked:
                        self._release_slot(req)
                    self._finish_request(req, FAILED, "engine stopped")
            # drain, don't snapshot-and-clear: a submit() racing this
            # shutdown must fail its request, not vanish from the queue
            while True:
                with self._cond:
                    if not self._pending:
                        break
                    req = self._pending.popleft()
                if not req.done.is_set():
                    self._finish_request(req, FAILED, "engine stopped")
        finally:
            if locked:
                self._sched_lock.release()
        if self._relaxed_longctx is not None:
            # the drain above already waited for the plane through
            # `idle`; this stops its worker and fails anything queued
            self._relaxed_longctx.stop(drain=drain, timeout=timeout)
        self.kvstore.close()

    def persist_cache(self, timeout: float = 30.0) -> int:
        """Force-persist every resident cached block (HBM radix + host
        ring) to the DFS tier and wait for durability — the drain half
        of affinity-aware scale-in. Returns the number of blocks
        enqueued; best-effort on timeout (whatever went durable is
        durable, the rest is recomputable by definition)."""
        if not self.kvstore.dfs_enabled:
            return 0
        with self._sched_lock:
            n = self.kvstore.persist_resident()
            watermark = self.kvstore.persists_enqueued
        if n and not self.kvstore.flush(timeout, up_to=watermark):
            log.warning("drain persist did not finish in %.1fs "
                        "(%d blocks enqueued)", timeout, n)
        return n

    # ------------------------------------------------ disaggregation face

    def prefill_to_store(self, prompt: List[int],
                         timeout: float = 60.0) -> int:
        """Prefill ``prompt`` and force-persist its full-block KV span
        to the DFS tier — the prefill half of prefill/decode
        disaggregation. The KV ships over the DataTransferProtocol via
        the DFS write pipeline; the decode replica's admission maps it
        back with hedged reads and prefills only the tail. Returns the
        number of tokens actually durable on return — re-verified
        against the radix after the flush, so a DataNode refusal can
        never be reported as a persisted handoff. Raises when nothing
        went durable (the router's signal to decode cold)."""
        if not self.kvstore.dfs_enabled:
            raise ValueError("DFS KV tier disabled (set "
                             "serving.kv.dfs.enable for prefill-role "
                             "replicas)")
        if self._relaxed_longctx is not None and \
                len(prompt) >= self._relaxed_longctx.min_tokens:
            # monster handoff: CP prefill + streamed tier ingest — the
            # radix never sees these blocks, so the radix-walking
            # persist below would report 0 durable tokens for a chain
            # that IS durable
            return self._relaxed_longctx.prefill_to_store(prompt,
                                                          timeout)
        req = self.submit(prompt, SamplingParams(max_new_tokens=1))
        if self._thread is None:
            # offline/test mode: no scheduler thread, drive it here
            deadline = time.monotonic() + timeout
            while not req.done.is_set():
                if time.monotonic() > deadline:
                    raise TimeoutError(f"prefill {req.id} not done")
                self.step()
        req.wait(timeout)
        with self._sched_lock:
            blocks = self.kvstore.persist_prefix(prompt,
                                                 parent_ctx=req.trace_ctx)
            # flush to THIS handoff's watermark, not the global queue
            # tail — other requests' min-refs persists keep arriving
            watermark = self.kvstore.persists_enqueued
        if not self.kvstore.flush(timeout, up_to=watermark):
            raise TimeoutError("DFS KV persist did not drain in "
                               f"{timeout}s")
        with self._sched_lock:
            durable = self.kvstore.persisted_span(prompt)
        if blocks and not durable:
            raise RuntimeError(
                f"handoff persist failed: 0/{blocks} blocks durable "
                "(DataNodes refusing writes?)")
        return durable * self.block_size

    def _run_loop(self) -> None:
        while not self._stop.is_set():
            with self._cond:
                # _local_idle, not idle: a busy longctx plane must not
                # flip this predicate — step() would return 0 in a
                # tight no-sleep loop for the whole monster request
                while self._local_idle and not self._stop.is_set():
                    self._cond.wait(0.05)
            if self._stop.is_set():
                return
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — fail requests, not
                # the thread: a poisoned request must not wedge the
                # replica with clients blocked on .done forever. Slot
                # state only moves under the scheduler lock (a racing
                # stop() must not double-release the same pages), and
                # the queue drains via popleft — a submit() racing this
                # handler is left pending for the next loop iteration,
                # never silently dropped
                with self._sched_lock:
                    # the failed step call consumed ALL the donated
                    # device buffers (KV pools + step state) — rebuild
                    # them BEFORE the release path scatters lane-clear
                    # events into the state, or the recovery itself
                    # raises on deleted buffers and wedges the replica
                    self._dstate = self._fresh_dstate()
                    self._kp, self._vp = self._fresh_kv_pools()
                    for req in [r for r in self._slots if r]:
                        self._release_slot(req)
                        self._finish_request(req, FAILED, f"decode failed: {e}")
                    # the HBM radix indexed pages that died with the
                    # pools: purge it (no demotion — the bytes are
                    # gone; host/DFS tier copies are digest-keyed and
                    # survive) so no future admission maps a zeroed
                    # page as a cached prefix
                    if self.prefix_cache is not None:
                        self.pool.free(self.prefix_cache.evict(
                            len(self.prefix_cache), self.pool.refcount))
                    while True:
                        with self._cond:
                            if not self._pending:
                                break
                            req = self._pending.popleft()
                        self._finish_request(req, FAILED, f"decode failed: {e}")

    # ------------------------------------------------------------- offline

    def generate(self, prompts: List[List[int]],
                 sampling: Optional[SamplingParams] = None,
                 ) -> List[List[int]]:
        """Offline batch API: submit everything, step until done."""
        reqs = [self.submit(p, sampling) for p in prompts]
        while not all(r.done.is_set() for r in reqs):
            self.step()
        return [r.wait(0) for r in reqs]
