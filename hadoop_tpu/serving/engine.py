"""Continuous-batching decode engine over ``models.decoder`` weights.

Design (TPU-first, same rules as the trainer):

- **Fixed shapes, compile once.** Two jit-compiled functions cover the
  whole lifetime of a replica: ``prefill`` (one request's prompt, padded
  to ``S_max``) and ``decode_step`` (one token for every slot of the
  fixed-size running batch). Requests of any length ride the same two
  executables — no per-request retracing, ever. ``decode_compiles`` /
  ``prefill_compiles`` count traces so tests and the bench can assert
  exactly-once compilation.

- **Paged KV cache.** K/V live in a block pool of shape
  ``[L, num_blocks, block_size, Hkv, Dh]``; each running request owns a
  block table (list of pool indices). The decode step scatters the new
  token's K/V into ``table[pos // bs], pos % bs`` and gathers the
  request's context back through the table — requests share one pool
  with no per-request padding waste (the vLLM PagedAttention layout,
  expressed as jnp scatter/gather so XLA keeps it fused). Block 0 is a
  write-off scratch page: inactive batch lanes and prompt padding
  scatter there, so masking never needs dynamic shapes.

- **Continuous batching.** New requests are admitted at any step
  boundary into free slots of the running batch (prefill fills their
  cache while other requests keep decoding on the next step); finished
  requests free their slot and blocks immediately. When the pool runs
  dry the youngest request is preempted — its blocks are freed and it
  re-queues for recompute-style re-admission (eviction policy of the
  paged pool).

- **Sharding.** Pass a ``MeshPlan`` (tp only) and the engine places the
  weights with ``parallel.mesh.param_specs`` and the KV pool with heads
  sharded over ``tp``; jit's SPMD partitioner inserts the decode
  collectives. Under ``JAX_PLATFORMS=cpu`` the same code runs on the
  virtual device mesh (tests) or a single device.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from hadoop_tpu.models.config import ModelConfig
from hadoop_tpu.ops import (apply_rope, causal_attention, gelu, layer_norm,
                            rms_norm, rope_frequencies, swiglu)
from hadoop_tpu.ops.attention import _repeat_kv
from hadoop_tpu.tracing.tracer import global_tracer

_NEG_INF = -1e30


# ------------------------------------------------------------- block pool

class BlockPool:
    """Fixed pool of KV-cache pages. Block 0 is reserved scratch (padding
    and inactive lanes scatter there), so ``num_blocks - 1`` are
    allocatable. Allocation is all-or-nothing; freeing returns pages for
    immediate reuse by the next admission."""

    SCRATCH = 0

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (one is scratch)")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._free = deque(range(1, num_blocks))
        self._lock = threading.Lock()

    @property
    def num_usable(self) -> int:
        return self.num_blocks - 1

    @property
    def num_free(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        with self._lock:
            if n > len(self._free):
                return None
            return [self._free.popleft() for _ in range(n)]

    def free(self, blocks: List[int]) -> None:
        with self._lock:
            for b in blocks:
                if b == self.SCRATCH:
                    raise ValueError("freeing the scratch block")
                self._free.append(b)


# --------------------------------------------------------------- requests

@dataclass
class SamplingParams:
    """Per-request decode controls. ``temperature <= 0`` is greedy;
    ``top_k <= 0`` disables the top-k filter."""
    max_new_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0
    stop_token: Optional[int] = None


_req_ids = itertools.count(1)

QUEUED, RUNNING, FINISHED, FAILED = "QUEUED", "RUNNING", "FINISHED", "FAILED"


@dataclass
class GenRequest:
    """One generation request. Tokens stream into ``tokens_out`` (a
    Queue terminated by ``None``); ``done`` fires at completion."""
    prompt: List[int]
    sampling: SamplingParams
    id: int = field(default_factory=lambda: next(_req_ids))
    state: str = QUEUED
    out_tokens: List[int] = field(default_factory=list)
    tokens_out: "queue.Queue" = field(default_factory=queue.Queue)
    done: threading.Event = field(default_factory=threading.Event)
    error: Optional[str] = None
    submitted_at: float = field(default_factory=time.monotonic)
    first_token_at: Optional[float] = None
    preemptions: int = 0
    # engine-private placement
    _slot: Optional[int] = None
    _blocks: List[int] = field(default_factory=list)
    _admit_seq: int = 0

    def _deliver(self, token: int) -> None:
        if self.first_token_at is None:
            self.first_token_at = time.monotonic()
        self.out_tokens.append(token)
        self.tokens_out.put(token)

    def _finish(self, state: str = FINISHED, error: str = None) -> None:
        self.state = state
        self.error = error
        self.tokens_out.put(None)
        self.done.set()

    def wait(self, timeout: Optional[float] = None) -> List[int]:
        if not self.done.wait(timeout):
            raise TimeoutError(f"request {self.id} not done")
        if self.state == FAILED:
            raise RuntimeError(self.error or "generation failed")
        return list(self.out_tokens)


# ----------------------------------------------------------------- engine

def _norm(x, w, b, cfg: ModelConfig):
    if cfg.use_rmsnorm:
        return rms_norm(x, w, cfg.norm_eps)
    return layer_norm(x, w, b, cfg.norm_eps)


def _rope_at(x, cos, sin, pos):
    """Rotate one token per batch row: x [B, H, Dh], pos [B]."""
    c = cos[pos][:, None, :]
    s = sin[pos][:, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = jnp.split(xf, 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
    return out.astype(x.dtype)


def _sample(logits, temps, topks, key):
    """logits [B, V] float32; per-row temperature/top-k; greedy when
    temperature <= 0 (the fused decode+sampling step of arxiv
    2502.17728 — sampling stays inside the compiled program so no
    [B, V] logits tensor crosses to the host)."""
    v = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    srt = jnp.sort(logits, axis=-1)                       # ascending
    kidx = jnp.clip(v - topks, 0, v - 1)
    kth = jnp.take_along_axis(srt, kidx[:, None], axis=1)[:, 0]
    use_topk = (topks > 0)[:, None]
    masked = jnp.where(use_topk & (logits < kth[:, None]), _NEG_INF, logits)
    scaled = masked / jnp.maximum(temps, 1e-6)[:, None]
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temps <= 0, greedy, sampled)


def _head(params, cfg: ModelConfig):
    return params["embed"].T if cfg.tie_embeddings else params["lm_head"]


class DecodeEngine:
    """Continuous-batching decode over a fixed slot batch and a paged KV
    pool. Drive it either with the background scheduler thread
    (``start``/``submit``/``stop`` — the serving replica) or by calling
    ``step()`` directly (tests, offline bench)."""

    def __init__(self, params, cfg: ModelConfig, *,
                 max_batch: int = 4, block_size: int = 8,
                 num_blocks: Optional[int] = None,
                 max_context: Optional[int] = None,
                 plan=None, metrics=None, tracer=None):
        if cfg.is_moe:
            raise NotImplementedError("serving MoE checkpoints is not "
                                      "wired up yet (dense decoders only)")
        self.cfg = cfg
        self.max_batch = max_batch
        self.block_size = block_size
        self.max_context = min(max_context or cfg.max_seq, cfg.max_seq)
        self.blocks_per_seq = -(-self.max_context // block_size)
        self.s_max = self.blocks_per_seq * block_size
        if self.s_max > cfg.max_seq:
            # never round past the rope/pos-embed tables: positions
            # beyond max_seq would silently clamp (wrong logits)
            self.blocks_per_seq = cfg.max_seq // block_size
            if self.blocks_per_seq == 0:
                raise ValueError(f"block_size {block_size} exceeds the "
                                 f"model's max_seq {cfg.max_seq}")
            self.s_max = self.blocks_per_seq * block_size
        if num_blocks is None:
            num_blocks = max_batch * self.blocks_per_seq + 1
        self.pool = BlockPool(num_blocks, block_size)
        self.metrics = metrics
        self.tracer = tracer or global_tracer()

        self._mesh = None
        if plan is not None:
            from hadoop_tpu.parallel.mesh import (make_mesh, param_specs,
                                                  shard_params)
            if plan.pp != 1 or plan.sp != 1 or plan.ep != 1:
                raise ValueError("serving shards over tp (and dp) only; "
                                 f"got plan={plan}")
            self._mesh = make_mesh(plan)
            params = shard_params(params, self._mesh, param_specs(cfg, plan))
        self.params = params

        L, hkv, dh = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
        pool_shape = (L, num_blocks, block_size, hkv, dh)
        self._kp = jnp.zeros(pool_shape, cfg.jax_dtype)
        self._vp = jnp.zeros(pool_shape, cfg.jax_dtype)
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            kv_sharding = NamedSharding(
                self._mesh, P(None, None, None, "tp", None))
            self._kp = jax.device_put(self._kp, kv_sharding)
            self._vp = jax.device_put(self._vp, kv_sharding)

        # host-side slot state (fixed shapes, rebuilt into jnp per step)
        self._tables = np.zeros((max_batch, self.blocks_per_seq), np.int32)
        self._seq_lens = np.zeros((max_batch,), np.int32)
        self._last_tokens = np.zeros((max_batch,), np.int32)
        self._active = np.zeros((max_batch,), bool)
        self._temps = np.zeros((max_batch,), np.float32)
        self._topks = np.zeros((max_batch,), np.int32)
        self._slots: List[Optional[GenRequest]] = [None] * max_batch

        self._pending: deque = deque()
        self._admit_counter = itertools.count()
        self._cond = threading.Condition()
        self._sched_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._step_seed = itertools.count()
        self.steps = 0
        self.tokens_generated = 0
        self.occupancy_log: List[int] = []      # active slots per step
        self.decode_compiles = 0
        self.prefill_compiles = 0
        self._decode_fn = jax.jit(self._decode_impl, donate_argnums=(1, 2))
        self._prefill_fn = jax.jit(self._prefill_impl, donate_argnums=(1, 2))

    # ----------------------------------------------------- compiled bodies

    def _rope_tables(self):
        if not self.cfg.use_rope:
            return None, None
        return rope_frequencies(self.cfg.head_dim, self.cfg.max_seq,
                                self.cfg.rope_theta)

    def _mlp(self, x, lp):
        if self.cfg.use_swiglu:
            return swiglu(x @ lp["w_gate"], x @ lp["w_up"]) @ lp["w_down"]
        return gelu(x @ lp["w_in"] + lp["b_in"]) @ lp["w_out"] + lp["b_out"]

    def _decode_impl(self, params, kp, vp, tables, seq_lens, tokens,
                     active, temps, topks, key):
        """One token for every slot. tables [B, blocks_per_seq];
        seq_lens[b] = tokens already cached = position of this token."""
        self.decode_compiles += 1     # python side effect: trace counter
        cfg = self.cfg
        b = tables.shape[0]
        hq, hkv, dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
        cos, sin = self._rope_tables()
        h = params["embed"][tokens]
        if not cfg.use_rope:
            h = h + params["pos_embed"][
                jnp.clip(seq_lens, 0, cfg.max_seq - 1)]
        pos = seq_lens
        blk = jnp.take_along_axis(
            tables, (pos // self.block_size)[:, None], axis=1)[:, 0]
        blk = jnp.where(active, blk, BlockPool.SCRATCH)
        off = pos % self.block_size
        scale = 1.0 / (dh ** 0.5)
        kpos = jnp.arange(self.s_max)

        def layer(h, xs):
            lp, kc, vc = xs
            x = _norm(h, lp["attn_norm_w"], lp.get("attn_norm_b"), cfg)
            q = (x @ lp["wq"]).reshape(b, hq, dh)
            k = (x @ lp["wk"]).reshape(b, hkv, dh)
            v = (x @ lp["wv"]).reshape(b, hkv, dh)
            if cfg.use_rope:
                q = _rope_at(q, cos, sin, pos)
                k = _rope_at(k, cos, sin, pos)
            kc = kc.at[blk, off].set(k.astype(kc.dtype))
            vc = vc.at[blk, off].set(v.astype(vc.dtype))
            # paged gather: each row pulls its own pages back into a
            # contiguous [S_max] context view through the block table
            kctx = kc[tables].reshape(b, self.s_max, hkv, dh)
            vctx = vc[tables].reshape(b, self.s_max, hkv, dh)
            kr = _repeat_kv(kctx, hq // hkv)
            vr = _repeat_kv(vctx, hq // hkv)
            logits = jnp.einsum(
                "bhd,bkhd->bhk", q, kr,
                preferred_element_type=jnp.float32) * scale
            mask = kpos[None, :] <= pos[:, None]
            logits = jnp.where(mask[:, None, :], logits, _NEG_INF)
            probs = jax.nn.softmax(logits, axis=-1).astype(vr.dtype)
            attn = jnp.einsum("bhk,bkhd->bhd", probs, vr)
            h2 = h + (attn.reshape(b, hq * dh) @ lp["wo"]).astype(h.dtype)
            x2 = _norm(h2, lp["mlp_norm_w"], lp.get("mlp_norm_b"), cfg)
            return h2 + self._mlp(x2, lp).astype(h.dtype), (kc, vc)

        h, (kp, vp) = jax.lax.scan(layer, h, (params["layers"], kp, vp))
        h = _norm(h, params["final_norm_w"], params.get("final_norm_b"),
                  cfg)
        logits = (h @ _head(params, cfg).astype(h.dtype)).astype(
            jnp.float32)
        return kp, vp, _sample(logits, temps, topks, key)

    def _prefill_impl(self, params, kp, vp, tokens, length, block_row,
                      temp, topk, key):
        """One request's prompt, padded to S_max: fills its KV pages and
        samples the first output token. tokens [S_max]; positions >=
        length scatter to the scratch page and are causally invisible to
        real positions."""
        self.prefill_compiles += 1    # python side effect: trace counter
        cfg = self.cfg
        p = tokens.shape[0]
        hq, hkv, dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
        cos, sin = self._rope_tables()
        t = tokens[None]
        h = params["embed"][t]
        if not cfg.use_rope:
            h = h + params["pos_embed"][:p][None]
        p_idx = jnp.arange(p)
        dest = block_row[p_idx // self.block_size]
        dest = jnp.where(p_idx < length, dest, BlockPool.SCRATCH)
        offs = p_idx % self.block_size

        def layer(h, xs):
            lp, kc, vc = xs
            x = _norm(h, lp["attn_norm_w"], lp.get("attn_norm_b"), cfg)
            q = (x @ lp["wq"]).reshape(1, p, hq, dh)
            k = (x @ lp["wk"]).reshape(1, p, hkv, dh)
            v = (x @ lp["wv"]).reshape(1, p, hkv, dh)
            if cfg.use_rope:
                q = apply_rope(q, cos, sin)
                k = apply_rope(k, cos, sin)
            kc = kc.at[dest, offs].set(k[0].astype(kc.dtype))
            vc = vc.at[dest, offs].set(v[0].astype(vc.dtype))
            attn = causal_attention(q, k, v)
            h2 = h + (attn.reshape(1, p, hq * dh) @ lp["wo"]).astype(
                h.dtype)
            x2 = _norm(h2, lp["mlp_norm_w"], lp.get("mlp_norm_b"), cfg)
            return h2 + self._mlp(x2, lp).astype(h.dtype), (kc, vc)

        h, (kp, vp) = jax.lax.scan(layer, h, (params["layers"], kp, vp))
        h_last = jnp.take(h[0], length - 1, axis=0)
        h_last = _norm(h_last, params["final_norm_w"],
                       params.get("final_norm_b"), cfg)
        logits = (h_last @ _head(params, cfg).astype(h_last.dtype))[None] \
            .astype(jnp.float32)
        tok = _sample(logits, temp[None], topk[None], key)[0]
        return kp, vp, tok

    # -------------------------------------------------------- public face

    def submit(self, prompt: List[int],
               sampling: Optional[SamplingParams] = None) -> GenRequest:
        sampling = sampling or SamplingParams()
        if not prompt:
            raise ValueError("empty prompt")
        if sampling.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1 (prefill "
                             "always emits the first token)")
        if len(prompt) + sampling.max_new_tokens > self.s_max:
            raise ValueError(
                f"prompt({len(prompt)}) + max_new({sampling.max_new_tokens})"
                f" exceeds engine max_context {self.s_max}")
        pages = -(-(len(prompt) + sampling.max_new_tokens)
                  // self.block_size)
        if pages > self.pool.num_usable:
            raise ValueError(
                f"request needs {pages} KV pages but the pool holds only "
                f"{self.pool.num_usable} — it could never run alone")
        req = GenRequest(prompt=list(prompt), sampling=sampling)
        with self._cond:
            self._pending.append(req)
            self._cond.notify_all()
        if self.metrics:
            self.metrics.requests.incr()
            self.metrics.queue_depth.set(len(self._pending))
        return req

    @property
    def num_active(self) -> int:
        return int(self._active.sum())

    @property
    def queue_depth(self) -> int:
        return len(self._pending)

    @property
    def idle(self) -> bool:
        return not self._pending and not self._active.any()

    # ------------------------------------------------------ the scheduler

    def step(self) -> int:
        """One scheduler iteration: admit waiting requests into free
        slots, ensure every running request has a page for this step's
        token, run one decode step, retire finished requests. Returns
        the number of tokens emitted."""
        with self._sched_lock:
            self._admit()
            self._ensure_blocks()
            emitted = self._decode()
            self._publish_metrics()
            return emitted

    def _admit(self) -> None:
        while self._pending:
            slot = next((i for i, r in enumerate(self._slots)
                         if r is None), None)
            if slot is None:
                return
            with self._cond:
                if not self._pending:
                    return
                req = self._pending[0]
            # prompt plus already-generated tokens (preempted requests
            # resume by recompute); the first decode step after
            # admission needs one more page slot for its token
            ctx = req.prompt + req.out_tokens
            need = -(-(len(ctx) + 1) // self.block_size)
            blocks = self.pool.alloc(need)
            if blocks is None:
                # running requests outrank waiting ones (preemption only
                # keeps the running set going, never feeds admission) —
                # wait for retirements to return pages
                return
            with self._cond:
                self._pending.popleft()
            self._place(req, slot, blocks, ctx)

    def _place(self, req: GenRequest, slot: int, blocks: List[int],
               ctx: List[int]) -> None:
        req.state = RUNNING
        req._slot = slot
        req._blocks = blocks
        req._admit_seq = next(self._admit_counter)
        self._slots[slot] = req
        row = np.zeros((self.blocks_per_seq,), np.int32)
        row[:len(blocks)] = blocks
        self._tables[slot] = row
        padded = np.zeros((self.s_max,), np.int32)
        padded[:len(ctx)] = ctx
        with self.tracer.span("serving.prefill") as sp:
            sp.add_kv("request", str(req.id))
            sp.add_kv("prompt_tokens", str(len(ctx)))
            key = jax.random.PRNGKey(next(self._step_seed))
            self._kp, self._vp, tok = self._prefill_fn(
                self.params, self._kp, self._vp, jnp.asarray(padded),
                np.int32(len(ctx)), jnp.asarray(row),
                np.float32(req.sampling.temperature),
                np.int32(req.sampling.top_k), key)
        tok = int(tok)
        self._seq_lens[slot] = len(ctx)
        self._temps[slot] = req.sampling.temperature
        self._topks[slot] = req.sampling.top_k
        self._active[slot] = True
        first = req.first_token_at is None
        req._deliver(tok)
        self._last_tokens[slot] = tok
        self.tokens_generated += 1
        if self.metrics:
            self.metrics.tokens_out.incr()
            if first:
                self.metrics.ttft.add(
                    req.first_token_at - req.submitted_at)
        self._maybe_finish(req, tok)

    def _ensure_blocks(self) -> None:
        """Every active slot must own the page its next token lands in;
        allocate at block boundaries, preempting the youngest request
        when the pool is dry."""
        for slot, req in enumerate(self._slots):
            if req is None:
                continue
            # this step scatters K/V at position seq_lens[slot]; that
            # page must be owned or the write would land in scratch and
            # silently corrupt the request's context
            need = int(self._seq_lens[slot]) // self.block_size + 1
            while req._slot is not None and len(req._blocks) < need:
                got = self.pool.alloc(1)
                if got is not None:
                    self._tables[slot][len(req._blocks)] = got[0]
                    req._blocks.extend(got)
                    continue
                # pool dry: evict the youngest running request — which
                # may be this one (then its slot empties and the loop
                # ends; it resumes by recompute once pages free up)
                victim = max((r for r in self._slots if r is not None),
                             key=lambda r: r._admit_seq)
                self._preempt(victim)

    def _preempt(self, victim: GenRequest) -> None:
        """vLLM-style recompute preemption: free the request's pages and
        requeue it at the front; re-admission prefills prompt + tokens
        generated so far."""
        self._release_slot(victim)
        victim.state = QUEUED
        victim.preemptions += 1
        with self._cond:
            self._pending.appendleft(victim)
        if self.metrics:
            self.metrics.preemptions.incr()
        self.tracer.span(f"serving.preempt.{victim.id}").finish()

    def _release_slot(self, req: GenRequest) -> None:
        slot = req._slot
        if slot is None:
            return
        self.pool.free(req._blocks)
        req._blocks = []
        req._slot = None
        self._slots[slot] = None
        self._active[slot] = False
        self._seq_lens[slot] = 0
        self._tables[slot] = 0
        self._last_tokens[slot] = 0

    def _decode(self) -> int:
        if not self._active.any():
            return 0
        t0 = time.monotonic()
        key = jax.random.PRNGKey(next(self._step_seed))
        self._kp, self._vp, nxt = self._decode_fn(
            self.params, self._kp, self._vp, jnp.asarray(self._tables),
            jnp.asarray(self._seq_lens), jnp.asarray(self._last_tokens),
            jnp.asarray(self._active), jnp.asarray(self._temps),
            jnp.asarray(self._topks), key)
        nxt = np.asarray(nxt)
        self.steps += 1
        emitted = 0
        self.occupancy_log.append(self.num_active)
        if len(self.occupancy_log) > 100_000:
            del self.occupancy_log[:50_000]
        for slot, req in enumerate(self._slots):
            if req is None:
                continue
            tok = int(nxt[slot])
            self._seq_lens[slot] += 1
            self._last_tokens[slot] = tok
            req._deliver(tok)
            emitted += 1
            self._maybe_finish(req, tok)
        self.tokens_generated += emitted
        if self.metrics:
            self.metrics.tokens_out.incr(emitted)
            self.metrics.decode_step.add(time.monotonic() - t0)
        return emitted

    def _maybe_finish(self, req: GenRequest, tok: int) -> None:
        sp = req.sampling
        if len(req.out_tokens) >= sp.max_new_tokens or \
                (sp.stop_token is not None and tok == sp.stop_token):
            self._release_slot(req)
            req._finish(FINISHED)

    def _publish_metrics(self) -> None:
        if not self.metrics:
            return
        m = self.metrics
        m.queue_depth.set(len(self._pending))
        m.batch_occupancy.set(self.num_active)
        used = self.pool.num_usable - self.pool.num_free
        m.kv_blocks_in_use.set(used)
        m.kv_block_utilization.set(used / max(1, self.pool.num_usable))

    # --------------------------------------------------- replica lifecycle

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._run_loop,
                                        name="decode-engine", daemon=True)
        self._thread.start()

    def stop(self, drain: bool = False, timeout: float = 30.0) -> None:
        """``drain=True``: keep decoding until every queued and running
        request completes (graceful replica shutdown), then stop."""
        if drain and self._thread is not None:
            deadline = time.monotonic() + timeout
            while not self.idle and time.monotonic() < deadline:
                time.sleep(0.01)
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        # only touch slot/pool state under the scheduler lock — a step
        # still stuck in compilation past the join timeout must not race
        # a double-free of its KV pages; if the lock can't be had the
        # pages stay allocated (the process is going down anyway)
        locked = self._sched_lock.acquire(timeout=5.0)
        try:
            for req in list(self._pending) + \
                    [r for r in self._slots if r]:
                if not req.done.is_set():
                    if locked:
                        self._release_slot(req)
                    req._finish(FAILED, "engine stopped")
            self._pending.clear()
        finally:
            if locked:
                self._sched_lock.release()

    def _run_loop(self) -> None:
        while not self._stop.is_set():
            with self._cond:
                while self.idle and not self._stop.is_set():
                    self._cond.wait(0.05)
            if self._stop.is_set():
                return
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — fail requests, not
                # the thread: a poisoned request must not wedge the
                # replica with clients blocked on .done forever
                for req in [r for r in self._slots if r] + \
                        list(self._pending):
                    if req._slot is not None:
                        self._release_slot(req)
                    req._finish(FAILED, f"decode failed: {e}")
                self._pending.clear()

    # ------------------------------------------------------------- offline

    def generate(self, prompts: List[List[int]],
                 sampling: Optional[SamplingParams] = None,
                 ) -> List[List[int]]:
        """Offline batch API: submit everything, step until done."""
        reqs = [self.submit(p, sampling) for p in prompts]
        while not all(r.done.is_set() for r in reqs):
            self.step()
        return [r.wait(0) for r in reqs]
