"""Continuous-batching decode engine over ``models.decoder`` weights.

Design (TPU-first, same rules as the trainer):

- **Fixed shapes, compile once per shape.** ONE step function covers
  the whole lifetime of a replica: every row of the step is "one token
  at one position, scattered into and gathered through a block table" —
  the first ``max_batch`` rows are the running decode lanes, the last
  ``prefill_chunk`` rows are a chunk of some request's prompt. It
  compiles at exactly TWO shapes: decode-only (``[max_batch]`` rows —
  steady-state decode pays nothing for an idle chunk lane) and fused
  (``[max_batch + prefill_chunk]`` rows when a prompt chunk rides
  along). Prompts of any length, any admission order, and any sampling
  mix ride those two executables — no per-request retracing, ever.
  ``decode_compiles`` / ``prefill_compiles`` count the two shape
  families' traces so tests and the bench can assert exactly-once
  compilation of each.

- **Paged KV cache.** K/V live in a block pool of shape
  ``[L, num_blocks, block_size, Hkv, Dh]``; each running request owns a
  block table (list of pool indices). Each step scatters the new
  tokens' K/V into ``table[pos // bs], pos % bs`` and gathers each
  row's context back through its table — requests share one pool with
  no per-request padding waste (the vLLM PagedAttention layout,
  expressed as jnp scatter/gather so XLA keeps it fused). Block 0 is a
  write-off scratch page: inactive rows and chunk padding scatter
  there, so masking never needs dynamic shapes.

- **Prefix-reuse KV cache.** The pool is refcounted and a radix index
  (block-granular trie keyed by token chunks) remembers fully-filled
  prompt blocks after prefill. A new request whose token prefix walks a
  cached path maps those blocks into its table (incref — shared,
  read-only: full blocks are never rewritten, so sharing needs no copy)
  and prefills only the tail; at least the last prompt token is always
  recomputed so the first output token has fresh logits. Blocks whose
  refcount drops to zero stay resident as cache and are evicted LRU
  (leaves first) when the pool runs dry — eviction composes with the
  recompute-preemption path: evict cold cache first, preempt the
  youngest request only when the cache is already dry.

- **Tiered fleet-wide cache.** The pool + radix moved into
  ``serving/kvstore`` and grew two cold tiers behind them: zero-ref
  blocks demote to a host-RAM ring (``serving.kv.host.bytes``) when the
  HBM tier evicts them, and hot shared prefixes persist as blocks on
  the DataNodes (``serving.kv.dfs.enable``) via the DFS write pipeline
  so ANY replica — including one that just restarted — maps them back
  with hedged reads instead of re-prefilling. A radix miss at admission
  consults host, then DFS, before falling back to prefill; promotions
  ride fixed-shape jitted page movers (no new compiles). See
  ``kvstore/tiered.py`` for the policy.

- **Chunked prefill, fused into the step.** A prompt is prefilled
  ``prefill_chunk`` tokens per engine step in the SAME compiled step
  that advances every running decode — a long prompt can no longer
  head-of-line-block the batch for a whole monolithic prefill call, so
  admitted requests keep streaming while a new prompt fills in.

- **Continuous batching.** New requests are admitted at any step
  boundary into free slots (their prefill chunks interleave with
  running decodes); finished requests free their slot and decref their
  blocks immediately. When pool + cache run dry the youngest request is
  preempted — its refs drop and it re-queues for recompute-style
  re-admission (warm: its own prompt blocks usually survive as cache).

- **Sharding.** Pass a ``MeshPlan`` (tp only) and the engine places the
  weights with ``parallel.mesh.param_specs`` and the KV pool with heads
  sharded over ``tp``; jit's SPMD partitioner inserts the decode
  collectives. Under ``JAX_PLATFORMS=cpu`` the same code runs on the
  virtual device mesh (tests) or a single device.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from hadoop_tpu.models.config import ModelConfig
from hadoop_tpu.models.decoder import _norm, head_matrix
from hadoop_tpu.ops import gelu, rope_frequencies, swiglu
from hadoop_tpu.ops.attention import _repeat_kv
# BlockPool/PrefixCache live in the kvstore package now (the tiered
# fleet-wide cache); re-exported here so `from serving.engine import
# BlockPool` keeps working for every existing consumer
from hadoop_tpu.serving.kvstore import (BlockPool, PrefixCache,
                                        TieredKVCache)
from hadoop_tpu.tracing.tracer import global_tracer

_NEG_INF = -1e30


# fixed-shape page movers for the cold tiers: one trace each for the
# replica's lifetime (the block index is a traced scalar, the payload
# shape is pinned by the engine config), shared across engine instances
# through jit's module-level cache — tier promotions and demotions ride
# these, never a fresh compile
def _inject_impl(kp, vp, blk, k, v):
    return kp.at[:, blk].set(k), vp.at[:, blk].set(v)


def _extract_impl(kp, vp, blk):
    return kp[:, blk], vp[:, blk]


_INJECT = jax.jit(_inject_impl, donate_argnums=(0, 1))
_EXTRACT = jax.jit(_extract_impl)


# --------------------------------------------------------------- requests

@dataclass
class SamplingParams:
    """Per-request decode controls. ``temperature <= 0`` is greedy;
    ``top_k <= 0`` disables the top-k filter."""
    max_new_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0
    stop_token: Optional[int] = None


_req_ids = itertools.count(1)

QUEUED, RUNNING, FINISHED, FAILED = "QUEUED", "RUNNING", "FINISHED", "FAILED"


@dataclass
class GenRequest:
    """One generation request. Tokens stream into ``tokens_out`` (a
    Queue terminated by ``None``); ``done`` fires at completion."""
    prompt: List[int]
    sampling: SamplingParams
    id: int = field(default_factory=lambda: next(_req_ids))
    state: str = QUEUED
    out_tokens: List[int] = field(default_factory=list)
    tokens_out: "queue.Queue" = field(default_factory=queue.Queue)
    done: threading.Event = field(default_factory=threading.Event)
    error: Optional[str] = None
    submitted_at: float = field(default_factory=time.monotonic)
    first_token_at: Optional[float] = None
    preemptions: int = 0
    prefix_tokens_reused: int = 0     # cached tokens mapped at admission
    # trace context of the request's door span: engine-side spans
    # (admit/preempt/first-token) run on the scheduler thread where no
    # contextvar survives, so the context rides the request itself
    trace_ctx: Optional[Any] = None
    # engine-private placement
    _slot: Optional[int] = None
    _blocks: List[int] = field(default_factory=list)
    _shared_blocks: int = 0           # leading blocks mapped from cache
    _ctx: List[int] = field(default_factory=list)
    _prefill_pos: Optional[int] = None  # next position to prefill
    _admit_seq: int = 0

    def _deliver(self, token: int) -> None:
        if self.first_token_at is None:
            self.first_token_at = time.monotonic()
        self.out_tokens.append(token)
        self.tokens_out.put(token)

    def _finish(self, state: str = FINISHED, error: str = None) -> None:
        self.state = state
        self.error = error
        self.tokens_out.put(None)
        self.done.set()

    def wait(self, timeout: Optional[float] = None) -> List[int]:
        if not self.done.wait(timeout):
            raise TimeoutError(f"request {self.id} not done")
        if self.state == FAILED:
            raise RuntimeError(self.error or "generation failed")
        return list(self.out_tokens)


# ----------------------------------------------------------------- engine
# (_norm and head_matrix come from models.decoder — the engine must
# apply EXACTLY the trained model's norm/head rules or served logits
# silently diverge from training)

def _rope_at(x, cos, sin, pos):
    """Rotate one token per row: x [T, H, Dh], pos [T]."""
    c = cos[pos][:, None, :]
    s = sin[pos][:, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = jnp.split(xf, 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
    return out.astype(x.dtype)


def _sample(logits, temps, topks, key):
    """logits [T, V] float32; per-row temperature/top-k; greedy when
    temperature <= 0 (the fused decode+sampling step of arxiv
    2502.17728 — sampling stays inside the compiled program so no
    [T, V] logits tensor crosses to the host)."""
    v = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    srt = jnp.sort(logits, axis=-1)                       # ascending
    kidx = jnp.clip(v - topks, 0, v - 1)
    kth = jnp.take_along_axis(srt, kidx[:, None], axis=1)[:, 0]
    use_topk = (topks > 0)[:, None]
    masked = jnp.where(use_topk & (logits < kth[:, None]), _NEG_INF, logits)
    scaled = masked / jnp.maximum(temps, 1e-6)[:, None]
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temps <= 0, greedy, sampled)


class DecodeEngine:
    """Continuous-batching decode over a fixed slot batch and a paged KV
    pool, with prefix reuse and step-fused chunked prefill. Drive it
    either with the background scheduler thread
    (``start``/``submit``/``stop`` — the serving replica) or by calling
    ``step()`` directly (tests, offline bench)."""

    def __init__(self, params, cfg: ModelConfig, *,
                 max_batch: int = 4, block_size: int = 8,
                 num_blocks: Optional[int] = None,
                 max_context: Optional[int] = None,
                 prefill_chunk: int = 16,
                 prefix_cache: bool = True,
                 kv_host_bytes: int = 0,
                 kv_store_fs=None, kv_store_dir: str = "/kvcache",
                 kv_dfs_min_refs: int = 1, kv_codec: str = "raw",
                 plan=None, metrics=None, tracer=None):
        if cfg.is_moe:
            raise NotImplementedError("serving MoE checkpoints is not "
                                      "wired up yet (dense decoders only)")
        self.cfg = cfg
        self.max_batch = max_batch
        self.block_size = block_size
        self.prefill_chunk = max(1, int(prefill_chunk))
        self.max_context = min(max_context or cfg.max_seq, cfg.max_seq)
        self.blocks_per_seq = -(-self.max_context // block_size)
        self.s_max = self.blocks_per_seq * block_size
        if self.s_max > cfg.max_seq:
            # never round past the rope/pos-embed tables: positions
            # beyond max_seq would silently clamp (wrong logits)
            self.blocks_per_seq = cfg.max_seq // block_size
            if self.blocks_per_seq == 0:
                raise ValueError(f"block_size {block_size} exceeds the "
                                 f"model's max_seq {cfg.max_seq}")
            self.s_max = self.blocks_per_seq * block_size
        if num_blocks is None:
            num_blocks = max_batch * self.blocks_per_seq + 1
        self.pool = BlockPool(num_blocks, block_size)
        self.metrics = metrics
        self.tracer = tracer or global_tracer()
        # the tier manager owns the radix index and the cold tiers;
        # the engine stays the device owner (extract/inject below)
        self.kvstore = TieredKVCache(
            self.pool, layers=cfg.n_layers, kv_heads=cfg.n_kv_heads,
            head_dim=cfg.head_dim, dtype=cfg.jax_dtype,
            enabled=prefix_cache, host_bytes=kv_host_bytes,
            fs=kv_store_fs, dfs_dir=kv_store_dir,
            dfs_min_refs=kv_dfs_min_refs, codec=kv_codec,
            metrics=metrics, tracer=self.tracer,
            extract=self._extract_block)
        self.prefix_cache = self.kvstore.radix

        self._mesh = None
        if plan is not None:
            from hadoop_tpu.parallel.mesh import (make_mesh, param_specs,
                                                  shard_params)
            if plan.pp != 1 or plan.sp != 1 or plan.ep != 1:
                raise ValueError("serving shards over tp (and dp) only; "
                                 f"got plan={plan}")
            self._mesh = make_mesh(plan)
            params = shard_params(params, self._mesh, param_specs(cfg, plan))
        self.params = params

        L, hkv, dh = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
        pool_shape = (L, num_blocks, block_size, hkv, dh)
        self._kp = jnp.zeros(pool_shape, cfg.jax_dtype)
        self._vp = jnp.zeros(pool_shape, cfg.jax_dtype)
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            kv_sharding = NamedSharding(
                self._mesh, P(None, None, None, "tp", None))
            self._kp = jax.device_put(self._kp, kv_sharding)
            self._vp = jax.device_put(self._vp, kv_sharding)

        # host-side slot state (fixed shapes, rebuilt into jnp per step)
        self._tables = np.zeros((max_batch, self.blocks_per_seq), np.int32)
        self._seq_lens = np.zeros((max_batch,), np.int32)
        self._last_tokens = np.zeros((max_batch,), np.int32)
        self._active = np.zeros((max_batch,), bool)
        self._temps = np.zeros((max_batch,), np.float32)
        self._topks = np.zeros((max_batch,), np.int32)
        self._slots: List[Optional[GenRequest]] = [None] * max_batch

        self._pending: deque = deque()  # guarded-by: _cond
        self._admit_counter = itertools.count()
        self._cond = threading.Condition()
        self._sched_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._step_seed = itertools.count()
        self.steps = 0
        self.tokens_generated = 0
        self.occupancy_log: List[int] = []      # active slots per step
        self._fused_compiles = 0                # [B + chunk]-row traces
        self._decode_only_compiles = 0          # [B]-row traces
        self._chunk_fill = 0                    # chunk rows used last step
        # prefix-cache lifetime stats (cold-start zeros)
        self.prefix_tokens_seen = 0
        self.prefix_tokens_matched = 0
        self.prefix_evictions = 0
        self.prefix_inserted_blocks = 0
        self._step_fn = jax.jit(self._step_impl, donate_argnums=(1, 2))

    @property
    def decode_compiles(self) -> int:
        """Traces of the decode-only shape of the step ([B] rows —
        dispatched when nothing is prefilling, so pure decode never
        pays for idle chunk rows). At most 1 or shapes are retracing."""
        return self._decode_only_compiles

    @property
    def prefill_compiles(self) -> int:
        """Traces of the fused shape of the step ([B + chunk] rows —
        dispatched when a prompt chunk rides along). At most 1."""
        return self._fused_compiles

    # ------------------------------------------------- tier page movers

    def _extract_block(self, blk: int):
        """One page's (K, V) payload to host numpy — the demotion /
        persistence copy. Fixed-shape jit, compiled once per layout."""
        k, v = _EXTRACT(self._kp, self._vp, jnp.int32(blk))
        return np.asarray(k), np.asarray(v)

    def _inject_block(self, blk: int, k, v) -> None:
        """Scatter a cold-tier payload into pool page ``blk`` (donated
        buffers — no pool-sized copy, no new compile)."""
        self._kp, self._vp = _INJECT(
            self._kp, self._vp, jnp.int32(blk),
            jnp.asarray(k, self._kp.dtype),
            jnp.asarray(v, self._vp.dtype))

    # ----------------------------------------------------- compiled body

    def _rope_tables(self):
        if not self.cfg.use_rope:
            return None, None
        return rope_frequencies(self.cfg.head_dim, self.cfg.max_seq,
                                self.cfg.rope_theta)

    def _mlp(self, x, lp):
        if self.cfg.use_swiglu:
            return swiglu(x @ lp["w_gate"], x @ lp["w_up"]) @ lp["w_down"]
        return gelu(x @ lp["w_in"] + lp["b_in"]) @ lp["w_out"] + lp["b_out"]

    def _step_impl(self, params, kp, vp, tables, positions, tokens,
                   active, temps, topks, key):
        """The ONE compiled function: every row is one token at one
        position — rows [0, max_batch) are the decode lanes (position =
        tokens already cached), rows [max_batch, max_batch +
        prefill_chunk) are consecutive positions of one request's
        prompt chunk (they share that request's block table row).
        Scatter-all-then-gather makes earlier chunk tokens visible to
        later ones within the same step; the causal mask
        ``kpos <= position`` does the rest.

        Compiled at exactly TWO shapes for the replica's lifetime:
        ``[max_batch]`` rows (decode-only — dispatched when nothing is
        prefilling, so steady-state decode pays nothing for the chunk
        lane) and ``[max_batch + prefill_chunk]`` rows (a prompt chunk
        riding along). Any further trace is a retracing bug the
        counters expose."""
        cfg = self.cfg
        t = tables.shape[0]
        # python side effect at trace time only: shape-family counters
        if t == self.max_batch:
            self._decode_only_compiles += 1
        else:
            self._fused_compiles += 1
        hq, hkv, dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
        cos, sin = self._rope_tables()
        h = params["embed"][tokens]
        if not cfg.use_rope:
            h = h + params["pos_embed"][
                jnp.clip(positions, 0, cfg.max_seq - 1)]
        pos = positions
        blk = jnp.take_along_axis(
            tables, (pos // self.block_size)[:, None], axis=1)[:, 0]
        blk = jnp.where(active, blk, BlockPool.SCRATCH)
        off = pos % self.block_size
        scale = 1.0 / (dh ** 0.5)
        kpos = jnp.arange(self.s_max)

        def layer(h, xs):
            lp, kc, vc = xs
            x = _norm(h, lp["attn_norm_w"], lp.get("attn_norm_b"), cfg)
            q = (x @ lp["wq"]).reshape(t, hq, dh)
            k = (x @ lp["wk"]).reshape(t, hkv, dh)
            v = (x @ lp["wv"]).reshape(t, hkv, dh)
            if cfg.use_rope:
                q = _rope_at(q, cos, sin, pos)
                k = _rope_at(k, cos, sin, pos)
            kc = kc.at[blk, off].set(k.astype(kc.dtype))
            vc = vc.at[blk, off].set(v.astype(vc.dtype))
            # paged gather: each row pulls its own pages back into a
            # contiguous [S_max] context view through the block table
            kctx = kc[tables].reshape(t, self.s_max, hkv, dh)
            vctx = vc[tables].reshape(t, self.s_max, hkv, dh)
            kr = _repeat_kv(kctx, hq // hkv)
            vr = _repeat_kv(vctx, hq // hkv)
            logits = jnp.einsum(
                "bhd,bkhd->bhk", q, kr,
                preferred_element_type=jnp.float32) * scale
            mask = kpos[None, :] <= pos[:, None]
            logits = jnp.where(mask[:, None, :], logits, _NEG_INF)
            probs = jax.nn.softmax(logits, axis=-1).astype(vr.dtype)
            attn = jnp.einsum("bhk,bkhd->bhd", probs, vr)
            h2 = h + (attn.reshape(t, hq * dh) @ lp["wo"]).astype(h.dtype)
            x2 = _norm(h2, lp["mlp_norm_w"], lp.get("mlp_norm_b"), cfg)
            return h2 + self._mlp(x2, lp).astype(h.dtype), (kc, vc)

        h, (kp, vp) = jax.lax.scan(layer, h, (params["layers"], kp, vp))
        h = _norm(h, params["final_norm_w"], params.get("final_norm_b"),
                  cfg)
        logits = (h @ head_matrix(params, cfg, h.dtype)).astype(
            jnp.float32)
        return kp, vp, _sample(logits, temps, topks, key)

    # -------------------------------------------------------- public face

    def submit(self, prompt: List[int],
               sampling: Optional[SamplingParams] = None,
               trace_ctx=None) -> GenRequest:
        sampling = sampling or SamplingParams()
        if not prompt:
            raise ValueError("empty prompt")
        if sampling.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1 (prefill "
                             "always emits the first token)")
        if len(prompt) + sampling.max_new_tokens > self.s_max:
            raise ValueError(
                f"prompt({len(prompt)}) + max_new({sampling.max_new_tokens})"
                f" exceeds engine max_context {self.s_max}")
        # fail fast on requests the pool can NEVER satisfy — parking
        # them in the admission queue would wedge the queue forever
        # (prefix hits could shrink the footprint, but cache contents
        # are transient and must not admit what can't run cold)
        pages = -(-(len(prompt) + sampling.max_new_tokens)
                  // self.block_size)
        if pages > self.pool.num_usable:
            raise ValueError(
                f"request needs {pages} KV pages but the pool holds only "
                f"{self.pool.num_usable} — it could never run alone")
        from hadoop_tpu.tracing.tracer import current_context
        req = GenRequest(prompt=list(prompt), sampling=sampling,
                         trace_ctx=trace_ctx or current_context())
        with self._cond:
            self._pending.append(req)
            depth = len(self._pending)
            self._cond.notify_all()
        if self.metrics:
            self.metrics.requests.incr()
            self.metrics.queue_depth.set(depth)
        return req

    @property
    def num_active(self) -> int:
        return int(self._active.sum())

    @property
    def num_prefilling(self) -> int:
        return sum(1 for r in self._slots
                   if r is not None and r._prefill_pos is not None)

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._pending)

    @property
    def idle(self) -> bool:
        with self._cond:
            has_pending = bool(self._pending)
        return not has_pending and all(r is None for r in self._slots)

    def cache_stats(self) -> Dict[str, Any]:
        """Prefix-cache + chunked-prefill observability (health, bench)."""
        seen = self.prefix_tokens_seen
        return {
            "enabled": self.prefix_cache is not None,
            "cached_blocks": len(self.prefix_cache)
                             if self.prefix_cache is not None else 0,
            "tokens_seen": seen,
            "tokens_matched": self.prefix_tokens_matched,
            "hit_rate": (self.prefix_tokens_matched / seen) if seen
                        else 0.0,
            "evictions": self.prefix_evictions,
            "inserted_blocks": self.prefix_inserted_blocks,
            "prefill_chunk": self.prefill_chunk,
            # per-tier traffic: HBM radix hits vs host-ring and DFS
            # recoveries, demotions/promotions/persists
            "tiers": self.kvstore.stats(),
        }

    # ------------------------------------------------------ the scheduler

    def step(self) -> int:
        """One scheduler iteration: admit waiting requests into free
        slots (mapping any cached prefix), ensure every decoding
        request has a page for this step's token, run the fused
        decode+prefill-chunk step, retire finished requests. Returns
        the number of tokens emitted."""
        with self._sched_lock:
            self._admit()
            self._ensure_blocks()
            emitted = self._run_step()
            self._publish_metrics()
            return emitted

    def _admit(self) -> None:
        while True:
            with self._cond:
                if not self._pending:
                    return
                req = self._pending[0]
            slot = next((i for i, r in enumerate(self._slots)
                         if r is None), None)
            if slot is None:
                return
            # prompt plus already-generated tokens (preempted requests
            # resume by recompute — often warm, off their own cached
            # prompt blocks); the first decode step after prefill needs
            # one more page slot for its token
            ctx = req.prompt + req.out_tokens
            shared: List[int] = []
            nodes = []
            cold = []
            limit = 0
            if self.prefix_cache is not None:
                # cap the match below the full context: the last token
                # must always be prefilled so its logits exist to
                # sample the first output token from
                limit = (len(ctx) - 1) // self.block_size
                nodes = self.prefix_cache.match_nodes(ctx)[:limit]
                if nodes:
                    shared = [n.block for n in nodes]
                    # pin before any eviction this admission might do
                    self.pool.incref(shared)
            need = -(-(len(ctx) + 1) // self.block_size) - len(shared)
            private = self._try_alloc(need)
            if private is None:
                # running requests outrank waiting ones (preemption only
                # keeps the running set going, never feeds admission) —
                # wait for retirements to return pages. The cold-tier
                # walk hasn't run yet, so a saturated pool never burns
                # DataNode reads on an admission it can't complete
                if shared:
                    # unpin; zero-ref pages stay resident in the index
                    self.pool.decref(shared)
                return
            if self.prefix_cache is not None:
                # a radix miss consults host RAM, then the DFS store,
                # for the next chunks of the chain — only the still-
                # uncached tail falls back to prefill. The matched
                # node's chain digest seeds the walk, so nothing is
                # rehashed from the root
                cold = self.kvstore.fetch_cold(
                    ctx, len(nodes), limit, parent_ctx=req.trace_ctx,
                    start_digest=nodes[-1].digest if nodes else None)
            with self._cond:
                self._pending.popleft()
            if cold:
                # cold payloads land in the first of the freshly
                # allocated pages (ref 1, owned by this request) and
                # re-register in the radix so siblings share them from
                # HBM; a mid-admission eviction above could only have
                # taken OTHER zero-ref pages — the shared span is
                # pinned and these pages are already allocated
                cold_pages = private[:len(cold)]
                for page, hit in zip(cold_pages, cold):
                    self._inject_block(page, hit.k, hit.v)
                span = shared + cold_pages
                self.prefix_cache.insert(
                    ctx[:len(span) * self.block_size], span)
                self.kvstore.mark_promoted(cold, cold_pages)
            self.kvstore.note_match(nodes, parent_ctx=req.trace_ctx,
                                    count=req.preemptions == 0)
            reused = (len(shared) + len(cold)) * self.block_size
            req.prefix_tokens_reused = reused
            if req.preemptions == 0:
                # hit-rate counts cross-request reuse only: a preempted
                # request re-matching its OWN surviving blocks is warm
                # resume, and counting it would inflate the gauge
                # exactly when the pool is thrashing
                self.prefix_tokens_seen += len(ctx)
                self.prefix_tokens_matched += reused
                if self.metrics and reused:
                    self.metrics.prefix_tokens_reused.incr(reused)
            self._place(req, slot, shared + private, ctx,
                        len(shared) + len(cold))

    def _try_alloc(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` pages, evicting LRU zero-ref cached blocks to
        make room before giving up (cold cache yields to live work).
        Victims demote to the host-RAM ring on their way out (the
        ``on_evict`` hook copies the payload while the page is still
        valid), so "evicted" means "one memcpy away", not "gone"."""
        if n <= 0:
            return []
        got = self.pool.alloc(n)
        if got is not None or self.prefix_cache is None:
            return got
        evicted = self.prefix_cache.evict(n - self.pool.num_free,
                                          self.pool.refcount,
                                          on_evict=self.kvstore.demote)
        if not evicted:
            return None
        self.pool.free(evicted)
        self.prefix_evictions += len(evicted)
        if self.metrics:
            self.metrics.prefix_cache_evictions.incr(len(evicted))
        return self.pool.alloc(n)

    def _place(self, req: GenRequest, slot: int, blocks: List[int],
               ctx: List[int], shared_blocks: int) -> None:
        req.state = RUNNING
        req._slot = slot
        req._blocks = blocks
        req._shared_blocks = shared_blocks
        req._ctx = ctx
        req._prefill_pos = shared_blocks * self.block_size
        req._admit_seq = next(self._admit_counter)
        self._slots[slot] = req
        row = np.zeros((self.blocks_per_seq,), np.int32)
        row[:len(blocks)] = blocks
        self._tables[slot] = row
        self._seq_lens[slot] = 0
        self._active[slot] = False
        self._last_tokens[slot] = 0
        sp = self.tracer.span("serving.admit", parent=req.trace_ctx)
        sp.add_kv("request", str(req.id))
        sp.add_kv("prompt_tokens", str(len(ctx)))
        sp.add_kv("prefix_tokens_reused", str(req.prefix_tokens_reused))
        sp.finish()

    def _ensure_blocks(self) -> None:
        """Every decoding slot must own the page its next token lands
        in; allocate at block boundaries (evicting cold cache first),
        preempting the youngest request when everything is dry."""
        for slot, req in enumerate(self._slots):
            if req is None or req._prefill_pos is not None:
                continue     # prefilling slots pre-allocated at admit
            # this step scatters K/V at position seq_lens[slot]; that
            # page must be owned or the write would land in scratch and
            # silently corrupt the request's context
            need = int(self._seq_lens[slot]) // self.block_size + 1
            while req._slot is not None and len(req._blocks) < need:
                got = self._try_alloc(1)
                if got is not None:
                    self._tables[slot][len(req._blocks)] = got[0]
                    req._blocks.extend(got)
                    continue
                # pool and cache dry: evict the youngest running
                # request — which may be this one (then its slot
                # empties and the loop ends; it resumes by recompute
                # once pages free up). Preempting a sharer only drops
                # its refs — pages still mapped by a sibling survive.
                victim = max((r for r in self._slots if r is not None),
                             key=lambda r: r._admit_seq)
                self._preempt(victim)

    def _preempt(self, victim: GenRequest) -> None:
        """vLLM-style recompute preemption: drop the request's page
        refs and requeue it at the front; re-admission prefills prompt
        + tokens generated so far (warm when its prompt blocks survive
        in the prefix index)."""
        self._release_slot(victim)
        victim.state = QUEUED
        victim.preemptions += 1
        with self._cond:
            self._pending.appendleft(victim)
        if self.metrics:
            self.metrics.preemptions.incr()
        psp = self.tracer.span("serving.preempt", parent=victim.trace_ctx)
        psp.add_kv("request", str(victim.id))
        psp.finish()

    def _release_slot(self, req: GenRequest) -> None:
        slot = req._slot
        if slot is None:
            return
        released = self.pool.decref(req._blocks)
        if self.prefix_cache is not None:
            # zero-ref pages registered in the radix index stay
            # resident as reusable cache; the rest return to the pool
            drop = [b for b in released
                    if not self.prefix_cache.contains_block(b)]
        else:
            drop = released
        self.pool.free(drop)
        req._blocks = []
        req._shared_blocks = 0
        req._ctx = []
        req._prefill_pos = None
        req._slot = None
        self._slots[slot] = None
        self._active[slot] = False
        self._seq_lens[slot] = 0
        self._tables[slot] = 0
        self._last_tokens[slot] = 0

    def _run_step(self) -> int:
        # oldest still-prefilling request gets this step's chunk budget
        pre: Optional[GenRequest] = None
        for r in self._slots:
            if r is not None and r._prefill_pos is not None:
                if pre is None or r._admit_seq < pre._admit_seq:
                    pre = r
        if pre is None and not self._active.any():
            return 0
        b, c = self.max_batch, self.prefill_chunk
        n_valid = 0
        if pre is None:
            # decode-only shape: no idle chunk rows to pay for
            tables, positions = self._tables, self._seq_lens
            tokens, active = self._last_tokens, self._active
            temps, topks = self._temps, self._topks
        else:
            c_tokens = np.zeros((c,), np.int32)
            c_pos = np.zeros((c,), np.int32)
            c_active = np.zeros((c,), bool)
            c_tables = np.zeros((c, self.blocks_per_seq), np.int32)
            start = pre._prefill_pos
            n_valid = min(c, len(pre._ctx) - start)
            c_tokens[:n_valid] = pre._ctx[start:start + n_valid]
            c_pos[:n_valid] = np.arange(start, start + n_valid)
            c_active[:n_valid] = True
            c_tables[:] = self._tables[pre._slot]
            tables = np.concatenate([self._tables, c_tables], axis=0)
            positions = np.concatenate([self._seq_lens, c_pos])
            tokens = np.concatenate([self._last_tokens, c_tokens])
            active = np.concatenate([self._active, c_active])
            temps = np.concatenate([
                self._temps,
                np.full((c,), pre.sampling.temperature, np.float32)])
            topks = np.concatenate([
                self._topks,
                np.full((c,), pre.sampling.top_k, np.int32)])
        t0 = time.monotonic()
        key = jax.random.PRNGKey(next(self._step_seed))
        self._kp, self._vp, sampled = self._step_fn(
            self.params, self._kp, self._vp, jnp.asarray(tables),
            jnp.asarray(positions), jnp.asarray(tokens),
            jnp.asarray(active), jnp.asarray(temps),
            jnp.asarray(topks), key)
        sampled = np.asarray(sampled)
        self.steps += 1
        self._chunk_fill = n_valid
        emitted = 0
        self.occupancy_log.append(self.num_active)
        if len(self.occupancy_log) > 100_000:
            del self.occupancy_log[:50_000]
        for slot, req in enumerate(self._slots):
            if req is None or not self._active[slot]:
                continue
            tok = int(sampled[slot])
            self._seq_lens[slot] += 1
            self._last_tokens[slot] = tok
            req._deliver(tok)
            emitted += 1
            self._maybe_finish(req, tok)
        if pre is not None:
            pre._prefill_pos += n_valid
            if pre._prefill_pos >= len(pre._ctx):
                # the chunk's last valid row sat at the final context
                # position — its sample is the first output token
                self._finish_prefill(pre, int(sampled[b + n_valid - 1]))
                emitted += 1
        self.tokens_generated += emitted
        if self.metrics:
            self.metrics.tokens_out.incr(emitted)
            step_s = time.monotonic() - t0
            self.metrics.decode_step.add(step_s)
            self.metrics.decode_step_hist.add(step_s)
        return emitted

    def _finish_prefill(self, req: GenRequest, tok: int) -> None:
        """Prompt fully cached: flip the slot to a decode lane, publish
        the fully-filled prompt blocks into the prefix index, deliver
        the first token."""
        slot = req._slot
        ctx_len = len(req._ctx)
        req._prefill_pos = None
        self._seq_lens[slot] = ctx_len
        self._temps[slot] = req.sampling.temperature
        self._topks[slot] = req.sampling.top_k
        self._last_tokens[slot] = tok
        self._active[slot] = True
        if self.prefix_cache is not None:
            full = ctx_len // self.block_size
            if full:
                self.prefix_inserted_blocks += self.prefix_cache.insert(
                    req._ctx[:full * self.block_size], req._blocks[:full])
        first = req.first_token_at is None
        req._deliver(tok)
        if first:
            ttft = req.first_token_at - req.submitted_at
            if self.metrics:
                self.metrics.ttft.add(ttft)
                self.metrics.ttft_hist.add(ttft)
            fsp = self.tracer.span("serving.first_token",
                                   parent=req.trace_ctx)
            fsp.add_kv("request", str(req.id))
            fsp.add_kv("ttft_s", f"{ttft:.6f}")
            fsp.finish()
        self._maybe_finish(req, tok)

    def _maybe_finish(self, req: GenRequest, tok: int) -> None:
        sp = req.sampling
        if len(req.out_tokens) >= sp.max_new_tokens or \
                (sp.stop_token is not None and tok == sp.stop_token):
            self._release_slot(req)
            req._finish(FINISHED)

    def _publish_metrics(self) -> None:
        if not self.metrics:
            return
        m = self.metrics
        with self._cond:
            depth = len(self._pending)
        m.queue_depth.set(depth)
        m.batch_occupancy.set(self.num_active)
        used = self.pool.num_usable - self.pool.num_free
        m.kv_blocks_in_use.set(used)
        m.kv_block_utilization.set(used / max(1, self.pool.num_usable))
        stats = self.cache_stats()
        m.prefix_cache_hit_rate.set(round(stats["hit_rate"], 4))
        m.prefix_cached_blocks.set(stats["cached_blocks"])
        m.chunk_occupancy.set(self._chunk_fill / self.prefill_chunk)
        m.prefill_backlog.set(sum(
            len(r._ctx) - r._prefill_pos for r in self._slots
            if r is not None and r._prefill_pos is not None))

    # --------------------------------------------------- replica lifecycle

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._run_loop,
                                        name="decode-engine", daemon=True)
        self._thread.start()

    def stop(self, drain: bool = False, timeout: float = 30.0) -> None:
        """``drain=True``: keep decoding until every queued and running
        request completes (graceful replica shutdown), then stop."""
        if drain and self._thread is not None:
            deadline = time.monotonic() + timeout
            while not self.idle and time.monotonic() < deadline:
                time.sleep(0.01)
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        # only touch slot/pool state under the scheduler lock — a step
        # still stuck in compilation past the join timeout must not race
        # a double-free of its KV pages; if the lock can't be had the
        # pages stay allocated (the process is going down anyway)
        locked = self._sched_lock.acquire(timeout=5.0)
        try:
            for req in [r for r in self._slots if r]:
                if not req.done.is_set():
                    if locked:
                        self._release_slot(req)
                    req._finish(FAILED, "engine stopped")
            # drain, don't snapshot-and-clear: a submit() racing this
            # shutdown must fail its request, not vanish from the queue
            while True:
                with self._cond:
                    if not self._pending:
                        break
                    req = self._pending.popleft()
                if not req.done.is_set():
                    req._finish(FAILED, "engine stopped")
        finally:
            if locked:
                self._sched_lock.release()
        self.kvstore.close()

    # ------------------------------------------------ disaggregation face

    def prefill_to_store(self, prompt: List[int],
                         timeout: float = 60.0) -> int:
        """Prefill ``prompt`` and force-persist its full-block KV span
        to the DFS tier — the prefill half of prefill/decode
        disaggregation. The KV ships over the DataTransferProtocol via
        the DFS write pipeline; the decode replica's admission maps it
        back with hedged reads and prefills only the tail. Returns the
        number of tokens actually durable on return — re-verified
        against the radix after the flush, so a DataNode refusal can
        never be reported as a persisted handoff. Raises when nothing
        went durable (the router's signal to decode cold)."""
        if not self.kvstore.dfs_enabled:
            raise ValueError("DFS KV tier disabled (set "
                             "serving.kv.dfs.enable for prefill-role "
                             "replicas)")
        req = self.submit(prompt, SamplingParams(max_new_tokens=1))
        if self._thread is None:
            # offline/test mode: no scheduler thread, drive it here
            deadline = time.monotonic() + timeout
            while not req.done.is_set():
                if time.monotonic() > deadline:
                    raise TimeoutError(f"prefill {req.id} not done")
                self.step()
        req.wait(timeout)
        with self._sched_lock:
            blocks = self.kvstore.persist_prefix(prompt,
                                                 parent_ctx=req.trace_ctx)
            # flush to THIS handoff's watermark, not the global queue
            # tail — other requests' min-refs persists keep arriving
            watermark = self.kvstore.persists_enqueued
        if not self.kvstore.flush(timeout, up_to=watermark):
            raise TimeoutError("DFS KV persist did not drain in "
                               f"{timeout}s")
        with self._sched_lock:
            durable = self.kvstore.persisted_span(prompt)
        if blocks and not durable:
            raise RuntimeError(
                f"handoff persist failed: 0/{blocks} blocks durable "
                "(DataNodes refusing writes?)")
        return durable * self.block_size

    def _run_loop(self) -> None:
        while not self._stop.is_set():
            with self._cond:
                while self.idle and not self._stop.is_set():
                    self._cond.wait(0.05)
            if self._stop.is_set():
                return
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — fail requests, not
                # the thread: a poisoned request must not wedge the
                # replica with clients blocked on .done forever. Slot
                # state only moves under the scheduler lock (a racing
                # stop() must not double-release the same pages), and
                # the queue drains via popleft — a submit() racing this
                # handler is left pending for the next loop iteration,
                # never silently dropped
                with self._sched_lock:
                    for req in [r for r in self._slots if r]:
                        self._release_slot(req)
                        req._finish(FAILED, f"decode failed: {e}")
                    while True:
                        with self._cond:
                            if not self._pending:
                                break
                            req = self._pending.popleft()
                        req._finish(FAILED, f"decode failed: {e}")

    # ------------------------------------------------------------- offline

    def generate(self, prompts: List[List[int]],
                 sampling: Optional[SamplingParams] = None,
                 ) -> List[List[int]]:
        """Offline batch API: submit everything, step until done."""
        reqs = [self.submit(p, sampling) for p in prompts]
        while not all(r.done.is_set() for r in reqs):
            self.step()
        return [r.wait(0) for r in reqs]
