"""Tiered fleet-wide KV cache: HBM radix → host-RAM ring → DFS store.

The storage half of the serving plane, extracted from the engine so the
cache outlives any one replica: ``BlockPool`` (refcounted HBM pages),
``PrefixCache`` (block-granular radix with prefix chain digests),
``HostTier`` (pinned numpy ring under a byte budget), ``DFSTier``
(blocks persisted through the DFS write pipeline, fetched with hedged
reads), and ``TieredKVCache`` (the demote/fetch/persist policy that
ties them together). ``serving/engine.py`` is a thin consumer.
"""

from hadoop_tpu.serving.kvstore.codec import (CODECS, decode_block,
                                              dequant_int8, encode_block,
                                              quant_int8)
from hadoop_tpu.serving.kvstore.dfstier import DFSTier
from hadoop_tpu.serving.kvstore.hosttier import HostTier
from hadoop_tpu.serving.kvstore.pool import BlockPool
from hadoop_tpu.serving.kvstore.radix import (PrefixCache, _RadixNode,
                                              chain_digest)
from hadoop_tpu.serving.kvstore.tiered import (CODEC_KEY, DFS_DIR_KEY,
                                               DFS_ENABLE_KEY,
                                               DFS_MIN_REFS_KEY,
                                               HOST_BYTES_KEY, ColdHit,
                                               TieredKVCache)

__all__ = [
    "BlockPool", "PrefixCache", "_RadixNode", "chain_digest",
    "HostTier", "DFSTier", "TieredKVCache", "ColdHit",
    "encode_block", "decode_block", "CODECS", "quant_int8",
    "dequant_int8",
    "HOST_BYTES_KEY", "DFS_ENABLE_KEY", "DFS_DIR_KEY",
    "DFS_MIN_REFS_KEY", "CODEC_KEY",
]
