"""Block codecs for the wire/DFS KV tiers.

A cached KV block leaving HBM for the DFS tier crosses the network
twice (write pipeline out, hedged read back), so comm volume is the
cost that decides how many prefixes the fleet can afford to share —
the bottleneck Flash Communication (arXiv:2412.04964) attacks with
low-bit quantization. Two codecs ship:

- ``raw``  — dtype bytes verbatim; demote/promote round-trips are
  bit-exact and the decoded tokens match a cold prefill exactly.
- ``int8`` — symmetric per-layer int8 with float32 scales (amax/127
  over each layer's ``[block, heads, dim]`` slab): ~2× (bf16) to ~4×
  (f32) smaller on the wire and on the DataNodes, decode is allclose
  rather than bit-exact.

The codec is a property of each stored block, not of the reader: the
file header records which codec wrote it, so a raw-configured replica
reads an int8 store (and vice versa) — mixed fleets stay compatible
during a codec rollout.

File layout: ``u32 BE header length || header JSON || k payload || v
payload``. The header pins shape and dtype; ``decode_block`` validates
both so a store written by an incompatible engine shape fails loudly
instead of silently corrupting a context.
"""

from __future__ import annotations

import json
import struct
from typing import Tuple

import numpy as np

CODECS = ("raw", "int8")
_MAGIC_VERSION = 1


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # bfloat16 and friends register through ml_dtypes, which numpy
        # cannot resolve from the string name alone
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def quant_int8(x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric per-layer int8: scales are float32 amax/127 over each
    layer's [block, heads, dim] slab (layer 0 of the array's axis 0).
    Shared by the file codec below and the host-RAM ring's resident
    form (hosttier.py) so one quantizer defines the int8 tier."""
    xf = np.asarray(x, np.float32)
    amax = np.max(np.abs(xf), axis=(1, 2, 3), keepdims=True)
    scales = np.maximum(amax, 1e-12) / 127.0
    q = np.clip(np.rint(xf / scales), -127, 127).astype(np.int8)
    return q, scales.reshape(-1).astype(np.float32)


def dequant_int8(q: np.ndarray, scales, dtype: np.dtype) -> np.ndarray:
    s = np.asarray(scales, np.float32).reshape(-1, 1, 1, 1)
    return (q.astype(np.float32) * s).astype(dtype)


def _quant_int8(x: np.ndarray) -> Tuple[np.ndarray, list]:
    q, scales = quant_int8(x)
    return q, [float(s) for s in scales]


def _dequant_int8(q: np.ndarray, scales: list, dtype: np.dtype
                  ) -> np.ndarray:
    return dequant_int8(q, scales, dtype)


def encode_block(k: np.ndarray, v: np.ndarray, codec: str = "raw"
                 ) -> bytes:
    """Serialize one block's (K, V) payload (shape [L, bs, Hkv, Dh])."""
    if codec not in CODECS:
        raise ValueError(f"unknown KV block codec {codec!r} "
                         f"(serving.kv.codec must be one of {CODECS})")
    header = {"v": _MAGIC_VERSION, "codec": codec,
              "dtype": str(np.dtype(k.dtype)), "shape": list(k.shape)}
    if codec == "raw":
        kb, vb = k.tobytes(), v.tobytes()
    else:
        kq, header["scales_k"] = _quant_int8(k)
        vq, header["scales_v"] = _quant_int8(v)
        kb, vb = kq.tobytes(), vq.tobytes()
    hj = json.dumps(header, separators=(",", ":")).encode()
    return struct.pack(">I", len(hj)) + hj + kb + vb


def decode_block(data: bytes, *, shape=None, dtype=None
                 ) -> Tuple[np.ndarray, np.ndarray, dict]:
    """Inverse of ``encode_block``; validates ``shape``/``dtype`` when
    the caller pins them (the tier manager always does — a mismatched
    payload must be a loud miss, never a silent context corruption)."""
    if len(data) < 4:
        raise ValueError("truncated KV block (no header length)")
    (hlen,) = struct.unpack(">I", data[:4])
    header = json.loads(data[4:4 + hlen].decode())
    if header.get("v") != _MAGIC_VERSION:
        raise ValueError(f"KV block version {header.get('v')!r} "
                         f"(expected {_MAGIC_VERSION})")
    hshape = tuple(header["shape"])
    hdtype = _np_dtype(header["dtype"])
    if shape is not None and hshape != tuple(shape):
        raise ValueError(f"KV block shape {hshape} != engine {shape}")
    if dtype is not None and hdtype != np.dtype(dtype):
        raise ValueError(f"KV block dtype {hdtype} != engine "
                         f"{np.dtype(dtype)}")
    n = int(np.prod(hshape))
    body = data[4 + hlen:]
    if header["codec"] == "raw":
        itemsize = hdtype.itemsize
        if len(body) != 2 * n * itemsize:
            raise ValueError("truncated raw KV block payload")
        k = np.frombuffer(body[:n * itemsize], hdtype).reshape(hshape)
        v = np.frombuffer(body[n * itemsize:], hdtype).reshape(hshape)
    elif header["codec"] == "int8":
        if len(body) != 2 * n:
            raise ValueError("truncated int8 KV block payload")
        kq = np.frombuffer(body[:n], np.int8).reshape(hshape)
        vq = np.frombuffer(body[n:], np.int8).reshape(hshape)
        k = _dequant_int8(kq, header["scales_k"], hdtype)
        v = _dequant_int8(vq, header["scales_v"], hdtype)
    else:
        raise ValueError(f"unknown KV block codec {header['codec']!r}")
    return k, v, header
