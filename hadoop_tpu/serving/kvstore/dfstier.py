"""DFS KV tier: hot shared prefixes persisted as blocks on the
DataNodes, mapped back by ANY replica.

The whole point of this tier is that it reuses the storage plane as-is:
a persisted KV block is an ordinary DFS file, so writes ride the
replicated write pipeline (client → DN → mirror over the
DataTransferProtocol) and fetches ride ``DFSInputStream`` — hedged
reads, CRC verification, the works. A replica restart loses HBM and
host RAM, but the DFS store survives, which is exactly the fleet-wide
hit-rate-under-churn property the per-replica cache could never have.
It is also the disaggregation channel: a prefill replica persists a
finished prompt's blocks here and the decode replica maps them instead
of re-prefilling.

Layout: ``<base>/<digest[:2]>/<digest>.kvb`` (two-level fan-out so one
directory never holds the whole fleet's prefixes). Writes go to a
unique ``.tmp`` sibling and rename into place — a reader can never see
a half-written block, and when two replicas race to persist the same
prefix the loser's rename fails against the existing file and its tmp
is simply deleted (content is identical by construction: the digest IS
the prefix).
"""

from __future__ import annotations

import logging
import uuid
from typing import Optional, Tuple

import numpy as np

from hadoop_tpu.serving.kvstore.codec import decode_block, encode_block

log = logging.getLogger(__name__)


class DFSTier:
    """KV block store over any ``FileSystem`` (DFS in production)."""

    def __init__(self, fs, base_dir: str, *, shape, dtype,
                 codec: str = "raw"):
        self.fs = fs
        self.base_dir = base_dir.rstrip("/") or "/kvcache"
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.codec = codec
        self._made_dirs = set()

    def path(self, digest: bytes) -> str:
        hexd = digest.hex()
        return f"{self.base_dir}/{hexd[:2]}/{hexd}.kvb"

    def _ensure_dir(self, path: str) -> None:
        d = path.rsplit("/", 1)[0]
        if d not in self._made_dirs:
            self.fs.mkdirs(d)
            self._made_dirs.add(d)

    def put(self, digest: bytes, k: np.ndarray, v: np.ndarray) -> bool:
        """Persist one block through the write pipeline. Returns True
        when the block is durable under its final name (including the
        lost-a-race-to-an-identical-writer case)."""
        final = self.path(digest)
        tmp = f"{final}.{uuid.uuid4().hex[:8]}.tmp"
        try:
            self._ensure_dir(final)
            self.fs.write_all(tmp, encode_block(k, v, self.codec))
            if not self.fs.rename(tmp, final):
                try:
                    self.fs.delete(tmp)
                except (OSError, IOError) as e:
                    log.debug("kv tmp cleanup of %s failed: %s", tmp, e)
                # a refused rename usually means another replica
                # persisted the same prefix first (the digest keys
                # identical content, so theirs is ours) — but verify:
                # claiming durability on any other refusal would mark
                # the block persisted forever with nothing on disk
                if not self.fs.exists(final):
                    log.warning("kv block rename %s -> %s refused with "
                                "no winner in place; not durable",
                                tmp, final)
                    return False
            return True
        except (OSError, IOError) as e:
            log.debug("kv block persist %s failed: %s", final, e)
            try:
                self.fs.delete(tmp)
            except (OSError, IOError):
                log.debug("kv tmp cleanup of %s failed after write "
                          "error", tmp)
            return False

    def get(self, digest: bytes
            ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Fetch + decode one block (hedged reads under a
        DistributedFileSystem); any failure is a miss — the caller
        falls back to prefill, never to a corrupt context."""
        try:
            data = self.fs.read_all(self.path(digest))
        except (OSError, IOError):
            return None
        try:
            k, v, _ = decode_block(data, shape=self.shape,
                                   dtype=self.dtype)
        except (ValueError, KeyError) as e:
            log.warning("undecodable KV block %s (%s); treating as "
                        "miss", self.path(digest), e)
            return None
        return k, v
