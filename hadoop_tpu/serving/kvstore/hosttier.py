"""Host-RAM KV tier: a pinned numpy ring under a conf-keyed byte budget.

Every block payload has one fixed shape (``[L, block_size, Hkv, Dh]``
twice, K and V), so the tier is two preallocated arenas sliced into
fixed slots — no per-block allocation, no fragmentation, and the pages
stay resident (the OS never has to fault them back in under memory
pressure from the model weights). Eviction is the ring itself: when the
budget wraps, the oldest slot is overwritten and its key drops out of
the index. A demoted block costs one ``memcpy`` in, a promotion one
``memcpy`` out; both are host-side only — the device round-trip happens
in the engine's fixed-shape inject/extract helpers.

``codec`` (``serving.kv.codec``, same knob the DFS tier honors): with
``int8`` the arenas hold symmetric per-layer int8 payloads beside a
small f32 scale plane — one quantize on ``put``, one dequantize on
``get`` — so the same ``serving.kv.host.bytes`` budget holds ~4× the
blocks of an f32 engine (~2× bf16). Promotions out of an int8 ring are
allclose rather than bit-exact, exactly like a DFS round-trip under the
same codec; ``raw`` (the default) stays byte-identical.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from hadoop_tpu.serving.kvstore.codec import (CODECS, dequant_int8,
                                              quant_int8)


class HostTier:
    """FIFO ring of demoted KV blocks keyed by prefix chain digest."""

    def __init__(self, shape: Tuple[int, ...], dtype, budget_bytes: int,
                 codec: str = "raw"):
        if codec not in CODECS:
            raise ValueError(f"serving.kv.codec must be one of {CODECS}, "
                             f"got {codec!r}")
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.codec = codec
        store_dtype = np.dtype(np.int8) if codec == "int8" else self.dtype
        n_layers = self.shape[0]
        per_block = 2 * int(np.prod(self.shape)) * store_dtype.itemsize
        if codec == "int8":
            per_block += 2 * n_layers * 4   # the f32 scale planes
        self.block_bytes = per_block
        self.capacity = max(0, int(budget_bytes) // per_block)
        self._k = np.zeros((self.capacity,) + self.shape, store_dtype)
        self._v = np.zeros_like(self._k)
        if codec == "int8":
            self._k_scales = np.zeros((self.capacity, n_layers),
                                      np.float32)
            self._v_scales = np.zeros_like(self._k_scales)
        self._index: Dict[bytes, int] = {}            # guarded-by: _lock
        self._slot_key: List[Optional[bytes]] = \
            [None] * self.capacity                    # guarded-by: _lock
        self._next = 0                                # guarded-by: _lock
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._index)

    @property
    def budget_bytes(self) -> int:
        return self.capacity * self.block_bytes

    def put(self, digest: bytes, k: np.ndarray, v: np.ndarray) -> bool:
        """Copy one block's payload into the ring (overwriting the
        oldest slot when full). Returns False when the tier has no
        capacity at all (budget below one block)."""
        if self.capacity == 0:
            return False
        if self.codec == "int8":
            # quantize OUTSIDE the lock — the ring write below is the
            # memcpy-cheap part a concurrent get should wait on
            kq, ks = quant_int8(k)
            vq, vs = quant_int8(v)
        with self._lock:
            slot = self._index.get(digest)
            if slot is None:
                slot = self._next
                self._next = (self._next + 1) % self.capacity
                old = self._slot_key[slot]
                if old is not None:
                    del self._index[old]
                self._slot_key[slot] = digest
                self._index[digest] = slot
            if self.codec == "int8":
                self._k[slot] = kq
                self._v[slot] = vq
                self._k_scales[slot] = ks
                self._v_scales[slot] = vs
            else:
                self._k[slot] = k
                self._v[slot] = v
        return True

    def _snapshot(self, slot: int) -> Tuple:
        """Copy one slot's raw payload (+ scales). Caller holds the
        lock — this is the memcpy-cheap part a concurrent ring wrap
        must not race; the float-expanding dequant runs OUTSIDE it."""
        if self.codec == "int8":
            return (self._k[slot].copy(), self._v[slot].copy(),
                    self._k_scales[slot].copy(),
                    self._v_scales[slot].copy())
        return self._k[slot].copy(), self._v[slot].copy(), None, None

    def _decode(self, snap: Tuple) -> Tuple[np.ndarray, np.ndarray]:
        """Materialize a snapshot's (K, V) in the engine dtype — lock
        NOT held (dequantization is per-block float math, the same
        reasoning that keeps ``put``'s quantize outside the lock)."""
        k, v, ks, vs = snap
        if ks is None:
            return k, v
        return (dequant_int8(k, ks, self.dtype),
                dequant_int8(v, vs, self.dtype))

    def items(self) -> List[Tuple[bytes, np.ndarray, np.ndarray]]:
        """Copies of every resident (digest, K, V) — the drain path
        persists the whole ring to the DFS tier before the process
        exits. Raw payloads copied under the lock like ``get``;
        decoded after it drops."""
        with self._lock:
            snaps = [(d, self._snapshot(s))
                     for d, s in self._index.items()]
        return [(d,) + self._decode(snap) for d, snap in snaps]

    def get(self, digest: bytes
            ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Copies of the block's (K, V), or None. Raw payload copied
        under the lock so a concurrent ring wrap can't overwrite the
        view mid-read; decoded after it drops."""
        with self._lock:
            slot = self._index.get(digest)
            if slot is None:
                return None
            snap = self._snapshot(slot)
        return self._decode(snap)
