"""Host-RAM KV tier: a pinned numpy ring under a conf-keyed byte budget.

Every block payload has one fixed shape (``[L, block_size, Hkv, Dh]``
twice, K and V), so the tier is two preallocated arenas sliced into
fixed slots — no per-block allocation, no fragmentation, and the pages
stay resident (the OS never has to fault them back in under memory
pressure from the model weights). Eviction is the ring itself: when the
budget wraps, the oldest slot is overwritten and its key drops out of
the index. A demoted block costs one ``memcpy`` in, a promotion one
``memcpy`` out; both are host-side only — the device round-trip happens
in the engine's fixed-shape inject/extract helpers.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np


class HostTier:
    """FIFO ring of demoted KV blocks keyed by prefix chain digest."""

    def __init__(self, shape: Tuple[int, ...], dtype, budget_bytes: int):
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        per_block = 2 * int(np.prod(self.shape)) * self.dtype.itemsize
        self.block_bytes = per_block
        self.capacity = max(0, int(budget_bytes) // per_block)
        self._k = np.zeros((self.capacity,) + self.shape, self.dtype)
        self._v = np.zeros_like(self._k)
        self._index: Dict[bytes, int] = {}            # guarded-by: _lock
        self._slot_key: List[Optional[bytes]] = \
            [None] * self.capacity                    # guarded-by: _lock
        self._next = 0                                # guarded-by: _lock
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._index)

    @property
    def budget_bytes(self) -> int:
        return self.capacity * self.block_bytes

    def put(self, digest: bytes, k: np.ndarray, v: np.ndarray) -> bool:
        """Copy one block's payload into the ring (overwriting the
        oldest slot when full). Returns False when the tier has no
        capacity at all (budget below one block)."""
        if self.capacity == 0:
            return False
        with self._lock:
            slot = self._index.get(digest)
            if slot is None:
                slot = self._next
                self._next = (self._next + 1) % self.capacity
                old = self._slot_key[slot]
                if old is not None:
                    del self._index[old]
                self._slot_key[slot] = digest
                self._index[digest] = slot
            self._k[slot] = k
            self._v[slot] = v
        return True

    def items(self) -> List[Tuple[bytes, np.ndarray, np.ndarray]]:
        """Copies of every resident (digest, K, V) — the drain path
        persists the whole ring to the DFS tier before the process
        exits. Copied under the lock like ``get``."""
        with self._lock:
            return [(d, self._k[s].copy(), self._v[s].copy())
                    for d, s in self._index.items()]

    def get(self, digest: bytes
            ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Copies of the block's (K, V), or None. Copied under the lock
        so a concurrent ring wrap can't overwrite the view mid-read."""
        with self._lock:
            slot = self._index.get(digest)
            if slot is None:
                return None
            return self._k[slot].copy(), self._v[slot].copy()
