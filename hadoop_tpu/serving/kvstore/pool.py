"""Refcounted fixed pool of KV-cache pages — the HBM tier's allocator.

Extracted from ``serving/engine.py`` so the tiered cache
(``kvstore.tiered``) and the engine share one ownership story: the pool
is the refcount truth for every resident page regardless of which tier
put its bytes there.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import List, Optional


class BlockPool:
    """Refcounted fixed pool of KV-cache pages. Block 0 is reserved
    scratch (padding and inactive lanes scatter there), so
    ``num_blocks - 1`` are allocatable.

    Lifecycle: ``alloc`` hands out pages at refcount 1; prefix sharing
    ``incref``s a page per additional mapper; ``decref`` drops one
    mapping and reports pages that reached zero WITHOUT freeing them —
    the engine decides whether a zero-ref page stays resident as prefix
    cache or returns to the free list via ``free``. ``free`` refuses
    pages still shared (refcount > 1), so a preemption can never yank a
    page out from under a sibling."""

    SCRATCH = 0

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (one is scratch)")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._free = deque(range(1, num_blocks))  # guarded-by: _lock
        self._ref = [0] * num_blocks              # guarded-by: _lock
        self._lock = threading.Lock()

    @property
    def num_usable(self) -> int:
        return self.num_blocks - 1

    @property
    def num_free(self) -> int:
        with self._lock:
            return len(self._free)

    def refcount(self, block: int) -> int:
        with self._lock:
            return self._ref[block]

    def alloc(self, n: int) -> Optional[List[int]]:
        with self._lock:
            if n > len(self._free):
                return None
            out = [self._free.popleft() for _ in range(n)]
            for b in out:
                self._ref[b] = 1
            return out

    def incref(self, blocks: List[int]) -> None:
        with self._lock:
            for b in blocks:
                if b == self.SCRATCH:
                    raise ValueError("incref of the scratch block")
                self._ref[b] += 1

    def decref(self, blocks: List[int]) -> List[int]:
        """Drop one reference per block; returns the blocks that hit
        zero (now unmapped — cacheable or freeable, caller's call)."""
        released = []
        with self._lock:
            for b in blocks:
                if self._ref[b] <= 0:
                    raise ValueError(f"decref of unreferenced block {b}")
                self._ref[b] -= 1
                if self._ref[b] == 0:
                    released.append(b)
        return released

    def free(self, blocks: List[int]) -> None:
        with self._lock:
            for b in blocks:
                if b == self.SCRATCH:
                    raise ValueError("freeing the scratch block")
                if self._ref[b] > 1:
                    raise ValueError(
                        f"freeing block {b} still shared "
                        f"(refcount {self._ref[b]}) — decref instead")
                self._ref[b] = 0
                self._free.append(b)
