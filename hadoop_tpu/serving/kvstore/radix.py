"""Block-granular radix index over fully-filled prompt KV blocks.

Extracted from ``serving/engine.py`` and extended for the tiered cache:
every node now carries a **chain digest** — a hash of the ENTIRE token
prefix from the root down to (and including) this node's chunk — which
is the key the host-RAM and DFS tiers store the block's payload under.
KV at position ``i`` depends on tokens ``0..i``, so the digest chains:
``digest = H(parent.digest || chunk_tokens)``; two blocks holding the
same tokens under different heads hash differently, exactly like the
trie path already guarantees for the HBM tier. Nodes also count
``hits`` (cross-request matches) so the tier manager can promote hot
shared prefixes to the DFS store past a conf-keyed threshold.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Callable, Dict, List, Optional


def chain_digest(parent_digest: bytes, chunk: tuple) -> bytes:
    """Digest of a prefix extended by one block-sized token chunk."""
    h = hashlib.sha256(parent_digest)
    h.update(("|".join(str(t) for t in chunk)).encode())
    return h.digest()


class _RadixNode:
    __slots__ = ("key", "block", "parent", "children", "digest", "hits",
                 "persisted")

    def __init__(self, key=None, block=None, parent=None,
                 digest: bytes = b""):
        self.key = key          # tuple of block_size tokens
        self.block = block      # pool page holding this chunk's K/V
        self.parent = parent
        self.children: Dict[tuple, "_RadixNode"] = {}
        self.digest = digest    # chain hash of the full prefix to here
        self.hits = 0           # cross-request matches (promotion signal)
        self.persisted = False  # already durable in the DFS tier


class PrefixCache:
    """Radix index over fully-filled prompt blocks: a trie at block
    granularity, where the path from the root IS the token prefix — so
    a block is only ever matched under the exact full prefix its K/V
    was computed for (KV at position i depends on tokens 0..i, not just
    the block's own tokens).

    The cache holds no refcounts itself; the pool's refcount is the
    truth. A node is evictable when it is a leaf and its block's
    refcount is zero; ``evict`` pops such leaves in LRU order (leaves
    first keeps the tree consistent — a parent can only go after its
    children). ``_lru`` holds ONLY the current leaves, in recency order
    (moved-to-end on every touch); evicting a leaf promotes a
    newly-childless parent to the cold end. So the steady-state
    eviction — pool full of zero-ref cache, evict one page per block
    allocation — pops the front in O(1) under the scheduler lock,
    scanning past a node only when it is pinned (actively shared).

    ``salt`` seeds the root digest: the tier manager folds the KV
    layout (layers/heads/dims/dtype/block size) in, so payloads from an
    incompatible engine shape can never key-collide in a shared store.
    """

    def __init__(self, block_size: int, salt: bytes = b""):
        self.block_size = block_size
        self._root = _RadixNode(digest=salt)
        self._nodes: Dict[int, _RadixNode] = {}        # every cached page
        self._lru: "OrderedDict[int, _RadixNode]" = OrderedDict()  # leaves

    def __len__(self) -> int:
        return len(self._nodes)

    @property
    def root_digest(self) -> bytes:
        return self._root.digest

    def contains_block(self, block: int) -> bool:
        return block in self._nodes

    def node_for_block(self, block: int) -> Optional[_RadixNode]:
        return self._nodes.get(block)

    def nodes(self) -> List["_RadixNode"]:
        """Every resident node (no particular order) — the drain path
        walks these to persist the whole cache."""
        return list(self._nodes.values())

    def _touch(self, node: _RadixNode) -> None:
        if node.block in self._lru:
            self._lru.move_to_end(node.block)

    def match_nodes(self, tokens: List[int]) -> List["_RadixNode"]:
        """Longest cached full-block prefix of ``tokens``; returns the
        nodes in prefix order (no refcounting — caller pins them)."""
        node = self._root
        out: List[_RadixNode] = []
        bs = self.block_size
        for i in range(len(tokens) // bs):
            child = node.children.get(tuple(tokens[i * bs:(i + 1) * bs]))
            if child is None:
                break
            self._touch(child)
            out.append(child)
            node = child
        return out

    def match(self, tokens: List[int]) -> List[int]:
        """Longest cached full-block prefix of ``tokens``; returns the
        pages in prefix order (no refcounting — caller pins them)."""
        return [n.block for n in self.match_nodes(tokens)]

    def insert(self, tokens: List[int], blocks: List[int]) -> int:
        """Register fully-filled pages for ``tokens`` (one page per
        ``block_size`` chunk, aligned). First writer wins: an existing
        node keeps its page and the duplicate stays with its owner (it
        is freed on that request's release). Returns how many pages
        were newly registered."""
        node = self._root
        new = 0
        bs = self.block_size
        for i, blk in enumerate(blocks):
            key = tuple(tokens[i * bs:(i + 1) * bs])
            child = node.children.get(key)
            if child is None:
                child = _RadixNode(key, blk, node,
                                   chain_digest(node.digest, key))
                node.children[key] = child
                self._nodes[blk] = child
                if node is not self._root:
                    self._lru.pop(node.block, None)    # no longer a leaf
                self._lru[blk] = child
                new += 1
            else:
                self._touch(child)
            node = child
        return new

    def evict(self, n: int, refcount: Callable[[int], int],
              on_evict: Optional[Callable[["_RadixNode"], None]] = None,
              ) -> List[int]:
        """Drop up to ``n`` LRU zero-ref leaf pages from the index and
        return them (caller returns them to the pool's free list).
        ``on_evict`` sees each victim BEFORE its page is dropped — the
        tier manager's demotion hook (the page's bytes are still valid
        in the pool arrays at that point, so the host tier can copy
        them out)."""
        out: List[int] = []
        while len(out) < n:
            victim = None
            for blk, node in self._lru.items():  # oldest leaf first;
                if refcount(blk) == 0:           # scan past pinned ones
                    victim = node
                    break
            if victim is None:
                break
            if on_evict is not None:
                on_evict(victim)
            del self._lru[victim.block]
            del self._nodes[victim.block]
            del victim.parent.children[victim.key]
            out.append(victim.block)
            parent = victim.parent
            if parent is not self._root and not parent.children:
                # newly a leaf, and at least as stale as the child we
                # just dropped: promote to the cold end of the LRU
                self._lru[parent.block] = parent
                self._lru.move_to_end(parent.block, last=False)
        return out
