"""Tier-aware KV cache manager: HBM radix → host-RAM ring → DFS store.

Policy (the engine stays the device owner; this module owns storage and
placement decisions):

- **Demote before drop.** When the HBM tier evicts a zero-ref cached
  page to feed a live allocation, its payload is copied into the host
  ring first (``demote`` runs inside the radix eviction, while the
  page's bytes are still valid in the pool arrays). Only cold pages
  demote — a page with a positive refcount is never evictable in the
  first place, so an active decode can never lose KV under it.

- **Miss walks down.** A radix miss at admission consults the host
  ring, then the DFS store, chunk by chunk along the prefix chain
  (``fetch_cold``); a hit is injected back into a pool page and
  re-registered in the radix so siblings share it from HBM. Only the
  still-uncached tail falls back to prefill.

- **Hot prefixes go durable.** Every cross-request radix match bumps
  the node's hit count; at ``serving.kv.dfs.min-refs`` the block is
  extracted once and handed to a background writer that persists it
  through the DFS write pipeline — admission never blocks on a
  DataNode. ``persist_prefix`` is the forced variant the
  prefill/decode disaggregation handoff uses.

All mutation of radix/pool state happens on the engine's scheduler
thread under its scheduler lock; the host ring and the writer queue
have their own locks and never call back into the engine — the lock
order is strictly engine → tier, so the xceiver path (reached from the
writer thread WITHOUT the scheduler lock) cannot close a cycle.
"""

from __future__ import annotations

import hashlib
import logging
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from hadoop_tpu.serving.kvstore.codec import CODECS
from hadoop_tpu.serving.kvstore.dfstier import DFSTier
from hadoop_tpu.serving.kvstore.hosttier import HostTier
from hadoop_tpu.serving.kvstore.pool import BlockPool
from hadoop_tpu.serving.kvstore.radix import (PrefixCache, _RadixNode,
                                              chain_digest)
from hadoop_tpu.tracing.tracer import carry_context, global_tracer

log = logging.getLogger(__name__)

HOST_BYTES_KEY = "serving.kv.host.bytes"
DFS_ENABLE_KEY = "serving.kv.dfs.enable"
DFS_DIR_KEY = "serving.kv.dfs.dir"
DFS_MIN_REFS_KEY = "serving.kv.dfs.min-refs"
CODEC_KEY = "serving.kv.codec"
FETCH_WINDOW_KEY = "serving.kv.fetch.window"


@dataclass
class ColdHit:
    """One chunk recovered from a cold tier, awaiting injection."""
    tier: str           # "host" | "dfs"
    digest: bytes
    k: np.ndarray
    v: np.ndarray


class TieredKVCache:
    """Storage/policy face of the KV cache; the engine owns the device
    arrays and passes ``extract(block) -> (k_np, v_np)`` for the
    payload copies demotion and persistence need."""

    def __init__(self, pool: BlockPool, *, layers: int, kv_heads: int,
                 head_dim: int, dtype, enabled: bool = True,
                 host_bytes: int = 0, fs=None,
                 dfs_dir: str = "/kvcache", dfs_min_refs: int = 1,
                 codec: str = "raw", fetch_window: int = 4,
                 metrics=None, tracer=None,
                 extract: Optional[Callable] = None):
        if codec not in CODECS:
            raise ValueError(f"{CODEC_KEY} must be one of {CODECS}, "
                             f"got {codec!r}")
        self.pool = pool
        self.block_size = pool.block_size
        shape = (layers, pool.block_size, kv_heads, head_dim)
        self.block_shape = shape
        self.dtype = np.dtype(dtype)
        # the salt folds the KV layout into every chain digest, so two
        # engines with incompatible shapes sharing one store can never
        # key-collide (the per-file header is the second, loud, check)
        salt = hashlib.sha256(
            f"htpu-kv1:{layers}:{pool.block_size}:{kv_heads}:"
            f"{head_dim}:{self.dtype}".encode()).digest()
        # the chain root: held here (not only on the radix) so the
        # radix-less chain surfaces — longctx ingest/read — key blocks
        # identically to the radix tier they interoperate with
        self.chain_salt = salt
        self.radix = PrefixCache(pool.block_size, salt=salt) if enabled \
            else None
        self.host = HostTier(shape, self.dtype, host_bytes,
                             codec=codec) \
            if enabled and host_bytes > 0 else None
        if self.host is not None and self.host.capacity == 0:
            log.warning("%s=%d holds zero KV blocks (one block is %d "
                        "bytes); host tier disabled", HOST_BYTES_KEY,
                        host_bytes, self.host.block_bytes)
            self.host = None
        self.dfs = DFSTier(fs, dfs_dir, shape=shape, dtype=self.dtype,
                           codec=codec) if enabled and fs is not None \
            else None
        self.dfs_min_refs = max(1, int(dfs_min_refs))
        self.codec = codec
        self.metrics = metrics
        self.tracer = tracer or global_tracer()
        self._extract = extract
        # engine-local lifetime stats (the process-global metrics source
        # is shared across engines in one process — tests and the bench
        # read these instead)
        self.hits = {"hbm": 0, "host": 0, "dfs": 0}
        self.demotions = 0
        self.promotions = 0
        self.persists_enqueued = 0
        self.persists_done = 0      # guarded-by: _stats_lock
        self.persist_failures = 0   # guarded-by: _stats_lock
        self._stats_lock = threading.Lock()
        self._write_q: "queue.Queue" = queue.Queue()
        self._writer: Optional[threading.Thread] = None
        # cold DFS chunks are read in speculative parallel windows of
        # this many blocks (``serving.kv.fetch.window``): one DataNode
        # round-trip of wall time per window instead of one per block
        # (the walk runs under the scheduler lock, so every serial
        # round-trip is a decode stall for the whole replica); reads
        # past the chain's first miss are wasted but bounded by the
        # window. The default of 4 suits short radix-miss tails; a
        # long-context chain wants a window sized so the whole chain
        # pages in with O(chain/window) round trips, not O(chain).
        self.fetch_window = max(1, int(fetch_window))
        self._fetch_pool = ThreadPoolExecutor(
            max_workers=min(self.fetch_window, 32),
            thread_name_prefix="kv-dfs-fetch") if self.dfs is not None \
            else None
        self.chain_ingested = 0     # longctx blocks streamed in

    # ------------------------------------------------------------- flags

    @property
    def cold_enabled(self) -> bool:
        return self.host is not None or self.dfs is not None

    @property
    def dfs_enabled(self) -> bool:
        return self.dfs is not None

    def set_extract(self, fn: Callable) -> None:
        self._extract = fn

    # ---------------------------------------------------------- demotion

    def demote(self, node: _RadixNode) -> None:
        """Radix eviction hook: spill the victim's payload to the host
        ring before the page returns to the free list. Costs one
        device→host copy per evicted block — only armed when the host
        tier exists."""
        if self.host is None or self._extract is None:
            return
        k, v = self._extract(node.block)
        if self.host.put(node.digest, k, v):
            self.demotions += 1
            if self.metrics:
                self.metrics.kv_demotions.incr()

    # ------------------------------------------------------ cold fetches

    def fetch_cold(self, ctx: List[int], start_block: int, limit: int,
                   parent_ctx=None, start_digest: Optional[bytes] = None
                   ) -> List[ColdHit]:
        """Probe host then DFS for consecutive full-block chunks
        ``[start_block, limit)`` of ``ctx``, stopping at the first
        chunk neither tier holds (the chain must stay contiguous — a
        gap would leave unprefilled positions behind cached ones).
        ``start_digest`` is the chain digest of ``ctx``'s first
        ``start_block`` chunks when the caller already holds it (the
        matched radix node carries exactly this value) — without it the
        chain is rehashed from the root."""
        if not self.cold_enabled or start_block >= limit:
            return []
        bs = self.block_size
        if start_digest is not None:
            digest = start_digest
        else:
            digest = self.chain_salt
            for i in range(start_block):
                digest = chain_digest(digest,
                                      tuple(ctx[i * bs:(i + 1) * bs]))
        digests: List[bytes] = []
        for i in range(start_block, limit):
            digest = chain_digest(digest,
                                  tuple(ctx[i * bs:(i + 1) * bs]))
            digests.append(digest)
        hits: List[ColdHit] = []
        lookahead: Dict[bytes, Any] = {}
        sp = None
        try:
            for idx, digest in enumerate(digests):
                got, tier = None, None
                if self.host is not None:
                    t0 = time.monotonic()
                    got = self.host.get(digest)
                    if got is not None:
                        tier = "host"
                        if self.metrics:
                            # hits only: a miss is a microsecond dict
                            # probe that would drown the real memcpy
                            # latency the histogram advertises
                            self.metrics.kv_fetch_hist["host"].add(
                                time.monotonic() - t0)
                if got is None and self.dfs is not None:
                    if sp is None:
                        # one span covers the whole cold walk; it joins
                        # the request's trace through the carried door
                        # context (the scheduler thread holds no
                        # contextvar of its own)
                        sp = self.tracer.span("serving.kv.fetch",
                                              parent=parent_ctx)
                    if digest not in lookahead:
                        lookahead = self._dfs_read_window(digests, idx)
                    got = lookahead.get(digest)
                    tier = "dfs"
                if got is None:
                    break
                hits.append(ColdHit(tier, digest, got[0], got[1]))
        finally:
            if sp is not None:
                sp.add_kv("blocks_host",
                          str(sum(1 for h in hits if h.tier == "host")))
                sp.add_kv("blocks_dfs",
                          str(sum(1 for h in hits if h.tier == "dfs")))
                sp.finish()
        return hits

    def _dfs_read_window(self, digests: List[bytes], idx: int
                         ) -> Dict[bytes, Optional[Tuple]]:
        """Read DFS chunks ``digests[idx : idx+window]`` concurrently
        (each a full hedged-read round trip) and return digest →
        payload-or-None. Every read records its own fetch latency —
        a DFS miss is a real DataNode round trip, unlike a host probe."""
        window = digests[idx:idx + self.fetch_window]

        def read(d: bytes):
            t0 = time.monotonic()
            got = self.dfs.get(d)
            if self.metrics:
                self.metrics.kv_fetch_hist["dfs"].add(
                    time.monotonic() - t0)
            return d, got

        if len(window) == 1:
            return dict([read(window[0])])
        if self._fetch_pool is None:
            # no executor (hand-wired tests): read the whole window
            # serially so the caller's lookahead still covers it
            return dict(read(d) for d in window)
        return dict(self._fetch_pool.map(read, window))

    def read_chain(self, ctx: List[int], limit: int, parent_ctx=None
                   ) -> List[ColdHit]:
        """Page a digest chain back from the cold tiers WITHOUT going
        through the radix/pool (the long-context decode path: the
        chain lands host-resident and visits HBM one window at a
        time, never as pool pages). Same contiguity contract and
        speculative DFS windows as ``fetch_cold``; per-tier hit
        counters are bumped here because no ``mark_promoted``
        follows."""
        hits = self.fetch_cold(ctx, 0, limit, parent_ctx=parent_ctx)
        for h in hits:
            self.hits[h.tier] += 1
            if self.metrics:
                (self.metrics.kv_hits_host if h.tier == "host"
                 else self.metrics.kv_hits_dfs).incr()
        return hits

    # --------------------------------------------------- streamed ingest

    def ingest_chain(self, tokens: List[int], payloads,
                     parent_ctx=None) -> int:
        """Stream full-block KV payloads for ``tokens`` straight into
        the cold tiers — the long-context prefill sink. ``payloads``
        yields ``(k, v)`` ``[L, bs, Hkv, Dh]`` blocks in chain order
        (a generator: the caller never holds the whole context);
        each lands in the host ring now and rides the background DFS
        writer (digest-chained with the SAME salt/keying as the radix
        tier, so a later radix-path admission — or another replica —
        maps these blocks like any other persisted prefix; the codec
        applies per tier exactly as on the demotion path). Returns the
        number of blocks ingested."""
        bs = self.block_size
        digest = self.chain_salt
        n = 0
        for k, v in payloads:
            digest = chain_digest(digest,
                                  tuple(tokens[n * bs:(n + 1) * bs]))
            if self.host is not None:
                self.host.put(digest, np.asarray(k), np.asarray(v))
            if self.dfs is not None:
                self._enqueue_raw(digest, np.asarray(k), np.asarray(v),
                                  parent_ctx)
            n += 1
        self.chain_ingested += n
        return n

    def mark_promoted(self, hits: List[ColdHit], pages: List[int]
                      ) -> None:
        """Cold payloads are now resident in ``pages`` and registered
        in the radix: carry over durability (a DFS-sourced block is
        already persisted) and count the traffic."""
        for hit, page in zip(hits, pages):
            node = self.radix.node_for_block(page) if self.radix else None
            if node is not None:
                node.hits = 1
                if hit.tier == "dfs":
                    node.persisted = True
            self.hits[hit.tier] += 1
            self.promotions += 1
            if self.metrics:
                self.metrics.kv_promotions.incr()
                (self.metrics.kv_hits_host if hit.tier == "host"
                 else self.metrics.kv_hits_dfs).incr()

    # ------------------------------------------------------- hot persist

    def note_match(self, nodes: List[_RadixNode], parent_ctx=None,
                   count: bool = True) -> None:
        """HBM radix hits at admission: bump per-node hit counts and
        enqueue DFS persistence for nodes crossing the threshold.
        ``count=False`` for a preempted request re-matching its own
        surviving blocks — warm resume is not fleet-level reuse, so it
        neither counts as a hit nor heats the node toward DFS
        persistence (a thrashing pool re-admitting one private prompt
        must not push its blocks over the min-refs threshold)."""
        if not count or not nodes:
            return
        self.hits["hbm"] += len(nodes)
        if self.metrics:
            self.metrics.kv_hits_hbm.incr(len(nodes))
        if self.dfs is None:
            return
        for n in nodes:
            n.hits += 1
            if not n.persisted and n.hits >= self.dfs_min_refs:
                self._enqueue_persist(n, parent_ctx)

    def persist_prefix(self, tokens: List[int], parent_ctx=None) -> int:
        """Force-persist every cached full block of ``tokens`` (the
        disaggregation handoff: the prefill replica calls this right
        after prefilling, bypassing the hotness threshold). Returns the
        durable span in blocks — already-persisted blocks count, they
        are exactly as durable. Caller holds the scheduler lock."""
        if self.dfs is None or self.radix is None:
            return 0
        nodes = self.radix.match_nodes(tokens)
        for node in nodes:
            if not node.persisted:
                self._enqueue_persist(node, parent_ctx)
        return len(nodes)

    def persist_resident(self, parent_ctx=None) -> int:
        """Drain-time handoff: enqueue persistence of EVERY resident
        cached block — the whole HBM radix (not just min-refs-hot
        nodes) plus the host ring — so scale-in hands the fleet its
        cache instead of torching it. A block another replica already
        persisted dedups at the DFSTier rename. Caller holds the
        scheduler lock (same contract as ``persist_prefix``); returns
        the number of blocks enqueued, which bounds the caller's
        ``flush`` watermark."""
        if self.dfs is None:
            return 0
        n = 0
        if self.radix is not None:
            for node in self.radix.nodes():
                if not node.persisted:
                    self._enqueue_persist(node, parent_ctx)
                    n += 1
        if self.host is not None:
            for digest, k, v in self.host.items():
                self._enqueue_raw(digest, k, v, parent_ctx)
                n += 1
        return n

    def _enqueue_raw(self, digest: bytes, k, v, parent_ctx) -> None:
        """Persist a payload that has no radix node (a host-ring entry
        whose HBM page is long gone). Rides the same writer queue and
        done/failure counters so ``flush`` watermarks cover it."""
        self.persists_enqueued += 1
        job = carry_context(
            lambda: self._write_block(None, k, v, parent_ctx,
                                      digest=digest))
        self._write_q.put(job)
        self._ensure_writer()

    def _enqueue_persist(self, node: _RadixNode, parent_ctx) -> None:
        """Extract now (scheduler thread — the page could be evicted or
        rewritten the moment the lock drops), write later (writer
        thread — the DataNode round-trip must not stall admission)."""
        if self._extract is None:
            return
        k, v = self._extract(node.block)
        node.persisted = True   # cleared by the writer on failure
        self.persists_enqueued += 1
        job = carry_context(
            lambda: self._write_block(node, k, v, parent_ctx))
        self._write_q.put(job)
        self._ensure_writer()

    def _ensure_writer(self) -> None:
        if self._writer is None:
            self._writer = threading.Thread(
                target=self._write_loop, name="kv-dfs-writer",
                daemon=True)
            self._writer.start()

    def _write_block(self, node: Optional[_RadixNode], k, v, parent_ctx,
                     digest: Optional[bytes] = None) -> None:
        sp = self.tracer.span("serving.kv.persist", parent=parent_ctx)
        sp.add_kv("bytes", str(k.nbytes + v.nbytes))
        sp.add_kv("codec", self.codec)
        ok = False
        try:
            ok = self.dfs.put(node.digest if node is not None
                              else digest, k, v)
        finally:
            sp.add_kv("ok", str(ok))
            sp.finish()
            if not ok and node is not None:
                # let a later hot match retry the write; MUST precede
                # the counter bump — flush() returns the moment
                # done+failures reaches its watermark, and the caller
                # immediately reads node.persisted for the durable span
                node.persisted = False
            with self._stats_lock:
                if ok:
                    self.persists_done += 1
                else:
                    self.persist_failures += 1
            if ok and self.metrics:
                self.metrics.kv_dfs_persists.incr()

    def _write_loop(self) -> None:
        while True:
            job = self._write_q.get()
            if job is None:
                return
            try:
                job()
            except Exception as e:  # noqa: BLE001 — a poisoned write
                # must not kill the writer; the block simply stays
                # un-persisted and a later match retries
                log.warning("kv persist job failed: %s", e)
            finally:
                self._write_q.task_done()

    def flush(self, timeout: float = 30.0,
              up_to: Optional[int] = None) -> bool:
        """Wait until the first ``up_to`` enqueued persists have
        completed (default: everything enqueued so far). The watermark
        matters on a busy replica: the scheduler keeps enqueuing
        min-refs persists for other requests while a prefill-door
        flush waits, and chasing the global queue tail could time the
        handoff out long after its own blocks went durable."""
        target = self.persists_enqueued if up_to is None else up_to
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._stats_lock:
                done = self.persists_done + self.persist_failures
            if done >= target:
                return True
            time.sleep(0.005)
        return False

    def persisted_span(self, tokens: List[int]) -> int:
        """Contiguous head blocks of ``tokens`` currently marked
        durable in the radix — the writer clears ``persisted`` on a
        failed write, so after a ``flush`` this is the span a decode
        replica will actually find on the DataNodes. Caller holds the
        scheduler lock."""
        if self.dfs is None or self.radix is None:
            return 0
        n = 0
        for node in self.radix.match_nodes(tokens):
            if not node.persisted:
                break
            n += 1
        return n

    def close(self) -> None:
        if self._writer is not None:
            self._write_q.put(None)
            self._writer.join(timeout=5.0)
            self._writer = None
        if self._fetch_pool is not None:
            self._fetch_pool.shutdown(wait=False)
            self._fetch_pool = None

    # ------------------------------------------------------------- stats

    def stats(self) -> Dict[str, Any]:
        with self._stats_lock:
            done, failed = self.persists_done, self.persist_failures
        return {
            "host_enabled": self.host is not None,
            "dfs_enabled": self.dfs is not None,
            "codec": self.codec,
            "hits_hbm": self.hits["hbm"],
            "hits_host": self.hits["host"],
            "hits_dfs": self.hits["dfs"],
            "demotions": self.demotions,
            "promotions": self.promotions,
            "host_resident": len(self.host) if self.host is not None
                             else 0,
            "host_capacity_blocks": self.host.capacity
                                    if self.host is not None else 0,
            "dfs_persists": done,
            "dfs_persist_failures": failed,
            "chain_ingested": self.chain_ingested,
            "fetch_window": self.fetch_window,
        }
