"""Serving-side checkpoint load: DFS path → decoder params.

Reads the trainer's sharded checkpoints (``parallel.checkpoint`` layout:
``step_N/manifest.json`` + ``shard_*.bin``) straight off any FileSystem —
for a ``DistributedFileSystem`` the shard reads ride the client's hedged
read pool (``dfs.client.hedged.read.*``), so one slow DataNode doesn't
stall replica startup, exactly the straggler story the trainer already
gets for input data. Shards are fetched CONCURRENTLY through a bounded
worker pool (``serving.loader.io.workers``): replica cold-start is pure
IO fan-in latency, and sequential shard pulls were paying one
round-trip per shard file.

The trainer persists ``{"params": ..., "opt": ...}``; serving wants the
params only. The manifest's leaf names tell us which layout we're
looking at, so both wrapped trees and bare param trees load — and the
optimizer shards are never even read.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Optional, Tuple

import jax

from hadoop_tpu.models.config import ModelConfig
from hadoop_tpu.models.decoder import init_params
from hadoop_tpu.parallel.checkpoint import latest_step, load_checkpoint

log = logging.getLogger(__name__)

HEDGED_POOL_KEY = "dfs.client.hedged.read.threadpool.size"
HEDGED_THRESHOLD_KEY = "dfs.client.hedged.read.threshold"
IO_WORKERS_KEY = "serving.loader.io.workers"


def serving_read_defaults(conf) -> None:
    """Arm hedged reads for checkpoint pulls unless the deployment
    already chose: replica startup is latency-critical fan-in from many
    DataNodes, the canonical hedged-read shape."""
    conf.set_if_unset(HEDGED_POOL_KEY, "4")
    conf.set_if_unset(HEDGED_THRESHOLD_KEY, "0.5")


def load_serving_params(fs, base_dir: str, cfg: ModelConfig, *,
                        step: Optional[int] = None,
                        mesh=None, specs=None,
                        io_workers: int = 4,
                        leaf_transform=None) -> Tuple[dict, int]:
    """Load decoder params for ``cfg`` from ``base_dir`` on ``fs``.

    Returns ``(params, step)``. With ``mesh`` + ``specs`` the leaves are
    placed sharded (the engine passes ``param_specs`` when it owns a
    mesh). ``io_workers`` bounds the concurrent shard fetches (1 =
    sequential). ``leaf_transform`` switches ``load_checkpoint`` to its
    streaming per-leaf mode — the weight plane's quantize-at-load seam
    (``serving/weightplane.py``): each assembled leaf is consumed the
    moment its shards arrive, so the full f32 model is never resident
    on the host. Raises FileNotFoundError when no complete checkpoint
    exists.
    """
    t0 = time.monotonic()
    if step is None:
        step = latest_step(fs, base_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {base_dir}")
    manifest = json.loads(fs.read_all(
        f"{base_dir}/step_{step:012d}/manifest.json").decode())
    shapes = jax.eval_shape(lambda k: init_params(k, cfg),
                            jax.random.PRNGKey(0))
    wrapped = any(name.startswith("['params']")
                  for name in manifest["leaves"])
    like = {"params": shapes} if wrapped else shapes
    spec_tree = {"params": specs} if (wrapped and specs is not None) \
        else specs
    tree, step = load_checkpoint(fs, base_dir, like, step=step,
                                 mesh=mesh, specs=spec_tree,
                                 io_workers=max(1, io_workers),
                                 leaf_transform=leaf_transform)
    params = tree["params"] if wrapped else tree
    n = sum(x.size for x in jax.tree_util.tree_leaves(params))
    log.info("loaded %d-param checkpoint step %d from %s in %.2fs "
             "(%d io workers)", n, step, base_dir,
             time.monotonic() - t0, max(1, io_workers))
    return params, step
