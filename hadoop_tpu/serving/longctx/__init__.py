"""Long-context serving plane: context length as a FLEET property.

Prompts longer than ``serving.longctx.min.tokens`` stop being a
workload class the replica refuses: prefill runs as a context-parallel
job across the replica's mesh (ring attention / ulysses, topology-aware
ring placement per TASP), the finished KV streams straight into the
tiered KV store (digest-chained, int8-codec eligible) instead of
pinning the whole context in HBM, and decode pages a working set back
in through a fixed device window.

This package IS the relaxed serving tier for context parallelism: the
CP softmax reassociation is not bitwise, so tpulint's
``parity/relaxed-gated`` checker requires every call into
``cp_prefill`` / ``paged_decode`` / ``longctx_submit`` /
``longctx_plane_from_conf`` from outside this package to sit under a
``serving.parity=relaxed`` guard, and ``guard.run_prefill_ab`` is the
A-B acceptance (exact at small shapes, bounded-logit at scale).
"""

from hadoop_tpu.serving.longctx.decode import (WorkingSetDecoder,
                                               trace_counts)
from hadoop_tpu.serving.longctx.guard import (longctx_ab_report,
                                              run_prefill_ab)
from hadoop_tpu.serving.longctx.plan import (choose_sp_mode, cp_mesh,
                                             ring_order)
from hadoop_tpu.serving.longctx.plane import (CHIPS_KEY, ENABLED_KEY,
                                              MAX_TOKENS_KEY,
                                              MIN_TOKENS_KEY,
                                              SP_MODE_KEY, TAIL_KEY,
                                              WINDOW_KEY,
                                              LongContextPlane,
                                              longctx_plane_from_conf)
from hadoop_tpu.serving.longctx.prefill import (ContextParallelPrefiller,
                                                PrefillResult)

__all__ = [
    "LongContextPlane", "longctx_plane_from_conf",
    "ContextParallelPrefiller", "PrefillResult", "WorkingSetDecoder",
    "run_prefill_ab", "longctx_ab_report", "ring_order", "cp_mesh",
    "choose_sp_mode", "trace_counts",
    "ENABLED_KEY", "MIN_TOKENS_KEY", "MAX_TOKENS_KEY", "CHIPS_KEY",
    "SP_MODE_KEY", "WINDOW_KEY", "TAIL_KEY",
]
