"""Working-set decode over a tier-resident context.

The engine's fused step gathers each lane's WHOLE context out of the
HBM block pool — which is exactly what a long-context request cannot
have. This decoder keeps the context where CP prefill streamed it (the
host ring / DFS tiers, chain-digest-keyed) and pages it through a
fixed-shape device window instead: per generated token, per layer, the
query merges online-softmax partials (``ops.attention.chunk_attention``
+ ``merge_attention`` — the same math ring attention runs across chips,
run here across TIME) over

- a device-resident TAIL buffer holding the prompt's partial last
  block plus every generated token's K/V (scattered in as they are
  computed, the ``_INJECT``-mover idiom), and
- a sliding WINDOW of ``serving.longctx.decode.window.blocks`` full
  blocks paged in from the host-resident chain on demand.

So decode HBM holds ``window + tail`` — a working set — while the
context itself lives a tier down. The chain is assembled once per
request with ``TieredKVCache.read_chain`` (host probe, then
DFS hedged reads in ``serving.kv.fetch.window``-sized speculative
windows: O(chain/window) DataNode round trips).

Compile-once: every jitted piece below is cached at module level per
(model config, window, tail capacity) and traced exactly once for the
process lifetime — ``trace_counts()`` exposes the counters and the
longctx smoke pins them, exactly like the engine's two step shapes.

Sampling runs host-side (greedy argmax / temperature + top-k with the
same mask-then-scale transform as the engine's in-graph sampler): the
per-token logits are already host-visible here, unlike the fused step
where keeping sampling in-graph is what avoids a [B, V] readback.
"""

from __future__ import annotations

import threading
from types import SimpleNamespace
from typing import Callable, Dict, List, Optional

import numpy as np

from hadoop_tpu.models.config import ModelConfig

_NEG_INF = -1e30
_FAR = 1 << 30     # a kv position no query position ever reaches


# one jit family per (cfg, window, tail) layout, shared by every
# decoder instance in the process — same compile-once contract as the
# engine's module-level _INJECT/_EXTRACT movers
_JIT_CACHE: Dict = {}                       # guarded-by: _JIT_LOCK
_JIT_LOCK = threading.Lock()
_TRACES: Dict[str, int] = {}


def trace_counts() -> Dict[str, int]:
    """Traces per jitted decode piece (name → count): the longctx
    smoke asserts every value stays exactly 1 per layout family."""
    return dict(_TRACES)


def _count(name: str) -> None:
    _TRACES[name] = _TRACES.get(name, 0) + 1


def _build_jits(cfg: ModelConfig, win: int, tail_cap: int):
    import jax
    import jax.numpy as jnp

    from hadoop_tpu.models.decoder import _norm, head_matrix
    from hadoop_tpu.ops import (apply_rope, gelu, rope_frequencies,
                                swiglu)
    from hadoop_tpu.ops.attention import (_repeat_kv, chunk_attention,
                                          merge_attention)

    hq, hkv, dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    nrep = hq // hkv
    scale = 1.0 / (dh ** 0.5)
    # the counter key must distinguish everything the jit cache key
    # does (the FULL config, not just the family) or two legitimate
    # jit families would share one counter and falsely read as
    # retracing; hash(cfg) is process-local, which is all a
    # process-local trace counter needs
    fam = f"{cfg.family}:{win}:{tail_cap}:{hash(cfg) & 0xffffff:x}"

    def embed_impl(params, tok, pos):
        _count(f"embed@{fam}")
        h = params["embed"][tok][None, None, :]
        if not cfg.use_rope:
            h = h + params["pos_embed"][
                jnp.clip(pos, 0, cfg.max_seq - 1)][None, None, :]
        return h                                        # [1, 1, D]

    def layer_in_impl(layers, l, h, pos):
        _count(f"layer_in@{fam}")
        x = _norm(h, layers["attn_norm_w"][l],
                  layers["attn_norm_b"][l]
                  if "attn_norm_b" in layers else None, cfg)
        q = (x @ layers["wq"][l]).reshape(1, 1, hq, dh)
        k = (x @ layers["wk"][l]).reshape(1, 1, hkv, dh)
        v = (x @ layers["wv"][l]).reshape(1, 1, hkv, dh)
        if cfg.use_rope:
            cos, sin = rope_frequencies(dh, cfg.max_seq, cfg.rope_theta)
            p = pos[None]
            q = apply_rope(q, cos, sin, p)
            k = apply_rope(k, cos, sin, p)
        return q, k[0, 0], v[0, 0]          # q [1,1,Hq,Dh]; k/v [Hkv,Dh]

    def tail_set_impl(ktail, vtail, l, idx, k, v):
        _count(f"tail_set@{fam}")
        return (ktail.at[l, idx].set(k.astype(ktail.dtype)),
                vtail.at[l, idx].set(v.astype(vtail.dtype)))

    def _partial(q, kc, vc, qpos, kvpos):
        return chunk_attention(
            q, _repeat_kv(kc[None], nrep).astype(jnp.float32),
            _repeat_kv(vc[None], nrep).astype(jnp.float32),
            scale, qpos[None], kvpos)

    def tail_part_impl(q, ktail, vtail, l, pos, base, n_tail):
        _count(f"tail@{fam}")
        j = jnp.arange(tail_cap)
        kvpos = jnp.where(j < n_tail, base + j, _FAR)
        return _partial(q, ktail[l], vtail[l], pos, kvpos)

    def win_part_impl(q, kw, vw, pos, w0, n_valid):
        _count(f"win@{fam}")
        j = jnp.arange(win)
        kvpos = jnp.where(j < n_valid, w0 + j, _FAR)
        return _partial(q, kw, vw, pos, kvpos)

    def merge_impl(oa, la, ob, lb):
        _count(f"merge@{fam}")
        return merge_attention(oa, la, ob, lb)

    def layer_out_impl(layers, l, h, o):
        _count(f"layer_out@{fam}")
        h = h + (o.astype(h.dtype).reshape(1, 1, hq * dh)
                 @ layers["wo"][l])
        x = _norm(h, layers["mlp_norm_w"][l],
                  layers["mlp_norm_b"][l]
                  if "mlp_norm_b" in layers else None, cfg)
        if cfg.use_swiglu:
            mlp = swiglu(x @ layers["w_gate"][l],
                         x @ layers["w_up"][l]) @ layers["w_down"][l]
        else:
            mlp = gelu(x @ layers["w_in"][l]
                       + layers["b_in"][l]) @ layers["w_out"][l] \
                + layers["b_out"][l]
        return h + mlp.astype(h.dtype)

    def head_impl(params, h):
        _count(f"head@{fam}")
        h = _norm(h, params["final_norm_w"],
                  params.get("final_norm_b"), cfg)
        return (h[0, 0] @ head_matrix(params, cfg, h.dtype)).astype(
            jnp.float32)

    return SimpleNamespace(
        embed=jax.jit(embed_impl),
        layer_in=jax.jit(layer_in_impl),
        tail_set=jax.jit(tail_set_impl, donate_argnums=(0, 1)),
        tail=jax.jit(tail_part_impl),
        win=jax.jit(win_part_impl),
        merge=jax.jit(merge_impl),
        layer_out=jax.jit(layer_out_impl),
        head=jax.jit(head_impl),
        family=fam)


def _jits_for(cfg: ModelConfig, win: int, tail_cap: int):
    key = (cfg, win, tail_cap)
    with _JIT_LOCK:
        if key not in _JIT_CACHE:
            _JIT_CACHE[key] = _build_jits(cfg, win, tail_cap)
        return _JIT_CACHE[key]


def _host_sample(logits: np.ndarray, temperature: float, top_k: int,
                 rng: np.random.Generator) -> int:
    """The engine's mask-then-scale sampling transform, host-side:
    greedy when temperature <= 0; top-k keeps values >= the k-th
    largest (ties included, matching ``engine._mask_and_scale``)."""
    if temperature <= 0:
        return int(np.argmax(logits))
    l = np.asarray(logits, np.float64).copy()
    if top_k > 0:
        kth = np.sort(l)[max(0, l.size - top_k)]
        l[l < kth] = _NEG_INF
    l = l / max(temperature, 1e-6)
    l -= l.max()
    p = np.exp(l)
    p /= p.sum()
    return int(rng.choice(l.size, p=p))


class WorkingSetDecoder:
    """Decode one long-context request with HBM bounded by
    window + tail, the context streamed from the cold tiers."""

    def __init__(self, params, cfg: ModelConfig, store, *,
                 block_size: int, window_blocks: int = 4,
                 tail_tokens: int = 128, metrics=None):
        import jax.numpy as jnp

        from hadoop_tpu.serving.weightplane import is_quantized_tree
        if is_quantized_tree(params):
            raise NotImplementedError(
                "the longctx decoder serves the checkpoint-dtype view; "
                "hand it dequantized params (the plane does this at "
                "construction)")
        if cfg.is_moe:
            raise NotImplementedError("longctx serves dense decoders "
                                      "only (same as the engine)")
        self.params = params
        self.cfg = cfg
        self.store = store
        self.block_size = int(block_size)
        self.win = int(window_blocks) * self.block_size
        self.tail_cap = int(tail_tokens)
        self._jnp = jnp
        self._jits = _jits_for(cfg, self.win, self.tail_cap)
        self.metrics = metrics
        self.window_fetches = 0     # device window loads (per l, w, tok)
        self.tokens_decoded = 0

    @property
    def hbm_working_set_bytes(self) -> int:
        """What this decoder keeps device-resident per request: the
        window (transient) + the tail buffers. The number the 'working
        set, not the full context' contract is about."""
        item = np.dtype(self.cfg.dtype).itemsize
        per_tok = 2 * self.cfg.n_layers * self.cfg.n_kv_heads * \
            self.cfg.head_dim * item
        return (self.win + self.tail_cap) * per_tok

    # ------------------------------------------------------------ decode

    def paged_decode(self, tokens: List[int], first_token: int,
                     sampling, *, tail_k=None, tail_v=None,
                     deliver: Callable[[int], None],
                     stop: Optional[Callable[[], bool]] = None,
                     seed: int = 0, rng=None, parent_ctx=None) -> int:
        """Generate up to ``sampling.max_new_tokens - 1`` tokens after
        ``first_token`` (which prefill already delivered), paging the
        prompt's KV chain in windows. Relaxed-tier entry point
        (``parity/relaxed-gated``). Returns tokens emitted here."""
        jnp = self._jnp
        cfg = self.cfg
        bs = self.block_size
        s = len(tokens)
        n_full = s // bs
        tail_len = s - n_full * bs
        if tail_len + sampling.max_new_tokens > self.tail_cap:
            raise ValueError(
                f"prompt tail ({tail_len}) + max_new "
                f"({sampling.max_new_tokens}) exceeds the longctx tail "
                f"budget {self.tail_cap} "
                f"(serving.longctx.decode.tail.tokens)")
        # ---- the chain pages back from the tiers (host probe, DFS
        # hedged-read windows) — NOT into the engine's pool: it lands
        # host-resident and only ever visits HBM one window at a time
        hits = self.store.read_chain(tokens, n_full,
                                     parent_ctx=parent_ctx)
        if len(hits) < n_full:
            raise RuntimeError(
                f"longctx KV chain has a gap: {len(hits)}/{n_full} "
                f"blocks recoverable from the host/DFS tiers (host ring "
                f"too small without the DFS tier?)")
        # ONE preallocated buffer at the window-padded shape, hits
        # written in place: the chain is the dominant host allocation
        # at real scale, and an assemble-then-pad concatenate pair
        # would hold TWO copies live at peak. Padding to a window
        # multiple once here keeps per-token window slicing
        # allocation-free on the decode critical path.
        chain_len = n_full * bs
        padded = chain_len + ((-chain_len) % self.win)
        shape = (cfg.n_layers, padded, cfg.n_kv_heads, cfg.head_dim)
        knp = np.zeros(shape, hits[0].k.dtype if hits else cfg.dtype)
        vnp = np.zeros(shape, knp.dtype)
        for i, h in enumerate(hits):
            knp[:, i * bs:(i + 1) * bs] = h.k
            vnp[:, i * bs:(i + 1) * bs] = h.v
        # ---- device-resident tail: prompt's partial block + every
        # generated token's K/V
        tshape = (cfg.n_layers, self.tail_cap, cfg.n_kv_heads,
                  cfg.head_dim)
        kt = np.zeros(tshape, cfg.dtype)
        vt = np.zeros(tshape, cfg.dtype)
        if tail_len:
            kt[:, :tail_len] = tail_k
            vt[:, :tail_len] = tail_v
        ktail, vtail = jnp.asarray(kt), jnp.asarray(vt)
        base = n_full * bs
        n_tail = tail_len
        if rng is None:
            rng = np.random.default_rng(seed)
        sp = sampling
        cur = first_token
        pos = s                        # first_token's absolute position
        emitted = 0
        out_count = 1                  # first_token already delivered
        while out_count < sp.max_new_tokens and \
                (sp.stop_token is None or cur != sp.stop_token) and \
                (stop is None or not stop()):
            logits, ktail, vtail, n_tail = self._token(
                cur, pos, knp, vnp, chain_len, ktail, vtail, base,
                n_tail)
            nxt = _host_sample(logits, sp.temperature, sp.top_k, rng)
            deliver(nxt)
            emitted += 1
            out_count += 1
            cur = nxt
            pos += 1
        self.tokens_decoded += emitted
        return emitted

    def _token(self, tok: int, pos: int, knp, vnp, chain_len: int,
               ktail, vtail, base: int, n_tail: int):
        """One full forward for one token: per layer, scatter its K/V
        into the tail, then merge attention partials over the tail and
        over the chain paged through the fixed window. ``knp``/``vnp``
        arrive padded to a window multiple; ``chain_len`` is the true
        context length the positions mask against."""
        jnp = self._jnp
        J = self._jits
        cfg = self.cfg
        pos_j = jnp.int32(pos)
        h = J.embed(self.params, jnp.int32(tok), pos_j)
        layers = self.params["layers"]
        n_win = knp.shape[1] // self.win
        idx = n_tail            # this token's tail slot
        for l in range(cfg.n_layers):
            l_j = jnp.int32(l)
            q, k, v = J.layer_in(layers, l_j, h, pos_j)
            ktail, vtail = J.tail_set(ktail, vtail, l_j,
                                      jnp.int32(idx), k, v)
            o, lse = J.tail(q, ktail, vtail, l_j, pos_j,
                            jnp.int32(base), jnp.int32(idx + 1))
            for w in range(n_win):
                w0 = w * self.win
                ow, lw = J.win(q, knp[l, w0:w0 + self.win],
                               vnp[l, w0:w0 + self.win], pos_j,
                               jnp.int32(w0),
                               jnp.int32(min(chain_len - w0, self.win)))
                o, lse = J.merge(o, lse, ow, lw)
                self.window_fetches += 1
                if self.metrics:
                    self.metrics.longctx_window_fetches.incr()
            h = J.layer_out(layers, l_j, h, o)
        logits = np.asarray(J.head(self.params, h))
        return logits, ktail, vtail, n_tail + 1
