"""Working-set decode over a tier-resident context.

The engine's fused step gathers each lane's WHOLE context out of the
HBM block pool — which is exactly what a long-context request cannot
have. This decoder keeps the context where CP prefill streamed it (the
host ring / DFS tiers, chain-digest-keyed) and pages it through a
fixed-shape device window instead: per generated token, per layer, the
query merges online-softmax partials (``ops.attention.chunk_attention``
+ ``merge_attention`` — the same math ring attention runs across chips,
run here across TIME) over

- a device-resident TAIL buffer holding the prompt's partial last
  block plus every generated token's K/V (scattered in as they are
  computed, the ``_INJECT``-mover idiom), and
- a sliding WINDOW of ``serving.longctx.decode.window.blocks`` full
  blocks paged in from the host-resident chain on demand.

So decode HBM holds ``window + tail`` — a working set — while the
context itself lives a tier down. The chain is assembled once per
request with ``TieredKVCache.read_chain`` (host probe, then
DFS hedged reads in ``serving.kv.fetch.window``-sized speculative
windows: O(chain/window) DataNode round trips).

Two decode loops share that working-set contract:

- the PIPELINED path (``serving.longctx.decode.pipeline``, the
  default): the per-layer op chain is fused into four scanned,
  fixed-shape dispatches (``fstart``/``fadvance``/``fwin``/
  ``ffinish``), the window transfer unit is a SLAB of
  ``serving.longctx.decode.fetch.windows`` consecutive windows for one
  layer (one async ``device_put`` per slab, consumed by one ``fwin``
  scan), and the next slab is shipped while the current one computes —
  a two-slab double buffer, the flash/paged-attention page-in idiom
  run at the jit boundary instead of inside a kernel. With the default
  slab depth (= ``n_layers``) host→HBM traffic per token is
  O(chain/window) slab transfers — O(layers × chain/window) slices on
  the legacy loop — and dispatches per token collapse from
  ~``2 + n_layers * (4 + 2*n_windows)`` to ``n_layers * n_slabs +
  n_layers + 1``. Sampling runs in-graph by default
  (``serving.longctx.decode.sampler=device``: the engine's
  mask-then-scale transform + categorical, one int32 readback per
  token) with the host sampler as fallback. Quantized (int8-resident)
  weight trees serve directly on this path: the fused pieces route
  matmuls through the weight plane's ``qdot``/``qslice``/``qhead``.
- the LEGACY path (``pipeline=false``): the pre-pipelining per-(layer,
  window) loop, kept byte-identical as the bitwise-parity fallback and
  the A-B reference for the fused path.

Compile-once: every jitted piece below is cached at module level per
(model config, window, tail capacity[, slab depth, weight tier]) and
traced exactly once for the process lifetime — ``trace_counts()``
exposes the trace counters and ``dispatch_counts()`` the per-dispatch
counters (stamped per jit call the way the comm ledger stamps
collectives); the longctx smoke pins the former at 1 and budgets the
latter per token.
"""

from __future__ import annotations

import threading
from types import SimpleNamespace
from typing import Callable, Dict, List, Optional

import numpy as np

from hadoop_tpu.models.config import ModelConfig

_NEG_INF = -1e30
_FAR = 1 << 30     # a kv position no query position ever reaches


# one jit family per (cfg, window, tail) layout, shared by every
# decoder instance in the process — same compile-once contract as the
# engine's module-level _INJECT/_EXTRACT movers
_JIT_CACHE: Dict = {}                       # guarded-by: _JIT_LOCK
_JIT_LOCK = threading.Lock()
_TRACES: Dict[str, int] = {}
_DISPATCHES: Dict[str, int] = {}


def trace_counts() -> Dict[str, int]:
    """Traces per jitted decode piece (name → count): the longctx
    smoke asserts every value stays exactly 1 per layout family."""
    return dict(_TRACES)


def dispatch_counts() -> Dict[str, int]:
    """Device dispatches per jitted decode piece (name → count),
    stamped host-side at every call the way the comm ledger stamps
    collectives — the number the ≤ 2-per-(token, window) budget is
    audited against."""
    return dict(_DISPATCHES)


def _count(name: str) -> None:
    _TRACES[name] = _TRACES.get(name, 0) + 1


def _build_jits(cfg: ModelConfig, win: int, tail_cap: int):
    import jax
    import jax.numpy as jnp

    from hadoop_tpu.models.decoder import _norm, head_matrix
    from hadoop_tpu.ops import (apply_rope, gelu, rope_frequencies,
                                swiglu)
    from hadoop_tpu.ops.attention import (_repeat_kv, chunk_attention,
                                          merge_attention)

    hq, hkv, dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    nrep = hq // hkv
    scale = 1.0 / (dh ** 0.5)
    # the counter key must distinguish everything the jit cache key
    # does (the FULL config, not just the family) or two legitimate
    # jit families would share one counter and falsely read as
    # retracing; hash(cfg) is process-local, which is all a
    # process-local trace counter needs
    fam = f"{cfg.family}:{win}:{tail_cap}:{hash(cfg) & 0xffffff:x}"

    def embed_impl(params, tok, pos):
        _count(f"embed@{fam}")
        h = params["embed"][tok][None, None, :]
        if not cfg.use_rope:
            h = h + params["pos_embed"][
                jnp.clip(pos, 0, cfg.max_seq - 1)][None, None, :]
        return h                                        # [1, 1, D]

    def layer_in_impl(layers, l, h, pos):
        _count(f"layer_in@{fam}")
        x = _norm(h, layers["attn_norm_w"][l],
                  layers["attn_norm_b"][l]
                  if "attn_norm_b" in layers else None, cfg)
        q = (x @ layers["wq"][l]).reshape(1, 1, hq, dh)
        k = (x @ layers["wk"][l]).reshape(1, 1, hkv, dh)
        v = (x @ layers["wv"][l]).reshape(1, 1, hkv, dh)
        if cfg.use_rope:
            cos, sin = rope_frequencies(dh, cfg.max_seq, cfg.rope_theta)
            p = pos[None]
            q = apply_rope(q, cos, sin, p)
            k = apply_rope(k, cos, sin, p)
        return q, k[0, 0], v[0, 0]          # q [1,1,Hq,Dh]; k/v [Hkv,Dh]

    def tail_set_impl(ktail, vtail, l, idx, k, v):
        _count(f"tail_set@{fam}")
        return (ktail.at[l, idx].set(k.astype(ktail.dtype)),
                vtail.at[l, idx].set(v.astype(vtail.dtype)))

    def _partial(q, kc, vc, qpos, kvpos):
        return chunk_attention(
            q, _repeat_kv(kc[None], nrep).astype(jnp.float32),
            _repeat_kv(vc[None], nrep).astype(jnp.float32),
            scale, qpos[None], kvpos)

    def tail_part_impl(q, ktail, vtail, l, pos, base, n_tail):
        _count(f"tail@{fam}")
        j = jnp.arange(tail_cap)
        kvpos = jnp.where(j < n_tail, base + j, _FAR)
        return _partial(q, ktail[l], vtail[l], pos, kvpos)

    def win_part_impl(q, kw, vw, pos, w0, n_valid):
        _count(f"win@{fam}")
        j = jnp.arange(win)
        kvpos = jnp.where(j < n_valid, w0 + j, _FAR)
        return _partial(q, kw, vw, pos, kvpos)

    def merge_impl(oa, la, ob, lb):
        _count(f"merge@{fam}")
        return merge_attention(oa, la, ob, lb)

    def layer_out_impl(layers, l, h, o):
        _count(f"layer_out@{fam}")
        h = h + (o.astype(h.dtype).reshape(1, 1, hq * dh)
                 @ layers["wo"][l])
        x = _norm(h, layers["mlp_norm_w"][l],
                  layers["mlp_norm_b"][l]
                  if "mlp_norm_b" in layers else None, cfg)
        if cfg.use_swiglu:
            mlp = swiglu(x @ layers["w_gate"][l],
                         x @ layers["w_up"][l]) @ layers["w_down"][l]
        else:
            mlp = gelu(x @ layers["w_in"][l]
                       + layers["b_in"][l]) @ layers["w_out"][l] \
                + layers["b_out"][l]
        return h + mlp.astype(h.dtype)

    def head_impl(params, h):
        _count(f"head@{fam}")
        h = _norm(h, params["final_norm_w"],
                  params.get("final_norm_b"), cfg)
        return (h[0, 0] @ head_matrix(params, cfg, h.dtype)).astype(
            jnp.float32)

    return SimpleNamespace(
        embed=jax.jit(embed_impl),
        layer_in=jax.jit(layer_in_impl),
        tail_set=jax.jit(tail_set_impl, donate_argnums=(0, 1)),
        tail=jax.jit(tail_part_impl),
        win=jax.jit(win_part_impl),
        merge=jax.jit(merge_impl),
        layer_out=jax.jit(layer_out_impl),
        head=jax.jit(head_impl),
        family=fam)


def _jits_for(cfg: ModelConfig, win: int, tail_cap: int):
    key = (cfg, win, tail_cap)
    with _JIT_LOCK:
        if key not in _JIT_CACHE:
            _JIT_CACHE[key] = _build_jits(cfg, win, tail_cap)
        return _JIT_CACHE[key]


# ------------------------------------------------- fused pipelined path

def _build_fused(cfg: ModelConfig, win: int, tail_cap: int, slab_wins: int,
                 quantized: bool):
    """The pipelined path's jit family: the per-token op chain folded
    into four fixed-shape dispatches (arXiv:2502.17728's fusion
    direction applied at the jit boundary).

    - ``fstart``: embed + layer 0's qkv/rope + tail scatter + tail
      attention partial.
    - ``fadvance``: layer ``l-1``'s wo/mlp exit + layer ``l``'s entry +
      tail scatter + tail partial (one trace serves every layer — the
      layer index is data).
    - ``fwin``: ``lax.scan`` over the ``slab_wins`` windows of one
      transferred slab, merging each window's online-softmax partial
      into the running (o, lse) — the scanned per-window step. Windows
      past the chain mask to -inf rows, which ``chunk_attention``
      documents as the merge identity, so slab padding needs no guard.
    - ``ffinish`` / ``fhead``: last layer's exit + final norm + head,
      then either the engine's mask-then-scale sampler in-graph
      (``ffinish`` → one int32 per token crosses back) or raw f32
      logits for the host fallback (``fhead``).

    ``quantized`` selects the int8-resident weight tier: matmuls route
    through the weight plane (``qdot``/``qslice``/``qrows``/``qhead``)
    wherever the leaf carries the quantized layout. The tier is part of
    the family key — a quantized tree is a different pytree structure,
    so sharing a counter with the f32 family would misread the second
    trace as a retracing bug.
    """
    import jax
    import jax.numpy as jnp

    from hadoop_tpu.models.decoder import _norm, head_matrix
    from hadoop_tpu.ops import (apply_rope, gelu, rope_frequencies,
                                swiglu)
    from hadoop_tpu.ops.attention import (_repeat_kv, chunk_attention,
                                          merge_attention)
    from hadoop_tpu.serving.weightplane import (is_qtensor, qdot, qhead,
                                                qrows, qslice)

    hq, hkv, dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    nrep = hq // hkv
    nl = cfg.n_layers
    scale = 1.0 / (dh ** 0.5)
    tier = "q8" if quantized else "f32"
    fam = (f"{cfg.family}:{win}:{tail_cap}:s{slab_wins}:{tier}:"
           f"{hash(cfg) & 0xffffff:x}")

    # trace-time weight routing: the pytree structure (qtensor vs
    # array) is static per family, so these branches compile away
    def _mm(x, w):
        return qdot(x, w) if is_qtensor(w) else x @ w

    def _lw(layers, name, l):
        w = layers[name]
        return qslice(w, l) if is_qtensor(w) else w[l]

    def _partial(q, kc, vc, qpos, kvpos):
        return chunk_attention(
            q, _repeat_kv(kc[None], nrep).astype(jnp.float32),
            _repeat_kv(vc[None], nrep).astype(jnp.float32),
            scale, qpos[None], kvpos)

    def _layer_in(layers, l, h, pos):
        x = _norm(h, layers["attn_norm_w"][l],
                  layers["attn_norm_b"][l]
                  if "attn_norm_b" in layers else None, cfg)
        q = _mm(x, _lw(layers, "wq", l)).reshape(1, 1, hq, dh)
        k = _mm(x, _lw(layers, "wk", l)).reshape(1, 1, hkv, dh)
        v = _mm(x, _lw(layers, "wv", l)).reshape(1, 1, hkv, dh)
        if cfg.use_rope:
            cos, sin = rope_frequencies(dh, cfg.max_seq, cfg.rope_theta)
            p = pos[None]
            q = apply_rope(q, cos, sin, p)
            k = apply_rope(k, cos, sin, p)
        return q, k[0, 0], v[0, 0]

    def _layer_out(layers, l, h, o):
        h = h + _mm(o.astype(h.dtype).reshape(1, 1, hq * dh),
                    _lw(layers, "wo", l))
        x = _norm(h, layers["mlp_norm_w"][l],
                  layers["mlp_norm_b"][l]
                  if "mlp_norm_b" in layers else None, cfg)
        if cfg.use_swiglu:
            mlp = _mm(swiglu(_mm(x, _lw(layers, "w_gate", l)),
                             _mm(x, _lw(layers, "w_up", l))),
                      _lw(layers, "w_down", l))
        else:
            mlp = _mm(gelu(_mm(x, _lw(layers, "w_in", l))
                           + layers["b_in"][l]),
                      _lw(layers, "w_out", l)) + layers["b_out"][l]
        return h + mlp.astype(h.dtype)

    def _tail_partial(q, ktail, vtail, l, pos, base, n_tail):
        j = jnp.arange(tail_cap)
        kvpos = jnp.where(j < n_tail, base + j, _FAR)
        return _partial(q, ktail[l], vtail[l], pos, kvpos)

    def _final_logits(params, layers, h, o):
        h = _layer_out(layers, nl - 1, h, o)
        h = _norm(h, params["final_norm_w"],
                  params.get("final_norm_b"), cfg)
        row = h[0, 0]
        head = params["embed"] if cfg.tie_embeddings \
            else params.get("lm_head")
        if is_qtensor(head):
            return qhead(params, row, cfg).astype(jnp.float32)
        return (row @ head_matrix(params, cfg, row.dtype)).astype(
            jnp.float32)

    def fstart_impl(params, layers, tok, pos, ktail, vtail, idx, base):
        _count(f"fstart@{fam}")
        emb = params["embed"]
        if is_qtensor(emb):
            h = qrows(emb, tok, cfg.jax_dtype)[None, None, :]
        else:
            h = emb[tok][None, None, :]
        if not cfg.use_rope:
            h = h + params["pos_embed"][
                jnp.clip(pos, 0, cfg.max_seq - 1)][None, None, :]
        q, k, v = _layer_in(layers, 0, h, pos)
        ktail = ktail.at[0, idx].set(k.astype(ktail.dtype))
        vtail = vtail.at[0, idx].set(v.astype(vtail.dtype))
        o, lse = _tail_partial(q, ktail, vtail, 0, pos, base, idx + 1)
        return h, q, ktail, vtail, o, lse

    def fadvance_impl(layers, l, h, o, pos, ktail, vtail, idx, base):
        _count(f"fadvance@{fam}")
        h = _layer_out(layers, l - 1, h, o)
        q, k, v = _layer_in(layers, l, h, pos)
        ktail = ktail.at[l, idx].set(k.astype(ktail.dtype))
        vtail = vtail.at[l, idx].set(v.astype(vtail.dtype))
        o2, lse2 = _tail_partial(q, ktail, vtail, l, pos, base, idx + 1)
        return h, q, ktail, vtail, o2, lse2

    def fwin_impl(q, o, lse, slab, slab0, chain_len, pos):
        _count(f"fwin@{fam}")
        ks = slab[0].reshape(slab_wins, win, hkv, dh)
        vs = slab[1].reshape(slab_wins, win, hkv, dh)
        w0s = slab0 + jnp.arange(slab_wins, dtype=jnp.int32) * win

        def body(carry, xs):
            o, lse = carry
            kw, vw, w0 = xs
            j = jnp.arange(win)
            n_valid = jnp.clip(chain_len - w0, 0, win)
            kvpos = jnp.where(j < n_valid, w0 + j, _FAR)
            ow, lw = _partial(q, kw, vw, pos, kvpos)
            return merge_attention(o, lse, ow, lw), None

        (o, lse), _ = jax.lax.scan(body, (o, lse), (ks, vs, w0s))
        return o, lse

    def fhead_impl(params, layers, h, o):
        _count(f"fhead@{fam}")
        return _final_logits(params, layers, h, o)

    def ffinish_impl(params, layers, h, o, pos, temp, topk, seed):
        _count(f"ffinish@{fam}")
        logits = _final_logits(params, layers, h, o)
        # the engine's mask-then-scale sampler, in-graph: greedy when
        # temp <= 0 (bit-identical to the host argmax), else top-k
        # mask + temperature + categorical off a position-folded key
        greedy = jnp.argmax(logits).astype(jnp.int32)
        v = logits.shape[-1]
        srt = jnp.sort(logits)
        kth = srt[jnp.clip(v - topk, 0, v - 1)]
        masked = jnp.where((topk > 0) & (logits < kth), _NEG_INF, logits)
        scaled = masked / jnp.maximum(temp, 1e-6)
        key = jax.random.fold_in(jax.random.PRNGKey(seed), pos)
        sampled = jax.random.categorical(key, scaled).astype(jnp.int32)
        return jnp.where(temp <= 0, greedy, sampled)

    return SimpleNamespace(
        fstart=jax.jit(fstart_impl, donate_argnums=(4, 5)),
        fadvance=jax.jit(fadvance_impl, donate_argnums=(5, 6)),
        fwin=jax.jit(fwin_impl),
        fhead=jax.jit(fhead_impl),
        ffinish=jax.jit(ffinish_impl),
        family=fam)


def _fused_for(cfg: ModelConfig, win: int, tail_cap: int, slab_wins: int,
               quantized: bool):
    key = ("fused", cfg, win, tail_cap, slab_wins, quantized)
    with _JIT_LOCK:
        if key not in _JIT_CACHE:
            _JIT_CACHE[key] = _build_fused(cfg, win, tail_cap,
                                           slab_wins, quantized)
        return _JIT_CACHE[key]


def _host_sample(logits: np.ndarray, temperature: float, top_k: int,
                 rng: np.random.Generator) -> int:
    """The engine's mask-then-scale sampling transform, host-side:
    greedy when temperature <= 0; top-k keeps values >= the k-th
    largest (ties included, matching ``engine._mask_and_scale``)."""
    if temperature <= 0:
        return int(np.argmax(logits))
    l = np.asarray(logits, np.float64).copy()
    if top_k > 0:
        kth = np.sort(l)[max(0, l.size - top_k)]
        l[l < kth] = _NEG_INF
    l = l / max(temperature, 1e-6)
    l -= l.max()
    p = np.exp(l)
    p /= p.sum()
    return int(rng.choice(l.size, p=p))


class WorkingSetDecoder:
    """Decode one long-context request with HBM bounded by
    window + tail, the context streamed from the cold tiers."""

    def __init__(self, params, cfg: ModelConfig, store, *,
                 block_size: int, window_blocks: int = 4,
                 tail_tokens: int = 128, pipeline: bool = True,
                 sampler: str = "device", fetch_windows: int = 0,
                 metrics=None):
        import jax.numpy as jnp

        from hadoop_tpu.serving.weightplane import is_quantized_tree
        if sampler not in ("device", "host"):
            raise ValueError(
                f"serving.longctx.decode.sampler must be 'device' or "
                f"'host', got {sampler!r}")
        quantized = is_quantized_tree(params)
        if quantized and not pipeline:
            raise ValueError(
                "int8-resident longctx weights need the pipelined "
                "decode path (serving.longctx.decode.pipeline=true): "
                "the legacy loop serves the checkpoint-dtype view only")
        if cfg.is_moe:
            raise NotImplementedError("longctx serves dense decoders "
                                      "only (same as the engine)")
        self.params = params
        self.cfg = cfg
        self.store = store
        self.block_size = int(block_size)
        self.win = int(window_blocks) * self.block_size
        self.tail_cap = int(tail_tokens)
        self.pipeline = bool(pipeline)
        self.sampler = sampler
        self.relaxed_qweights = quantized
        # slab depth: windows shipped per transfer/dispatch. The auto
        # default (= n_layers) makes per-token transfer count equal
        # the legacy loop's per-LAYER window count — O(chain/window)
        # slabs instead of O(layers x chain/window) slices — and makes
        # the two in-flight slabs together cost exactly 2 windows of
        # per-token working-set bytes.
        self.fetch_windows = int(fetch_windows) or cfg.n_layers
        if self.fetch_windows < 1:
            raise ValueError("serving.longctx.decode.fetch.windows "
                             "must be >= 1")
        self._jnp = jnp
        if self.pipeline:
            self._fused = _fused_for(cfg, self.win, self.tail_cap,
                                     self.fetch_windows, quantized)
            self._jits = None
        else:
            self._jits = _jits_for(cfg, self.win, self.tail_cap)
            self._fused = None
        self.metrics = metrics
        self.window_fetches = 0     # host->device window transfers
        self.tokens_decoded = 0
        self.dispatches = 0         # jit calls on the decode hot path

    # ------------------------------------------------------- accounting

    @property
    def _per_tok_bytes(self) -> int:
        item = np.dtype(self.cfg.dtype).itemsize
        return 2 * self.cfg.n_layers * self.cfg.n_kv_heads * \
            self.cfg.head_dim * item

    @property
    def slab_bytes(self) -> int:
        """One transferred slab: ``fetch_windows`` windows of ONE
        layer's K+V."""
        return self.fetch_windows * self.win * \
            (self._per_tok_bytes // self.cfg.n_layers)

    @property
    def hbm_window_bytes(self) -> int:
        """Device bytes the window paging keeps in flight: both slabs
        of the double buffer when pipelining (one computing, one in
        transfer), one window's worth on the legacy loop."""
        if self.pipeline:
            return 2 * self.slab_bytes
        return self.win * self._per_tok_bytes

    @property
    def sampler_state_bytes(self) -> int:
        """Device-resident sampler state (in-graph sampling only): the
        folded PRNG key + the sampled int32 token."""
        if self.pipeline and self.sampler == "device":
            return 12
        return 0

    @property
    def hbm_working_set_bytes(self) -> int:
        """What this decoder keeps device-resident per request: the
        in-flight window slabs + the tail buffers + sampler state. The
        number the 'working set, not the full context' contract is
        about."""
        return self.hbm_window_bytes + \
            self.tail_cap * self._per_tok_bytes + \
            self.sampler_state_bytes

    @property
    def dispatches_per_token(self) -> float:
        return self.dispatches / max(1, self.tokens_decoded)

    def _disp(self, name: str) -> None:
        self.dispatches += 1
        _DISPATCHES[name] = _DISPATCHES.get(name, 0) + 1

    def _note_fetch(self) -> None:
        self.window_fetches += 1
        if self.metrics:
            self.metrics.longctx_window_fetches.incr()

    # ------------------------------------------------------------ decode

    def paged_decode(self, tokens: List[int], first_token: int,
                     sampling, *, tail_k=None, tail_v=None,
                     deliver: Callable[[int], None],
                     stop: Optional[Callable[[], bool]] = None,
                     seed: int = 0, rng=None, parent_ctx=None) -> int:
        """Generate up to ``sampling.max_new_tokens - 1`` tokens after
        ``first_token`` (which prefill already delivered), paging the
        prompt's KV chain in windows. Relaxed-tier entry point
        (``parity/relaxed-gated``). Returns tokens emitted here."""
        jnp = self._jnp
        cfg = self.cfg
        bs = self.block_size
        s = len(tokens)
        n_full = s // bs
        tail_len = s - n_full * bs
        if tail_len + sampling.max_new_tokens > self.tail_cap:
            raise ValueError(
                f"prompt tail ({tail_len}) + max_new "
                f"({sampling.max_new_tokens}) exceeds the longctx tail "
                f"budget {self.tail_cap} "
                f"(serving.longctx.decode.tail.tokens)")
        # ---- the chain pages back from the tiers (host probe, DFS
        # hedged-read windows) — NOT into the engine's pool: it lands
        # host-resident and only ever visits HBM one window at a time
        hits = self.store.read_chain(tokens, n_full,
                                     parent_ctx=parent_ctx)
        if len(hits) < n_full:
            raise RuntimeError(
                f"longctx KV chain has a gap: {len(hits)}/{n_full} "
                f"blocks recoverable from the host/DFS tiers (host ring "
                f"too small without the DFS tier?)")
        chain_len = n_full * bs
        # ---- device-resident tail: prompt's partial block + every
        # generated token's K/V
        tshape = (cfg.n_layers, self.tail_cap, cfg.n_kv_heads,
                  cfg.head_dim)
        kt = np.zeros(tshape, cfg.dtype)
        vt = np.zeros(tshape, cfg.dtype)
        if tail_len:
            kt[:, :tail_len] = tail_k
            vt[:, :tail_len] = tail_v
        ktail, vtail = jnp.asarray(kt), jnp.asarray(vt)
        base = n_full * bs
        n_tail = tail_len
        if rng is None:
            rng = np.random.default_rng(seed)
        sp = sampling
        cur = first_token
        pos = s                        # first_token's absolute position
        emitted = 0
        out_count = 1                  # first_token already delivered
        if self.pipeline:
            return self._decode_fused(
                hits, chain_len, cur, pos, ktail, vtail, base, n_tail,
                sp, seed, rng, deliver, stop, out_count)
        # ---- legacy per-(layer, window) loop: the pre-pipelining path,
        # byte-identical — the bitwise fallback and the fused path's
        # A-B reference. ONE preallocated buffer at the window-padded
        # shape, hits written in place: the chain is the dominant host
        # allocation at real scale, and an assemble-then-pad
        # concatenate pair would hold TWO copies live at peak.
        padded = chain_len + ((-chain_len) % self.win)
        shape = (cfg.n_layers, padded, cfg.n_kv_heads, cfg.head_dim)
        knp = np.zeros(shape, hits[0].k.dtype if hits else cfg.dtype)
        vnp = np.zeros(shape, knp.dtype)
        for i, h in enumerate(hits):
            knp[:, i * bs:(i + 1) * bs] = h.k
            vnp[:, i * bs:(i + 1) * bs] = h.v
        while out_count < sp.max_new_tokens and \
                (sp.stop_token is None or cur != sp.stop_token) and \
                (stop is None or not stop()):
            logits, ktail, vtail, n_tail = self._token(
                cur, pos, knp, vnp, chain_len, ktail, vtail, base,
                n_tail)
            nxt = _host_sample(logits, sp.temperature, sp.top_k, rng)
            deliver(nxt)
            emitted += 1
            out_count += 1
            cur = nxt
            pos += 1
        self.tokens_decoded += emitted
        return emitted

    def _decode_fused(self, hits, chain_len: int, cur: int, pos: int,
                      ktail, vtail, base: int, n_tail: int, sp,
                      seed: int, rng, deliver, stop,
                      out_count: int) -> int:
        """The pipelined loop: pack the chain into per-(layer, slab)
        transfer units, then per token run the fused dispatch chain
        with the next slab always in flight behind the current one."""
        cfg = self.cfg
        bs = self.block_size
        st = self.fetch_windows * self.win      # tokens per slab
        # slab-packed host chain: [L, n_slabs, 2(k,v), slab_tokens,
        # Hkv, Dh]. Each [l, s] plane is one contiguous device_put —
        # a block (bs | win | slab_tokens) never straddles a slab.
        padded = chain_len + ((-chain_len) % st)
        n_slabs = padded // st
        kvnp = np.zeros((cfg.n_layers, n_slabs, 2, st, cfg.n_kv_heads,
                         cfg.head_dim),
                        hits[0].k.dtype if hits else cfg.dtype)
        for i, h in enumerate(hits):
            sl, off = divmod(i * bs, st)
            kvnp[:, sl, 0, off:off + bs] = h.k
            kvnp[:, sl, 1, off:off + bs] = h.v
        emitted = 0
        while out_count < sp.max_new_tokens and \
                (sp.stop_token is None or cur != sp.stop_token) and \
                (stop is None or not stop()):
            res, ktail, vtail = self._token_fused(
                cur, pos, kvnp, chain_len, ktail, vtail, base, n_tail,
                sp, seed)
            if self.sampler == "device":
                nxt = int(res)    # the one 4-byte readback per token
            else:
                # deliberate host sync: the fallback sampler draws from
                # the [V] logits on the host rng stream
                nxt = _host_sample(np.asarray(res), sp.temperature,  # lint: disable=jit/blocking-in-step
                                   sp.top_k, rng)
            n_tail += 1
            deliver(nxt)
            emitted += 1
            out_count += 1
            cur = nxt
            pos += 1
        self.tokens_decoded += emitted
        return emitted

    def _token_fused(self, tok: int, pos: int, kvnp, chain_len: int,
                     ktail, vtail, base: int, n_tail: int, sampling,
                     seed: int):
        """One token through the fused dispatch chain. Per (layer,
        slab) the NEXT slab's ``device_put`` is issued before the
        current slab's ``fwin`` dispatch, so the transfer rides behind
        the attention partials (the paged-attention double buffer at
        the jit boundary). Dispatches: 1 fstart + (L-1) fadvance +
        L*n_slabs fwin + 1 ffinish/fhead."""
        import jax
        jnp = self._jnp
        J = self._fused
        nl = self.cfg.n_layers
        pos_j = jnp.int32(pos)
        idx_j = jnp.int32(n_tail)
        base_j = jnp.int32(base)
        cl_j = jnp.int32(chain_len)
        layers = self.params["layers"]
        n_slabs = kvnp.shape[1]
        st = self.fetch_windows * self.win
        # slab (0, 0) goes in flight BEFORE the first dispatch: the
        # embed + layer-0 entry computes under the first transfer
        nxt_slab = None
        if n_slabs:
            nxt_slab = jax.device_put(kvnp[0, 0])
            self._note_fetch()
        h, q, ktail, vtail, o, lse = J.fstart(
            self.params, layers, jnp.int32(tok), pos_j, ktail, vtail,
            idx_j, base_j)
        self._disp(f"fstart@{J.family}")
        for l in range(nl):
            if l > 0:
                h, q, ktail, vtail, o, lse = J.fadvance(
                    layers, jnp.int32(l), h, o, pos_j, ktail, vtail,
                    idx_j, base_j)
                self._disp(f"fadvance@{J.family}")
            for s in range(n_slabs):
                cur_slab = nxt_slab
                if s + 1 < n_slabs:
                    nxt_slab = jax.device_put(kvnp[l, s + 1])
                    self._note_fetch()
                elif l + 1 < nl:
                    nxt_slab = jax.device_put(kvnp[l + 1, 0])
                    self._note_fetch()
                o, lse = J.fwin(q, o, lse, cur_slab, jnp.int32(s * st),
                                cl_j, pos_j)
                self._disp(f"fwin@{J.family}")
        if self.sampler == "device":
            out = J.ffinish(self.params, layers, h, o, pos_j,
                            jnp.float32(sampling.temperature),
                            jnp.int32(sampling.top_k), jnp.int32(seed))
            self._disp(f"ffinish@{J.family}")
        else:
            out = J.fhead(self.params, layers, h, o)
            self._disp(f"fhead@{J.family}")
        return out, ktail, vtail

    def _token(self, tok: int, pos: int, knp, vnp, chain_len: int,
               ktail, vtail, base: int, n_tail: int):
        """One full forward for one token (legacy loop): per layer,
        scatter its K/V into the tail, then merge attention partials
        over the tail and over the chain paged through the fixed
        window. ``knp``/``vnp`` arrive padded to a window multiple;
        ``chain_len`` is the true context length the positions mask
        against."""
        jnp = self._jnp
        J = self._jits
        cfg = self.cfg
        pos_j = jnp.int32(pos)
        h = J.embed(self.params, jnp.int32(tok), pos_j)
        self._disp(f"embed@{J.family}")
        layers = self.params["layers"]
        n_win = knp.shape[1] // self.win
        idx = n_tail            # this token's tail slot
        for l in range(cfg.n_layers):
            l_j = jnp.int32(l)
            q, k, v = J.layer_in(layers, l_j, h, pos_j)
            self._disp(f"layer_in@{J.family}")
            ktail, vtail = J.tail_set(ktail, vtail, l_j,
                                      jnp.int32(idx), k, v)
            self._disp(f"tail_set@{J.family}")
            o, lse = J.tail(q, ktail, vtail, l_j, pos_j,
                            jnp.int32(base), jnp.int32(idx + 1))
            self._disp(f"tail@{J.family}")
            for w in range(n_win):
                w0 = w * self.win
                ow, lw = J.win(q, knp[l, w0:w0 + self.win],
                               vnp[l, w0:w0 + self.win], pos_j,
                               jnp.int32(w0),
                               jnp.int32(min(chain_len - w0, self.win)))
                self._disp(f"win@{J.family}")
                o, lse = J.merge(o, lse, ow, lw)
                self._disp(f"merge@{J.family}")
                # every J.win call slices+transfers one (layer, window)
                # piece of the host chain — that IS this loop's HBM
                # traffic unit (the pipelined path counts per slab)
                self._note_fetch()
            h = J.layer_out(layers, l_j, h, o)
            self._disp(f"layer_out@{J.family}")
        logits = np.asarray(J.head(self.params, h))
        self._disp(f"head@{J.family}")
        return logits, ktail, vtail, n_tail + 1
