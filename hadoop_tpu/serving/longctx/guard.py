"""A-B acceptance for the long-context plane.

The CP softmax reassociation (online-softmax merges across ranks, and
across paged windows at decode) is not bitwise vs the single-chip
reference — same deal as the lowp collectives — so the plane ships
behind the repo's standard two-mode guard:

- **exact** (small shapes, where the single-chip reference fits): the
  CP prefill's last-token logits must be allclose at tight tolerance
  AND greedy-argmax-identical to ``models.decoder.forward`` — the
  ``run_weight_ab``-style contract.
- **relaxed** (at scale): bounded logit divergence
  (``serving.longctx.guard.rel-tol``) plus argmax agreement — the
  logits guard, reported with the measured divergence so a rejection
  says HOW far off (``parallel.lowp.guard.allclose_guard`` ethos).

Both return a plain report dict (benches record it; the smoke's JSON
carries the trajectory) and raise ``ParityGuardError`` on rejection —
the same exception the lowp and weight-plane guards raise, so "the
guard rejected" means one thing everywhere.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from hadoop_tpu.parallel.lowp.guard import ParityGuardError


def longctx_ab_report(ref_logits, cp_logits, *, mode: str = "exact",
                      rel_tol: float = 0.05,
                      exact_atol: float = 5e-4) -> Dict:
    """Judge CP last-token logits against the single-chip reference.
    Raises :class:`ParityGuardError` on rejection, returns the
    divergence report on acceptance."""
    ref = np.asarray(ref_logits, np.float32).reshape(-1)
    got = np.asarray(cp_logits, np.float32).reshape(-1)
    if ref.shape != got.shape:
        raise ParityGuardError(
            f"longctx guard: logits shape {got.shape} != {ref.shape}")
    d = np.abs(ref - got)
    max_abs = float(d.max(initial=0.0))
    max_rel = float((d / np.maximum(np.abs(ref), 1e-6)).max(initial=0.0))
    agree = int(np.argmax(ref)) == int(np.argmax(got))
    report = {"mode": mode, "max_abs": max_abs, "max_rel": max_rel,
              "argmax_agree": agree}
    if mode == "exact":
        report["atol"] = exact_atol
        ok = agree and max_abs <= exact_atol
    elif mode == "relaxed":
        report["rel_tol"] = rel_tol
        ok = agree and max_rel <= rel_tol
    else:
        raise ValueError(f"guard mode must be exact|relaxed, got {mode!r}")
    report["accepted"] = ok
    if not ok:
        raise ParityGuardError(
            f"longctx {mode} guard rejected: max_abs={max_abs:.3e}, "
            f"max_rel={max_rel:.3e}, argmax_agree={agree}")
    return report


def run_prefill_ab(params, cfg, tokens: List[int], prefiller, *,
                   mode: str = "exact", rel_tol: float = 0.05,
                   exact_atol: float = 5e-4) -> Dict:
    """The prefill A-B: CP prefill of ``tokens`` on ``prefiller`` vs
    the single-chip ``decoder.forward`` last-token logits. One shared
    harness — tests, the smoke and the bench all call this, so
    "passes the longctx guard" means the same thing everywhere."""
    import jax.numpy as jnp

    from hadoop_tpu.models.decoder import forward

    ref = np.asarray(
        forward(params, jnp.asarray(tokens, jnp.int32)[None, :],
                cfg)[0, -1], np.float32)
    res = prefiller.cp_prefill(tokens)
    report = longctx_ab_report(ref, res.last_logits, mode=mode,
                               rel_tol=rel_tol, exact_atol=exact_atol)
    report.update(chips=res.chips, sp_mode=res.sp_mode,
                  prompt_tokens=len(tokens),
                  prefill_seconds=round(res.seconds, 4))
    return report
