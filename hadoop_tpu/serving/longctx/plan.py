"""CP plan construction for inference-shaped batches.

Training already knows how to build meshes (``parallel/mesh.py``), but
its five-axis mesh is shaped for dp×pp×tp×ep×sp training steps; a
long-context prefill is a batch-of-one, sequence-sharded job, so the
serving plane builds a dedicated ONE-axis ``sp`` mesh instead — every
chip of the replica becomes a context-parallel rank, and outputs are
sharded over the only axis there is (which also keeps shard_map's
replication checks trivially satisfiable on every jax version the
repo runs under).

Topology-aware placement per TASP (PAPERS: arXiv:2509.26541): ring
attention moves one K/V shard per step between CONSECUTIVE ranks, so
the rank order decides whether every hop is one ICI link or a tour of
the pod. ``ring_order`` snakes through the device coordinate grid so
consecutive ranks are physical neighbors (and the wrap-around hop is
short); devices without coordinates (the CPU-sim mesh, single hosts)
fall back to id order, which is exactly the old behavior.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Sequence

import numpy as np

log = logging.getLogger(__name__)


def ring_order(devices: Sequence) -> List:
    """Order ``devices`` so consecutive entries are topology neighbors.

    Devices exposing ``coords`` (TPU PJRT) are snake-sorted through
    their coordinate grid: ranks walk axis -1 forward on even rows and
    backward on odd rows, so every consecutive pair differs by one
    step on one axis — each ring hop is a single ICI link. Devices
    without coords keep id order (the CPU-sim mesh has no topology to
    respect)."""
    devs = list(devices)
    if any(getattr(d, "coords", None) is None for d in devs):
        return sorted(devs, key=lambda d: d.id)
    coords = {d: tuple(d.coords) for d in devs}
    ndim = max(len(c) for c in coords.values())
    spans = [sorted({c[i] for c in coords.values() if len(c) > i})
             for i in range(ndim)]

    def snake_key(d):
        c = coords[d]
        key = []
        flip = False
        for i in range(ndim):
            axis = spans[i]
            idx = axis.index(c[i]) if i < len(c) and c[i] in axis else 0
            key.append(len(axis) - 1 - idx if flip else idx)
            # an odd ORIGINAL position on this axis reverses the walk
            # of the next — the mixed-radix reflected-Gray rule that
            # turns row-major order into a snake (propagating the
            # REFLECTED digit's parity instead breaks the unit-hop
            # invariant on even-sized 3D grids, e.g. 2x2x2)
            flip = (flip != bool(idx % 2))
        return tuple(key)

    return sorted(devs, key=snake_key)


def cp_mesh(sp: int, devices: Optional[Sequence] = None):
    """A one-axis ``sp`` mesh over the first ``sp`` ring-ordered
    devices — the mesh every long-context prefill runs on."""
    import jax
    from jax.sharding import Mesh

    devs = ring_order(devices if devices is not None else jax.devices())
    if len(devs) < sp:
        raise ValueError(f"longctx plan needs {sp} devices, "
                         f"have {len(devs)}")
    return Mesh(np.array(devs[:sp]), ("sp",))


def choose_sp_mode(cfg, sp: int, requested: str = "ring") -> str:
    """Validate the conf-selected CP attention strategy against the
    model's head counts: ulysses needs both head counts divisible by
    the axis (``parallel/ulysses.py``); ring handles any shape. An
    impossible ulysses request degrades to ring with a loud log — a
    conf typo must not refuse a fleet's whole long-context workload."""
    if requested not in ("ring", "ulysses"):
        raise ValueError("serving.longctx.sp.mode must be ring|ulysses, "
                         f"got {requested!r}")
    if requested == "ulysses" and sp > 1:
        from hadoop_tpu.parallel.ulysses import supports
        if not supports(cfg.n_heads, cfg.n_kv_heads, sp):
            log.warning(
                "serving.longctx.sp.mode=ulysses needs n_heads(%d) and "
                "n_kv_heads(%d) divisible by the %d-chip axis; "
                "falling back to ring", cfg.n_heads, cfg.n_kv_heads, sp)
            return "ring"
    return requested
