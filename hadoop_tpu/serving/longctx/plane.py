"""The long-context serving plane: one replica's monster-prompt lane.

Ties the pieces into a request lifecycle the door already understands:

    engine.submit() routes prompts >= ``serving.longctx.min.tokens``
    here (under the ``serving.parity=relaxed`` guard) →
    CP prefill across the replica's mesh (``prefill.py``) →
    finished KV chunks stream STRAIGHT into the host/DFS tiers
    (``TieredKVCache.ingest_chain`` — digest-chained, codec-eligible,
    never pinned in the HBM pool) →
    first token sampled from the CP logits →
    working-set decode (``decode.py``) pages the chain back through a
    fixed device window while generated tokens' KV accumulates in the
    device tail.

The plane runs its own single worker thread: a monster prefill is a
whole-mesh job, so two can't overlap anyway, and the engine's fused
step keeps serving short prompts underneath it untouched (the
compile-once contract of the two step shapes survives — the longctx
path adds only its OWN pinned shapes, counted separately).

Requests are ordinary ``GenRequest``s: tokens stream through the same
queue, the same door handlers, the same trace ids
(``serving.longctx.prefill`` / ``serving.longctx.decode`` spans join
the request trace), and the same metrics surface (``htpu_longctx_*``).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from hadoop_tpu.models.config import ModelConfig
from hadoop_tpu.serving.longctx.decode import (WorkingSetDecoder,
                                               _host_sample)
from hadoop_tpu.serving.longctx.prefill import ContextParallelPrefiller
from hadoop_tpu.tracing.tracer import global_tracer

log = logging.getLogger(__name__)

ENABLED_KEY = "serving.longctx.enabled"
MIN_TOKENS_KEY = "serving.longctx.min.tokens"
MAX_TOKENS_KEY = "serving.longctx.max.tokens"
CHIPS_KEY = "serving.longctx.chips"
SP_MODE_KEY = "serving.longctx.sp.mode"
WINDOW_KEY = "serving.longctx.decode.window.blocks"
TAIL_KEY = "serving.longctx.decode.tail.tokens"
PIPELINE_KEY = "serving.longctx.decode.pipeline"
SAMPLER_KEY = "serving.longctx.decode.sampler"
FETCH_KEY = "serving.longctx.decode.fetch.windows"


class LongContextPlane:
    """CP prefill + tier streaming + working-set decode behind one
    submit seam. Construct directly (tests, benches) or from conf via
    :func:`longctx_plane_from_conf`."""

    def __init__(self, params, cfg: ModelConfig, store, *,
                 block_size: int, min_tokens: int,
                 max_tokens: Optional[int] = None, sp: int = 0,
                 sp_mode: str = "ring", window_blocks: int = 4,
                 tail_tokens: int = 256, pipeline: bool = True,
                 sampler: str = "device", fetch_windows: int = 0,
                 devices=None, metrics=None, tracer=None):
        if not store.cold_enabled:
            raise ValueError(
                "the longctx plane streams prefill KV into the cold "
                "tiers — enable serving.kv.host.bytes and/or "
                "serving.kv.dfs.enable")
        # a quantized tree serves int8-resident: CP prefill and the
        # pipelined decoder route their matmuls through the weight
        # plane (qdot/qslice/qhead), so the plane shares the engine's
        # one resident copy — no dequantized view, no second model in
        # HBM. The attribute survives (always 0 now) for the stats /
        # health surface that capacity tooling already scrapes.
        self.dequantized_view_bytes = 0
        self.cfg = cfg
        self.store = store
        self.min_tokens = int(min_tokens)
        self.metrics = metrics
        self.tracer = tracer or global_tracer()
        self.prefiller = ContextParallelPrefiller(
            params, cfg, block_size=block_size,
            pad_tokens=max_tokens or cfg.max_seq, sp=sp,
            sp_mode=sp_mode, devices=devices)
        self.decoder = WorkingSetDecoder(
            params, cfg, store, block_size=block_size,
            window_blocks=window_blocks, tail_tokens=tail_tokens,
            pipeline=pipeline, sampler=sampler,
            fetch_windows=fetch_windows, metrics=metrics)
        self.requests_served = 0
        self.blocks_streamed = 0
        self._q: "queue.Queue" = queue.Queue()
        # accepted-but-unfinished requests: incremented at submit
        # BEFORE the queue put, decremented after serve — `idle` can
        # never race a request sitting between q.get() and "busy"
        self._inflight = 0              # guarded-by: _inflight_lock
        self._inflight_lock = threading.Lock()
        # invoked after every request completes (success or failure):
        # the engine wires its scheduler condition here so a drain
        # parked on `idle` wakes when the plane finishes, instead of
        # sleeping out its whole timeout
        self.on_done = None
        self._stopped = threading.Event()
        # orders submit's stopped-check+enqueue against stop(): a
        # submit racing shutdown either lands BEFORE the sentinel (the
        # drain loop fails it) or observes _stopped and raises — never
        # an orphaned request behind a dead worker
        self._admit_lock = threading.Lock()
        self._worker = threading.Thread(target=self._work_loop,
                                        name="longctx-plane",
                                        daemon=True)
        self._worker.start()
        if metrics:
            metrics.longctx_chips.set(self.prefiller.sp)
        # live HBM ledger (obs/hbm.py): the decode working set split
        # into window (BOTH in-flight slabs of the double buffer when
        # pipelining — 2x one window at the default slab depth), tail
        # (device-resident prompt tail + generated tokens), and the
        # in-graph sampler's device state when it is on
        from hadoop_tpu.obs.hbm import hbm_ledger
        # trailing separator: see engine's _hbm_owner note
        self._hbm_owner = f"longctx@{id(self)}."
        dec = self.decoder
        led = hbm_ledger()
        led.register(f"{self._hbm_owner}window", "longctx_window",
                     lambda: dec.hbm_window_bytes)
        led.register(f"{self._hbm_owner}tail", "longctx_tail",
                     lambda: dec.tail_cap * dec._per_tok_bytes)
        if dec.sampler_state_bytes:
            led.register(f"{self._hbm_owner}sampler", "longctx_sampler",
                         lambda: dec.sampler_state_bytes)

    # ----------------------------------------------------------- submit

    def longctx_submit(self, prompt: List[int], sampling=None,
                       trace_ctx=None, tenant: str = ""):
        """Admit one monster prompt. Relaxed-tier entry point
        (``parity/relaxed-gated``): the engine calls this under its
        ``serving.parity=relaxed`` guard. Raises ``ValueError`` for
        requests the plane can NEVER serve (the door's 400)."""
        from hadoop_tpu.serving.engine import GenRequest, SamplingParams
        sampling = sampling or SamplingParams()
        s = len(prompt)
        bs = self.decoder.block_size
        if s > self.prefiller.pad_tokens:
            raise ValueError(
                f"prompt ({s} tokens) exceeds {MAX_TOKENS_KEY}="
                f"{self.prefiller.pad_tokens}")
        if s + sampling.max_new_tokens > self.cfg.max_seq:
            # generated-token positions past the rope/pos tables would
            # silently clamp to the last row — wrong logits, no error
            # (the fused path's s_max check, which this lane bypasses,
            # guards exactly this)
            raise ValueError(
                f"prompt({s}) + max_new({sampling.max_new_tokens}) "
                f"exceeds the model's max_seq {self.cfg.max_seq}")
        tail_len = s % bs
        if tail_len + sampling.max_new_tokens > self.decoder.tail_cap:
            raise ValueError(
                f"prompt tail ({tail_len}) + max_new "
                f"({sampling.max_new_tokens}) exceeds {TAIL_KEY}="
                f"{self.decoder.tail_cap}")
        n_full = s // bs
        if not self.store.dfs_enabled and self.store.host is not None:
            # host-ring-only deployments must hold the WHOLE chain
            # PLUS churn slack: the fused step demotes its evictions
            # into the SAME ring, and an exact-fit chain would lose
            # its head to the first concurrent short-prompt demotion
            # (one full pool sweep is the realistic per-request bound;
            # sustained heavier churn wants the DFS tier)
            need = n_full + self.store.pool.num_usable
            if self.store.host.capacity < need:
                raise ValueError(
                    f"longctx chain needs {n_full} host-ring blocks "
                    f"plus {self.store.pool.num_usable} demotion-churn "
                    f"slack but serving.kv.host.bytes holds "
                    f"{self.store.host.capacity}; grow the ring or "
                    f"enable the DFS tier")
        req = GenRequest(prompt=list(prompt), sampling=sampling,
                         trace_ctx=trace_ctx, tenant=tenant)
        with self._admit_lock:
            if self._stopped.is_set():
                raise ValueError("longctx plane is stopped")
            with self._inflight_lock:
                self._inflight += 1
            self._q.put(req)
        if self.metrics:
            self.metrics.requests.incr()
            self.metrics.longctx_requests.incr()
        return req

    # ----------------------------------------------------- request work

    def _work_loop(self) -> None:
        from hadoop_tpu.serving.engine import FAILED
        while True:
            req = self._q.get()
            if req is None:
                return
            try:
                self._serve(req)
            except Exception as e:  # noqa: BLE001 — fail the request,
                # not the lane: a poisoned prompt must not wedge every
                # future monster prompt behind a dead worker
                log.warning("longctx request %d failed: %s", req.id, e)
                req._finish(FAILED, f"longctx failed: {e}")
            finally:
                with self._inflight_lock:
                    self._inflight -= 1
                done_cb = self.on_done
                if done_cb is not None:
                    done_cb()

    def _serve(self, req) -> None:
        from hadoop_tpu.serving.engine import FAILED, FINISHED, RUNNING
        req.state = RUNNING
        sp = self.tracer.span("serving.longctx.prefill",
                              parent=req.trace_ctx)
        sp.add_kv("request", str(req.id))
        sp.add_kv("prompt_tokens", str(len(req.prompt)))
        sp.add_kv("chips", str(self.prefiller.sp))
        sp.add_kv("sp_mode", self.prefiller.sp_mode)
        try:
            res = self.prefiller.cp_prefill(req.prompt)
        finally:
            sp.finish()
        # first token BEFORE the tier ingest: it only needs the CP
        # logits, so TTFT is prefill time, not prefill + DataNode writes
        rng = np.random.default_rng(req.id)
        smp = req.sampling
        first = _host_sample(res.last_logits, smp.temperature,
                             smp.top_k, rng)
        self._deliver(req, first)
        ttft = req.first_token_at - req.submitted_at
        if self.metrics:
            self.metrics.ttft.add(ttft)
            self.metrics.ttft_hist.add(
                ttft, exemplar_trace=req.trace_ctx.trace_id
                if req.trace_ctx is not None and req.trace_ctx.sampled
                else None)
        streamed = self.store.ingest_chain(req.prompt, res.blocks,
                                           parent_ctx=req.trace_ctx)
        self.blocks_streamed += streamed
        if self.metrics:
            self.metrics.longctx_blocks_streamed.incr(streamed)
            self.metrics.longctx_prefill_hist.add(res.seconds)
        if streamed and self.store.dfs_enabled:
            # decode reads the chain back THROUGH the tiers: when the
            # host ring is smaller than the chain, the head blocks only
            # exist on the DataNodes — wait for durability or the
            # read_chain below races the background writer into a gap
            if not self.store.flush(timeout=120.0,
                                    up_to=self.store.persists_enqueued):
                # fail with the REAL cause, not the downstream
                # chain-gap error read_chain would report
                raise RuntimeError(
                    "longctx DFS persist did not drain before decode "
                    "(DataNodes slow or refusing writes?)")
        done = smp.max_new_tokens <= 1 or \
            (smp.stop_token is not None and first == smp.stop_token)
        if not done:
            dsp = self.tracer.span("serving.longctx.decode",
                                   parent=req.trace_ctx)
            dsp.add_kv("request", str(req.id))
            try:
                # the SAME rng that drew the first token: re-seeding
                # here would replay its uniform stream on the second
                # token's sample (correlated consecutive draws). The
                # in-graph sampler keys off seed=req.id instead (its
                # jax key stream is position-folded per token, so it
                # never replays either); greedy decoding is identical
                # on both.
                self.decoder.paged_decode(
                    req.prompt, first, smp,
                    tail_k=res.tail_k, tail_v=res.tail_v,
                    deliver=lambda t: self._deliver(req, t),
                    stop=self._stopped.is_set, seed=req.id, rng=rng,
                    parent_ctx=req.trace_ctx)
            finally:
                dsp.add_kv("tokens_out", str(len(req.out_tokens)))
                dsp.finish()
        self.requests_served += 1
        # a non-drain stop truncates the generation mid-flight: that
        # must surface as a FAILURE (the fused-step path fails its
        # in-flight requests on stop too) — a client asking for 200
        # tokens must be able to tell 37-then-stopped from complete
        truncated = self._stopped.is_set() and \
            len(req.out_tokens) < smp.max_new_tokens and \
            (smp.stop_token is None or
             req.out_tokens[-1] != smp.stop_token)
        if truncated:
            req._finish(FAILED, "longctx plane stopped mid-generation")
        else:
            req._finish(FINISHED)

    def _deliver(self, req, tok: int) -> None:
        req._deliver(tok)
        if self.metrics:
            self.metrics.tokens_out.incr()

    # ------------------------------------------- disaggregation handoff

    def prefill_to_store(self, prompt: List[int],
                         timeout: float = 60.0) -> int:
        """The /v1/prefill half for monster prompts: CP prefill,
        stream the chain into the tiers, wait for DFS durability.
        Returns the durable token span (full blocks only)."""
        if not self.store.dfs_enabled:
            raise ValueError("longctx prefill handoff needs the DFS KV "
                             "tier (serving.kv.dfs.enable)")
        # the handoff runs on the door's HTTP thread, not the worker:
        # it must still count as in-flight work or a concurrent
        # engine.stop(drain=True) reads the plane idle and closes the
        # kvstore (killing the writer) under this flush
        with self._admit_lock:
            if self._stopped.is_set():
                raise ValueError("longctx plane is stopped")
            with self._inflight_lock:
                self._inflight += 1
        try:
            fails_before = self.store.stats()["dfs_persist_failures"]
            res = self.prefiller.cp_prefill(prompt)
            n = self.store.ingest_chain(prompt, res.blocks)
            watermark = self.store.persists_enqueued
            if n and not self.store.flush(timeout, up_to=watermark):
                raise TimeoutError(
                    f"longctx DFS persist did not drain in {timeout}s")
            # flush() counts FAILED persists toward its watermark — a
            # refused DataNode must not be reported as a durable
            # handoff (the engine's radix path re-verifies via
            # persisted_span; the chain path re-verifies via the
            # failure counter). Concurrent requests' failures can only
            # make this report MORE conservative, never claim
            # durability that isn't there.
            fails = self.store.stats()["dfs_persist_failures"] \
                - fails_before
            durable = max(0, n - fails)
            if n and not durable:
                raise RuntimeError(
                    f"longctx handoff persist failed: 0/{n} blocks "
                    "durable (DataNodes refusing writes?)")
            return durable * self.decoder.block_size
        finally:
            with self._inflight_lock:
                self._inflight -= 1
            done_cb = self.on_done
            if done_cb is not None:
                done_cb()

    # -------------------------------------------------------- lifecycle

    @property
    def idle(self) -> bool:
        with self._inflight_lock:
            return self._inflight == 0

    def stop(self, drain: bool = False, timeout: float = 30.0) -> None:
        from hadoop_tpu.serving.engine import FAILED
        from hadoop_tpu.obs.hbm import hbm_ledger
        hbm_ledger().unregister_prefix(self._hbm_owner)
        if drain:
            deadline = time.monotonic() + timeout
            while not self.idle and time.monotonic() < deadline:
                time.sleep(0.02)
        with self._admit_lock:
            # once set under the lock no further submit can enqueue;
            # everything in the queue is older than the sentinel
            self._stopped.set()
            self._q.put(None)
        self._worker.join(timeout=timeout)
        # fail anything still queued — a submit that raced this
        # shutdown must fail its request, never strand a client parked
        # on .done forever
        sentinel_seen = False
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                break
            if req is None:
                sentinel_seen = True
                continue
            with self._inflight_lock:
                self._inflight -= 1
            if not req.done.is_set():
                req._finish(FAILED, "longctx plane stopped")
        if sentinel_seen and self._worker.is_alive():
            # the join timed out mid-request and this drain swallowed
            # the worker's shutdown sentinel — re-arm it, or the
            # worker's next q.get() blocks forever
            self._q.put(None)

    def stats(self) -> Dict:
        from hadoop_tpu.serving.longctx.decode import (dispatch_counts,
                                                       trace_counts)
        dec = self.decoder
        return {
            "enabled": True,
            "min_tokens": self.min_tokens,
            "max_tokens": self.prefiller.pad_tokens,
            "chips": self.prefiller.sp,
            "sp_mode": self.prefiller.sp_mode,
            "requests": self.requests_served,
            "blocks_streamed": self.blocks_streamed,
            "window_fetches": dec.window_fetches,
            "window_tokens": dec.win,
            "tail_tokens": dec.tail_cap,
            "decode_pipeline": dec.pipeline,
            "decode_sampler": dec.sampler,
            "fetch_windows": dec.fetch_windows,
            "int8_weights": dec.relaxed_qweights,
            "tokens_decoded": dec.tokens_decoded,
            "decode_dispatches": dec.dispatches,
            "dispatches_per_token":
                round(dec.dispatches_per_token, 2),
            "hbm_window_bytes": dec.hbm_window_bytes,
            "hbm_working_set_bytes": dec.hbm_working_set_bytes,
            "dequantized_view_bytes": self.dequantized_view_bytes,
            "prefill_compiles": self.prefiller.prefill_compiles,
            "decode_traces": trace_counts(),
            "decode_dispatch_counts": dispatch_counts(),
        }


def longctx_plane_from_conf(conf, cfg: ModelConfig, engine
                            ) -> LongContextPlane:
    """Build the plane off a replica's conf + engine. Relaxed-tier
    entry point: callers gate on ``serving.parity=relaxed`` (and this
    re-validates — the CP softmax reassociation is not bitwise, so the
    plane must be unreachable under the bitwise default)."""
    from hadoop_tpu.serving.weightplane import weightplane_from_conf
    wp = weightplane_from_conf(conf)
    if not wp.relaxed:
        raise ValueError(
            f"{ENABLED_KEY} requires serving.parity=relaxed — the CP "
            "softmax reassociation is not bitwise vs the single-chip "
            "step")
    return LongContextPlane(
        engine.params, cfg, engine.kvstore,
        block_size=engine.block_size,
        min_tokens=conf.get_int(MIN_TOKENS_KEY, 4096),
        max_tokens=conf.get_int(MAX_TOKENS_KEY, 0) or cfg.max_seq,
        sp=conf.get_int(CHIPS_KEY, 0),
        sp_mode=conf.get(SP_MODE_KEY, "ring"),
        window_blocks=conf.get_int(WINDOW_KEY, 4),
        tail_tokens=conf.get_int(TAIL_KEY, 256),
        pipeline=conf.get_bool(PIPELINE_KEY, True),
        sampler=conf.get(SAMPLER_KEY, "device"),
        fetch_windows=conf.get_int(FETCH_KEY, 0),
        metrics=engine.metrics, tracer=engine.tracer)
