"""Context-parallel prefill: one monster prompt sharded over the mesh.

The engine's chunked prefill walks a prompt ``prefill_chunk`` tokens
per fused step — time linear in the prompt, HBM linear in the prompt.
This module runs the SAME prefill as a CP job instead: the prompt is
sequence-sharded over a one-axis ``sp`` mesh (``plan.cp_mesh``), every
rank runs the full layer stack on its shard with ring attention
(``parallel/ring_attention.py`` — K/V shards rotate over ICI) or the
all-to-all ulysses strategy (``parallel/ulysses.py``, conf-selectable
via ``serving.longctx.sp.mode``), and the per-layer post-RoPE K/V of
every position comes back as data (``models.decoder.run_layers_kv``)
rather than staying trapped in activations. Prefill wall time divides
by the chip count; no single chip ever holds more than ``S/sp`` of
the context.

Compile-once: the job is jitted at ONE pinned shape —
``serving.longctx.max.tokens`` rounded up to a multiple of
``sp * block_size`` — and every prompt pads up to it (causal masking
makes the padded tail invisible to real positions, and padded KV is
never streamed). ``prefill_compiles`` counts traces exactly like the
engine's step counters; a second trace is a retracing bug.

The CP softmax reassociation (online-softmax merges across ranks) is
not bitwise vs the single-chip reference, which is why every call
into this module sits behind a ``serving.parity=relaxed`` guard
(tpulint's ``parity/relaxed-gated`` checker, with this package exempt
as the tier itself) and behind the A-B guard in ``guard.py``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Iterator, List, Optional, Tuple

import numpy as np

from hadoop_tpu.models.config import ModelConfig
from hadoop_tpu.serving.longctx.plan import choose_sp_mode, cp_mesh


@dataclass
class PrefillResult:
    """Everything a CP prefill hands downstream: the last real
    token's logits (first output token samples from these), the
    full-block K/V payloads as a STREAM (the caller forwards them to
    the tiered store without ever holding the whole context), and the
    partial tail block's K/V (never stored — digest chaining only keys
    full blocks — so it seeds the decoder's device-resident tail)."""
    last_logits: np.ndarray                 # [V] float32
    n_full_blocks: int
    blocks: Iterator[Tuple[np.ndarray, np.ndarray]] = field(repr=False)
    tail_k: Optional[np.ndarray] = None     # [L, S % bs, Hkv, Dh]
    tail_v: Optional[np.ndarray] = None
    seconds: float = 0.0
    chips: int = 1
    sp_mode: str = "ring"
    prompt_tokens: int = 0


class ContextParallelPrefiller:
    """One replica's CP prefill executable: mesh + one jitted
    shard_map program at one pinned shape, reused for every monster
    prompt the plane admits."""

    def __init__(self, params, cfg: ModelConfig, *, block_size: int,
                 pad_tokens: int, sp: int = 0, sp_mode: str = "ring",
                 devices=None):
        import jax

        devs = devices if devices is not None else jax.devices()
        self.sp = int(sp) if sp else len(devs)
        self.cfg = cfg
        self.params = params
        self.block_size = int(block_size)
        self.sp_mode = choose_sp_mode(cfg, self.sp, sp_mode)
        quantum = self.sp * self.block_size
        if int(pad_tokens) > cfg.max_seq:
            raise ValueError(
                f"serving.longctx.max.tokens={pad_tokens} exceeds the "
                f"model's max_seq {cfg.max_seq} — positions past the "
                f"rope/pos tables would silently clamp")
        self.pad_tokens = -(-int(pad_tokens) // quantum) * quantum
        if self.pad_tokens > cfg.max_seq:
            # the requested budget is legal but rounding UP to the
            # chip quantum overshoots max_seq (max_seq not divisible
            # by sp*block): round DOWN instead of refusing to start —
            # prompts in the shaved tail reject per-request, loudly
            self.pad_tokens = (cfg.max_seq // quantum) * quantum
            if self.pad_tokens < self.block_size:
                raise ValueError(
                    f"max_seq {cfg.max_seq} below one sp*block "
                    f"quantum ({quantum}) — too many chips for this "
                    f"model's sequence budget")
            import logging
            logging.getLogger(__name__).warning(
                "longctx pad budget rounded DOWN to %d (max_seq %d is "
                "not divisible by sp*block %d); prompts above it are "
                "rejected per-request", self.pad_tokens, cfg.max_seq,
                quantum)
        self.mesh = cp_mesh(self.sp, devices=devs)
        self.prefill_compiles = 0     # traces of the one pinned shape
        self.head_compiles = 0
        self._fn = self._build()
        self._head = self._build_head()

    # ---------------------------------------------------- compiled body

    def _build(self):
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        from hadoop_tpu.models.decoder import (ParallelCtx, embed_tokens,
                                               final_hidden, run_layers_kv)
        from hadoop_tpu.ops import rope_frequencies

        from hadoop_tpu.serving.weightplane import is_quantized_tree

        cfg, sp = self.cfg, self.sp
        # int8-resident CP weights: a quantized tree (the engine's own
        # weight plane, shared — no second resident copy) routes every
        # local matmul through the dequantizing qdot inside the decoder
        # body. The ctx flag is the relaxed-tier opt-in; a bitwise
        # deployment never loads a quantized tree in the first place.
        ctx = ParallelCtx(ring_axis="sp", ring_size=sp,
                          sp_mode=self.sp_mode,
                          relaxed_qweights=is_quantized_tree(self.params))

        def local(params, tokens):
            # tokens: this rank's [S_pad / sp] shard
            cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq,
                                        cfg.rope_theta)
            h = embed_tokens(params, tokens[None, :], cfg, ctx)
            h, (ks, vs) = run_layers_kv(h, params["layers"], cfg, ctx,
                                        cos, sin)
            h = final_hidden(params, h, cfg, ctx)
            # [S_local, D], [L, S_local, Hkv, Dh] x2 — K/V leave as
            # DATA, post-RoPE, exactly the engine's pool row layout
            return h[0], ks[:, 0], vs[:, 0]

        sharded = shard_map(
            local, mesh=self.mesh,
            in_specs=(P(), P("sp")),
            out_specs=(P("sp", None), P(None, "sp", None, None),
                       P(None, "sp", None, None)))

        def impl(params, tokens):
            # python side effect at trace time only: the compile-once
            # counter (same pattern as the engine's step counters)
            self.prefill_compiles += 1
            return sharded(params, tokens)

        return jax.jit(impl)

    def _build_head(self):
        import jax

        from hadoop_tpu.models.decoder import head_matrix
        from hadoop_tpu.serving.weightplane import is_qtensor, qhead
        cfg = self.cfg

        def impl(params, row):
            self.head_compiles += 1
            head = params["embed"] if cfg.tie_embeddings \
                else params.get("lm_head")
            if is_qtensor(head):
                return qhead(params, row, cfg).astype(np.float32)
            return (row @ head_matrix(params, cfg, row.dtype)).astype(
                np.float32)

        return jax.jit(impl)

    # -------------------------------------------------------- the job

    def cp_prefill(self, tokens: List[int]) -> PrefillResult:
        """Prefill ``tokens`` across the mesh. Relaxed-tier entry
        point (``parity/relaxed-gated``): callers outside this package
        must sit under a ``serving.parity=relaxed`` guard."""
        import jax.numpy as jnp

        s = len(tokens)
        if s < 2:
            raise ValueError("longctx prefill needs at least 2 tokens")
        if s > self.pad_tokens:
            raise ValueError(
                f"prompt ({s} tokens) exceeds the pinned longctx "
                f"budget {self.pad_tokens} (serving.longctx.max.tokens)")
        padded = np.zeros((self.pad_tokens,), np.int32)
        padded[:s] = tokens
        t0 = time.monotonic()
        # runtime comm ledger dispatch seam: the first call traces the
        # CP program inside this window (binding the ring-hop /
        # all-to-all byte records to "longctx.prefill"); every prefill
        # advances the cp.* byte counters and records its host wall
        # into the htpu_comm histograms. Nothing enters the graph.
        from hadoop_tpu.obs.comm import comm_runtime
        with comm_runtime().step("longctx.prefill"):
            h, ks, vs = self._fn(self.params, jnp.asarray(padded))
            row = np.asarray(h[s - 1])
        logits = np.asarray(self._head(self.params, row))
        seconds = time.monotonic() - t0
        bs = self.block_size
        n_full = s // bs
        tail_k = tail_v = None
        tail_len = s - n_full * bs
        if tail_len:
            tail_k, tail_v = self._slice_seq(ks, vs, n_full * bs, s)
        return PrefillResult(
            last_logits=logits, n_full_blocks=n_full,
            blocks=self._iter_blocks(ks, vs, n_full),
            tail_k=tail_k, tail_v=tail_v, seconds=seconds,
            chips=self.sp, sp_mode=self.sp_mode, prompt_tokens=s)

    # -------------------------------------------- shard-order streaming

    @staticmethod
    def _seq_shards(arr):
        """(start, shard) per device shard, in sequence order — axis 1
        is the sequence axis of the [L, S_pad, Hkv, Dh] KV. The shard
        payload is NOT materialized here: callers np.asarray only the
        shards they actually consume (the tail slice must not pull the
        whole context to host on the TTFT path)."""
        shards = sorted(arr.addressable_shards,
                        key=lambda sh: sh.index[1].start or 0)
        for sh in shards:
            yield (sh.index[1].start or 0), sh

    def _iter_blocks(self, ks, vs, n_full: int
                     ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Yield full-block [L, bs, Hkv, Dh] (K, V) payloads in chain
        order, pulling ONE rank's shard to host at a time — the
        streamed-ingest shape: the full context never materializes as
        one host array on this path."""
        bs = self.block_size
        limit = n_full * bs
        for (k_off, ksh), (_, vsh) in zip(self._seq_shards(ks),
                                          self._seq_shards(vs)):
            if k_off >= limit:
                return
            k_np = np.asarray(ksh.data)
            v_np = np.asarray(vsh.data)
            for off in range(0, k_np.shape[1], bs):
                if k_off + off + bs > limit:
                    return
                yield (k_np[:, off:off + bs], v_np[:, off:off + bs])

    def _slice_seq(self, ks, vs, lo: int, hi: int):
        """Host copy of sequence positions [lo, hi) — the partial tail
        block (never crosses a shard: shard boundaries are multiples of
        block_size and hi - lo < block_size). Only the OWNING shard is
        pulled to host."""
        local = self.pad_tokens // self.sp
        for (off, ksh), (_, vsh) in zip(self._seq_shards(ks),
                                        self._seq_shards(vs)):
            if off <= lo < off + local:
                k_np = np.asarray(ksh.data)
                v_np = np.asarray(vsh.data)
                return (k_np[:, lo - off:hi - off],
                        v_np[:, lo - off:hi - off])
        raise AssertionError(f"tail [{lo},{hi}) not in any shard")
