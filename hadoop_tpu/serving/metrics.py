"""Serving metrics — the replica's observability face.

Registered into the process-wide ``metrics_system()`` so they surface
through every existing sink: the ``/jmx`` endpoint of the replica's own
HTTP server, file sinks, and the periodic publisher. Source name
``serving.engine`` mirrors the ``namenode.ops`` convention.
"""

from __future__ import annotations

from hadoop_tpu.metrics import metrics_system

SOURCE = "serving.engine"


class ServingMetrics:
    """Queue depth / batch occupancy / TTFT / tokens/s / KV-pool usage.

    - ``queue_depth``        requests waiting for a slot or pages
    - ``batch_occupancy``    running requests in the fixed decode batch
    - ``kv_blocks_in_use``   allocated KV pages (and a 0..1 utilization)
    - ``time_to_first_token`` quantiles (s), submit → first token
    - ``decode_step``        per-step latency rate (num_ops = steps)
    - ``tokens_out``         generated tokens (monotonic; tokens/s is the
                             derivative any sink can take)
    - ``requests`` / ``preemptions`` lifetime counters
    - ``prefix_cache_hit_rate``  fraction of admitted prompt tokens
                             served from cached KV blocks (0..1)
    - ``prefix_cached_blocks``   resident reusable KV pages
    - ``prefix_tokens_reused`` / ``prefix_cache_evictions`` counters
    - ``chunk_occupancy``    fraction of the per-step prefill-chunk
                             budget actually used last step
    - ``prefill_backlog``    prompt tokens still awaiting prefill across
                             admitted requests (the stall gauge: how far
                             first tokens lag behind admission)
    - ``kv_hits_{hbm,host,dfs}`` per-tier KV block hit counters
    - ``kv_demotions`` / ``kv_promotions`` / ``kv_dfs_persists``
                             tier traffic (HBM→host spills, cold-tier
                             re-injections, DFS write-pipeline persists)
    - ``kv_fetch_seconds{tier=host|dfs}`` log-bucketed cold-fetch
                             latency histograms (one prom family)
    - ``spec_proposed`` / ``spec_accepted`` speculative-decoding draft
                             token counters (proposal vs verifier)
    - ``spec_accept_len``    log-bucketed accepted-draft-length
                             histogram per speculating lane-step
    - ``qos_admitted`` / ``qos_shed``  door QoS gate outcomes (sheds
                             are 429 + Retry-After responses)
    - ``qos_tenants``        tenants tracked by the decay scheduler
    - ``longctx_requests`` / ``longctx_blocks_streamed`` /
      ``longctx_window_fetches`` / ``longctx_chips`` /
      ``longctx_prefill_seconds``  long-context plane: prompts routed
                             to CP prefill, KV blocks streamed to the
                             cold tiers, decode window page-ins, CP
                             width, prefill wall time
    - ``weight_bytes``       measured resident model weight bytes
                             (``htpu_weight_bytes`` on ``/prom`` — the
                             weight-plane capacity signal: int8 resident
                             weights shrink it ~4x and the KV budget
                             grows by exactly the difference)
    """

    def __init__(self, source: str = SOURCE):
        reg = metrics_system().source(source)
        self.registry = reg
        self.queue_depth = reg.gauge(
            "queue_depth", "requests waiting for admission")
        self.batch_occupancy = reg.gauge(
            "batch_occupancy", "running requests in the decode batch")
        self.kv_blocks_in_use = reg.gauge(
            "kv_blocks_in_use", "allocated KV-cache pages")
        self.kv_block_utilization = reg.gauge(
            "kv_block_utilization", "fraction of the KV pool in use")
        self.ttft = reg.quantiles(
            "time_to_first_token", "submit to first token, seconds")
        # log-bucketed twins for the /prom exposition (quantiles/rates
        # stay for JMX parity — same samples, two shapes)
        self.ttft_hist = reg.histogram(
            "time_to_first_token_seconds", "submit to first token")
        self.decode_step = reg.rate(
            "decode_step", "one continuous-batching decode step")
        self.decode_step_hist = reg.histogram(
            "decode_step_seconds", "one continuous-batching decode step")
        self.tokens_out = reg.counter(
            "tokens_out", "tokens generated (all requests)")
        self.requests = reg.counter("requests", "requests submitted")
        self.preemptions = reg.counter(
            "preemptions", "requests evicted from the KV pool")
        self.prefix_cache_hit_rate = reg.gauge(
            "prefix_cache_hit_rate",
            "fraction of prompt tokens served from cached KV blocks")
        self.prefix_cached_blocks = reg.gauge(
            "prefix_cached_blocks", "resident reusable KV pages")
        self.prefix_tokens_reused = reg.counter(
            "prefix_tokens_reused",
            "prompt tokens whose prefill was skipped via the prefix cache")
        self.prefix_cache_evictions = reg.counter(
            "prefix_cache_evictions",
            "cached KV pages evicted (LRU) to feed live allocations")
        self.chunk_occupancy = reg.gauge(
            "chunk_occupancy",
            "fraction of the per-step prefill chunk budget used")
        self.prefill_backlog = reg.gauge(
            "prefill_backlog",
            "prompt tokens still awaiting prefill across admitted "
            "requests")
        # tiered KV cache: per-tier hit counters, demotion/promotion
        # traffic, and log-bucketed fetch latency published under ONE
        # prom family (kv_fetch_seconds{tier=...}) — a dashboard reads
        # the HBM→host→DFS waterfall off a single query
        self.kv_hits_hbm = reg.counter(
            "kv_hits_hbm", "KV blocks served from the HBM radix tier")
        self.kv_hits_host = reg.counter(
            "kv_hits_host",
            "KV blocks recovered from the host-RAM ring")
        self.kv_hits_dfs = reg.counter(
            "kv_hits_dfs",
            "KV blocks recovered from the DFS prefix store")
        self.kv_demotions = reg.counter(
            "kv_demotions",
            "zero-ref KV pages spilled HBM -> host ring at eviction")
        self.kv_promotions = reg.counter(
            "kv_promotions",
            "KV pages re-injected into HBM from a cold tier")
        self.kv_dfs_persists = reg.counter(
            "kv_dfs_persists",
            "KV pages persisted to the DFS prefix store")
        self.kv_fetch_hist = {
            tier: reg.histogram(
                f"kv_fetch_seconds_{tier}",
                "cold-tier KV block fetch latency",
                prom_name="kv_fetch_seconds",
                prom_labels={"tier": tier})
            for tier in ("host", "dfs")}
        # speculative decoding: draft tokens proposed by the n-gram
        # index vs accepted by the in-step verifier, plus a
        # log-bucketed per-lane accepted-length histogram (one prom
        # family — the acceptance-depth distribution in one query)
        self.spec_proposed = reg.counter(
            "spec_proposed",
            "draft tokens proposed to the speculation lane")
        self.spec_accepted = reg.counter(
            "spec_accepted",
            "draft tokens accepted by the in-step verifier")
        self.spec_accept_len = reg.histogram(
            "spec_accept_len",
            "accepted draft-prefix length per speculating lane-step")
        # door QoS: admissions vs sheds (429) and tracked tenants — the
        # autoscaler scrapes qos_shed off /prom as a scale-out signal
        # (a shedding fleet is past its SLO by definition)
        self.qos_admitted = reg.counter(
            "qos_admitted", "requests admitted through the QoS gate")
        self.qos_shed = reg.counter(
            "qos_shed",
            "requests shed (429 + Retry-After) at the serving door")
        self.qos_tenants = reg.gauge(
            "qos_tenants", "tenants tracked by the decay cost scheduler")
        # fleet SLO scoreboard (obs/slo): class-labeled request
        # accounting the doctor diffs per poll window. The class set
        # is the BOUNDED p0..p3 ladder (DecayCostScheduler level,
        # clamped — see hadoop_tpu.obs.slo.SLO_CLASSES); the tuples
        # stay inline literals so the label lint can prove the bound.
        self.slo_ttft_hist = {
            cls: reg.histogram(
                f"slo_ttft_seconds_{cls}",
                "submit to first token by tenant class",
                prom_name="slo_ttft_seconds",
                prom_labels={"class": cls})
            for cls in ("p0", "p1", "p2", "p3")}
        self.slo_token_hist = {
            cls: reg.histogram(
                f"slo_token_seconds_{cls}",
                "per-token decode seconds by tenant class",
                prom_name="slo_token_seconds",
                prom_labels={"class": cls})
            for cls in ("p0", "p1", "p2", "p3")}
        self.slo_requests = {
            (cls, outcome): reg.counter(
                f"slo_requests_{cls}_{outcome}",
                "door outcomes by tenant class",
                prom_name="slo_requests",
                prom_labels={"class": cls, "outcome": outcome})
            for cls in ("p0", "p1", "p2", "p3")
            for outcome in ("ok", "shed", "failed")}
        # the weight plane: measured resident weight bytes (int8
        # payloads + scale planes under serving.parity=relaxed, plain
        # dtype bytes bitwise) — the number the KV budget subtracts
        self.weight_bytes = reg.gauge(
            "weight_bytes", "resident model weight bytes on the chip")
        # the long-context plane (serving/longctx): monster prompts
        # routed to CP prefill, KV blocks streamed into the cold
        # tiers, decode window page-ins, CP width, and the prefill
        # wall-time histogram (htpu_longctx_* on /prom)
        self.longctx_requests = reg.counter(
            "longctx_requests",
            "prompts routed to the long-context CP prefill plane")
        self.longctx_blocks_streamed = reg.counter(
            "longctx_blocks_streamed",
            "prefilled KV blocks streamed into the cold tiers")
        self.longctx_window_fetches = reg.counter(
            "longctx_window_fetches",
            "decode working-set window page-ins (per layer, window)")
        self.longctx_chips = reg.gauge(
            "longctx_chips", "context-parallel width of the mesh")
        self.longctx_prefill_hist = reg.histogram(
            "longctx_prefill_seconds",
            "context-parallel prefill wall time per prompt")

    def snapshot(self):
        return self.registry.snapshot()
