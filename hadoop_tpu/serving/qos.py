"""Serving-door QoS — FairCallQueue ported to the generation path.

The RPC plane already sheds heavy tenants before they starve light ones
(``ipc/callqueue.py``: DecayRpcScheduler assigns priority by decayed
usage share, FairCallQueue drains per-priority queues by weighted
round-robin, CallQueueManager backs off when full). The serving door had
none of that: one FIFO admission queue, so a single tenant replaying a
batch job through ``/v1/generate`` could park hundreds of requests ahead
of every interactive user. This module ports the same three pieces to
generation admission, with one serving-specific twist — requests are not
unit-cost, so the decay accounting charges **tokens** (prompt +
requested output), not calls:

- ``DecayCostScheduler`` — per-tenant (auth identity) cost counters with
  periodic exponential decay; a tenant's share of the decayed total maps
  to a priority level through the same ``1/2^k`` thresholds the RPC
  scheduler uses.
- ``FairAdmissionQueue`` — a drop-in for the engine's pending deque:
  per-priority-level sub-queues drained by weighted round-robin
  (weights ``2^(L-1-i)``), so a starved-but-light tenant's request
  overtakes a heavy tenant's backlog at the admission seam. Preempted
  requests ride an urgent lane that always re-admits first (preemption
  semantics are the engine's, not a fairness question).
- ``QoSGate`` — the load-shedding decision at the door: under overload
  (engine queue past ``serving.qos.shed.queue.depth``) requests from
  over-share tenants are rejected with ``429 + Retry-After`` instead of
  queued; past ``serving.qos.queue.max`` everyone sheds (the hard cap —
  an unbounded queue is just a slower failure). Shed/admit counters feed
  ``/prom``, where the autoscaler reads them as a scale-out signal.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional

from hadoop_tpu.conf import Configuration

ENABLED_KEY = "serving.qos.enabled"
LEVELS_KEY = "serving.qos.levels"
DECAY_PERIOD_KEY = "serving.qos.decay.period"
DECAY_FACTOR_KEY = "serving.qos.decay.factor"
THRESHOLDS_KEY = "serving.qos.thresholds"
SHED_QUEUE_KEY = "serving.qos.shed.queue.depth"
HARD_QUEUE_KEY = "serving.qos.queue.max"
RETRY_AFTER_KEY = "serving.qos.retry.after"

DEFAULT_TENANT = "anonymous"


class DecayCostScheduler:
    """Per-tenant decayed cost accounting → priority level.

    The serving twin of ``ipc.callqueue.DecayRpcScheduler`` (same decay
    loop, same share thresholds), except ``charge`` takes an explicit
    cost — a 4k-token prefill and a 3-token probe are not the same unit
    of work, and counting calls would let a megaprompt tenant look
    light. Shed requests are charged too: demand is demand, and a
    shedding tenant that retries in a tight loop must not decay its way
    back to priority 0 while doing so.
    """

    def __init__(self, num_levels: int = 4,
                 conf: Optional[Configuration] = None):
        conf = conf or Configuration(load_defaults=False)
        self.num_levels = max(2, int(num_levels))
        self.decay_period_s = conf.get_time_seconds(DECAY_PERIOD_KEY, 5.0)
        self.decay_factor = conf.get_float(DECAY_FACTOR_KEY, 0.5)
        raw = conf.get_list(THRESHOLDS_KEY)
        if raw:
            self.thresholds = [float(t) for t in raw]
        else:
            self.thresholds = [1.0 / (2 ** (self.num_levels - i))
                               for i in range(1, self.num_levels)]
        self._costs: Dict[str, float] = {}   # guarded-by: _lock
        self._total = 0.0                    # guarded-by: _lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        threading.Thread(target=self._decay_loop, daemon=True,
                         name="qos-decay").start()

    def _decay_loop(self) -> None:
        # fixed-cadence decay tick, not a retry loop: jitter here would
        # skew every tenant's share identically and buys nothing
        while not self._stop.wait(self.decay_period_s):
            with self._lock:
                dead = []
                self._total = 0.0
                for tenant, cost in self._costs.items():
                    cost *= self.decay_factor
                    if cost < 0.5:
                        dead.append(tenant)
                    else:
                        self._costs[tenant] = cost
                        self._total += cost
                for tenant in dead:
                    del self._costs[tenant]

    def charge(self, tenant: str, cost: float) -> None:
        cost = max(1.0, float(cost))
        with self._lock:
            self._costs[tenant] = self._costs.get(tenant, 0.0) + cost
            self._total += cost

    def share_of(self, tenant: str) -> float:
        with self._lock:
            if not self._total:
                return 0.0
            return self._costs.get(tenant, 0.0) / self._total

    def level_of(self, tenant: str) -> int:
        share = self.share_of(tenant)
        level = 0
        for i, th in enumerate(self.thresholds):
            if share >= th:
                level = i + 1
        return min(level, self.num_levels - 1)

    @property
    def num_tenants(self) -> int:
        with self._lock:
            return len(self._costs)

    def snapshot(self) -> dict:
        with self._lock:
            return {"total": self._total, "tenants": dict(self._costs)}

    def stop(self) -> None:
        self._stop.set()


class FairAdmissionQueue:
    """Weighted-round-robin admission queue, deque-compatible with the
    engine's pending queue (``append``/``appendleft``/``popleft``/
    ``len``/``[0]`` — every call happens on the engine's paths under its
    scheduler condition, so the queue needs no lock of its own).

    Requests land in the sub-queue of their tenant's priority level at
    submit time (the FairCallQueue contract: priority is assigned at
    put) and are drained by weighted round-robin — level 0 gets
    ``2^(L-1)`` takes per cycle, the heaviest level 1 — so every level
    always eventually drains (no starvation) but light tenants overtake
    a heavy tenant's parked backlog. ``appendleft`` (the engine's
    preemption re-queue) rides an urgent lane that always pops first:
    a preempted request was already running, and fairness must not
    reorder the engine's recompute-resume contract.
    """

    def __init__(self, scheduler: DecayCostScheduler):
        self.sched = scheduler
        L = scheduler.num_levels
        self._levels: List[deque] = [deque() for _ in range(L)]
        self._urgent: deque = deque()
        self._weights = [2 ** (L - 1 - i) for i in range(L)]
        self._rr_level = 0
        self._rr_credit = self._weights[0]
        self._size = 0
        # the last [0] peek, pinned: the engine peeks, drops the lock
        # for allocation (new requests can append meanwhile — possibly
        # into a lighter, now-preferred level), then pops. The pop MUST
        # return the peeked request or the engine admits one request
        # and silently discards another (its client would hang forever)
        self._peeked: Optional[tuple] = None    # (lane, req)

    def __len__(self) -> int:
        return self._size

    def append(self, req) -> None:
        lvl = self.sched.level_of(
            getattr(req, "tenant", "") or DEFAULT_TENANT)
        self._levels[lvl].append(req)
        self._size += 1

    def appendleft(self, req) -> None:
        self._urgent.appendleft(req)
        self._size += 1

    def _choose(self) -> Optional[int]:
        """The level the next pop comes from (-1 = urgent lane), chosen
        WITHOUT mutating round-robin state — deterministic, so the
        engine's peek-then-pop (``[0]`` then ``popleft`` with no pops in
        between) always sees the same request."""
        if self._urgent:
            return -1
        lvl, credit = self._rr_level, self._rr_credit
        for _ in range(2 * len(self._levels)):
            if credit > 0 and self._levels[lvl]:
                return lvl
            lvl = (lvl + 1) % len(self._levels)
            credit = self._weights[lvl]
        for i, q in enumerate(self._levels):   # exhausted credits: scan
            if q:
                return i
        return None

    def __getitem__(self, i: int):
        if i != 0:
            raise IndexError("admission queue exposes only the head")
        c = self._choose()
        if c is None:
            raise IndexError("empty admission queue")
        req = self._urgent[0] if c == -1 else self._levels[c][0]
        self._peeked = (c, req)
        return req

    def _commit(self, c: int):
        """Pop from lane ``c``, advancing the WRR cursor the way
        ``_choose`` walked to it."""
        self._size -= 1
        if c == -1:
            return self._urgent.popleft()
        for _ in range(2 * len(self._levels)):
            if self._rr_level == c and self._rr_credit > 0:
                break
            self._rr_level = (self._rr_level + 1) % len(self._levels)
            self._rr_credit = self._weights[self._rr_level]
        if self._rr_level != c:                # starvation-scan pick
            self._rr_level = c
            self._rr_credit = self._weights[c]
        self._rr_credit -= 1
        return self._levels[c].popleft()

    def popleft(self):
        if self._peeked is not None:
            c, req = self._peeked
            self._peeked = None
            lane = self._urgent if c == -1 else self._levels[c]
            if lane and lane[0] is req:
                return self._commit(c)
        c = self._choose()
        if c is None:
            raise IndexError("pop from empty admission queue")
        self._peeked = None
        return self._commit(c)


class QoSGate:
    """The shed decision at the door, consulted before every
    ``engine.submit``. Admits freely below the overload line; between
    the overload line and the hard cap only tenants at priority 0
    (under-share) may queue; past the hard cap everyone sheds. Shedding
    requires at least two tracked tenants — fairness needs someone to
    be unfair TO, and shedding a deployment's only tenant would turn
    overload into an outage instead of a queue."""

    def __init__(self, conf: Configuration, engine, metrics=None,
                 scheduler: Optional[DecayCostScheduler] = None):
        self.engine = engine
        self.metrics = metrics
        self.sched = scheduler or DecayCostScheduler(
            conf.get_int(LEVELS_KEY, 4), conf)
        self.shed_depth = conf.get_int(SHED_QUEUE_KEY, 32)
        self.hard_max = conf.get_int(HARD_QUEUE_KEY, 256)
        self.retry_after_s = conf.get_time_seconds(RETRY_AFTER_KEY, 1.0)
        self.admitted = 0                     # guarded-by: _lock
        self.sheds = 0                        # guarded-by: _lock
        self.sheds_by_tenant: Dict[str, int] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    @staticmethod
    def cost_of(tokens, max_new_tokens: int) -> float:
        """Tokens of work the request demands — prompt prefill plus the
        requested decode budget."""
        return float(len(tokens) + max(1, int(max_new_tokens)))

    def admit(self, tenant: str, cost: float):
        """Returns ``(admitted, retry_after_s, level)``. Charges the
        tenant either way (see DecayCostScheduler)."""
        tenant = tenant or DEFAULT_TENANT
        self.sched.charge(tenant, cost)
        level = self.sched.level_of(tenant)
        depth = self.engine.queue_depth
        shed = depth >= self.hard_max or (
            depth >= self.shed_depth and level > 0
            and self.sched.num_tenants > 1)
        with self._lock:
            if shed:
                self.sheds += 1
                self.sheds_by_tenant[tenant] = \
                    self.sheds_by_tenant.get(tenant, 0) + 1
            else:
                self.admitted += 1
        if self.metrics:
            if shed:
                self.metrics.qos_shed.incr()
            else:
                self.metrics.qos_admitted.incr()
            self.metrics.qos_tenants.set(self.sched.num_tenants)
        if shed:
            # heavier tenants wait longer before retrying: the door's
            # Retry-After is the fleet-wide pushback signal the router
            # honors before its next pick
            return False, self.retry_after_s * (1 + level), level
        return True, 0.0, level

    def stats(self) -> dict:
        with self._lock:
            return {
                "admitted": self.admitted,
                "sheds": self.sheds,
                "sheds_by_tenant": dict(self.sheds_by_tenant),
                "tenants": self.sched.num_tenants,
                "shed_queue_depth": self.shed_depth,
                "queue_max": self.hard_max,
            }

    def stop(self) -> None:
        self.sched.stop()
