"""Client-side router over serving replicas.

Discovery comes from the service registry (``registry.registry``): every
replica registers an ephemeral record under
``/services/serving/<name>/<instance>`` with its HTTP endpoint; records
vanish on lease expiry, so a dead replica falls out of the candidate set
by itself, and a draining replica flips its ``state`` attribute before
unregistering so the router stops picking it ahead of the TTL.

Balancing is prefix-affinity first, power-of-two-choices underneath:
requests carrying tokens hash a bounded prompt prefix and rendezvous-
hash it over the live replica set, so requests sharing a prefix keep
landing on the replica whose prefix-reuse KV cache likely holds it —
cache hit-rate survives a multi-replica fleet instead of decaying
1/N. Rendezvous (highest-random-weight) hashing keeps the mapping
stable when replicas come and go: only keys owned by the departed
replica move. When the affinity target is overloaded relative to the
lightest candidate (``serving.router.affinity.max.imbalance``
outstanding requests), the router falls back to power-of-two-choices
over its own outstanding counts (the classic result: two random probes
+ pick-the-lighter gets within a constant of perfect balance without
any global state) — affinity is a preference, never a hotspot
generator. Failures ride the IPC retry policies
(``ipc.retry.RetryPolicies``): connection errors and 503-draining
responses retry against a different replica with exponential backoff,
deterministic application errors (400s) fail fast.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import logging
import random
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

from hadoop_tpu.conf import Configuration
from hadoop_tpu.ipc.errors import RetriableError
from hadoop_tpu.ipc.retry import RetryAction, RetryPolicies, RetryPolicy
from hadoop_tpu.registry.registry import (RegistryClient, ServiceRecord,
                                          record_is_stale, record_ttl)
from hadoop_tpu.tracing.tracer import current_context, global_tracer

log = logging.getLogger(__name__)

REGISTRY_PREFIX = "/services/serving"


def replica_path(service: str, instance: str) -> str:
    return f"{REGISTRY_PREFIX}/{service}/{instance}"


def affinity_key(tokens, prefix_tokens: int = 64) -> str:
    """Digest of a bounded prompt prefix — THE routing key. One
    definition: the router routes by it and the storm bench predicts
    owners with it; forking the formula would silently split them."""
    head = ",".join(str(t) for t in tokens[:prefix_tokens])
    return hashlib.sha256(head.encode()).hexdigest()


def rendezvous_owner(key: str, paths):
    """Highest-random-weight owner of ``key`` among replica ``paths``
    (stable under membership churn: only the departed owner's keys
    move)."""
    return max(paths, key=lambda p: hashlib.sha256(
        f"{key}|{p}".encode()).digest())


class NoReplicasError(RetriableError):
    pass


class ReplicaRequestError(Exception):
    """Deterministic replica rejection (4xx): retrying the identical
    request elsewhere cannot succeed, so this deliberately does NOT
    subclass OSError/RetriableError — it fails fast through the retry
    loop."""

    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


class ServingRouter:
    """Resolve + balance + retry over one serving service's replicas."""

    def __init__(self, registry_addr: Tuple[str, int], service: str,
                 conf: Optional[Configuration] = None,
                 policy: Optional[RetryPolicy] = None,
                 cache_ttl_s: float = 2.0):
        self.conf = conf or Configuration()
        self.service = service
        self.reg = RegistryClient(registry_addr, self.conf)
        self.policy = policy or RetryPolicies.exponential_backoff(
            max_retries=self.conf.get_int("serving.router.max.retries", 6),
            base_delay_s=0.05, max_delay_s=2.0)
        self._cache_ttl = cache_ttl_s
        self._cache: List[ServiceRecord] = []
        self._cache_at = 0.0
        self._outstanding: Dict[str, int] = {}
        self._lock = threading.Lock()
        self.affinity_enabled = self.conf.get_bool(
            "serving.router.affinity.enabled", True)
        self.affinity_prefix = self.conf.get_int(
            "serving.router.affinity.prefix.tokens", 64)
        self.affinity_max_imbalance = self.conf.get_int(
            "serving.router.affinity.max.imbalance", 4)
        self.affinity_routed = 0      # picks that followed the prefix hash
        # prefill/decode disaggregation: prompts at least this long are
        # offered to a prefill-role replica first (it prefills and ships
        # the KV through the DFS tier), then decoded on a decode/mixed
        # replica that maps the shipped blocks instead of re-prefilling.
        # Engaged ONLY when prefill-role replicas exist — a fleet of
        # mixed (default-role) replicas behaves exactly as before.
        self.prefill_min_tokens = self.conf.get_int(
            "serving.router.prefill.min.tokens", 32)
        # the handoff POST is synchronous on the request path: a wedged
        # prefill replica must cost at most this long before the cold
        # fallback engages, never _post's generous generate timeout
        self.prefill_timeout = self.conf.get_time_seconds(
            "serving.router.prefill.timeout", 20.0)
        self.prefill_offloaded = 0    # handoffs that reached a prefill
        #                               replica (failures decode cold)
        # prompts never OFFERED to a prefill replica whose advertised
        # KV capacity (registry kv_hbm_blocks x kv_block_size tokens;
        # longctx+DFS replicas are unbounded) cannot hold even the
        # paged working set — a loud skip here beats a handoff that
        # fails or times out there
        self.prefill_capacity_skips = 0
        # heartbeat staleness: a replica that died without deregistering
        # (SIGKILL, kernel panic) stops stamping its record; past this
        # TTL the router skips it instead of burning a retry into a
        # corpse — which matters most on the stale-cache path below,
        # where a registry outage would otherwise freeze membership
        self.record_ttl = record_ttl(self.conf)

    # ------------------------------------------------------------ discovery

    def replicas(self, refresh: bool = False) -> List[ServiceRecord]:
        """Live, non-draining replicas (briefly cached: the registry is
        one RPC away and the router sits on every request path)."""
        now = time.monotonic()
        with self._lock:
            if not refresh and self._cache and \
                    now - self._cache_at < self._cache_ttl:
                return [r for r in self._cache
                        if not record_is_stale(r, self.record_ttl)]
        try:
            recs = [r for r in self.reg.list(
                        f"{REGISTRY_PREFIX}/{self.service}")
                    if "http" in r.endpoints
                    and r.attributes.get("state", "serving") == "serving"
                    and not record_is_stale(r, self.record_ttl)]
        except (OSError, IOError) as e:
            # registry briefly unreachable (restart, RPC timeout): the
            # stale cache is a better answer than aborting every
            # request mid-flight — minus replicas whose heartbeats have
            # aged out (through a LONG outage the cache decays to empty
            # instead of pointing at corpses forever); with nothing
            # live the failure is retriable like any transport error
            with self._lock:
                live = [r for r in self._cache
                        if not record_is_stale(r, self.record_ttl)]
                if live:
                    log.debug("registry lookup failed (%s); serving "
                              "stale replica cache", e)
                    return live
            raise NoReplicasError(f"registry unreachable: {e}")
        with self._lock:
            self._cache = recs
            self._cache_at = now
        return list(recs)

    def _affinity_key(self, payload: Dict) -> Optional[str]:
        """Digest of a bounded prompt prefix — the routing key that
        keeps shared-prefix traffic on one replica's warm KV cache.
        Bounded so two prompts diverging past the prefix still share a
        replica, and hashing a megaprompt costs O(prefix)."""
        tokens = payload.get("tokens")
        if (not self.affinity_enabled or not isinstance(tokens, list)
                or not tokens):
            return None
        return affinity_key(tokens, self.affinity_prefix)

    @staticmethod
    def _rec_role(rec: ServiceRecord) -> str:
        return rec.attributes.get("role", "mixed")

    @staticmethod
    def _kv_fit(rec: ServiceRecord, n_tokens: int) -> bool:
        """Can this replica's prefill admission hold ``n_tokens`` of
        KV? A normal prefill admits the WHOLE prompt into the HBM
        block pool (the host ring and DFS tiers receive demotions,
        they cannot back an admission), so the gate is the advertised
        pool: ``kv_hbm_blocks`` x ``kv_block_size`` tokens. A replica
        advertising the long-context plane (``longctx=1``) with a DFS
        tier streams monster prompts into the cold tiers instead of
        the pool — its capacity is effectively unbounded, so the gate
        never skips it. A record missing the attributes stays eligible
        — hand-registered or mid-upgrade replicas must not be starved
        by a stricter router."""
        a = rec.attributes
        if a.get("longctx") == "1" and a.get("kv_dfs") != "0":
            # unbounded only up to the plane's pinned prompt budget:
            # past serving.longctx.max.tokens the replica's own door
            # rejects, so offering it would be the failed handoff
            # this gate exists to prevent
            try:
                return n_tokens <= int(a["longctx_max_tokens"])
            except (KeyError, ValueError):
                return True
        try:
            block_size = int(a["kv_block_size"])
            pool_blocks = int(a["kv_hbm_blocks"])
        except (KeyError, ValueError):
            return True
        if block_size <= 0:
            return True
        # +1 token, not +1 block: the handoff's single generated token
        # rides the prompt's last partial page when there is one —
        # exactly the engine's own admission formula
        return -(-(n_tokens + 1) // block_size) <= pool_blocks

    def _pick(self, exclude: set, affinity: Optional[str] = None,
              role: Optional[str] = None,
              prefer_dfs: bool = False) -> ServiceRecord:
        """Prefix-affinity (rendezvous hash) with a load guard, else
        power-of-two-choices on local outstanding counts. ``role``
        prefers replicas of that role (``mixed`` always qualifies);
        when no replica matches, the filter is dropped entirely — a
        deployment without role separation behaves exactly as today,
        and a fleet that lost its last decode replica still serves off
        whatever is alive rather than wedging. ``prefer_dfs`` steers
        toward replicas that can map a just-completed prefill handoff
        (a kv_dfs=0 pick would re-prefill what the handoff already
        paid for) — a preference, never a hard filter."""
        cands = [r for r in self.replicas() if r.path not in exclude]
        if not cands:
            cands = [r for r in self.replicas(refresh=True)
                     if r.path not in exclude]
        if role is not None:
            roled = [r for r in cands
                     if self._rec_role(r) in (role, "mixed")]
            if roled:
                cands = roled
        if prefer_dfs:
            dfsable = [r for r in cands
                       if r.attributes.get("kv_dfs") != "0"]
            if dfsable:
                cands = dfsable
        if not cands:
            raise NoReplicasError(
                f"no live replicas for {self.service}")
        if len(cands) == 1:
            return cands[0]
        with self._lock:
            loads = {r.path: self._outstanding.get(r.path, 0)
                     for r in cands}
        if affinity is not None:
            owner = rendezvous_owner(affinity,
                                     [r.path for r in cands])
            target = next(r for r in cands if r.path == owner)
            if loads[target.path] - min(loads.values()) <= \
                    self.affinity_max_imbalance:
                self.affinity_routed += 1
                return target
        a, b = random.sample(cands, 2)
        return a if loads[a.path] <= loads[b.path] else b

    # -------------------------------------------------------------- request

    def generate(self, payload: Dict, user: Optional[str] = None) -> Dict:
        """POST /v1/generate on a balanced replica; returns the decoded
        JSON. Retries per policy on transport errors / draining.

        Roots the request's trace (unless the caller already holds a
        span): the replica door resumes it from ``X-Htpu-Trace``, so
        one trace id runs router → door → engine admit → first token."""
        with global_tracer().span("serving.router.generate") as rsp:
            rsp.add_kv("prompt_tokens",
                       str(len(payload.get("tokens") or [])))
            offloaded = self._maybe_offload_prefill(payload, user)
            return self._with_retry(
                lambda rec: self._post(rec, payload, user),
                self._affinity_key(payload), role="decode",
                prefer_dfs=offloaded)

    def generate_stream(self, payload: Dict,
                        user: Optional[str] = None) -> Iterator[Dict]:
        """Streaming variant: yields one dict per JSON line. Replica
        choice and retry apply to connection setup only — a stream that
        dies mid-flight surfaces to the caller (resuming a half-decoded
        request on another replica would re-emit tokens). The router
        span covers routing + connection setup (a minutes-long stream
        must not hold a span open; the replica-side spans carry on)."""
        payload = dict(payload, stream=True)
        with global_tracer().span("serving.router.generate_stream"):
            offloaded = self._maybe_offload_prefill(payload, user)
            resp, conn, rec = self._with_retry(
                lambda rec: self._post(rec, payload, user, stream=True)
                + (rec,), self._affinity_key(payload), role="decode",
                prefer_dfs=offloaded)
        # the stream holds its p2c weight for its whole life, not just
        # connection setup — a minutes-long stream is real load
        with self._lock:
            self._outstanding[rec.path] = \
                self._outstanding.get(rec.path, 0) + 1
        try:
            for raw in resp:
                line = raw.strip()
                if line:
                    yield json.loads(line)
        finally:
            conn.close()
            with self._lock:
                n = self._outstanding.get(rec.path, 1)
                self._outstanding[rec.path] = max(0, n - 1)

    def _maybe_offload_prefill(self, payload: Dict,
                               user: Optional[str]) -> bool:
        """The disaggregation hook on the request path: a long prompt is
        first POSTed to a strict ``prefill``-role replica, which
        prefills it and ships the finished KV to the DataNodes through
        the DFS write pipeline (durable on return). The decode replica
        picked next maps those blocks back via hedged reads at
        admission and prefills only the tail, so its MXU never burns a
        full prefill. Strictly best-effort: no prefill replicas, a
        short prompt, or ANY handoff failure mean the decode replica
        simply prefills cold — disaggregation can shed load, never add
        a failure mode. Returns True when KV actually shipped (the
        decode pick then prefers a replica that can map it back)."""
        tokens = payload.get("tokens")
        if (not isinstance(tokens, list) or
                len(tokens) < self.prefill_min_tokens):
            return False
        try:
            recs = self.replicas()
        except NoReplicasError:
            # registry blip on a cold router: the offload is strictly
            # best-effort — let _with_retry's policy handle discovery
            # with backoff exactly as it does for short prompts
            return False
        pres = [r for r in recs if self._rec_role(r) == "prefill"]
        # capacity gate: a monster prompt must never be OFFERED to a
        # replica that cannot hold even its paged working set — that
        # handoff ends as a timeout on the request path, while this
        # skip is free. Loud (counter + warn), never silent. (The
        # empty-pres case falls through to the check below.)
        fit = []
        for r in pres:
            if self._kv_fit(r, len(tokens)):
                fit.append(r)
            else:
                self.prefill_capacity_skips += 1
                log.warning(
                    "prefill offload: %s advertises too little KV "
                    "capacity for a %d-token prompt; skipping it "
                    "(prefill_capacity_skips=%d)", r.path, len(tokens),
                    self.prefill_capacity_skips)
        pres = fit
        if not pres:
            return False
        # the handoff only pays off when the replica decoding next can
        # map the shipped blocks back: when every decode-capable
        # replica explicitly advertises kv_dfs=0, offloading would pay
        # the prefill twice — once on the prefill replica, once cold on
        # the decode side — plus the DataNode writes. A record without
        # the attribute (hand-registered, mid-upgrade) stays eligible
        dec = [r for r in recs if self._rec_role(r) != "prefill"]
        if dec and all(r.attributes.get("kv_dfs") == "0" for r in dec):
            return False
        with self._lock:
            loads = {r.path: self._outstanding.get(r.path, 0)
                     for r in pres}
        rec = min(pres, key=lambda r: loads[r.path])
        # the handoff is a full prefill — it must weigh on the replica's
        # outstanding count or every offload piles onto the same pick
        with self._lock:
            self._outstanding[rec.path] = \
                self._outstanding.get(rec.path, 0) + 1
        shipped = False
        try:
            with global_tracer().span(
                    "serving.router.prefill_offload") as sp:
                sp.add_kv("replica", rec.path)
                sp.add_kv("prompt_tokens", str(len(tokens)))
                try:
                    out = self._post(rec, {"tokens": tokens,
                                           "timeout":
                                               self.prefill_timeout},
                                     user, api_path="/v1/prefill",
                                     timeout=self.prefill_timeout)
                    sp.add_kv("persisted_tokens",
                              str(out.get("persisted_tokens", 0)))
                    self.prefill_offloaded += 1
                    shipped = True
                except Exception as e:  # noqa: BLE001 — ANY handoff
                    # failure (transport, 4xx, replica without the DFS
                    # tier) falls back to a cold decode-side prefill
                    sp.add_kv("failed", str(e))
                    log.debug("prefill offload to %s failed (%s); "
                              "decoding cold", rec.path, e)
        finally:
            with self._lock:
                n = self._outstanding.get(rec.path, 1)
                self._outstanding[rec.path] = max(0, n - 1)
        return shipped

    def _with_retry(self, fn, affinity: Optional[str] = None,
                    role: Optional[str] = None,
                    prefer_dfs: bool = False):
        retries = failovers = 0
        exclude: set = set()
        shed_floor = 0.0      # max Retry-After seen from 429 sheds
        while True:
            try:
                rec = self._pick(exclude, affinity, role=role,
                                 prefer_dfs=prefer_dfs)
            except NoReplicasError as e:
                action = self.policy.should_retry(e, retries, failovers,
                                                  True)
                if action.action == RetryAction.FAIL:
                    raise
                retries += 1
                # every candidate failed or shed this round: honor the
                # strongest Retry-After the doors pushed back with
                # (capped — a misconfigured door must not park the
                # client) before re-opening the whole candidate set
                time.sleep(max(action.delay_s, 0.05,
                               min(shed_floor, 5.0)))
                shed_floor = 0.0
                exclude.clear()
                continue
            with self._lock:
                self._outstanding[rec.path] = \
                    self._outstanding.get(rec.path, 0) + 1
            try:
                return fn(rec)
            except (ConnectionError, OSError, RetriableError) as e:
                exclude.add(rec.path)
                shed_floor = max(shed_floor,
                                 getattr(e, "retry_after_s", 0.0))
                action = self.policy.should_retry(e, retries, failovers,
                                                  True)
                log.debug("replica %s failed (%s); %s", rec.path, e,
                          action.action)
                if action.action == RetryAction.FAIL:
                    raise
                if action.action == RetryAction.FAILOVER_AND_RETRY:
                    failovers += 1
                retries += 1
                if action.delay_s > 0:
                    time.sleep(action.delay_s)
            finally:
                with self._lock:
                    n = self._outstanding.get(rec.path, 1)
                    self._outstanding[rec.path] = max(0, n - 1)

    def _post(self, rec: ServiceRecord, payload: Dict,
              user: Optional[str], stream: bool = False,
              api_path: str = "/v1/generate",
              timeout: float = 300.0):
        host, _, port = rec.endpoints["http"].rpartition(":")
        path = api_path
        if user:
            path += f"?user.name={user}"
        conn = http.client.HTTPConnection(host, int(port),
                                          timeout=timeout)
        try:
            headers = {"Content-Type": "application/json"}
            ctx = current_context()
            if ctx is not None:
                headers["X-Htpu-Trace"] = ctx.to_header()
            conn.request("POST", path, body=json.dumps(payload).encode(),
                         headers=headers)
            resp = conn.getresponse()
            if resp.status == 503:
                # replica started draining between registry refreshes
                raise RetriableError(f"replica {rec.path} draining")
            if resp.status == 429:
                # QoS shed: THIS replica is over its overload line for
                # this tenant, but another replica may have headroom —
                # retriable-on-another-replica, unlike 408 (below via
                # the 4xx arm), where the generation is already running
                # here and a replay would amplify load exactly when the
                # fleet is slow. Retry-After rides along as a delay
                # floor for when every replica is shedding.
                body = resp.read().decode(errors="replace")
                err = RetriableError(
                    f"replica {rec.path} shedding: {body}")
                try:
                    err.retry_after_s = float(
                        resp.getheader("Retry-After") or 0.0)
                except ValueError:
                    err.retry_after_s = 0.0
                raise err
            if 400 <= resp.status < 500:
                # deterministic rejection (bad request, auth): the same
                # request fails everywhere — no retry
                body = resp.read().decode(errors="replace")
                raise ReplicaRequestError(
                    resp.status, f"replica {rec.path}: {body}")
            if resp.status != 200:
                body = resp.read().decode(errors="replace")
                raise RetriableError(
                    f"replica {rec.path} -> {resp.status}: {body}")
            if stream:
                return resp, conn   # caller iterates + closes
            data = json.loads(resp.read())
        except Exception:
            conn.close()
            raise
        conn.close()
        return data

    def close(self) -> None:
        self.reg.close()
