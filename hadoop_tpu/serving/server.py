"""HTTP front-end of a serving replica.

Rides the embedded admin HTTP server (``http.server.HttpServer``), the
same chassis every daemon exposes — so a replica gets ``/jmx`` (serving
metrics), ``/conf``, ``/stacks`` for free next to its API:

    POST /v1/generate   {"tokens": [...], "max_new_tokens": 8,
                         "temperature": 0.7, "top_k": 40,
                         "stream": true}
    GET  /v1/health     liveness + load (queue depth, occupancy,
                        free KV pages, prefix-cache hit rate / resident
                        blocks / chunk budget) — what the router
                        balances on and dashboards scrape

``/v1/generate`` is wrapped in the hadoop-auth filter
(``security.http_auth.AuthFilter``): callers present ``?user.name=`` or
the signed ``hadoop.auth`` cookie; anonymous access only if the
deployment allows it. Streaming responses ride the chassis' chunked
iterator payloads — one JSON line per token, then a terminal summary
line — so a client renders tokens as they decode.

``/v1/health`` stays outside the filter (liveness probes and the router
must not need credentials — parity with every daemon's ``/health``).

Two control-plane additions ride the same chassis: ``POST
/v1/admin/drain`` (the autoscaler's retirement knock — async graceful
drain, 202 immediately) and, when a ``QoSGate`` is wired, per-tenant
fairness in front of engine admission with ``429 + Retry-After``
shedding for over-share tenants under overload (``serving/qos.py``).
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import time
from typing import Dict, Optional, Tuple

from hadoop_tpu.conf import Configuration
from hadoop_tpu.http.server import HttpServer
from hadoop_tpu.obs.slo import parse_class_map, slo_class_of
from hadoop_tpu.security.http_auth import AuthFilter
from hadoop_tpu.serving.engine import DecodeEngine, SamplingParams
from hadoop_tpu.tracing.tracer import SpanContext, global_tracer

log = logging.getLogger(__name__)

SECRET_KEY = "serving.http.auth.secret"
ANON_KEY = "serving.http.auth.anonymous.allowed"
MAX_NEW_CAP_KEY = "serving.max.new.tokens"


class ServingServer:
    """One replica's HTTP door in front of a ``DecodeEngine``."""

    def __init__(self, engine: DecodeEngine,
                 conf: Optional[Configuration] = None,
                 bind: Tuple[str, int] = ("127.0.0.1", 0),
                 qos=None, drain_cb=None):
        self.engine = engine
        self.conf = conf or Configuration()
        self.http = HttpServer(self.conf, bind, daemon_name="serving")
        self.tracer = global_tracer()
        self._draining = threading.Event()
        # set when drain() has fully FINISHED (in-flight requests
        # delivered AND the cache persist flushed) — _draining only
        # marks the start. /v1/health exposes it so a controller never
        # retires a replica that is still persisting
        self._drain_done = threading.Event()
        self.max_new_cap = self.conf.get_int(MAX_NEW_CAP_KEY, 1024)
        # door QoS (serving/qos.py): per-tenant decay-cost accounting +
        # load shedding in front of engine admission. None = open door
        # (bare servers in tests; ServingReplica wires the gate).
        self.qos = qos
        # fleet SLO scoreboard (obs/slo): every request is stamped
        # with a bounded tenant class — the QoS scheduler's decay
        # level clamped into p0..p3, or a conf-pinned identity — and
        # the door records class-labeled TTFT / per-token / outcome
        # families the doctor diffs per poll window
        self._class_map = parse_class_map(self.conf)
        # autoscaler hook: /v1/admin/drain invokes this (async) so a
        # controller can retire THIS replica — the replica process
        # wires its own full drain-and-exit here
        self.drain_cb = drain_cb
        self._drain_lock = threading.Lock()
        self._drain_started = False     # guarded-by: _drain_lock
        secret = self.conf.get(SECRET_KEY, "")
        handler = self._generate
        admin_drain = self._admin_drain
        if secret:
            filt = AuthFilter(
                secret.encode(),
                allow_anonymous=self.conf.get_bool(ANON_KEY, False))
            handler = filt.wrap(handler)
            admin_drain = filt.wrap(admin_drain)
        prefill_handler = self._prefill
        if secret:
            prefill_handler = filt.wrap(prefill_handler)
        self.http.add_handler("/v1/generate", handler)
        self.http.add_handler("/v1/prefill", prefill_handler)
        self.http.add_handler("/v1/health", self._health)
        self.http.add_handler("/v1/admin/drain", admin_drain)

    # ------------------------------------------------------------ lifecycle

    @property
    def port(self) -> int:
        return self.http.port

    def start(self) -> None:
        self.http.start()
        log.info("serving replica on :%d (slots=%d, kv pages=%d)",
                 self.port, self.engine.max_batch,
                 self.engine.pool.num_usable)

    def drain(self, timeout: float = 60.0) -> None:
        """Graceful shutdown, phase 1: refuse new work (503 + draining
        health so the router stops routing here), let the engine finish
        what it holds."""
        self._draining.set()
        self.engine.stop(drain=True, timeout=timeout)
        self._drain_done.set()

    def stop(self) -> None:
        if not self._draining.is_set():
            self.engine.stop()
        if self.qos is not None:
            self.qos.stop()
        self.http.stop()

    # ------------------------------------------------------------------ slo

    def _slo_class(self, tenant: str, level: int) -> str:
        """Bounded tenant class: the conf identity map wins, else the
        QoS decay level clamps into p0..p3 (open door => p0)."""
        cls = self._class_map.get(tenant or "anonymous")
        return cls if cls is not None else slo_class_of(level)

    def _slo_record(self, cls: str, outcome: str,
                    ttft_s: Optional[float] = None,
                    token_s: Optional[float] = None) -> None:
        m = getattr(self.engine, "metrics", None)
        if m is None or not hasattr(m, "slo_requests"):
            return                       # bare engines mint no metrics
        m.slo_requests[(cls, outcome)].incr()
        if ttft_s is not None:
            m.slo_ttft_hist[cls].add(ttft_s)
        if token_s is not None:
            m.slo_token_hist[cls].add(token_s)

    def _slo_finish(self, cls: str, handle, failed: bool) -> None:
        """Terminal accounting for an admitted request: outcome plus
        the latency families when a first token was delivered."""
        ttft_s = None
        token_s = None
        if handle.first_token_at is not None:
            ttft_s = max(0.0, handle.first_token_at
                         - handle.submitted_at)
            n = len(handle.out_tokens)
            if n >= 2:
                token_s = max(0.0, (time.monotonic()
                                    - handle.first_token_at)
                              / (n - 1))
        self._slo_record(cls, "failed" if failed else "ok",
                         ttft_s=ttft_s, token_s=token_s)

    # ------------------------------------------------------------- handlers

    def _health(self, query: Dict, body) -> Tuple[int, Dict]:
        eng = self.engine
        out = {
            "status": "draining" if self._draining.is_set() else "serving",
            "drain_complete": self._drain_done.is_set(),
            "queue_depth": eng.queue_depth,
            "active": eng.num_active,
            "slots": eng.max_batch,
            "kv_blocks_free": eng.pool.num_free,
            "kv_blocks_total": eng.pool.num_usable,
            "tokens_generated": eng.tokens_generated,
            "prefilling": eng.num_prefilling,
            # the autoscaler's per-replica load signals ride here (the
            # /prom exposition is process-wide, so an in-process fleet
            # can only tell replicas apart through this door)
            "prefill_backlog": eng.prefill_backlog,
            # prefix-reuse cache + chunked-prefill observability: the
            # router and ops dashboards read hit_rate/cached_blocks here
            "prefix_cache": eng.cache_stats(),
            # the weight plane: resident dtype, measured weight bytes,
            # quantize-at-load seconds, and the lanes x context those
            # bytes left room for (serving/weightplane.py)
            "weights": eng.weight_plane(),
            # the long-context plane: CP width, streamed-block and
            # window-page-in traffic, pinned compile counters — or
            # {"enabled": False} on a bitwise replica
            "longctx": eng.longctx_stats(),
        }
        # the live HBM ledger: what the chip's memory is spent on, one
        # scrape — weights / kv_pool / longctx window+tail components
        # cross-checked against backend device stats where reported
        from hadoop_tpu.obs.hbm import hbm_ledger
        out["hbm"] = hbm_ledger().report()
        if self.qos is not None:
            out["qos"] = self.qos.stats()
        return 200, out

    def _admin_drain(self, query: Dict, body) -> Tuple[int, Dict]:
        """Autoscaler-initiated retirement: refuse new work, persist
        hot prefixes to the DFS tier, finish in-flight generations —
        asynchronously, so the controller gets its 202 immediately and
        watches /v1/health (then the registry record vanishing) for
        completion. Idempotent: a second POST during an active drain
        just reports it."""
        if query.get("__method__") != "POST":
            return 200, {"draining": self._draining.is_set()}
        # atomic check-and-set: two racing POSTs (controller retry vs
        # operator) must start exactly ONE drain thread — _draining is
        # only set later inside that thread, so it can't be the guard
        with self._drain_lock:
            already = self._drain_started
            self._drain_started = True
        if not already:
            cb = self.drain_cb or self.drain
            threading.Thread(target=cb, name="admin-drain",
                             daemon=True).start()
        return 202, {"draining": True, "already_draining": already}

    def _prefill(self, query: Dict, body):
        """The prefill half of prefill/decode disaggregation: prefill
        the prompt and persist its full-block KV span to the DFS tier
        (durable on return — the decode replica the router picks next
        maps it back immediately). 400 when this replica has no DFS
        tier, so a router probing a misconfigured fleet fails fast
        instead of retrying the handoff everywhere."""
        if self._draining.is_set():
            return 503, {"RemoteException": {
                "exception": "RetriableException",
                "message": "replica draining"}}
        try:
            req = json.loads(body or b"{}")
            tokens = req["tokens"]
            if (not isinstance(tokens, list) or not tokens or
                    not all(isinstance(t, int) for t in tokens)):
                raise ValueError("'tokens' must be a non-empty int list")
            timeout = float(req.get("timeout", 300.0))
        except (KeyError, TypeError, ValueError) as e:
            return 400, {"RemoteException": {
                "exception": "IllegalArgumentException",
                "message": f"bad prefill request: {e}"}}
        parent = SpanContext.from_header(query.get("__trace__"))
        with self.tracer.span("serving.prefill_request",
                              parent=parent) as span:
            span.add_kv("prompt_tokens", str(len(tokens)))
            try:
                persisted = self.engine.prefill_to_store(
                    tokens, timeout=timeout)
            except ValueError as e:
                return 400, {"RemoteException": {
                    "exception": "IllegalArgumentException",
                    "message": str(e)}}
            except (RuntimeError, TimeoutError) as e:
                span.add_kv("failed", str(e))
                return 500, {"RemoteException": {
                    "exception": "PrefillFailedException",
                    "message": str(e)}}
            span.add_kv("persisted_tokens", str(persisted))
        return 200, {"persisted_tokens": persisted,
                     "prompt_tokens": len(tokens)}

    def _generate(self, query: Dict, body):
        if self._draining.is_set():
            return 503, {"RemoteException": {
                "exception": "RetriableException",
                "message": "replica draining"}}
        try:
            req = json.loads(body or b"{}")
            tokens = req["tokens"]
            if (not isinstance(tokens, list) or not tokens or
                    not all(isinstance(t, int) for t in tokens)):
                raise ValueError("'tokens' must be a non-empty int list")
            sampling = SamplingParams(
                max_new_tokens=min(int(req.get("max_new_tokens", 16)),
                                   self.max_new_cap),
                temperature=float(req.get("temperature", 0.0)),
                top_k=int(req.get("top_k", 0)),
                stop_token=req.get("stop_token"))
            timeout = float(req.get("timeout", 300.0))
        except (KeyError, TypeError, ValueError) as e:
            return 400, {"RemoteException": {
                "exception": "IllegalArgumentException",
                "message": f"bad generate request: {e}"}}
        # the tenant is the authenticated principal (the auth filter's
        # __user__), falling back to the unauthenticated ?user.name=
        # claim — QoS fairness, unlike authz, is useful even on an
        # open door
        tenant = query.get("__user__") or query.get("user.name") or ""
        slo_cls = self._slo_class(tenant, 0)
        if self.qos is not None:
            ok, retry_after, level = self.qos.admit(
                tenant, self.qos.cost_of(tokens,
                                         sampling.max_new_tokens))
            slo_cls = self._slo_class(tenant, level)
            if not ok:
                self._slo_record(slo_cls, "shed")
                # the router treats 429 + Retry-After as
                # retriable-on-another-replica; a direct caller backs
                # off — either way this replica sheds the over-share
                # tenant before light tenants feel the overload
                return (429,
                        {"RemoteException": {
                            "exception": "ServerTooBusyException",
                            "message": f"tenant {tenant or 'anonymous'} "
                                       f"over fair share (priority "
                                       f"{level}) under overload"}},
                        {"Retry-After": f"{retry_after:g}"})
        # resume the ROUTER's trace from the X-Htpu-Trace header (the
        # HTTP twin of the RPC header's SpanContext): the door, engine
        # admit, and first token all join the request's one trace
        parent = SpanContext.from_header(query.get("__trace__"))
        span = self.tracer.span("serving.request", parent=parent)
        span.add_kv("user", query.get("__user__", ""))
        span.add_kv("prompt_tokens", str(len(tokens)))
        try:
            # the door span's context rides the request into the engine
            # so admit/preempt/first-token spans join this trace
            handle = self.engine.submit(tokens, sampling,
                                        trace_ctx=span.context(),
                                        tenant=tenant)
        except ValueError as e:
            span.finish()
            return 400, {"RemoteException": {
                "exception": "IllegalArgumentException",
                "message": str(e)}}
        span.add_kv("request", str(handle.id))
        if str(req.get("stream", "")).lower() in ("1", "true", "yes") or \
                req.get("stream") is True:
            return 200, self._stream(handle, span, slo_cls)
        try:
            out = handle.wait(timeout=timeout)
        except RuntimeError as e:
            # engine failed the request (decode error, stop/drain):
            # the span must still deliver — the failure path is exactly
            # where the cross-daemon trace earns its keep
            span.add_kv("failed", str(e))
            span.finish()
            self._slo_finish(slo_cls, handle, failed=True)
            return 500, {"RemoteException": {
                "exception": "GenerationFailedException",
                "message": f"request {handle.id}: {e}"}}
        except TimeoutError:
            # 4xx on purpose: the router fails 4xx fast, so a slow
            # generation is NOT replayed end-to-end on every other
            # replica (retry amplification exactly when the fleet is
            # loaded); the request keeps decoding here and its tokens
            # drop — same semantics as a client killing a stream
            span.add_kv("timed_out", "true")
            span.finish()
            # a missed deadline spends error budget: the caller never
            # got their generation, whatever the engine does next
            self._slo_record(slo_cls, "failed")
            return 408, {"RemoteException": {
                "exception": "RequestTimedOutException",
                "message": f"request {handle.id} still decoding after "
                           f"{timeout}s"}}
        span.add_kv("tokens_out", str(len(out)))
        span.finish()
        self._slo_finish(slo_cls, handle, failed=False)
        return 200, {"request_id": handle.id, "tokens": out,
                     "prompt_tokens": len(tokens)}

    def _stream(self, handle, span, slo_cls: str = "p0"):
        """Chunked body: one JSON line per token, terminal summary line.
        The chassis frames each yielded chunk; a killed connection just
        ends the generator — the engine finishes the request and the
        tokens fall on the floor, which is the right drop semantics."""
        timed_out = [False]

        def gen():
            try:
                while True:
                    try:
                        tok = handle.tokens_out.get(timeout=300.0)
                    except queue.Empty:
                        timed_out[0] = True
                        yield (json.dumps(
                            {"error": "timed out"}) + "\n").encode()
                        return
                    if tok is None:
                        break
                    yield (json.dumps({"token": tok}) + "\n").encode()
                done = {"done": True, "request_id": handle.id,
                        "tokens": list(handle.out_tokens)}
                if handle.state == "FAILED":
                    done = {"done": True, "error": handle.error,
                            "request_id": handle.id}
                yield (json.dumps(done) + "\n").encode()
            finally:
                span.add_kv("tokens_out", str(len(handle.out_tokens)))
                span.finish()
                self._slo_finish(
                    slo_cls, handle,
                    failed=timed_out[0] or handle.state == "FAILED")
        return gen()
