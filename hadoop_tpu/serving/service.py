"""The serving replica as a deployable unit.

Two faces:

- ``serving_service_spec`` packages N replicas as a YARN long-running
  service (``yarn.services``): the RM places the containers, the service
  AM restarts exited replicas (RESTART_ALWAYS), and ``flex`` scales the
  replica count at runtime — serving capacity is a YARN knob, exactly
  like every other long-running daemon on the cluster.

- ``replica_main`` is what runs inside each container (and behind
  ``hadoop-tpu serve``): pull the checkpoint from the DFS (hedged
  reads), build the engine + HTTP server, register in the service
  registry with an ephemeral lease, and on SIGTERM flip the registry
  record to draining, finish in-flight requests, then exit — the
  graceful-drain half of the router's balancing contract.
"""

from __future__ import annotations

import logging
import signal
import socket
import sys
import threading
import time
import uuid
from typing import List, Optional, Tuple

from hadoop_tpu.conf import Configuration
from hadoop_tpu.ipc.errors import RpcError
from hadoop_tpu.models.config import get_config
from hadoop_tpu.serving.loader import (IO_WORKERS_KEY,
                                       load_serving_params,
                                       serving_read_defaults)
from hadoop_tpu.serving.metrics import ServingMetrics
from hadoop_tpu.serving.router import replica_path
from hadoop_tpu.yarn.records import Resource
from hadoop_tpu.yarn.services import (RESTART_ALWAYS, Component,
                                      ServiceSpec)

log = logging.getLogger(__name__)


def serving_service_spec(name: str, *, checkpoint: str, preset: str,
                         replicas: int = 2,
                         registry_addr: Optional[str] = None,
                         resource: Optional[Resource] = None,
                         extra_args: Optional[List[str]] = None,
                         ) -> ServiceSpec:
    """YARN service spec: N identical replica containers."""
    cmd = [sys.executable, "-m", "hadoop_tpu.serving.service",
           "--replica", "--name", name,
           "--checkpoint", checkpoint, "--preset", preset,
           # containers land on arbitrary hosts: bind the wildcard so
           # the replica advertises its hostname, not some loopback the
           # router would resolve to its own machine
           "--host", "0.0.0.0"]
    if registry_addr:
        cmd += ["--registry", registry_addr]
    cmd += list(extra_args or [])
    return ServiceSpec(name, [
        Component("replica", replicas, cmd,
                  resource=resource or Resource(1024, 1),
                  restart_policy=RESTART_ALWAYS),
    ])


def autoscaler_service_spec(name: str, *, registry_addr: str,
                            service: str,
                            resource: Optional[Resource] = None,
                            extra_args: Optional[List[str]] = None,
                            ) -> ServiceSpec:
    """The SLO controller as its own YARN long-running service, placed
    next to the replica fleet it scales (one instance; the RM restarts
    it like any daemon — the controller is stateless, its hysteresis
    counters rebuild within a few polls)."""
    cmd = [sys.executable, "-m", "hadoop_tpu.serving.autoscale",
           "--registry", registry_addr, "--service", service]
    cmd += list(extra_args or [])
    return ServiceSpec(name, [
        Component("autoscaler", 1, cmd,
                  resource=resource or Resource(256, 1),
                  restart_policy=RESTART_ALWAYS),
    ])


class ServingReplica:
    """Engine + HTTP server + registry lease, wired for one process."""

    def __init__(self, conf: Configuration, *, name: str,
                 checkpoint: str, preset: str,
                 registry_addr: Optional[Tuple[str, int]] = None,
                 bind: Tuple[str, int] = ("127.0.0.1", 0),
                 instance: Optional[str] = None):
        from hadoop_tpu.fs import FileSystem, Path
        from hadoop_tpu.serving.engine import DecodeEngine
        from hadoop_tpu.serving.server import ServingServer
        self.conf = conf
        self.name = name
        self.instance = instance or \
            f"{socket.gethostname()}-{uuid.uuid4().hex[:8]}"
        serving_read_defaults(conf)
        cfg = get_config(preset)
        fs = FileSystem.get(checkpoint, conf)
        ckpt_dir = Path(checkpoint).path
        # the weight plane (serving/weightplane.py): serving.parity
        # picks the tier. bitwise (default) loads the checkpoint's own
        # dtypes untouched; relaxed streams each shard through the int8
        # quantizer at load so the full f32 model is never host-resident
        from hadoop_tpu.serving.weightplane import weightplane_from_conf
        weights = weightplane_from_conf(conf)
        t0 = time.monotonic()
        self.quantize_seconds = 0.0
        if weights.relaxed:
            from hadoop_tpu.serving.weightplane import quantized_load
            params, step, wreport = quantized_load(
                fs, ckpt_dir, cfg, weights,
                io_workers=conf.get_int(IO_WORKERS_KEY, 4))
            self.quantize_seconds = wreport["quantize_seconds"]
        else:
            params, step = load_serving_params(
                fs, ckpt_dir, cfg,
                io_workers=conf.get_int(IO_WORKERS_KEY, 4))
        self.load_seconds = round(time.monotonic() - t0, 3)
        self.step = step
        # the tiered KV cache: host-RAM spill ring byte budget, and the
        # DFS prefix store on the SAME filesystem the checkpoint came
        # from (the replica already holds a client with hedged reads
        # armed). role=prefill replicas require the DFS tier — without
        # it they could never ship finished KV to a decode replica.
        self.role = conf.get("serving.role", "mixed")
        if self.role not in ("prefill", "decode", "mixed"):
            raise ValueError(f"serving.role must be prefill/decode/"
                             f"mixed, got {self.role!r}")
        self.kv_host_bytes = conf.get_int("serving.kv.host.bytes", 0)
        # any explicitly role'd replica defaults the DFS tier ON: the
        # handoff needs the prefill side writing AND the decode side
        # reading the same store. A mixed (default) replica keeps
        # today's behavior unless the deployment opts in.
        kv_dfs = conf.get_bool("serving.kv.dfs.enable",
                               self.role != "mixed")
        if self.role == "prefill" and not kv_dfs:
            raise ValueError("a prefill-role replica needs the DFS KV "
                             "tier (serving.kv.dfs.enable)")
        self.kv_dfs_enabled = kv_dfs
        # runtime comm ledger gate (obs.comm.timing, default on): the
        # replica process owns the conf, so it configures the
        # process-global ledger the CP prefill dispatches record into
        from hadoop_tpu.obs.comm import comm_runtime
        comm_runtime().configure(conf)
        metrics = ServingMetrics()
        # door QoS (serving/qos.py): the decay scheduler + the fair
        # admission queue must exist BEFORE the engine (the queue is
        # the engine's pending queue) and the gate after it (the shed
        # decision reads live queue depth)
        self.qos_enabled = conf.get_bool("serving.qos.enabled", True)
        qos_queue = qos_sched = None
        if self.qos_enabled:
            from hadoop_tpu.serving.qos import (DecayCostScheduler,
                                                FairAdmissionQueue)
            qos_sched = DecayCostScheduler(
                conf.get_int("serving.qos.levels", 4), conf)
            qos_queue = FairAdmissionQueue(qos_sched)
        self.engine = DecodeEngine(
            params, cfg,
            # unset = engine default (4), or budget-derived lanes when
            # serving.kv.hbm.bytes is set
            max_batch=conf.get_int("serving.max.batch", 0) or None,
            block_size=conf.get_int("serving.kv.block.size", 16),
            num_blocks=conf.get_int("serving.kv.num.blocks", 0) or None,
            max_context=conf.get_int("serving.max.context", 0) or None,
            prefill_chunk=conf.get_int("serving.prefill.chunk", 16),
            prefix_cache=conf.get_bool("serving.prefix_cache.enabled",
                                       True),
            kv_host_bytes=self.kv_host_bytes,
            kv_store_fs=fs if kv_dfs else None,
            kv_store_dir=conf.get("serving.kv.dfs.dir", "/kvcache"),
            kv_dfs_min_refs=conf.get_int("serving.kv.dfs.min-refs", 1),
            kv_codec=conf.get("serving.kv.codec", "raw"),
            # speculative cold-fetch window: how many chain blocks one
            # DFS round trip reads ahead (longctx chains want this
            # sized so paging is O(chain/window) round trips)
            kv_fetch_window=conf.get_int("serving.kv.fetch.window", 4),
            # speculative decoding: k draft tokens per decode lane from
            # the per-request n-gram index, verified in the same fused
            # step (0 = off; exact sampling either way)
            speculate_k=conf.get_int("serving.speculate.k", 0),
            speculate_ngram=conf.get_int("serving.speculate.ngram", 3),
            admission_queue=qos_queue,
            # drain-aware scale-in: ship resident cached prefixes to
            # the DFS tier before this replica exits
            drain_persist=conf.get_bool("serving.kv.drain.persist",
                                        True),
            # fixed HBM budget: KV pool (and lanes, when
            # serving.max.batch is unset) sized against the MEASURED
            # resident-weight bytes — int8 weights become lanes, capped
            # by serving.max.lanes (step rows scale with the lane count)
            hbm_bytes=conf.get_int("serving.kv.hbm.bytes", 0),
            max_lanes=conf.get_int("serving.max.lanes", 16),
            quantize_seconds=self.quantize_seconds,
            # expert-parallel MoE serving: capacity-factor override
            # (0 = the model config's), expert-dim shard count across
            # the replica's chips (0 = auto), and the relaxed-tier
            # all2all payload codec for the dispatch/combine legs
            moe_capacity_factor=conf.get_float(
                "serving.moe.capacity.factor", 0.0),
            moe_shards=conf.get_int("serving.moe.shards", 0),
            moe_a2a_codec=conf.get("serving.moe.a2a.codec", "int8"),
            metrics=metrics)
        qos_gate = None
        if self.qos_enabled:
            from hadoop_tpu.serving.qos import QoSGate
            qos_gate = QoSGate(conf, self.engine, metrics=metrics,
                               scheduler=qos_sched)
        # the long-context plane (serving/longctx): CP prefill across
        # the replica's mesh + streamed tier ingest + working-set
        # decode for prompts >= serving.longctx.min.tokens. Relaxed
        # tier ONLY — the CP softmax reassociation is not bitwise.
        self.longctx_enabled = conf.get_bool("serving.longctx.enabled",
                                             False)
        if self.longctx_enabled and weights.relaxed:
            from hadoop_tpu.serving.longctx import \
                longctx_plane_from_conf
            self.engine.attach_longctx(
                longctx_plane_from_conf(conf, cfg, self.engine))
        elif self.longctx_enabled:
            raise ValueError(
                "serving.longctx.enabled requires serving.parity="
                "relaxed (context-parallel prefill reassociates the "
                "softmax — not bitwise vs the single-chip step)")
        self.server = ServingServer(self.engine, conf, bind=bind,
                                    qos=qos_gate,
                                    # the autoscaler's /v1/admin/drain
                                    # retires the WHOLE replica, not
                                    # just the door
                                    drain_cb=self.drain_and_stop)
        # advertise a reachable address: the bind host when concrete, the
        # hostname when bound to the wildcard (cross-host routing must
        # not resolve to some other machine's loopback)
        self.advertise_host = bind[0] if bind[0] not in ("", "0.0.0.0") \
            else socket.gethostname()
        self.reg = None
        self._registry_addr = registry_addr
        self._stopped = threading.Event()
        self._drain_lock = threading.Lock()
        # set when drain_and_stop has fully FINISHED (persist included)
        # — _stopped only means it began. The process main loop exits
        # on this one: leaving on _stopped would kill the daemon
        # drain thread mid-persist and strand half-written KV blocks
        self.drained = threading.Event()

    def start(self) -> None:
        self.engine.start()
        self.server.start()
        self._top_source = None
        if self.qos_enabled and self.server.qos is not None:
            # /ws/v1/top on this replica's chassis reads the door's
            # decay-cost accounting — the serving twin of nntop, no
            # second counter (obs/top.py)
            from hadoop_tpu.obs.top import register_top_source
            self._top_source = f"serving.{self.name}.tenants"
            register_top_source(self._top_source,
                                self.server.qos.sched.snapshot)
        if self._registry_addr:
            from hadoop_tpu.registry.registry import (HEARTBEAT_ATTR,
                                                      RegistryClient,
                                                      ServiceRecord,
                                                      record_ttl)
            self.reg = RegistryClient(self._registry_addr, self.conf)
            self._record_ttl = record_ttl(self.conf)
            self.record = ServiceRecord(
                replica_path(self.name, self.instance),
                endpoints={"http":
                           f"{self.advertise_host}:{self.server.port}"},
                attributes={"state": "serving",
                            "slots": str(self.engine.max_batch),
                            "step": str(self.step),
                            # liveness stamp: routers/autoscalers skip
                            # the record once this ages past the TTL,
                            # even before the registry sweep evicts it
                            HEARTBEAT_ATTR: f"{time.time():.3f}",
                            # checkpoint pull latency: the fleet-level
                            # cold-start signal the autoscaler scales
                            # AHEAD of (a 5-minute load means growing
                            # 5 minutes before saturation)
                            "load_seconds": str(self.load_seconds),
                            # the weight plane: resident dtype +
                            # measured bytes + quantize-at-load cost —
                            # an autoscaler/dashboard reads capacity
                            # and cold-start directly off the record
                            "weight_dtype":
                                self.engine.weight_plane()["dtype"],
                            "weight_bytes":
                                str(self.engine.weight_bytes),
                            "quantize_seconds":
                                str(self.quantize_seconds),
                            # expert placement: count/shards/resident
                            # bytes (0s on dense) — the autoscaler sees
                            # an MoE replica's real HBM split without
                            # scraping /v1/health
                            "experts": str(self.engine.cfg.n_experts),
                            "expert_shards":
                                str(self.engine.expert_shards),
                            "expert_bytes":
                                str(self.engine.expert_bytes),
                            # disaggregation + tier capacities: the
                            # router routes long prompts to role=prefill
                            # and decodes on decode/mixed; an autoscaler
                            # reads the tier budgets for drain planning
                            "role": self.role,
                            "kv_host_bytes": str(self.kv_host_bytes),
                            # KV capacity in routable units: the
                            # router's prefill capacity gate computes
                            # a prompt's paged working set from these
                            # and never offers a monster prompt to a
                            # replica that cannot hold it
                            "kv_block_bytes":
                                str(self.engine.block_nbytes),
                            "kv_block_size":
                                str(self.engine.block_size),
                            "kv_hbm_blocks":
                                str(self.engine.pool.num_usable),
                            "longctx": "1" if self.longctx_enabled
                                       else "0",
                            # the plane's pinned prompt budget: the
                            # router's capacity gate treats a
                            # longctx+DFS replica as unbounded only
                            # UP TO this — offering a prompt past it
                            # would fail at the replica's door
                            "longctx_max_tokens": str(
                                self.engine.longctx_stats().get(
                                    "max_tokens", 0)),
                            "kv_dfs": "1" if self.kv_dfs_enabled
                                      else "0"})
            # the heartbeat loop below refreshes the record (stamp +
            # live load) — it IS the renewal, so no auto_renew twin
            self.reg.register(self.record, ttl_s=self._record_ttl,
                              auto_renew=False)
            from hadoop_tpu.util.misc import Daemon
            Daemon(self._heartbeat_loop,
                   f"replica-heartbeat-{self.instance}").start()
        log.info("serving replica %s/%s up on :%d (checkpoint step %d)",
                 self.name, self.instance, self.server.port, self.step)

    def _heartbeat_loop(self) -> None:
        """Refresh the registry record at a third of its TTL: the stamp
        keeps staleness checks green, the re-register keeps the lease
        alive (and recreates the record after a registry restart), and
        the live load attributes give the autoscaler a signal even when
        it cannot reach the replica's own door."""
        from hadoop_tpu.registry.registry import HEARTBEAT_ATTR
        period = max(0.2, self._record_ttl / 3.0)
        while not self._stopped.wait(period):
            self.record.attributes.update({
                HEARTBEAT_ATTR: f"{time.time():.3f}",
                "queue_depth": str(self.engine.queue_depth),
                "active": str(self.engine.num_active)})
            try:
                self.reg.register(self.record, ttl_s=self._record_ttl,
                                  auto_renew=False)
            except (RpcError, OSError) as e:
                # a dead registry must not kill the replica; the next
                # beat retries and re-registration heals a restart
                log.debug("registry heartbeat failed: %s", e)

    def drain_and_stop(self, timeout: float = 60.0) -> None:
        # atomic check-and-set: a SIGTERM racing an /v1/admin/drain
        # must yield exactly ONE drain sequence (two concurrent
        # engine.stop calls would race _thread=None against join)
        with self._drain_lock:
            mine = not self._stopped.is_set()
            self._stopped.set()
        if not mine:
            # a drain is already running on another thread: wait for
            # IT to finish rather than returning mid-persist
            self.drained.wait(timeout)
            return
        try:
            if self.reg is not None:
                # flip the record before unregistering so routers that
                # hold a cached copy see 'draining' on their next
                # refresh even if the lease outlives us briefly
                self.record.attributes["state"] = "draining"
                try:
                    self.reg.register(self.record, ttl_s=10.0,
                                      auto_renew=False)
                except (RpcError, OSError) as e:  # drain must not hang
                    log.debug("draining-state publish failed: %s",
                              e)                  # on a dead registry
            self.server.drain(timeout=timeout)
            if self.reg is not None:
                try:
                    self.reg.unregister(self.record.path)
                except (RpcError, OSError) as e:
                    log.debug("unregister on drain failed: %s", e)
                self.reg.close()
            self.server.stop()
        finally:
            if getattr(self, "_top_source", None):
                from hadoop_tpu.obs.top import unregister_top_source
                unregister_top_source(self._top_source)
            self.drained.set()


def replica_main(argv: List[str],
                 conf: Optional[Configuration] = None) -> int:
    """Entry point of one replica process (container / `serve` CLI)."""
    conf = conf or Configuration()
    args = dict(name="serving", checkpoint=None, preset="tiny",
                registry=None, port=0, host="127.0.0.1", role=None)
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--replica":
            i += 1
            continue
        key = a.lstrip("-").replace("-", "_")
        if key in args and i + 1 < len(argv):
            args[key] = argv[i + 1]
            i += 2
        else:
            print(f"unknown serve option {a}", file=sys.stderr)
            return 2
    if not args["checkpoint"]:
        print("usage: serve --checkpoint URI --preset NAME "
              "[--name SVC] [--registry HOST:PORT] [--port N]",
              file=sys.stderr)
        return 2
    if args["role"]:
        conf.set("serving.role", str(args["role"]))
    registry_addr = None
    if args["registry"]:
        host, _, port = str(args["registry"]).rpartition(":")
        registry_addr = (host or "127.0.0.1", int(port))
    replica = ServingReplica(
        conf, name=str(args["name"]), checkpoint=str(args["checkpoint"]),
        preset=str(args["preset"]), registry_addr=registry_addr,
        bind=(str(args["host"]), int(args["port"])))
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    replica.start()
    try:
        while not stop.wait(0.5):
            if replica.drained.is_set():
                # an autoscaler retired us through /v1/admin/drain and
                # the drain FINISHED (prefixes persisted, in-flight
                # requests delivered) — exit the container cleanly
                break
    finally:
        replica.drain_and_stop()
    return 0


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    sys.exit(replica_main(sys.argv[1:]))
