"""N-gram / prompt-lookup draft proposal for speculative decoding.

The cheapest useful draft model is the request's own history: serving
traffic is full of self-similar token streams (templated answers, code,
retrieval echoes, and the short cycles greedy decode settles into), so
the tokens that followed the last n-gram *last time* are a strong guess
for what follows it now. ``NgramProposer`` keeps an O(1)-per-token
index over one request's prompt + generated tokens and proposes up to
``k`` draft tokens per step; the engine verifies all of them in ONE
batched forward through the same fused fixed-shape step that runs the
decode lanes (see ``engine._step_impl``) and accepts the longest
agreeing prefix.

Exactness is the engine's job, not the proposer's: a bad proposal costs
wasted verify rows, never a wrong token — greedy lanes accept a draft
only on argmax equality, sampled lanes rejection-sample against the
verifier distribution (the draft is a point mass, so the acceptance
test is ``u < p(draft)`` and a rejection re-samples from the target
with the draft token removed — the classic speculative-sampling
identity keeps the output distribution exactly the target's).

The index maps every ``min_n..max_n``-gram to the END position of its
most recent *interior* occurrence (n-grams ending at the current tip
are registered only when the next token arrives, so a lookup can never
match the tip against itself). Proposal chains: after predicting one
token the lookup repeats on the virtually-extended tail, so a period-p
cycle proposes whole periods up to ``k``, not just the p tokens that
physically follow the match.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple


class NgramProposer:
    """Per-request prompt-lookup index. Not thread-safe: owned and
    driven by the engine's scheduler thread only."""

    def __init__(self, tokens: Sequence[int], max_n: int = 3,
                 min_n: int = 1):
        if min_n < 1 or max_n < min_n:
            raise ValueError(f"need 1 <= min_n <= max_n, got "
                             f"[{min_n}, {max_n}]")
        self.max_n = max_n
        self.min_n = min_n
        self._hist: List[int] = []
        # ngram tuple -> end index of its most recent occurrence that
        # is strictly behind the tip (registration is deferred by one
        # append, so the tip never matches itself)
        self._index: Dict[Tuple[int, ...], int] = {}
        self.extend(tokens)

    def __len__(self) -> int:
        return len(self._hist)

    def extend(self, tokens: Sequence[int]) -> None:
        for t in tokens:
            self.append(t)

    def append(self, tok: int) -> None:
        h = self._hist
        i = len(h) - 1          # old tip becomes interior: register it
        if i >= 0:
            for n in range(self.min_n, self.max_n + 1):
                if i - n + 1 < 0:
                    break
                self._index[tuple(h[i - n + 1:i + 1])] = i
        h.append(int(tok))

    def _next(self, ext: List[int]) -> Optional[int]:
        """Predict the token after ``history + ext`` by longest-n-gram
        lookup (longer context wins ties against staler matches)."""
        h = self._hist
        tail = h[-self.max_n:] + ext
        total = len(h) + len(ext)
        for n in range(min(self.max_n, total, len(tail)),
                       self.min_n - 1, -1):
            pos = self._index.get(tuple(tail[-n:]))
            if pos is not None:
                # index entries always end before the real tip, so the
                # continuation h[pos + 1] exists
                return h[pos + 1]
        return None

    def propose(self, k: int) -> List[int]:
        """Up to ``k`` draft tokens continuing the history. Chained:
        each prediction extends the virtual tail for the next lookup,
        so repeating structure proposes as deep as ``k`` allows."""
        out: List[int] = []
        if not self._hist:
            return out
        while len(out) < k:
            nxt = self._next(out)
            if nxt is None:
                break
            out.append(nxt)
        return out
