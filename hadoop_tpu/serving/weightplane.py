"""The serving weight plane: per-tensor dtype/layout policy for
resident model weights.

PROFILE.md's measured wall is HBM, not FLOPs: flagship-1b serving caps
at batch 2 and decode is memory-bandwidth-bound, yet every serving
matmul reads f32-resident weights. ISSUE 10's lowp plane quantized the
*wires* (grad buckets, TP reduces, KV blocks); this module extends the
same quantization story to resident state (the Flash Communication
direction, arXiv:2412.04964, applied where the fleet actually spends):
int8 weights + per-group f32 scales live in HBM, dequantized
in-register inside each matmul, and the ~4x of freed HBM converts
directly into more decode lanes x context at fixed chip memory
(the engine sizes its KV pool against the MEASURED resident bytes).

Tiering mirrors ``parallel.parity``:

- ``serving.parity=bitwise`` (the default): the loader places the
  checkpoint's f32/bf16 leaves untouched and ZERO code in this module
  is reachable from the engine's compiled step — enforced statically
  by tpulint's ``parity/relaxed-gated`` checker (the in-graph entry
  points here, :func:`qdot` / :func:`qrows` / :func:`qhead`, and the
  load-time :func:`quantized_load`, must sit under a lexical guard
  naming the relaxed tier at every call site outside this module).
- ``serving.parity=relaxed``: matmul weights are int8 with per-group
  scales. Values are allclose, never bitwise; acceptance is the
  logits/output A-B guard (:func:`run_weight_ab` — same machinery
  family as ``lowp/guard.py``'s ``run_loss_ab``: same inputs through
  both planes, bounded divergence, verdict recorded as a plain dict).

One quantizer defines every int8 surface (the kvstore ``codec.py``
precedent): the host-side per-group codec here IS
``parallel/lowp/quant.py``'s public ``quantize_array`` /
``dequantize_array`` pair — weight groups ride the contraction
dimension so the scales dequantize next to the MXU.

Layout: a weight that contracts over its dimension ``D`` (``x @ w``
with ``w [D, N]``) is stored transposed-and-grouped as
``{"q": int8 [N, G, gs], "s": f32 [N, G]}`` with ``G * gs == D`` —
one scale per (output column, input group), the GPTQ/AWQ-style
weight-only grouping. Embedding rows ([V, D], a gather not a matmul)
group along D without the transpose so a row dequantizes in one fused
multiply. Norm weights, biases and ``pos_embed`` never quantize (they
are bytes-irrelevant and value-critical).

Quantize-at-load streams per shard: the loader's concurrent shard
fetch feeds :func:`make_load_quantizer` one assembled leaf at a time
(``load_checkpoint(leaf_transform=...)``), the f32 buffer is dropped
the moment its int8 twin exists, so peak host RAM during a quantized
load stays bounded by the LARGEST leaf, never the full f32 model —
``report["peak_f32_bytes"]`` records the measured bound.

Conf keys (read by :func:`weightplane_from_conf`):

  serving.parity                    bitwise | relaxed  (default bitwise)
  serving.weights.codec             int8               (the wired codec)
  serving.weights.group             default 64   (elements per scale
                                    group along the contraction dim;
                                    must divide every contraction dim)
  serving.weights.embed             default false (quantize embedding)
  serving.weights.head              default false (quantize LM head;
                                    tied embeddings quantize as one)
  serving.weights.guard.min-agree   default 0.95 (greedy argmax
                                    agreement floor of the A-B guard)
  serving.weights.guard.rel-tol     default 0.25 (max |logit err| /
                                    std(reference logits))
  serving.kv.hbm.bytes              default 0    (engine HBM budget:
                                    KV pool + lanes sized against the
                                    measured resident weight bytes)
"""

from __future__ import annotations

import dataclasses
import re
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hadoop_tpu.models.config import ModelConfig
from hadoop_tpu.parallel.lowp.quant import dequantize_array, quantize_array

WEIGHTS_PARITY_KEY = "serving.parity"
TIERS = ("bitwise", "relaxed")

# the per-layer matmul weights: every one contracts x over its -2 axis
# (x @ w), so all of them store transposed-and-grouped. On a MoE config
# the same three FFN names carry the layer-stacked EXPERT stacks
# ([L, E, D, F] / [L, E, F, D]): quantize_weight groups the trailing
# contraction dim under any leading axes, so ONE policy table covers
# dense and sparse — per-expert int8 payloads + per-(expert, column)
# scale groups. The ROUTER stays f32 on purpose: it is value-critical
# (a flipped top-k re-routes whole tokens, not a bounded perturbation)
# and bytes-irrelevant next to the expert stacks — the norms precedent.
LAYER_MATMULS = frozenset({
    "wq", "wk", "wv", "wo",
    "w_gate", "w_up", "w_down",          # swiglu mlp / MoE expert stacks
    "w_in", "w_out",                     # gelu mlp (biases stay f32)
})

# the expert FFN stacks of a MoE layer — the subset of LAYER_MATMULS
# whose resident bytes the engine ledgers under the dedicated
# ``moe_experts`` HBM component and shards along the expert dim
EXPERT_STACKS = frozenset({"w_gate", "w_up", "w_down"})

_QKEYS = frozenset({"q", "s"})
_KEYSTR = re.compile(r"\['([^']+)'\]")


@dataclasses.dataclass(frozen=True)
class WeightPlaneConfig:
    """Static weight-plane policy, fixed at load time.

    ``tier == "bitwise"`` disables everything: the loader never calls
    the quantizer and the engine's compiled step contains zero
    weightplane code. The per-tensor flags describe what the relaxed
    tier quantizes, not whether the tier is on.
    """
    tier: str = "bitwise"
    codec: str = "int8"
    group: int = 64                  # elements per scale group (contraction dim)
    quant_embed: bool = False
    quant_head: bool = False
    guard_min_agree: float = 0.95
    guard_rel_tol: float = 0.25

    def __post_init__(self):
        if self.tier not in TIERS:
            raise ValueError(f"{WEIGHTS_PARITY_KEY} must be one of "
                             f"{TIERS}, got {self.tier!r}")
        if self.codec != "int8":
            raise ValueError(f"serving.weights.codec: only 'int8' is "
                             f"wired, got {self.codec!r}")
        if self.group < 1:
            raise ValueError(f"serving.weights.group must be >= 1, "
                             f"got {self.group}")

    @property
    def relaxed(self) -> bool:
        return self.tier == "relaxed"


BITWISE_WEIGHTS = WeightPlaneConfig()


def weightplane_from_conf(conf) -> WeightPlaneConfig:
    """Build a WeightPlaneConfig from a Configuration (defaults above)."""
    if conf is None:
        return BITWISE_WEIGHTS
    return WeightPlaneConfig(
        tier=conf.get(WEIGHTS_PARITY_KEY, "bitwise"),
        codec=conf.get("serving.weights.codec", "int8"),
        group=conf.get_int("serving.weights.group", 64),
        quant_embed=conf.get_bool("serving.weights.embed", False),
        quant_head=conf.get_bool("serving.weights.head", False),
        guard_min_agree=conf.get_float("serving.weights.guard.min-agree",
                                       0.95),
        guard_rel_tol=conf.get_float("serving.weights.guard.rel-tol",
                                     0.25))


# ------------------------------------------------------- the weight codec

def quantize_weight(arr, group: int, *, transpose: bool) -> Dict[str, Any]:
    """One weight leaf -> ``{"q": int8 [..., G, gs], "s": f32 [..., G]}``.

    ``transpose=True`` swaps the last two axes first so the group axis
    is the CONTRACTION dimension of ``x @ w`` (matmul weights store
    ``[.., N, D]``-major); embedding-style rows ([V, D], contraction
    already last) pass ``transpose=False``. The quantizer is
    ``lowp.quant.quantize_array`` — the one public per-group int8
    codec — applied at full +/-127 range (resident weights accumulate
    nothing in-wire, so no headroom is carved out).

    Loud failure on a group/shape mismatch: a contraction dim the
    group does not divide raises instead of silently regrouping
    across rows, which would dequantize against the wrong scales.
    """
    a = np.asarray(arr)
    if transpose:
        a = np.swapaxes(a, -1, -2)
    gs = int(group)
    d = a.shape[-1] if a.ndim else 0
    if a.ndim < 1 or d % gs != 0:
        raise ValueError(
            f"serving.weights.group={gs} does not divide the "
            f"contraction dim {d} of a weight with shape "
            f"{tuple(np.shape(arr))} — pick a group that divides every "
            f"quantized contraction dimension")
    q, s = quantize_array(np.ascontiguousarray(a, np.float32), codec="int8",
                          group=gs)
    g = d // gs
    lead = a.shape[:-1]
    return {"q": q.reshape(*lead, g, gs),
            "s": s.reshape(*lead, g)}


def dequantize_weight(qw: Dict[str, Any], *, transpose: bool,
                      dtype=np.float32) -> np.ndarray:
    """Inverse of :func:`quantize_weight` (values are the int8
    reconstruction, allclose to — never bitwise — the original)."""
    q = np.asarray(qw["q"])
    s = np.asarray(qw["s"])
    *lead, g, gs = q.shape
    if tuple(s.shape) != tuple(lead) + (g,):
        raise ValueError(f"weight scale plane {s.shape} does not match "
                         f"quantized payload {q.shape} (expected "
                         f"{tuple(lead) + (g,)})")
    out = dequantize_array(q.reshape(-1, gs), s.reshape(-1),
                           tuple(lead) + (g * gs,), dtype)
    if transpose:
        out = np.swapaxes(out, -1, -2)
    return np.ascontiguousarray(out)


def is_qtensor(leaf) -> bool:  # lint: static-fn — pytree structure
    """Is this params-tree node a quantized weight? Structure, not
    values: static at trace time (the fused decode jits branch on it
    to pick the weight route per family)."""
    return isinstance(leaf, dict) and set(leaf.keys()) == _QKEYS


def is_quantized_tree(params) -> bool:
    """Does any leaf of ``params`` carry the quantized layout?"""
    def walk(node) -> bool:
        if is_qtensor(node):
            return True
        if isinstance(node, dict):
            return any(walk(v) for v in node.values())
        return False
    return walk(params)


def resident_weight_bytes(params) -> int:
    """MEASURED resident bytes of a params tree — int8 payloads count
    one byte per element, scale planes four; this is the number the
    engine budgets its KV pool and decode lanes against."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        total += int(np.prod(np.shape(leaf))) * \
            jnp.dtype(leaf.dtype).itemsize
    return total


def describe_tree(params) -> Dict[str, Any]:
    """Weight-plane summary for /v1/health, the registry record and
    bench JSON: resident dtype, measured bytes, quantized-leaf count."""
    leaves = jax.tree_util.tree_leaves(params)
    n_int8 = sum(1 for x in leaves if jnp.dtype(x.dtype) == jnp.int8)
    quantized = is_quantized_tree(params)
    if quantized:
        dtype = "int8"
    else:
        dtype = str(np.dtype(leaves[0].dtype)) if leaves else "none"
    return {"dtype": dtype, "quantized": quantized,
            "weight_bytes": resident_weight_bytes(params),
            "int8_leaves": n_int8, "leaves": len(leaves)}


# --------------------------------------------------------- policy + apply

def _resolve_flags(cfg: ModelConfig,
                   wp: WeightPlaneConfig) -> Tuple[bool, bool]:
    """(quant_embed, quant_head) with the tied-embedding coupling
    resolved: a tied model has ONE matrix serving both surfaces, so the
    two flags must agree — quantizing "just the head" of a tied model
    would quantize the gather too, silently."""
    if cfg.tie_embeddings and wp.quant_head != wp.quant_embed:
        raise ValueError(
            "serving.weights.embed and serving.weights.head must match "
            "on a tied-embeddings model (one matrix serves both)")
    return wp.quant_embed, wp.quant_head


def _quantize_one(key: str, arr, *, in_layers: bool, cfg: ModelConfig,
                  wp: WeightPlaneConfig, report: Dict[str, Any]):
    """Apply the per-tensor policy to one leaf; returns the (possibly
    quantized) leaf and updates the running load report."""
    q_embed, q_head = report["_flags"]
    t0 = time.monotonic()
    if in_layers and key in LAYER_MATMULS:
        out = quantize_weight(arr, wp.group, transpose=True)
    elif key == "embed" and q_embed:
        out = quantize_weight(arr, wp.group, transpose=False)
    elif key == "lm_head" and q_head:
        out = quantize_weight(arr, wp.group, transpose=True)
    else:
        return arr
    report["quantize_seconds"] += time.monotonic() - t0
    report["leaves_quantized"] += 1
    return out


def _fresh_report(cfg: ModelConfig,
                  wp: WeightPlaneConfig) -> Dict[str, Any]:
    if not wp.relaxed:
        # the module contract, enforced here and not by call-site
        # discipline: the bitwise tier NEVER quantizes — a bitwise
        # config reaching the quantizer is a wiring bug upstream
        raise ValueError(
            f"{WEIGHTS_PARITY_KEY}={wp.tier!r} must be 'relaxed' to "
            f"quantize resident weights (the bitwise tier loads the "
            f"checkpoint's own dtypes untouched)")
    return {"tier": wp.tier, "codec": wp.codec, "group": wp.group,
            "quant_embed": wp.quant_embed, "quant_head": wp.quant_head,
            "leaves_quantized": 0, "quantize_seconds": 0.0,
            "total_f32_bytes": 0, "peak_f32_bytes": 0,
            "moe_experts": cfg.n_experts if cfg.is_moe else 0,
            "_flags": _resolve_flags(cfg, wp)}


def _finish_report(report: Dict[str, Any], params) -> Dict[str, Any]:
    report.pop("_flags", None)
    report["quantize_seconds"] = round(report["quantize_seconds"], 3)
    report["weight_bytes"] = resident_weight_bytes(params)
    if report.get("moe_experts"):
        report["expert_bytes"] = _expert_stack_bytes(params)
    return report


def _expert_stack_bytes(params) -> int:
    layers = params.get("layers", {}) if isinstance(params, dict) else {}
    return sum(resident_weight_bytes(layers[k])
               for k in EXPERT_STACKS if k in layers)


def expert_weight_bytes(params, cfg: ModelConfig) -> int:
    """MEASURED resident bytes of the expert FFN stacks (0 on a dense
    config) — what the engine ledgers under the ``moe_experts`` HBM
    component, beside (not inside) the dense ``weights`` remainder."""
    if not cfg.is_moe:
        return 0
    return _expert_stack_bytes(params)


def expert_shard_count(n_experts: int, requested: int,
                       n_devices: int) -> int:
    """Resolve ``serving.moe.shards``: how many chips the expert dim
    splits across. ``requested=0`` (auto) picks the largest shard count
    the replica's devices allow that divides the expert count; an
    explicit request that does not divide the experts or exceeds the
    devices is a loud error, never a silent round-down."""
    if n_experts <= 0:
        return 1
    if requested:
        if requested > n_devices:
            raise ValueError(
                f"serving.moe.shards={requested} exceeds the replica's "
                f"{n_devices} local device(s)")
        if n_experts % requested:
            raise ValueError(
                f"serving.moe.shards={requested} does not divide "
                f"n_experts={n_experts} — expert shards must be equal")
        return int(requested)
    for d in range(min(n_devices, n_experts), 0, -1):
        if n_experts % d == 0:
            return d
    return 1


def quantize_params(params, cfg: ModelConfig,
                    wp: WeightPlaneConfig) -> Tuple[dict, Dict[str, Any]]:
    """In-memory policy application: a loaded f32 params tree -> its
    weight-plane form + the load report (the bench/test twin of the
    streaming :func:`quantized_load` — both run the same per-leaf
    transform, so the two paths can never disagree on policy)."""
    report = _fresh_report(cfg, wp)
    out: Dict[str, Any] = {}
    for key, val in params.items():
        if key == "layers":
            out["layers"] = {
                lk: _quantize_one(lk, lv, in_layers=True, cfg=cfg,
                                  wp=wp, report=report)
                for lk, lv in val.items()}
        else:
            out[key] = _quantize_one(key, val, in_layers=False, cfg=cfg,
                                     wp=wp, report=report)
    return out, _finish_report(report, out)


def _leaf_key(name: str) -> Tuple[str, bool]:
    """(trailing key, under-"layers") of a checkpoint keystr like
    ``['params']['layers']['wq']``."""
    keys = _KEYSTR.findall(name)
    if not keys:
        return name, False
    return keys[-1], "layers" in keys[:-1]


def make_load_quantizer(cfg: ModelConfig, wp: WeightPlaneConfig
                        ) -> Tuple[Callable, Dict[str, Any]]:
    """The streaming form of :func:`quantize_params`: a
    ``leaf_transform`` for ``load_checkpoint`` that quantizes each
    assembled leaf the moment its shards arrive, so the full f32 model
    is never resident on the host. The shared ``report`` dict fills in
    as leaves stream through; ``peak_f32_bytes`` tracks the measured
    high-water mark of live float bytes (the assembled leaf plus its
    in-flight shard payloads — ~2x the largest leaf, a hard bound far
    below the full model)."""
    report = _fresh_report(cfg, wp)

    def transform(name: str, arr: np.ndarray):
        key, in_layers = _leaf_key(name)
        f32 = int(arr.nbytes)
        report["total_f32_bytes"] += f32
        # the raw shard bytes of THIS leaf are still referenced by the
        # caller while we transform — count both sides of the copy
        report["peak_f32_bytes"] = max(report["peak_f32_bytes"], 2 * f32)
        return _quantize_one(key, arr, in_layers=in_layers, cfg=cfg,
                             wp=wp, report=report)

    return transform, report


def quantized_load(fs, base_dir: str, cfg: ModelConfig,
                   wp: WeightPlaneConfig, *, step: Optional[int] = None,
                   io_workers: int = 4):
    """Quantize-at-load from the DFS checkpoint shards: the loader's
    concurrent shard fetch feeds the quantizer one leaf at a time (see
    ``parallel.checkpoint.load_checkpoint``'s ``leaf_transform``
    streaming mode). Returns ``(params, step, report)``; ``report``
    carries ``quantize_seconds``, the measured ``weight_bytes`` and the
    streaming peak. RELAXED-TIER ENTRY POINT: call sites outside this
    module must sit under a lexical relaxed-parity guard."""
    from hadoop_tpu.serving.loader import load_serving_params
    transform, report = make_load_quantizer(cfg, wp)
    t0 = time.monotonic()
    params, step = load_serving_params(fs, base_dir, cfg, step=step,
                                       io_workers=io_workers,
                                       leaf_transform=transform)
    _finish_report(report, params)
    report["load_seconds"] = round(time.monotonic() - t0, 3)
    return params, step, report


def dequantize_params(qparams, cfg: ModelConfig) -> dict:
    """The f32 reconstruction of a weight-plane tree (guard/test use:
    ``forward(dequantize_params(q))`` computes exactly the floats the
    engine's in-graph dequantizing matmuls contract against)."""
    dt = cfg.jax_dtype

    def walk(node, key: str):
        if is_qtensor(node):
            # every quantized leaf stores transposed except the
            # embedding matrix (a row gather, contraction already last)
            return jnp.asarray(dequantize_weight(
                node, transpose=key != "embed", dtype=dt))
        if isinstance(node, dict):
            return {k: walk(v, k) for k, v in node.items()}
        return node

    return walk(qparams, "")


# ------------------------------------------------- in-graph entry points
# (RELAXED-TIER ENTRY POINTS: tpulint's parity/relaxed-gated checker
# requires every call site outside this module to sit under a lexical
# guard naming the relaxed tier, so serving.parity=bitwise provably
# compiles zero quantized code.)

def qdot(x, qw):
    """Weight-only int8 matmul: ``x [..., D] @ w`` against a quantized
    weight ``{"q": int8 [N, G, gs], "s": f32 [N, G]}``. The dequantize
    (one multiply per int8 element) happens in-register next to the
    contraction — XLA fuses the convert+scale into the matmul operand
    read, so HBM only ever moves the int8 payload + the scale plane."""
    q, s = qw["q"], qw["s"]
    n = q.shape[0]
    w = (q.astype(jnp.float32) * s[..., None]).reshape(n, -1)
    return jnp.einsum("...d,nd->...n", x, w.astype(x.dtype))


def qrows(qe, tokens, dtype):
    """Quantized embedding gather: int8 rows + their scale groups are
    gathered and dequantized per token (``qe`` = {"q": [V, G, gs],
    "s": [V, G]})."""
    q = qe["q"][tokens]
    s = qe["s"][tokens]
    rows = q.astype(jnp.float32) * s[..., None]
    return rows.reshape(*rows.shape[:-2], -1).astype(dtype)


def qslice(qw, l):
    """Layer ``l``'s slice of a layer-stacked quantized weight — the
    quantized twin of ``layers["wq"][l]``: both planes slice their
    leading ``n_layers`` dim together so the scales can never pair
    with another layer's payload. In-graph (``l`` may be a traced
    index, as in the longctx decoder's per-layer dispatches)."""
    return {"q": qw["q"][l], "s": qw["s"][l]}


def qhead(params, h, cfg: ModelConfig):
    """Quantized LM head: ``h [..., D] @ head [D, V]`` where the head
    is the (transposed-stored) quantized ``lm_head`` — or the quantized
    ``embed`` matrix when embeddings are tied (one tensor, both
    surfaces, same int8 bytes). Delegates to :func:`qdot` so the head
    contraction can never drift from the layer matmuls'."""
    return qdot(h, params["embed"] if cfg.tie_embeddings
                else params["lm_head"])


def qedot(x, qw):
    """Expert-batched int8 matmul: ``x [E, C, D]`` against a quantized
    expert stack ``{"q": int8 [E, N, G, gs], "s": f32 [E, N, G]}`` —
    the MoE twin of :func:`qdot`, one contraction per expert with that
    expert's own scale plane (scales can never cross experts). Covers
    both orientations of the stacks: w_gate/w_up store [E, F, D]
    (contract D), w_down stores [E, D, F] (contract F) — the stored
    trailing dim is always the contraction dim, exactly as for qdot."""
    q, s = qw["q"], qw["s"]
    e, n = q.shape[0], q.shape[1]
    w = (q.astype(jnp.float32) * s[..., None]).reshape(e, n, -1)
    return jnp.einsum("ecd,end->ecn", x, w.astype(x.dtype))


# -------------------------------------------------- logits/output guard

def weight_ab_report(logits_ref, logits_q, *, min_agree: float = 0.95,
                     rel_tol: float = 0.25) -> Dict[str, Any]:
    """Accept/reject the quantized weight plane from two teacher-forced
    logit tensors over identical inputs (the serving twin of
    ``lowp.guard.loss_curve_report``: same inputs through both planes,
    bounded divergence, a plain-dict verdict the bench records).

    Accepted iff (a) both tensors are finite, (b) the per-position
    greedy argmax agrees on at least ``min_agree`` of positions
    (teacher-forced, so one flip never compounds into the next
    position), and (c) the max absolute logit error stays within
    ``rel_tol`` of the reference logit spread (std) — quantization
    noise must stay a perturbation, never a re-ranking of the whole
    distribution."""
    a = np.asarray(logits_ref, np.float64)
    b = np.asarray(logits_q, np.float64)
    report: Dict[str, Any] = {"min_agree": min_agree, "rel_tol": rel_tol,
                              "positions": int(np.prod(a.shape[:-1]))}
    if a.shape != b.shape:
        report.update(accepted=False,
                      reason=f"logits shape {b.shape} != {a.shape}")
        return report
    if not (np.isfinite(a).all() and np.isfinite(b).all()):
        report.update(accepted=False, reason="non-finite logits")
        return report
    agree = float(np.mean(np.argmax(a, -1) == np.argmax(b, -1)))
    spread = float(max(a.std(), 1e-6))
    max_abs = float(np.abs(a - b).max())
    mean_abs = float(np.abs(a - b).mean())
    report.update(greedy_agree=round(agree, 4),
                  max_abs=round(max_abs, 6),
                  mean_abs=round(mean_abs, 6),
                  ref_std=round(spread, 6),
                  max_rel=round(max_abs / spread, 6))
    if agree < min_agree:
        report.update(accepted=False,
                      reason=f"greedy argmax agreement {agree:.4f} < "
                             f"{min_agree}")
        return report
    if max_abs / spread > rel_tol:
        report.update(accepted=False,
                      reason=f"max |logit err| {max_abs:.4f} is "
                             f"{max_abs / spread:.3f}x the reference "
                             f"spread (> {rel_tol})")
        return report
    report["accepted"] = True
    return report


def run_weight_ab(cfg: ModelConfig, params, qparams, *, batch: int = 8,
                  seq: int = 48, seed: int = 0,
                  min_agree: Optional[float] = None,
                  rel_tol: Optional[float] = None,
                  wp: Optional[WeightPlaneConfig] = None
                  ) -> Dict[str, Any]:
    """The logits/output A-B: teacher-forced forward of the SAME random
    token batch through the f32 params and the dequantized weight-plane
    params (numerically what the engine's in-graph qdot contracts
    against), judged by :func:`weight_ab_report`. Returns the report
    dict — never raises on rejection, so benches record a failing rung
    as data (the ``run_loss_ab`` convention)."""
    from hadoop_tpu.models.decoder import forward
    wp = wp or BITWISE_WEIGHTS
    if min_agree is None:
        min_agree = wp.guard_min_agree
    if rel_tol is None:
        rel_tol = wp.guard_rel_tol
    seq = min(seq, cfg.max_seq)
    tokens = jax.random.randint(jax.random.PRNGKey(seed), (batch, seq),
                                0, cfg.vocab_size, dtype=jnp.int32)
    logits_ref = forward(params, tokens, cfg)
    logits_q = forward(dequantize_params(qparams, cfg), tokens, cfg)
    report = weight_ab_report(np.asarray(logits_ref, np.float32),
                              np.asarray(logits_q, np.float32),
                              min_agree=min_agree, rel_tol=rel_tol)
    report["batch"], report["seq"] = batch, seq
    return report


__all__ = [
    "WEIGHTS_PARITY_KEY", "TIERS", "LAYER_MATMULS", "EXPERT_STACKS",
    "WeightPlaneConfig", "BITWISE_WEIGHTS", "weightplane_from_conf",
    "quantize_weight", "dequantize_weight", "is_qtensor",
    "is_quantized_tree", "resident_weight_bytes", "describe_tree",
    "quantize_params", "make_load_quantizer", "quantized_load",
    "dequantize_params", "qdot", "qrows", "qhead", "qslice", "qedot",
    "expert_weight_bytes", "expert_shard_count",
    "weight_ab_report", "run_weight_ab",
]
