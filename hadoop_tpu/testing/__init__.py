from hadoop_tpu.testing.minicluster import MiniDFSCluster, MiniYARNCluster

__all__ = ["MiniDFSCluster", "MiniYARNCluster"]
