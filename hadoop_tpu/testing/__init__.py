from hadoop_tpu.testing.minicluster import MiniDFSCluster

__all__ = ["MiniDFSCluster"]
