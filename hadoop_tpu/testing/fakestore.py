"""In-process object-store server for connector tests.

The test double the object-store FileSystem connector
(fs/objectstore.py) runs against — the role S3AMockTest/S3 mock
endpoints play for the reference's hadoop-aws module (ref:
hadoop-tools/hadoop-aws/src/test/.../MockS3AFileSystem.java and the
ITest* suites pointed at a store endpoint). Speaks a minimal
path-style HTTP object API:

  PUT    /bucket/key                     store object (x-htpu-copy-source
                                         header → server-side copy)
  GET    /bucket/key                     fetch; honors Range: bytes=a-b
  HEAD   /bucket/key                     size/mtime or 404
  DELETE /bucket/key                     remove (idempotent)
  GET    /bucket?list&prefix=&delimiter=&max-keys=&token=
                                         paginated listing (JSON)
  POST   /bucket/key?uploads             initiate multipart → upload id
  PUT    /bucket/key?uploadId=U&part=N   upload one part
  POST   /bucket/key?uploadId=U&complete JSON [part numbers] → assemble
  DELETE /bucket/key?uploadId=U          abort multipart
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple
from urllib.parse import parse_qs, unquote, urlparse


class FakeObjectStore:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._objects: Dict[Tuple[str, str], Tuple[bytes, float]] = {}
        self._uploads: Dict[str, Dict] = {}
        self._next_upload = 0
        self._lock = threading.Lock()
        store = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _path(self):
                u = urlparse(self.path)
                parts = unquote(u.path).lstrip("/").split("/", 1)
                bucket = parts[0]
                key = parts[1] if len(parts) > 1 else ""
                return bucket, key, parse_qs(u.query,
                                             keep_blank_values=True)

            def _send(self, code: int, body: bytes = b"",
                      headers: Optional[Dict] = None):
                self.send_response(code)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def _body(self) -> bytes:
                n = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(n) if n else b""

            def do_PUT(self):
                bucket, key, q = self._path()
                if "uploadId" in q:
                    uid = q["uploadId"][0]
                    part = int(q["part"][0])
                    data = self._body()
                    with store._lock:
                        up = store._uploads.get(uid)
                        if up is None or up["bucket"] != bucket or \
                                up["key"] != key:
                            self._send(404)
                            return
                        up["parts"][part] = data
                    self._send(200)
                    return
                src = self.headers.get("x-htpu-copy-source")
                if src:
                    sb, sk = unquote(src).lstrip("/").split("/", 1)
                    with store._lock:
                        obj = store._objects.get((sb, sk))
                        if obj is None:
                            self._send(404)
                            return
                        store._objects[(bucket, key)] = (obj[0],
                                                         time.time())
                    self._send(200)
                    return
                data = self._body()
                with store._lock:
                    store._objects[(bucket, key)] = (data, time.time())
                self._send(200)

            def do_GET(self):
                bucket, key, q = self._path()
                if "list" in q:
                    self._list(bucket, q)
                    return
                with store._lock:
                    obj = store._objects.get((bucket, key))
                if obj is None:
                    self._send(404)
                    return
                data = obj[0]
                rng = self.headers.get("Range")
                if rng and rng.startswith("bytes="):
                    a, _, b = rng[6:].partition("-")
                    start = int(a)
                    end = int(b) if b else len(data) - 1
                    if start >= len(data):
                        self._send(416)
                        return
                    body = data[start:min(end + 1, len(data))]
                    self._send(206, body, {
                        "Content-Range":
                            f"bytes {start}-{start + len(body) - 1}"
                            f"/{len(data)}"})
                    return
                self._send(200, data)

            def do_HEAD(self):
                bucket, key, _ = self._path()
                with store._lock:
                    obj = store._objects.get((bucket, key))
                if obj is None:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(obj[0])))
                self.send_header("x-htpu-mtime", str(obj[1]))
                self.end_headers()

            def do_DELETE(self):
                bucket, key, q = self._path()
                with store._lock:
                    if "uploadId" in q:
                        store._uploads.pop(q["uploadId"][0], None)
                    else:
                        store._objects.pop((bucket, key), None)
                self._send(204)

            def do_POST(self):
                bucket, key, q = self._path()
                if "uploads" in q:
                    with store._lock:
                        store._next_upload += 1
                        uid = f"up-{store._next_upload}"
                        store._uploads[uid] = {"bucket": bucket,
                                               "key": key, "parts": {}}
                    self._send(200, json.dumps({"uploadId": uid}).encode())
                    return
                if "uploadId" in q and "complete" in q:
                    uid = q["uploadId"][0]
                    order = json.loads(self._body() or b"[]")
                    with store._lock:
                        up = store._uploads.pop(uid, None)
                        if up is None or up["bucket"] != bucket or \
                                up["key"] != key:
                            self._send(404)
                            return
                        try:
                            data = b"".join(up["parts"][n] for n in order)
                        except KeyError:
                            self._send(400, b"missing part")
                            return
                        store._objects[(bucket, key)] = (data, time.time())
                    self._send(200)
                    return
                self._send(400)

            def _list(self, bucket: str, q):
                prefix = q.get("prefix", [""])[0]
                delimiter = q.get("delimiter", [""])[0]
                max_keys = int(q.get("max-keys", ["1000"])[0])
                token = q.get("token", [""])[0]
                with store._lock:
                    keys = sorted(k for (b, k) in store._objects
                                  if b == bucket and k.startswith(prefix))
                objects, prefixes = [], []
                seen_prefixes = set()
                started = not token
                truncated_at = None
                for k in keys:
                    if not started:
                        if k > token:
                            started = True
                        else:
                            continue
                    if delimiter:
                        rest = k[len(prefix):]
                        cut = rest.find(delimiter)
                        if cut >= 0:
                            cp = prefix + rest[:cut + 1]
                            if cp not in seen_prefixes:
                                seen_prefixes.add(cp)
                                prefixes.append(cp)
                                if len(objects) + len(prefixes) \
                                        >= max_keys:
                                    truncated_at = k
                                    break
                            continue
                    with store._lock:
                        obj = store._objects.get((bucket, k))
                    if obj is None:
                        continue  # deleted since the key snapshot
                    objects.append({"key": k, "size": len(obj[0]),
                                    "mtime": obj[1]})
                    if len(objects) + len(prefixes) >= max_keys:
                        truncated_at = k
                        break
                body = {"objects": objects, "prefixes": prefixes}
                if truncated_at is not None and truncated_at != keys[-1]:
                    body["next_token"] = truncated_at
                self._send(200, json.dumps(body).encode())

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._httpd.server_port

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "FakeObjectStore":
        t = threading.Thread(target=self._httpd.serve_forever,
                             name=f"fakestore-{self.port}", daemon=True)
        t.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    # test inspection helpers
    def object_count(self) -> int:
        with self._lock:
            return len(self._objects)

    def pending_uploads(self) -> int:
        with self._lock:
            return len(self._uploads)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
