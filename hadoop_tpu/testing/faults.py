"""Flag-file fault injection shared by smokes and soak harnesses.

Extracted from ``benchmarks/flight_smoke.py``'s ad-hoc slow-file so the
flight legs, the elastic leg, and any future soak harness inject faults
through ONE api instead of each growing its own file conventions. The
transport is deliberately primitive — a flag file per (rank, kind) —
because it crosses process boundaries with no shared runtime: the
parent (or a test) writes flags with :class:`FaultInjector`, and the
victim calls :func:`apply_faults` once per step.

Kinds:

- ``kill``     — hard process death (``os._exit(KILL_EXIT)``), the
  lost-host case. No cleanup runs: exactly what a real kill looks like
  to the doctor (heartbeats stop, the roster row goes ``ok=False``).
- ``delay-ms`` — per-step latency injection; the flag file's content
  is the delay in milliseconds (the straggler case the step_wall
  median/MAD detector flags).
- ``hang``     — the step blocks until the flag is cleared (a stuck
  DFS read / collective). Bounded by ``hang_timeout_s`` so a harness
  bug can't wedge a worker forever.
"""

from __future__ import annotations

import os
import time
from typing import Optional

KINDS = ("kill", "delay-ms", "hang")

# mirrors the rc of SIGKILL'd processes (128+9) so the parent's
# post-mortem can't mistake an injected kill for a clean exit
KILL_EXIT = 137


class FaultInjector:
    """Parent-side writer of per-(rank, kind) flag files."""

    def __init__(self, base_dir: str):
        self.base_dir = base_dir
        os.makedirs(base_dir, exist_ok=True)

    def path(self, rank: int, kind: str) -> str:
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r} "
                             f"(one of {KINDS})")
        return os.path.join(self.base_dir, f"fault-{kind}-rank{rank}")

    def inject(self, rank: int, kind: str, value: str = "1") -> str:
        """Arm one fault; atomic (write + rename) so a checker never
        reads a half-written value."""
        p = self.path(rank, kind)
        with open(p + ".tmp", "w") as f:
            f.write(value)
        os.replace(p + ".tmp", p)
        return p

    def clear(self, rank: int, kind: str) -> None:
        try:
            os.remove(self.path(rank, kind))
        except FileNotFoundError:
            pass

    def clear_all(self) -> None:
        for name in os.listdir(self.base_dir):
            if name.startswith("fault-") and not name.endswith(".tmp"):
                try:
                    os.remove(os.path.join(self.base_dir, name))
                except FileNotFoundError:
                    pass

    def armed(self, rank: int, kind: str) -> bool:
        return os.path.exists(self.path(rank, kind))


def _read(path: str) -> Optional[str]:
    try:
        with open(path) as f:
            return f.read().strip()
    except (FileNotFoundError, OSError):
        return None


def apply_faults(base_dir: str, rank: int, *,
                 hang_timeout_s: float = 60.0,
                 poll_s: float = 0.05) -> None:
    """Worker-side checker: call once per step. Applies, in order,
    ``kill`` (never returns), ``delay-ms`` (sleeps), ``hang`` (blocks
    until cleared, bounded by ``hang_timeout_s``)."""
    inj = FaultInjector(base_dir)
    if inj.armed(rank, "kill"):
        # no cleanup, no atexit: a real lost host doesn't say goodbye
        os._exit(KILL_EXIT)
    delay = _read(inj.path(rank, "delay-ms"))
    if delay:
        try:
            time.sleep(float(delay) / 1e3)
        except ValueError:
            pass
    deadline = time.monotonic() + hang_timeout_s
    while inj.armed(rank, "hang") and time.monotonic() < deadline:
        time.sleep(poll_s)
