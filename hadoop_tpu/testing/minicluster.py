"""In-process minicluster — the signature test harness.

Parity with the reference's pattern (ref:
hadoop-hdfs/src/test/java/org/apache/hadoop/hdfs/MiniDFSCluster.java:157,
3,423 LoC): real daemons (NameNode + N DataNodes), real protocols, one
process, temp dirs, ephemeral ports, aggressive intervals — multi-node
behavior (replication, dead-node handling, re-replication, restart recovery)
exercised without mocking peers. Kill/restart APIs drive failure tests.
"""

from __future__ import annotations

import logging
import os
import shutil
import tempfile
import time
from typing import List, Optional

from hadoop_tpu.conf import Configuration
from hadoop_tpu.ipc.errors import RpcError
from hadoop_tpu.dfs.client.filesystem import DistributedFileSystem
from hadoop_tpu.dfs.datanode import DataNode
from hadoop_tpu.dfs.namenode import NameNode

log = logging.getLogger(__name__)


class MiniQJMHACluster:
    """HA minicluster: N JournalNodes + M NameNodes (QJM shared edits,
    automatic lease failover) + D DataNodes reporting to every NN.
    Ref: hadoop-hdfs/src/test/.../qjournal/MiniQJMHACluster.java:47 +
    MiniJournalCluster."""

    def __init__(self, num_journalnodes: int = 3, num_namenodes: int = 2,
                 num_datanodes: int = 3, num_observers: int = 0,
                 conf: Optional[Configuration] = None,
                 base_dir: Optional[str] = None):
        self.conf = fast_conf(conf)
        self.conf.set_if_unset("dfs.ha.tail-edits.period", "0.2s")
        self.conf.set_if_unset("dfs.ha.lease-duration", "1.5s")
        self.conf.set_if_unset("dfs.ha.health-check.interval", "0.3s")
        self.base_dir = base_dir or tempfile.mkdtemp(prefix="htpu-qjmha-")
        self._owns_dir = base_dir is None
        self.num_journalnodes = num_journalnodes
        self.num_namenodes = num_namenodes
        self.num_observers = num_observers
        self.num_datanodes = num_datanodes
        self.journalnodes: List = []
        self.namenodes: List[Optional[NameNode]] = []
        self.datanodes: List[Optional[DataNode]] = []
        self._fs_instances: List[DistributedFileSystem] = []
        self._nn_ports: dict = {}  # index → last known port (for restarts)

    def start(self) -> "MiniQJMHACluster":
        from hadoop_tpu.dfs.qjournal import JournalNode
        for i in range(self.num_journalnodes):
            jn_conf = Configuration(other=self.conf)
            jn = JournalNode(jn_conf, storage_dir=os.path.join(
                self.base_dir, f"journal{i}"))
            jn.init(jn_conf)
            jn.start()
            self.journalnodes.append(jn)
        jn_spec = ",".join(f"127.0.0.1:{j.port}" for j in self.journalnodes)
        self.conf.set("dfs.namenode.shared.edits.dir", jn_spec)
        total_nn = self.num_namenodes + self.num_observers
        for i in range(total_nn):
            self._start_namenode(i, observer=i >= self.num_namenodes)
        self.conf.set("dfs.namenode.rpc-address", ",".join(
            f"127.0.0.1:{nn.port}" for nn in self.namenodes))
        for i in range(self.num_datanodes):
            dn_conf = Configuration(other=self.conf)
            dn = DataNode(dn_conf,
                          data_dir=os.path.join(self.base_dir, f"data{i}"),
                          nn_addr=[("127.0.0.1", nn.port)
                                   for nn in self.namenodes])
            dn.init(dn_conf)
            dn.start()
            self.datanodes.append(dn)
        return self

    def _start_namenode(self, i: int, observer: bool = False) -> None:
        nn_conf = Configuration(other=self.conf)
        if observer:
            nn_conf.set("dfs.ha.initial-state", "observer")
        if i in self._nn_ports:
            nn_conf.set("dfs.namenode.rpc-port", self._nn_ports[i])
        nn = NameNode(nn_conf,
                      name_dir=os.path.join(self.base_dir, f"name{i}"),
                      nn_id=f"nn{i + 1}")
        nn.init(nn_conf)
        nn.start()
        self._nn_ports[i] = nn.port
        if i < len(self.namenodes):
            self.namenodes[i] = nn
        else:
            self.namenodes.append(nn)

    # --------------------------------------------------------------- access

    def active_index(self) -> Optional[int]:
        for i, nn in enumerate(self.namenodes):
            if nn is not None and nn.ha_state == "active":
                return i
        return None

    def wait_active(self, timeout: float = 30.0) -> int:
        """Wait for an elected active NN with all DNs live + safemode off."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            idx = self.active_index()
            if idx is not None:
                fsn = self.namenodes[idx].fsn
                live = len(fsn.bm.dn_manager.live_nodes())
                want = sum(1 for d in self.datanodes if d is not None)
                if not fsn.bm.safemode.is_on() and live >= want:
                    return idx
            time.sleep(0.05)
        raise TimeoutError(
            f"no active NN (states: "
            f"{[nn.ha_state if nn else None for nn in self.namenodes]})")

    def kill_active(self) -> int:
        """Stop the active NN (simulates a crash); returns its index."""
        idx = self.active_index()
        assert idx is not None, "no active to kill"
        nn = self.namenodes[idx]
        self.namenodes[idx] = None
        nn.stop()
        return idx

    def restart_namenode(self, i: int) -> None:
        self._start_namenode(i, observer=i >= self.num_namenodes)

    def get_filesystem(self, observer_reads: bool = False
                       ) -> DistributedFileSystem:
        conf = Configuration(other=self.conf)
        if observer_reads:
            conf.set("dfs.client.observer.reads.enabled", "true")
        fs = DistributedFileSystem(
            [("127.0.0.1", nn.port) for nn in self.namenodes
             if nn is not None], conf)
        self._fs_instances.append(fs)
        return fs

    def shutdown(self) -> None:
        for fs in self._fs_instances:
            try:
                fs.close()
            except (OSError, RpcError) as e:
                log.debug("fs close during shutdown failed: %s", e)
        for dn in self.datanodes:
            if dn is not None:
                dn.stop()
        for nn in self.namenodes:
            if nn is not None:
                nn.stop()
        for jn in self.journalnodes:
            jn.stop()
        if self._owns_dir:
            shutil.rmtree(self.base_dir, ignore_errors=True)

    def __enter__(self) -> "MiniQJMHACluster":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.shutdown()
        return False


def fast_conf(base: Optional[Configuration] = None) -> Configuration:
    """Aggressive intervals so failure paths run in test time."""
    conf = Configuration(other=base) if base else Configuration(
        load_defaults=False)
    conf.set_if_unset("dfs.heartbeat.interval", "0.1s")
    conf.set_if_unset("dfs.namenode.heartbeat.recheck-interval", "0.25s")
    conf.set_if_unset("dfs.namenode.redundancy.interval", "0.2s")
    conf.set_if_unset("dfs.namenode.reconstruction.pending.timeout", "4s")
    conf.set_if_unset("dfs.blockreport.interval", "5s")
    conf.set_if_unset("dfs.lease.soft-limit", "2s")
    conf.set_if_unset("dfs.lease.hard-limit", "5s")
    conf.set_if_unset("dfs.blocksize", "1m")
    conf.set_if_unset("dfs.replication", "3")
    conf.set_if_unset("ipc.client.connect.timeout", "5s")
    conf.set_if_unset("ipc.client.rpc-timeout", "30s")
    conf.set_if_unset("ipc.ping.interval", "0.5s")
    return conf


class MiniDFSCluster:
    def __init__(self, num_datanodes: int = 3,
                 conf: Optional[Configuration] = None,
                 base_dir: Optional[str] = None,
                 storage_types: Optional[List[str]] = None):
        self.conf = fast_conf(conf)
        # fd-passing short-circuit on by default, like the reference's
        # MiniDFSCluster with domain sockets. Path lives under /tmp, NOT
        # base_dir: AF_UNIX paths cap at ~107 bytes and pytest tmp
        # paths routinely blow that.
        self.conf.set_if_unset(
            "dfs.domain.socket.path",
            f"/tmp/htpu-ds-{os.getpid()}-_PORT.sock")
        self.base_dir = base_dir or tempfile.mkdtemp(prefix="htpu-minidfs-")
        self._owns_dir = base_dir is None
        self.num_datanodes = num_datanodes
        self.storage_types = storage_types  # per-DN media class (mover tests)
        self.namenode: Optional[NameNode] = None
        self.datanodes: List[Optional[DataNode]] = []
        self._fs_instances: List[DistributedFileSystem] = []

    # ------------------------------------------------------------- lifecycle

    def start(self) -> "MiniDFSCluster":
        self._start_namenode()
        for i in range(self.num_datanodes):
            self._start_datanode(i)
        self.wait_active()
        return self

    def _start_namenode(self) -> None:
        nn_conf = Configuration(other=self.conf)
        if self.namenode is not None:
            # Restart keeps the address (clients hold it), like a real daemon.
            nn_conf.set("dfs.namenode.rpc-port", self.namenode.port)
        self.namenode = NameNode(
            nn_conf, name_dir=os.path.join(self.base_dir, "name"))
        self.namenode.init(nn_conf)
        self.namenode.start()
        self.conf.set("dfs.namenode.rpc-address",
                      f"127.0.0.1:{self.namenode.port}")

    def _start_datanode(self, i: int) -> None:
        dn_conf = Configuration(other=self.conf)
        if self.storage_types:
            dn_conf.set("dfs.datanode.storage.type",
                        self.storage_types[i % len(self.storage_types)])
        dn = DataNode(dn_conf,
                      data_dir=os.path.join(self.base_dir, f"data{i}"),
                      nn_addr=("127.0.0.1", self.namenode.port))
        dn.init(dn_conf)
        dn.start()
        if i < len(self.datanodes):
            self.datanodes[i] = dn
        else:
            self.datanodes.append(dn)

    def wait_active(self, timeout: float = 30.0) -> None:
        """Safemode off + all DNs live."""
        deadline = time.monotonic() + timeout
        fsn = self.namenode.fsn
        while time.monotonic() < deadline:
            live = len(fsn.bm.dn_manager.live_nodes())
            want = sum(1 for d in self.datanodes if d is not None)
            if not fsn.bm.safemode.is_on() and live >= want:
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"cluster not active: safemode={fsn.bm.safemode.status()} "
            f"live={len(fsn.bm.dn_manager.live_nodes())}")

    def shutdown(self) -> None:
        for fs in self._fs_instances:
            try:
                fs.close()
            except (OSError, RpcError) as e:
                log.debug("fs close during shutdown failed: %s", e)
        for dn in self.datanodes:
            if dn is not None:
                dn.stop()
        if self.namenode is not None:
            self.namenode.stop()
        if self._owns_dir:
            shutil.rmtree(self.base_dir, ignore_errors=True)

    def __enter__(self) -> "MiniDFSCluster":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.shutdown()
        return False

    # --------------------------------------------------------------- access

    @property
    def nn_addr(self):
        return ("127.0.0.1", self.namenode.port)

    @property
    def default_fs(self) -> str:
        return f"htpu://127.0.0.1:{self.namenode.port}"

    def get_filesystem(self) -> DistributedFileSystem:
        fs = DistributedFileSystem([self.nn_addr],
                                   Configuration(other=self.conf))
        self._fs_instances.append(fs)
        return fs

    # ---------------------------------------------------------- fault tools

    def kill_datanode(self, i: int) -> DataNode:
        """Hard-stop a DN (no dereg — the NN must notice via heartbeats).
        Ref: MiniDFSCluster.stopDataNode."""
        dn = self.datanodes[i]
        dn.stop()
        self.datanodes[i] = None
        return dn

    def restart_datanode(self, i: int) -> None:
        self._start_datanode(i)

    def restart_namenode(self) -> None:
        """Stop + cold-start the NN from its on-disk state (image + edits).
        Ref: MiniDFSCluster.restartNameNode."""
        self.namenode.stop()
        # Let DN actors notice and re-register after the new NN is up.
        self._start_namenode()
        for dn in self.datanodes:
            if dn is not None:
                dn.nn_addr = ("127.0.0.1", self.namenode.port)
        self.conf.set("dfs.namenode.rpc-address",
                      f"127.0.0.1:{self.namenode.port}")

    def corrupt_replica(self, block_id: int, dn_index: int) -> bool:
        """Flip a byte in a stored replica (tests checksum paths).
        Ref: MiniDFSCluster.corruptReplica."""
        dn = self.datanodes[dn_index]
        if dn is None:
            return False
        rep = dn.store.get_replica(block_id)
        if rep is None:
            return False
        path = dn.store._path(rep.state, block_id)
        with open(path, "r+b") as f:
            f.seek(0)
            b = f.read(1)
            f.seek(0)
            f.write(bytes([b[0] ^ 0xFF]))
        return True


class MiniYARNCluster:
    """RM + N node agents in one process (real RPC, real subprocess
    containers). Ref: hadoop-yarn-server-tests MiniYARNCluster.java:127."""

    def __init__(self, num_nodes: int = 2,
                 conf: Optional[Configuration] = None,
                 base_dir: Optional[str] = None,
                 node_resource: Optional[dict] = None):
        self.conf = Configuration(other=conf) if conf else Configuration(
            load_defaults=False)
        self.conf.set_if_unset("yarn.nodemanager.heartbeat.interval", "0.1s")
        self.conf.set_if_unset("yarn.am.liveness-monitor.expiry-interval", "10s")
        self.conf.set_if_unset("yarn.nm.liveness-monitor.expiry-interval", "5s")
        self.conf.set_if_unset("ipc.client.connect.timeout", "5s")
        self.conf.set_if_unset("ipc.ping.interval", "0.5s")
        nr = node_resource or {}
        self.conf.set_if_unset("yarn.nodemanager.resource.memory-mb",
                               str(nr.get("memory_mb", 4096)))
        self.conf.set_if_unset("yarn.nodemanager.resource.cpu-vcores",
                               str(nr.get("vcores", 8)))
        self.conf.set_if_unset("yarn.nodemanager.resource.tpu-chips",
                               str(nr.get("tpu_chips", 0)))
        self.base_dir = base_dir or tempfile.mkdtemp(prefix="htpu-miniyarn-")
        self._owns_dir = base_dir is None
        self.num_nodes = num_nodes
        self.rm = None
        self.node_agents = []

    def start(self) -> "MiniYARNCluster":
        from hadoop_tpu.yarn.rm import ResourceManager
        from hadoop_tpu.yarn.nm import NodeAgent
        rm_conf = Configuration(other=self.conf)
        self.rm = ResourceManager(
            rm_conf, state_dir=os.path.join(self.base_dir, "rm-state"))
        self.rm.init(rm_conf)
        self.rm.start()
        for i in range(self.num_nodes):
            nm_conf = Configuration(other=self.conf)
            nm = NodeAgent(nm_conf, rm_addr=("127.0.0.1", self.rm.port),
                           work_root=os.path.join(self.base_dir, f"nm{i}"))
            nm.init(nm_conf)
            nm.start()
            self.node_agents.append(nm)
        self.wait_nodes()
        return self

    def wait_nodes(self, timeout: float = 20.0) -> None:
        deadline = time.monotonic() + timeout
        want = len(self.node_agents)
        while time.monotonic() < deadline:
            if len(self.rm.nodes) >= want:
                return
            time.sleep(0.05)
        raise TimeoutError(f"only {len(self.rm.nodes)}/{want} nodes registered")

    @property
    def rm_addr(self):
        return ("127.0.0.1", self.rm.port)

    def restart_rm(self) -> None:
        """Bounce the RM on the SAME port + state dir — the work-
        preserving restart scenario (NMs re-register with live
        containers, AMs re-register on their next allocate).
        Ref: TestWorkPreservingRMRestart's rm2-with-same-store pattern."""
        from hadoop_tpu.yarn.rm import ResourceManager
        old_port = self.rm.port
        self.rm.stop()
        rm_conf = Configuration(other=self.conf)
        rm_conf.set("yarn.resourcemanager.port", str(old_port))
        self.rm = ResourceManager(
            rm_conf, state_dir=os.path.join(self.base_dir, "rm-state"))
        self.rm.init(rm_conf)
        self.rm.start()

    def shutdown(self) -> None:
        for nm in self.node_agents:
            nm.stop()
        if self.rm is not None:
            self.rm.stop()
        if self._owns_dir:
            shutil.rmtree(self.base_dir, ignore_errors=True)

    def __enter__(self) -> "MiniYARNCluster":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.shutdown()
        return False


class MiniMRYarnCluster:
    """DFS + YARN + shuffle aux service — full MapReduce-on-YARN in one
    process. Ref: hadoop-mapreduce-client-jobclient MiniMRYarnCluster.java:63
    (whole-job integration tests like TestMRJobs run on it)."""

    def __init__(self, num_nodes: int = 3,
                 conf: Optional[Configuration] = None,
                 base_dir: Optional[str] = None,
                 node_resource: Optional[dict] = None):
        self.base_dir = base_dir or tempfile.mkdtemp(prefix="htpu-minimr-")
        self._owns_dir = base_dir is None
        self.conf = Configuration(other=conf) if conf else Configuration(
            load_defaults=False)
        self.conf.set_if_unset(
            "yarn.nodemanager.aux-services",
            "hadoop_tpu.mapreduce.shuffle:ShuffleService")
        self.dfs = MiniDFSCluster(
            num_datanodes=num_nodes, conf=self.conf,
            base_dir=os.path.join(self.base_dir, "dfs"))
        self.yarn = MiniYARNCluster(
            num_nodes=num_nodes, conf=self.conf,
            base_dir=os.path.join(self.base_dir, "yarn"),
            node_resource=node_resource or {"memory_mb": 8192, "vcores": 16})

    def start(self) -> "MiniMRYarnCluster":
        self.dfs.start()
        self.yarn.start()
        return self

    @property
    def default_fs(self) -> str:
        host, port = self.dfs.nn_addr
        return f"htpu://{host}:{port}"

    @property
    def rm_addr(self):
        return self.yarn.rm_addr

    def get_filesystem(self):
        return self.dfs.get_filesystem()

    def shutdown(self) -> None:
        self.yarn.shutdown()
        self.dfs.shutdown()
        if self._owns_dir:
            shutil.rmtree(self.base_dir, ignore_errors=True)

    def __enter__(self) -> "MiniMRYarnCluster":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.shutdown()
        return False
