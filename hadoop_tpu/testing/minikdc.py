"""MiniKdc — in-process credential authority for security tests.

Parity with the reference's test KDC (ref:
hadoop-common-project/hadoop-minikdc/src/main/java/org/apache/hadoop/
minikdc/MiniKdc.java:71 — an embedded Kerberos KDC that provisions
principals and writes keytabs for tests). There is no Kerberos here;
the SASL-analog (security/sasl.py) authenticates from shared secrets,
so the KDC-analog's job is exactly the part tests need: mint per-
principal secrets, write client "keytab" files, and expose the
server-side CredentialStore daemons verify against.
"""

from __future__ import annotations

import os
import secrets
from typing import Dict, Optional

from hadoop_tpu.io import pack
from hadoop_tpu.security.sasl import CredentialStore


class MiniKdc:
    def __init__(self, workdir: str):
        self.workdir = workdir
        os.makedirs(workdir, exist_ok=True)
        self._passwords: Dict[str, bytes] = {}
        self.credentials = CredentialStore()

    def create_principal(self, principal: str,
                         password: Optional[bytes] = None) -> bytes:
        """Provision a principal; returns its secret. Short name only
        (``nn/host@REALM`` collapses to ``nn`` like UGI's short names)."""
        user = principal.split("/")[0].split("@")[0]
        pw = password or secrets.token_bytes(24)
        self._passwords[user] = pw
        self.credentials.add_principal(user, pw)
        return pw

    def create_keytab(self, path: str, *principals: str) -> str:
        """Write a client keytab holding the named principals' secrets
        (all provisioned principals when none are named). Ref:
        MiniKdc.createPrincipal(File keytab, String... principals)."""
        users = [p.split("/")[0].split("@")[0] for p in principals] \
            or list(self._passwords)
        missing = [u for u in users if u not in self._passwords]
        if missing:
            raise KeyError(f"principals not provisioned: {missing}")
        with open(path, "wb") as f:
            f.write(pack({u: self._passwords[u] for u in users}))
        os.chmod(path, 0o600)
        return path

    def keytab_for(self, principal: str) -> str:
        """Provision (if needed) + write a one-principal keytab file."""
        user = principal.split("/")[0].split("@")[0]
        if user not in self._passwords:
            self.create_principal(user)
        path = os.path.join(self.workdir, f"{user}.keytab")
        return self.create_keytab(path, user)
