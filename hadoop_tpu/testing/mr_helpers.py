"""Importable user-code classes for MR integration tests.

Task containers import user classes by ``module:Class`` reference
(mapreduce.api.load_class), so test mappers/reducers must live on the
framework's import path, not inside a pytest module (whose module name
differs between pytest and plain imports)."""

from __future__ import annotations

import os
import time


class SlowGateReducer:
    """Summing reducer that blocks in setup until the gate file (conf
    ``test.reduce.gate``) disappears — lets tests hold a job mid-flight."""

    def setup(self, ctx):
        gate = ctx.conf.get("test.reduce.gate", "")
        while gate and os.path.exists(gate):
            time.sleep(0.1)

    def reduce(self, key, values, ctx):
        ctx.emit(key, str(sum(int(v) for v in values)).encode())

    def cleanup(self, ctx):
        pass
