"""Tools layer (L5) — utilities riding the core framework.

The counterpart of ``hadoop-tools`` (SURVEY §2.5): each tool is a small
CLI + library on top of the MR engine / FileSystem SPI:

- ``hadoop_tpu.tools.distcp``     distributed copy        (ref: hadoop-distcp)
- ``hadoop_tpu.tools.streaming``  external-process tasks  (ref: hadoop-streaming)
- ``hadoop_tpu.tools.sls``        scheduler load simulator (ref: hadoop-sls)
- ``hadoop_tpu.tools.archive``    har-style archives      (ref: hadoop-archives)
"""
