"""HAR-style archives: pack a tree into index + part files.

Parity with the reference archives tool (ref: hadoop-tools/
hadoop-archives/.../HadoopArchives.java + the HarFileSystem in
hadoop-common fs/HarFileSystem.java): many small files collapse into one
``_index`` (JSON: path → part/offset/length) plus concatenated ``part-*``
data files, relieving NameNode inode pressure; ``HarFileSystem`` serves
the archived namespace read-only through the ordinary FileSystem SPI
(open/list/status), resolving byte ranges out of the parts.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from hadoop_tpu.conf import Configuration
from hadoop_tpu.dfs.protocol.records import FileStatus
from hadoop_tpu.fs import FileSystem
from hadoop_tpu.fs.filesystem import Path

INDEX_NAME = "_index"
PART_SIZE = 512 * 1024 * 1024


def create_archive(fs: FileSystem, src_dir: str, archive_dir: str) -> Dict:
    """Pack src_dir into archive_dir (…/<name>.har by convention).
    Returns the index. Ref: HadoopArchives.archive (the MR-parallel copy
    phase collapses to a streaming client copy here — parts are written
    sequentially either way)."""
    index: Dict[str, Dict] = {}
    part_no = 0
    part_stream = None
    part_written = 0
    fs.mkdirs(archive_dir)

    def open_part():
        nonlocal part_stream, part_no, part_written
        part_stream = fs.create(f"{archive_dir}/part-{part_no}",
                                overwrite=True)
        part_written = 0

    open_part()
    root = src_dir.rstrip("/") or "/"

    def walk(path: str) -> None:
        nonlocal part_no, part_written, part_stream
        st = fs.get_file_status(path)
        rel = path[len(root):].lstrip("/") if path != root else ""
        key = "/" + rel if rel else "/"
        if st.is_dir:
            children = sorted(s.path for s in fs.list_status(path))
            index[key] = {"dir": True,
                          "children": [c.rsplit("/", 1)[-1]
                                       for c in children]}
            for child in children:
                walk(child)
            return
        if part_written >= PART_SIZE:
            part_stream.close()
            part_no += 1
            open_part()
        src = fs.open(path)
        length = 0
        try:
            while True:
                chunk = src.read(4 * 1024 * 1024)
                if not chunk:
                    break
                part_stream.write(chunk)
                length += len(chunk)
        finally:
            src.close()
        index[key] = {"dir": False, "part": f"part-{part_no}",
                      "off": part_written, "len": length}
        part_written += length

    walk(root)
    part_stream.close()
    fs.write_all(f"{archive_dir}/{INDEX_NAME}",
                 json.dumps(index).encode())
    return index


class HarFileSystem(FileSystem):
    """Read-only view over an archive. Ref: fs/HarFileSystem.java —
    open() resolves to a (part, offset, length) range read."""

    def __init__(self, underlying: FileSystem, archive_dir: str):
        self.fs = underlying
        self.dir = archive_dir.rstrip("/")
        self.index: Dict[str, Dict] = json.loads(
            underlying.read_all(f"{self.dir}/{INDEX_NAME}").decode())

    # --------------------------------------------------------------- reads

    def _entry(self, path: str) -> Dict:
        key = "/" + path.strip("/") if path.strip("/") else "/"
        entry = self.index.get(key)
        if entry is None:
            raise FileNotFoundError(f"{path} not in archive {self.dir}")
        return entry

    def get_file_status(self, path: str) -> FileStatus:
        e = self._entry(path)
        return FileStatus(path, is_dir=e["dir"],
                          length=0 if e["dir"] else e["len"])

    def exists(self, path: str) -> bool:
        try:
            self._entry(path)
            return True
        except FileNotFoundError:
            return False

    def list_status(self, path: str) -> List[FileStatus]:
        e = self._entry(path)
        if not e["dir"]:
            return [self.get_file_status(path)]
        base = "/" + path.strip("/") if path.strip("/") else ""
        return [self.get_file_status(f"{base}/{name}")
                for name in e["children"]]

    def open(self, path: str):
        e = self._entry(path)
        if e["dir"]:
            raise IsADirectoryError(path)
        return _HarRangeStream(self.fs, f"{self.dir}/{e['part']}",
                               e["off"], e["len"])

    def read_all(self, path: str) -> bytes:
        with self.open(path) as s:
            return s.read()

    # ------------------------------------------------- writes: read-only

    def create(self, path, overwrite=False, replication=None, **kw):
        raise PermissionError("har archives are immutable")

    def mkdirs(self, path):
        raise PermissionError("har archives are immutable")

    def delete(self, path, recursive=False):
        raise PermissionError("har archives are immutable")

    def rename(self, src, dst):
        raise PermissionError("har archives are immutable")

    def close(self) -> None:
        pass


class _HarRangeStream:
    """Seekable read view of one [off, off+len) range of a part file."""

    def __init__(self, fs: FileSystem, part_path: str, off: int,
                 length: int):
        self._stream = fs.open(part_path)
        self._base = off
        self._len = length
        self._pos = 0
        self._stream.seek(off)

    def read(self, n: int = -1) -> bytes:
        remaining = self._len - self._pos
        if remaining <= 0:
            return b""
        take = remaining if n is None or n < 0 else min(n, remaining)
        data = self._stream.read(take)
        self._pos += len(data)
        return data

    def seek(self, pos: int) -> None:
        self._pos = min(max(pos, 0), self._len)
        self._stream.seek(self._base + self._pos)

    def tell(self) -> int:
        return self._pos

    def close(self) -> None:
        self._stream.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="archive")
    ap.add_argument("src")
    ap.add_argument("dst", help="archive directory (e.g. /out/foo.har)")
    ap.add_argument("--fs", required=True, help="filesystem URI")
    args = ap.parse_args(argv)
    fs = FileSystem.get(args.fs, Configuration())
    try:
        index = create_archive(fs, Path(args.src).path, Path(args.dst).path)
        files = sum(1 for e in index.values() if not e["dir"])
        print(json.dumps({"archived_files": files,
                          "entries": len(index)}))
    finally:
        fs.close()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
