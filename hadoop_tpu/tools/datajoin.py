"""DataJoin — reduce-side join library for MR jobs.

Parity with the reference contrib (ref: hadoop-tools/hadoop-datajoin —
DataJoinMapperBase tags each record with its source, DataJoinReducerBase
groups by join key and crosses the per-source groups; TaggedMapOutput
carries the tag): records from N inputs meet in the reducer keyed by
the join key; the reducer emits the combination of every source's
rows for that key.

Usage: subclass ``JoinMapper`` per source (or configure
``datajoin.tag.<basename>`` mappings), run with ``JoinReducer``.
"""

from __future__ import annotations

import json
from typing import Iterator, List

from hadoop_tpu.mapreduce.api import Mapper, Reducer, TaskContext

TAG_SEP = b"\x01"


class JoinMapper(Mapper):
    """Tag + key extraction (ref: DataJoinMapperBase.map → generate
    TaggedMapOutput + generateGroupKey). Default record shape: TSV with
    the join key in column 0; the tag is the input file's basename
    (override ``tag_of``/``join_key`` for other shapes)."""

    def setup(self, ctx: TaskContext) -> None:
        self._tag = self.tag_of(ctx)

    def tag_of(self, ctx: TaskContext) -> bytes:
        path = getattr(getattr(ctx, "split", None), "path", "") or \
            ctx.conf.get("datajoin.tag", "src")
        base = path.rsplit("/", 1)[-1]
        # the documented per-file override FIRST (two directory inputs
        # commonly share part-file basenames — tagging by basename alone
        # would collapse both sources and the inner join would silently
        # emit nothing)
        mapped = ctx.conf.get(f"datajoin.tag.{base}")
        if mapped:
            return mapped.encode()
        parent = path.rsplit("/", 2)[-2] if path.count("/") >= 2 else ""
        if base.startswith("part-") and parent:
            return parent.encode()  # source dir distinguishes the inputs
        return base.encode()

    def join_key(self, key: bytes, value: bytes) -> bytes:
        return value.split(b"\t", 1)[0]

    def map(self, key: bytes, value: bytes, ctx: TaskContext) -> None:
        if not value.strip():
            return
        ctx.emit(self.join_key(key, value), self._tag + TAG_SEP + value)


class JoinReducer(Reducer):
    """Cross the per-tag groups (ref: DataJoinReducerBase.joinAndCollect
    — the default inner join over every source combination)."""

    def combine(self, key: bytes, rows: List[bytes]) -> bytes:
        """One joined output row; override for custom shapes."""
        return b"\t".join(rows)

    def reduce(self, key: bytes, values: Iterator[bytes],
               ctx: TaskContext) -> None:
        by_tag: dict = {}
        for v in values:
            tag, _, row = v.partition(TAG_SEP)
            by_tag.setdefault(tag, []).append(row)
        if len(by_tag) < 2:
            return  # inner join: key must appear in 2+ sources
        tags = sorted(by_tag)
        # cross product across sources (ref: joinAndCollect's recursion)
        combos: List[List[bytes]] = [[]]
        for t in tags:
            combos = [c + [row] for c in combos for row in by_tag[t]]
        for c in combos:
            ctx.emit(key, self.combine(key, c))
            ctx.incr_counter("DataJoin", "JOINED")
