"""DistCp — distributed copy as a map-only MR job.

Parity with the reference tool (ref: hadoop-tools/hadoop-distcp/.../
DistCp.java:60, CopyListing.java (the staged file list), mapred/
CopyMapper.java (per-file copy + verification), -update/-overwrite
semantics): the client walks the source tree into a copy listing staged
on the DFS, a map-only job partitions the listing across the cluster,
and each mapper streams files source→target with a CRC32C read-back
verification (the reference compares FileChecksums; our DFS exposes no
composite checksum RPC, so the mapper checksums both streams itself —
same guarantee, one extra read).

  distcp(rm_addr, default_fs, src_uri, dst_uri, update=True)

``src``/``dst`` may be full URIs on DIFFERENT filesystems (the classic
cluster→cluster migration).
"""

from __future__ import annotations

import json
import logging
from typing import Dict, List, Optional, Tuple

from hadoop_tpu.fs import FileSystem
from hadoop_tpu.fs.filesystem import Path
from hadoop_tpu.mapreduce.api import Mapper, TextInputFormat
from hadoop_tpu.util.crc import crc32c

log = logging.getLogger(__name__)

COPY_BUF = 4 * 1024 * 1024


def build_copy_listing(src_fs: FileSystem, src_root: str,
                       dst_root: str) -> Tuple[List[Dict], List[str]]:
    """(files, dirs): every file under src_root with its destination.
    Ref: SimpleCopyListing.doBuildListing."""
    files: List[Dict] = []
    dirs: List[str] = []
    root = src_root.rstrip("/") or "/"

    def walk(path: str) -> None:
        st = src_fs.get_file_status(path)
        rel = path[len(root):].lstrip("/") if path != root else ""
        dst = f"{dst_root.rstrip('/')}/{rel}" if rel else dst_root.rstrip("/")
        if st.is_dir:
            dirs.append(dst)
            for child in src_fs.list_status(path):
                walk(child.path)
        else:
            files.append({"src": path, "dst": dst, "size": st.length,
                          "mtime": st.mtime})

    walk(root)
    return files, dirs


def resolve_single_file_dst(dst_fs: FileSystem, src_root: str,
                            dst_root: str) -> str:
    """Reference semantics: copying ONE file onto an existing directory
    lands it INSIDE as dst/<name> — mapping the file onto the directory
    path itself would try create() on a directory and fail (or clobber
    it)."""
    dst = dst_root.rstrip("/") or "/"
    try:
        if dst_fs.get_file_status(dst).is_dir:
            name = src_root.rstrip("/").rsplit("/", 1)[-1]
            return f"{dst}/{name}"
    except (FileNotFoundError, IOError):
        pass
    return dst


class CopyMapper(Mapper):
    """One input record per file: value = JSON {src,dst,size,update}.
    Ref: mapred/CopyMapper.java map()."""

    def setup(self, ctx):
        self._fs_cache: Dict[str, FileSystem] = {}
        self.src_fs_uri = ctx.conf["distcp.src.fs"]
        self.dst_fs_uri = ctx.conf["distcp.dst.fs"]
        self.update = ctx.conf.get("distcp.update", "true") == "true"

    def _fs(self, uri: str) -> FileSystem:
        if uri not in self._fs_cache:
            from hadoop_tpu.conf import Configuration
            self._fs_cache[uri] = FileSystem.get(uri, Configuration())
        return self._fs_cache[uri]

    def map(self, key: bytes, value: bytes, ctx) -> None:
        entry = json.loads(value.decode())
        src_fs = self._fs(self.src_fs_uri)
        dst_fs = self._fs(self.dst_fs_uri)
        src, dst = entry["src"], entry["dst"]
        if self.update and dst_fs.exists(dst):
            st = dst_fs.get_file_status(dst)
            # size alone cannot prove freshness: a same-length in-place
            # change (fixed-width records) would be skipped forever and
            # the stale copy could become authoritative after a
            # fedbalance repoint (ref: -update compares FileChecksums;
            # mtime is the cheap witness both sides carry)
            if st.length == entry["size"] and \
                    st.mtime >= entry.get("mtime", float("inf")):
                ctx.incr_counter("DistCp", "SKIPPED")
                return
        parent = Path(dst).parent
        if parent:
            dst_fs.mkdirs(parent)
        src_crc = 0
        dst_crc = 0
        copied = 0
        in_s = src_fs.open(src)
        try:
            out_s = dst_fs.create(dst, overwrite=True)
            try:
                while True:
                    chunk = in_s.read(COPY_BUF)
                    if not chunk:
                        break
                    src_crc = crc32c(chunk, src_crc)
                    out_s.write(chunk)
                    copied += len(chunk)
            finally:
                out_s.close()
        finally:
            in_s.close()
        # read-back verification (ref: CopyMapper.compareCheckSums)
        back = dst_fs.open(dst)
        try:
            while True:
                chunk = back.read(COPY_BUF)
                if not chunk:
                    break
                dst_crc = crc32c(chunk, dst_crc)
        finally:
            back.close()
        if src_crc != dst_crc:
            raise IOError(f"distcp verification failed for {dst}: "
                          f"crc {src_crc:#x} != {dst_crc:#x}")
        ctx.incr_counter("DistCp", "COPIED")
        ctx.incr_counter("DistCp", "BYTES_COPIED", copied)


def distcp(rm_addr, default_fs: str, src_uri: str, dst_uri: str, *,
           update: bool = True, num_maps: int = 4,
           conf=None) -> Dict:
    """Run the copy; returns the job counters. Ref: DistCp.execute."""
    from hadoop_tpu.conf import Configuration
    from hadoop_tpu.mapreduce import Job
    from hadoop_tpu.mapreduce.api import class_ref
    conf = conf or Configuration()

    src_path = Path(src_uri)
    dst_path = Path(dst_uri)
    src_fs = FileSystem.get(src_uri, conf)
    dst_fs = FileSystem.get(dst_uri, conf)
    try:
        dst_root = dst_path.path
        if not src_fs.get_file_status(src_path.path).is_dir:
            dst_root = resolve_single_file_dst(dst_fs, src_path.path,
                                               dst_root)
        files, dirs = build_copy_listing(src_fs, src_path.path, dst_root)
        for d in dirs:
            dst_fs.mkdirs(d)
        if not files:
            return {}
        # stage the listing, one JSON per line, striped over num_maps
        # files so splits parallelize even when the listing is tiny
        work_fs = FileSystem.get(default_fs, conf)
        try:
            import uuid
            listing_dir = f"/tmp/distcp-{uuid.uuid4().hex[:8]}"
            work_fs.mkdirs(listing_dir)
            shards = max(1, min(num_maps, len(files)))
            for i in range(shards):
                body = "\n".join(
                    json.dumps(e) for e in files[i::shards]) + "\n"
                work_fs.write_all(f"{listing_dir}/listing-{i:04d}",
                                  body.encode())
            out_dir = f"{listing_dir}-out"
            job = (Job(rm_addr, default_fs, name="distcp")
                   .set_mapper(class_ref(CopyMapper))
                   .set_input_format(class_ref(TextInputFormat))
                   .add_input_path(listing_dir)
                   .set_output_path(out_dir)
                   .set_num_reduces(0)
                   .set("distcp.src.fs", src_uri)
                   .set("distcp.dst.fs", dst_uri)
                   .set("distcp.update", "true" if update else "false"))
            if not job.wait_for_completion():
                raise IOError(f"distcp job failed: {job.diagnostics[:3]}")
            counters = job.counters
            work_fs.delete(listing_dir, recursive=True)
            work_fs.delete(out_dir, recursive=True)
            return counters
        finally:
            work_fs.close()
    finally:
        src_fs.close()
        dst_fs.close()


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="distcp")
    ap.add_argument("src")
    ap.add_argument("dst")
    ap.add_argument("--rm", required=True, help="host:port of the RM")
    ap.add_argument("--fs", required=True, help="default filesystem URI")
    ap.add_argument("--overwrite", action="store_true")
    ap.add_argument("--maps", type=int, default=4)
    args = ap.parse_args(argv)
    host, _, port = args.rm.rpartition(":")
    counters = distcp((host, int(port)), args.fs, args.src, args.dst,
                      update=not args.overwrite, num_maps=args.maps)
    print(json.dumps(counters.get("DistCp", {})))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
