"""Dynamometer — NameNode performance under replayed audit workloads.

Parity with the reference tool (ref: hadoop-tools/hadoop-dynamometer —
its workload half replays production NN AUDIT LOGS against a real
NameNode (AuditReplayMapper.java) and reports per-op throughput; the
infra half that simulates a DN fleet maps to the in-process minicluster
here): parse the framework's own audit trail
(hadoop_tpu.audit lines: allowed/ugi/ip/cmd/src/dst) and re-issue the
namespace ops against a live NameNode through a real client, reporting
achieved ops/sec per command.

  python -m hadoop_tpu.tools.dynamometer --fs htpu://... audit.log
"""

from __future__ import annotations

import json
import time
from typing import Dict, Iterable, List, Optional

from hadoop_tpu.conf import Configuration
from hadoop_tpu.fs import FileSystem


def parse_audit_line(line: str) -> Optional[Dict[str, str]]:
    """One ``k=v\\t…`` audit line → dict (None for non-audit lines)."""
    fields = {}
    for part in line.strip().split("\t"):
        k, sep, v = part.partition("=")
        if not sep:
            return None
        fields[k] = v
    return fields if "cmd" in fields and "src" in fields else None


def replay(fs: FileSystem, lines: Iterable[str],
           remap_root: str = "/dyn") -> Dict:
    """Re-issue audited ops (paths re-rooted under ``remap_root`` so the
    replay can't disturb live data — the reference remaps the same way
    via auditreplay.command-parser). Returns per-op counts + ops/sec."""
    counts: Dict[str, int] = {}
    errors = 0
    t0 = time.perf_counter()
    total = 0
    for line in lines:
        ev = parse_audit_line(line)
        if ev is None:
            continue
        cmd = ev["cmd"]
        src = remap_root + ev["src"]
        try:
            if cmd == "mkdirs":
                fs.mkdirs(src)
            elif cmd == "create":
                parent = src.rsplit("/", 1)[0]
                if parent:
                    fs.mkdirs(parent)
                fs.write_all(src, b"")
            elif cmd == "open":
                if fs.exists(src):
                    fs.read_all(src)
            elif cmd == "listStatus":
                if fs.exists(src):
                    fs.list_status(src)
            elif cmd == "rename":
                dst = remap_root + ev.get("dst", "null")
                if fs.exists(src):
                    fs.rename(src, dst)
            elif cmd == "delete":
                fs.delete(src, recursive=True)
            else:
                continue
        except (IOError, OSError):
            errors += 1
            continue
        counts[cmd] = counts.get(cmd, 0) + 1
        total += 1
    dt = time.perf_counter() - t0
    return {
        "ops": total,
        "errors": errors,
        "per_op": counts,
        "wall_seconds": round(dt, 3),
        "ops_per_sec": round(total / dt, 1) if dt else 0.0,
    }


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="dynamometer")
    ap.add_argument("audit_log")
    ap.add_argument("--fs", required=True)
    ap.add_argument("--remap-root", default="/dyn")
    args = ap.parse_args(argv)
    fs = FileSystem.get(args.fs, Configuration())
    try:
        with open(args.audit_log) as f:
            report = replay(fs, f, args.remap_root)
    finally:
        fs.close()
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
