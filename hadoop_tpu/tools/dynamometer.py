"""Dynamometer — NameNode performance under replayed audit workloads.

Parity with the reference tool (ref: hadoop-tools/hadoop-dynamometer —
its workload half replays production NN AUDIT LOGS against a real
NameNode (AuditReplayMapper.java) and reports per-op throughput; the
infra half that simulates a DN fleet maps to the in-process minicluster
here): parse the framework's own audit trail
(hadoop_tpu.audit lines: allowed/ugi/ip/cmd/src/dst) and re-issue the
namespace ops against a live NameNode through a real client, reporting
achieved ops/sec per command.

  python -m hadoop_tpu.tools.dynamometer --fs htpu://... audit.log
"""

from __future__ import annotations

import json
import time
from typing import Dict, Iterable, List, Optional

from hadoop_tpu.conf import Configuration
from hadoop_tpu.fs import FileSystem


def parse_audit_line(line: str) -> Optional[Dict[str, str]]:
    """One ``k=v\\t…`` audit line → dict (None for non-audit lines)."""
    fields = {}
    for part in line.strip().split("\t"):
        k, sep, v = part.partition("=")
        if not sep:
            return None
        fields[k] = v
    return fields if "cmd" in fields and "src" in fields else None


def replay(fs: FileSystem, lines: Iterable[str],
           remap_root: str = "/dyn") -> Dict:
    """Re-issue audited ops (paths re-rooted under ``remap_root`` so the
    replay can't disturb live data — the reference remaps the same way
    via auditreplay.command-parser). Returns per-op counts + ops/sec."""
    counts: Dict[str, int] = {}
    errors = 0
    t0 = time.perf_counter()
    total = 0
    for line in lines:
        ev = parse_audit_line(line)
        if ev is None:
            continue
        cmd = ev["cmd"]
        src = remap_root + ev["src"]
        try:
            if cmd == "mkdirs":
                fs.mkdirs(src)
            elif cmd == "create":
                parent = src.rsplit("/", 1)[0]
                if parent:
                    fs.mkdirs(parent)
                fs.write_all(src, b"")
            elif cmd == "open":
                if fs.exists(src):
                    fs.read_all(src)
            elif cmd == "listStatus":
                if fs.exists(src):
                    fs.list_status(src)
            elif cmd == "rename":
                dst = remap_root + ev.get("dst", "null")
                if fs.exists(src):
                    fs.rename(src, dst)
            elif cmd == "delete":
                fs.delete(src, recursive=True)
            else:
                continue
        except (IOError, OSError):
            errors += 1
            continue
        counts[cmd] = counts.get(cmd, 0) + 1
        total += 1
    dt = time.perf_counter() - t0
    return {
        "ops": total,
        "errors": errors,
        "per_op": counts,
        "wall_seconds": round(dt, 3),
        "ops_per_sec": round(total / dt, 1) if dt else 0.0,
    }


OP_MIX = (  # realistic audit mix (ref: the workload profiles the
    # dynamometer docs use — reads dominate production NN load)
    ("open", 0.40), ("listStatus", 0.20), ("create", 0.20),
    ("delete", 0.10), ("mkdirs", 0.05), ("rename", 0.05),
)


def generate_trace(path: str, n_ops: int, workers: int = 8,
                   seed: int = 1234) -> str:
    """Write a synthetic audit log of ``n_ops`` lines (ref: the
    reference generates workloads when no production log is at hand).
    Paths are partitioned under /w<k>/ so a ``workers``-way replay can
    keep per-path op ordering within one worker."""
    import random
    rng = random.Random(seed)
    counters = [0] * workers
    ops = [op for op, _ in OP_MIX]
    weights = [w for _, w in OP_MIX]
    with open(path, "w") as f:
        for i in range(n_ops):
            w = i % workers
            cmd = rng.choices(ops, weights)[0]
            known = counters[w]
            if cmd in ("create", "mkdirs") or known == 0:
                cmd = "create" if cmd not in ("mkdirs",) else cmd
                counters[w] += 1
                target = counters[w]
            else:
                target = rng.randrange(1, known + 1)
            src = f"/w{w}/d{target % 97:02d}/f{target:06d}"
            if cmd == "mkdirs":
                src = f"/w{w}/d{target % 97:02d}"
            dst = "null"
            if cmd == "rename":
                counters[w] += 1
                dst = f"/w{w}/d{counters[w] % 97:02d}/f{counters[w]:06d}"
            f.write(f"allowed=true\tugi=dyn\tip=127.0.0.1\t"
                    f"cmd={cmd}\tsrc={src}\tdst={dst}\t"
                    f"callerContext=dynamometer\n")
    return path


def replay_parallel(fs_uri: str, lines: List[str], threads: int = 8,
                    remap_root: str = "/dyn",
                    conf=None) -> Dict:
    """Multi-worker replay against a live NameNode over real RPC (ref:
    AuditReplayMapper runs many mapper threads). Lines partition by the
    /w<k>/ top directory so per-path ordering holds within a worker;
    each worker drives its OWN client (separate RPC connection)."""
    from concurrent.futures import ThreadPoolExecutor

    from hadoop_tpu.conf import Configuration
    conf = conf or Configuration()

    buckets: List[List[str]] = [[] for _ in range(threads)]
    for line in lines:
        ev = parse_audit_line(line)
        if ev is None:
            continue
        src = ev.get("src", "")
        if src.startswith("/w"):
            try:
                k = int(src[2:src.index("/", 1)]) % threads
            except ValueError:
                k = hash(src.split("/", 2)[1]) % threads
        else:
            k = hash(src.split("/", 2)[1] if src.count("/") > 1
                     else src) % threads
        buckets[k].append(line)

    def worker(batch: List[str]) -> Dict:
        wfs = FileSystem.get(fs_uri, conf)
        try:
            return replay(wfs, batch, remap_root)
        finally:
            wfs.close()

    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=threads) as pool:
        parts = list(pool.map(worker, [b for b in buckets if b]))
    dt = time.perf_counter() - t0
    total = sum(p["ops"] for p in parts)
    per_op: Dict[str, int] = {}
    for p in parts:
        for k, v in p["per_op"].items():
            per_op[k] = per_op.get(k, 0) + v
    return {
        "ops": total,
        "errors": sum(p["errors"] for p in parts),
        "threads": threads,
        "per_op": per_op,
        "wall_seconds": round(dt, 3),
        "ops_per_sec": round(total / dt, 1) if dt else 0.0,
    }


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="dynamometer")
    ap.add_argument("audit_log")
    ap.add_argument("--fs", required=True)
    ap.add_argument("--remap-root", default="/dyn")
    ap.add_argument("--threads", type=int, default=1)
    ap.add_argument("--generate", type=int, metavar="N_OPS",
                    help="generate a synthetic N-op trace first")
    args = ap.parse_args(argv)
    if args.generate:
        generate_trace(args.audit_log, args.generate,
                       workers=max(1, args.threads))
    if args.threads > 1:
        with open(args.audit_log) as f:
            report = replay_parallel(args.fs, list(f), args.threads,
                                     args.remap_root)
    else:
        fs = FileSystem.get(args.fs, Configuration())
        try:
            with open(args.audit_log) as f:
                report = replay(fs, f, args.remap_root)
        finally:
            fs.close()
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
