"""FedBalance — move a federated mount's data between nameservices.

Parity with the reference tool (ref: hadoop-tools/hadoop-federation-
balance — FedBalance.java's DistCpProcedure + MountTableProcedure: copy
the mount's subtree to the target nameservice with distcp, then
atomically repoint the router mount entry, then clean up the source),
driven against this framework's Router (dfs/router/router.py).

    python -m hadoop_tpu.tools.fedbalance --router host:port \
        --rm host:port --workfs URI /mount dst_ns /dst/path
"""

from __future__ import annotations

import logging
from typing import Dict, Optional, Tuple

from hadoop_tpu.conf import Configuration
from hadoop_tpu.ipc import Client, get_proxy

log = logging.getLogger(__name__)


def fedbalance(router, rm_addr, default_fs: str, mount: str,
               dst_ns: str, dst_path: str, *,
               delete_source: bool = True,
               conf: Optional[Configuration] = None) -> Dict:
    """Move ``mount``'s subtree to (dst_ns, dst_path) and repoint the
    mount. ``router`` is the Router service instance (in-process admin,
    like the reference's RouterAdmin client would be). Phases mirror
    DistCpProcedure → MountTableProcedure → TrashProcedure."""
    from hadoop_tpu.tools.distcp import distcp
    conf = conf or Configuration()

    entries = router.mounts.entries()
    if mount not in entries:
        raise ValueError(f"unknown mount {mount!r} "
                         f"(have {sorted(entries)})")
    src_ns, src_path = entries[mount]
    if src_ns == dst_ns:
        raise ValueError(f"mount {mount} already on {dst_ns}")
    src_addrs = router.ns_addrs[src_ns]
    dst_addrs = router.ns_addrs[dst_ns]
    src_uri = f"htpu://{src_addrs[0][0]}:{src_addrs[0][1]}{src_path}"
    dst_uri = f"htpu://{dst_addrs[0][0]}:{dst_addrs[0][1]}{dst_path}"

    # Phase 1: copy (ref: DistCpProcedure — the reference does an
    # initial + diff round; a single round suffices with the mount
    # quiesced, which the reference also requires for the final diff).
    counters = distcp(rm_addr, default_fs, src_uri, dst_uri, conf=conf)

    # Phase 2: atomically repoint the mount (ref: MountTableProcedure).
    router.mounts.add(mount, dst_ns, dst_path)

    # Phase 3: retire source data (ref: TrashProcedure).
    if delete_source:
        from hadoop_tpu.fs import FileSystem
        sfs = FileSystem.get(src_uri, conf)
        try:
            sfs.delete(src_path, recursive=True)
        finally:
            sfs.close()
    log.info("fedbalance %s: %s%s -> %s%s", mount, src_ns, src_path,
             dst_ns, dst_path)
    return {"mount": mount, "from": [src_ns, src_path],
            "to": [dst_ns, dst_path], "copy_counters": counters}
