"""fs2img — mount an external tree into the DFS as PROVIDED storage.

Parity with the reference tool (ref: hadoop-tools/hadoop-fs2img —
ImageWriter walks a remote FileSystem and emits an fsimage whose files
are PROVIDED-storage blocks backed by a block alias map; DataNodes with
PROVIDED volumes then serve that external data as if it were local,
HDFS-9806): here the walk registers each external file with the LIVE
NameNode (``add_provided_file``), which persists the namespace + alias
map through its ordinary image/edit-log machinery — same end state as
an offline image build, no data copied.

  python -m hadoop_tpu.tools.fs2img --fs htpu://nn:port \
      file:///datasets /provided
"""

from __future__ import annotations

import json
import logging
from typing import Dict, Optional

from hadoop_tpu.conf import Configuration
from hadoop_tpu.fs import FileSystem
from hadoop_tpu.fs.filesystem import Path

log = logging.getLogger(__name__)


def mount_tree(dfs, external_uri: str, dfs_root: str, *,
               block_size: Optional[int] = None,
               conf: Optional[Configuration] = None) -> Dict:
    """Walk ``external_uri`` and register every file under ``dfs_root``
    as a provided file. ``dfs`` is a DistributedFileSystem (its client
    RPCs carry add_provided_file). Ref: ImageWriter.run's tree walk."""
    conf = conf or Configuration()
    ext = FileSystem.get(external_uri, conf)
    base = Path(external_uri)
    scheme_prefix = f"{base.scheme}://{base.authority}" \
        if base.authority else f"{base.scheme}://"
    root = base.path.rstrip("/") or "/"
    files = 0
    total = 0
    try:
        def walk(path: str, st) -> None:
            nonlocal files, total
            rel = path[len(root):].lstrip("/") if path != root else ""
            target = f"{dfs_root.rstrip('/')}/{rel}" if rel \
                else dfs_root.rstrip("/")
            if st.is_dir:
                dfs.mkdirs(target)
                for child in ext.list_status(path):
                    # reuse the listing's FileStatus — one metadata RPC
                    # per node, not two (the walk IS remote traffic)
                    walk(Path(child.path).path, child)
            else:
                dfs.client.nn.add_provided_file(
                    target, f"{scheme_prefix}{path}", st.length,
                    block_size)
                files += 1
                total += st.length
        walk(root, ext.get_file_status(root))
    finally:
        ext.close()
    log.info("fs2img: mounted %d files (%d bytes) from %s at %s",
             files, total, external_uri, dfs_root)
    return {"files": files, "bytes": total, "root": dfs_root}


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="fs2img")
    ap.add_argument("external", help="external tree URI (file://, htps://)")
    ap.add_argument("dfs_root", help="DFS path to mount under")
    ap.add_argument("--fs", required=True, help="DFS URI (htpu://nn:port)")
    args = ap.parse_args(argv)
    dfs = FileSystem.get(args.fs, Configuration())
    try:
        print(json.dumps(mount_tree(dfs, args.external, args.dfs_root)))
    finally:
        dfs.close()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
