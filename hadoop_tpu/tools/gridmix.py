"""GridMix — replay job traces as REAL jobs on a live cluster.

Parity with the reference load generator (ref: hadoop-tools/
hadoop-gridmix — Gridmix.java submits synthetic jobs shaped like a
rumen trace against a real cluster; its SleepJob/LoadJob models): where
SLS (tools/sls.py) simulates the scheduler, GridMix exercises the WHOLE
stack — every trace entry becomes a real MR job (sleep-task model:
``containers`` map tasks × ``sleep_ms`` runtime) submitted through the
ordinary Job client, and the report is end-to-end job latency under
contention.

  python -m hadoop_tpu.tools.gridmix --rm host:port --fs URI trace.json
"""

from __future__ import annotations

import json
import logging
import time
from typing import Dict, List, Optional

from hadoop_tpu.mapreduce.api import InputFormat, Mapper

log = logging.getLogger(__name__)


class SleepInputFormat(InputFormat):
    """N splits with no backing file — each split is one synthetic map
    (ref: gridmix's SleepJob.SleepInputFormat)."""

    NUM_MAPS_KEY = "gridmix.sleep.maps"

    def get_splits(self, fs, paths, conf):
        from hadoop_tpu.mapreduce.api import FileSplit
        n = int(conf.get(self.NUM_MAPS_KEY, "1"))
        return [FileSplit(f"synthetic://sleep/{i}", 0, 1)
                for i in range(n)]

    def read(self, fs, split, conf):
        yield split.path.encode(), b""


class SleepMapper(Mapper):
    """Hold a container for the modeled task runtime."""

    def map(self, key, value, ctx):
        time.sleep(float(ctx.conf.get("gridmix.sleep.ms", "100")) / 1000.0)
        ctx.emit(key, b"done")


def run_trace(rm_addr, default_fs: str, trace: List[Dict], *,
              sleep_ms: int = 100, max_concurrent: int = 4,
              out_root: str = "/gridmix-out") -> Dict:
    """Submit every trace entry as a real sleep job; returns latency
    stats. Ref: Gridmix.run's JobSubmitter/JobMonitor pair (bounded
    in-flight jobs)."""
    from hadoop_tpu.mapreduce import Job
    from hadoop_tpu.mapreduce.api import class_ref
    pending = sorted(trace, key=lambda j: j.get("arrival", 0))
    inflight: List[Dict] = []
    latencies: List[float] = []
    failed = 0
    t0 = time.perf_counter()
    idx = 0
    while pending or inflight:
        while pending and len(inflight) < max_concurrent:
            entry = pending.pop(0)
            job = (Job(rm_addr, default_fs,
                       name=f"gridmix-{entry.get('job_id', idx)}")
                   .set_mapper(class_ref(SleepMapper))
                   .set_input_format(class_ref(SleepInputFormat))
                   .add_input_path("/")
                   .set_output_path(f"{out_root}/{idx}")
                   .set_num_reduces(0)
                   .set(SleepInputFormat.NUM_MAPS_KEY,
                        str(max(1, min(int(entry.get("containers", 1)),
                                       64))))
                   # Trace fidelity: a rumen trace carries the source
                   # job's measured task runtime; replay each task for
                   # that long (ref: gridmix's SleepJob using
                   # LoggedTask runtimes). Fixed sleep_ms otherwise.
                   .set("gridmix.sleep.ms", str(
                       entry.get("task_ms", {}).get("mean")
                       or sleep_ms)))
            job.submit()
            inflight.append({"job": job, "start": time.perf_counter()})
            idx += 1
        still = []
        for rec in inflight:
            try:
                ok = rec["job"].wait_for_completion(timeout=0.05)
                latencies.append(time.perf_counter() - rec["start"])
                if not ok:
                    failed += 1
            except TimeoutError:
                still.append(rec)
        inflight = still
        time.sleep(0.05)
    dt = time.perf_counter() - t0
    lat = sorted(latencies)

    def pct(p):
        return round(lat[min(len(lat) - 1, int(p * len(lat)))], 3) \
            if lat else None
    return {"jobs": idx, "failed": failed,
            "wall_seconds": round(dt, 2),
            "job_latency_s": {"p50": pct(0.5), "p95": pct(0.95),
                              "max": pct(1.0)}}


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="gridmix")
    ap.add_argument("trace")
    ap.add_argument("--rm", required=True)
    ap.add_argument("--fs", required=True)
    ap.add_argument("--sleep-ms", type=int, default=100)
    ap.add_argument("--concurrent", type=int, default=4)
    args = ap.parse_args(argv)
    with open(args.trace) as f:
        trace = json.load(f)
    host, _, port = args.rm.rpartition(":")
    print(json.dumps(run_trace((host, int(port)), args.fs, trace,
                               sleep_ms=args.sleep_ms,
                               max_concurrent=args.concurrent)))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
