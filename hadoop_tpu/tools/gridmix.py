"""GridMix — replay job traces as REAL jobs on a live cluster.

Parity with the reference load generator (ref: hadoop-tools/
hadoop-gridmix — Gridmix.java submits synthetic jobs shaped like a
rumen trace against a real cluster): where SLS (tools/sls.py) simulates
the scheduler, GridMix exercises the WHOLE stack — every trace entry
becomes a real MR job submitted through the ordinary Job client, and
the report is end-to-end job latency under contention.

Two job models, matching the reference's:

- **LoadJob** (default when the trace carries a rumen ``load`` model —
  ref: gridmix/LoadJob.java + its ResourceUsageMatcher emulator
  plugins): every map/reduce task reproduces the traced task's SHAPE —
  reads the modeled input record count, burns the modeled CPU time
  (progressively, interleaved with records, measured by
  ``time.process_time``), holds the modeled heap, and emits the
  modeled output records/bytes through the real collector/shuffle —
  so the replay stresses the data plane the way the original did.
- **SleepJob** (ref: gridmix/SleepJob.java): containers held for the
  traced runtime with zero load; measures scheduler latency only.

  python -m hadoop_tpu.tools.gridmix --rm host:port --fs URI trace.json
"""

from __future__ import annotations

import json
import logging
import time
from typing import Dict, List, Optional

from hadoop_tpu.mapreduce.api import (InputFormat, Mapper,
                                      Reducer)

log = logging.getLogger(__name__)


class SleepInputFormat(InputFormat):
    """N splits with no backing file — each split is one synthetic map
    (ref: gridmix's SleepJob.SleepInputFormat)."""

    NUM_MAPS_KEY = "gridmix.sleep.maps"

    def get_splits(self, fs, paths, conf):
        from hadoop_tpu.mapreduce.api import FileSplit
        n = int(conf.get(self.NUM_MAPS_KEY, "1"))
        return [FileSplit(f"synthetic://sleep/{i}", 0, 1)
                for i in range(n)]

    def read(self, fs, split, conf):
        yield split.path.encode(), b""


class SleepMapper(Mapper):
    """Hold a container for the modeled task runtime."""

    def map(self, key, value, ctx):
        time.sleep(float(ctx.conf.get("gridmix.sleep.ms", "100")) / 1000.0)
        ctx.emit(key, b"done")


# ------------------------------------------------------------------ load job

class LoadInputFormat(InputFormat):
    """N synthetic splits, each describing one modeled map's record
    stream (ref: LoadJob's use of the trace's per-task record counts;
    the data itself is generated, like GenerateData's corpus)."""

    NUM_MAPS_KEY = "gridmix.load.maps"
    IN_RECORDS_KEY = "gridmix.load.map.input-records"
    REC_BYTES_KEY = "gridmix.load.record-bytes"

    def get_splits(self, fs, paths, conf):
        from hadoop_tpu.mapreduce.api import FileSplit
        n = int(conf.get(self.NUM_MAPS_KEY, "1"))
        return [FileSplit(f"synthetic://load/{i}", 0, 1)
                for i in range(n)]

    def read(self, fs, split, conf):
        import os as _os
        n_rec = max(1, int(conf.get(self.IN_RECORDS_KEY, "100")))
        rec_bytes = max(1, int(conf.get(self.REC_BYTES_KEY, "100")))
        payload = _os.urandom(rec_bytes)
        for i in range(n_rec):
            yield f"{split.path}/{i}".encode(), payload


class _CpuBurner:
    """Progressive CPU emulation (ref: CumulativeCpuUsageEmulatorPlugin:
    burn in small chunks as records flow, not one big spin at the end).
    Targets PROCESS time so sleeps/IO don't count toward the budget."""

    def __init__(self, total_ms: float):
        self.deadline_used = 0.0
        self.total_s = total_ms / 1000.0
        self.start = time.process_time()
        self._x = 12345

    def burn_fraction(self, frac: float) -> None:
        target = self.start + min(1.0, frac) * self.total_s
        while time.process_time() < target:
            # arithmetic chunk; keep the GIL releasable between chunks
            for _ in range(1000):
                self._x = (self._x * 1103515245 + 12345) & 0x7FFFFFFF


class LoadMapper(Mapper):
    """Reproduce the traced map shape: record IO at the modeled in/out
    ratio, modeled output bytes, progressive CPU burn, held heap."""

    def setup(self, ctx):
        import os as _os
        self._out_records = max(0, int(ctx.conf.get(
            "gridmix.load.map.output-records", "100")))
        self._in_records = max(1, int(ctx.conf.get(
            LoadInputFormat.IN_RECORDS_KEY, "100")))
        out_bytes = max(0, int(ctx.conf.get(
            "gridmix.load.map.output-bytes", "10000")))
        self._val = _os.urandom(
            max(1, out_bytes // max(1, self._out_records)))
        self._burner = _CpuBurner(float(ctx.conf.get(
            "gridmix.load.cpu-ms", "0")))
        # heap emulation (ref: TotalHeapUsageEmulatorPlugin): hold the
        # modeled working set for the task's lifetime
        heap_mb = int(ctx.conf.get("gridmix.load.heap-mb", "0"))
        self._ballast = bytearray(heap_mb * 1024 * 1024) if heap_mb else None
        self._seen = 0
        self._emitted = 0

    def map(self, key, value, ctx):
        self._seen += 1
        self._burner.burn_fraction(self._seen / self._in_records)
        # emit at the traced out/in ratio, spread evenly
        want = (self._seen * self._out_records) // self._in_records
        while self._emitted < want:
            self._emitted += 1
            ctx.emit(f"k{self._emitted % 997:03d}".encode(), self._val)


class LoadReducer(Reducer):
    """Consume groups and emit at the traced reduce out/in ratio."""

    def setup(self, ctx):
        self._ratio = float(ctx.conf.get("gridmix.load.reduce.ratio", "1"))
        self._burner = _CpuBurner(float(ctx.conf.get(
            "gridmix.load.reduce.cpu-ms", "0")))
        # this task's expected share of the traced reduce input, so the
        # CPU burn completes over the real record stream instead of a
        # hard-coded count
        self._in_records = max(1, int(ctx.conf.get(
            "gridmix.load.reduce.input-records", "10000")))
        self._seen = 0
        self._acc = 0.0

    def reduce(self, key, values, ctx):
        n = sum(1 for _ in values)
        self._seen += n
        self._burner.burn_fraction(self._seen / self._in_records)
        # emit at the traced out/in ratio PER INPUT RECORD (a group of
        # 100 at ratio 1.0 must emit ~100, not 1)
        self._acc += self._ratio * n
        while self._acc >= 1.0:
            self._acc -= 1.0
            ctx.emit(key, str(n).encode())


def _make_sleep_job(Job, class_ref, rm_addr, default_fs, entry, idx,
                    out_root, sleep_ms):
    return (Job(rm_addr, default_fs,
                name=f"gridmix-{entry.get('job_id', idx)}")
            .set_mapper(class_ref(SleepMapper))
            .set_input_format(class_ref(SleepInputFormat))
            .add_input_path("/")
            .set_output_path(f"{out_root}/{idx}")
            .set_num_reduces(0)
            .set(SleepInputFormat.NUM_MAPS_KEY,
                 str(max(1, min(int(entry.get("containers", 1)), 64))))
            .set("gridmix.sleep.ms", str(
                entry.get("task_ms", {}).get("mean") or sleep_ms)))


def _make_load_job(Job, class_ref, rm_addr, default_fs, entry, idx,
                   out_root, cpu_fraction):
    load = entry["load"]
    m = load.get("map") or {"n": 1, "ms": 100, "input_records": 100,
                            "output_records": 100, "output_bytes": 10000}
    r = load.get("reduce")
    out_per_rec = max(1, m["output_bytes"] //
                      max(1, m["output_records"]))
    job = (Job(rm_addr, default_fs,
               name=f"gridmix-load-{entry.get('job_id', idx)}")
           .set_mapper(class_ref(LoadMapper))
           .set_input_format(class_ref(LoadInputFormat))
           .add_input_path("/")
           .set_output_path(f"{out_root}/{idx}")
           .set(LoadInputFormat.NUM_MAPS_KEY, str(max(1, m["n"])))
           .set(LoadInputFormat.IN_RECORDS_KEY,
                str(max(1, m["input_records"])))
           .set(LoadInputFormat.REC_BYTES_KEY, str(out_per_rec))
           .set("gridmix.load.map.output-records",
                str(m["output_records"]))
           .set("gridmix.load.map.output-bytes", str(m["output_bytes"]))
           .set("gridmix.load.cpu-ms",
                str(int(m["ms"] * cpu_fraction))))
    if r:
        n_red = max(1, r["n"])
        job.set_reducer(class_ref(LoadReducer)) \
           .set_num_reduces(n_red) \
           .set("gridmix.load.reduce.ratio", str(
               r["output_records"] / max(1, r["input_records"]))) \
           .set("gridmix.load.reduce.input-records", str(
               max(1, r["input_records"] // n_red))) \
           .set("gridmix.load.reduce.cpu-ms",
                str(int(r["ms"] * cpu_fraction)))
    else:
        job.set_num_reduces(0)
    return job


def run_trace(rm_addr, default_fs: str, trace: List[Dict], *,
              sleep_ms: int = 100, max_concurrent: int = 4,
              out_root: str = "/gridmix-out", mode: str = "auto",
              cpu_fraction: float = 0.5, policy: str = "stress",
              tick_seconds: float = 0.0) -> Dict:
    """Submit every trace entry as a real job; returns latency stats.
    Ref: Gridmix.run's JobSubmitter/JobMonitor pair (bounded in-flight
    jobs). ``mode``: "load" (emulate the rumen load model), "sleep",
    or "auto" (load when the entry carries one). ``cpu_fraction``:
    share of the traced task runtime modeled as compute (the rest was
    IO/framework in the source job).

    ``policy`` mirrors the reference's job-submission policies (ref:
    hadoop-gridmix GridmixJobSubmissionPolicy.{STRESS,REPLAY,SERIAL}):
    "stress" keeps up to ``max_concurrent`` jobs in flight (greedy),
    "replay" additionally holds each entry until its trace arrival
    tick (× ``tick_seconds`` of real time per tick) so the original
    inter-arrival gaps are reproduced, and "serial" submits one job at
    a time, each after the previous completes."""
    from hadoop_tpu.mapreduce import Job
    from hadoop_tpu.mapreduce.api import class_ref
    if policy not in ("stress", "replay", "serial"):
        raise ValueError(f"unknown submission policy {policy!r}")
    if policy == "replay" and tick_seconds <= 0:
        # a zero tick makes every arrival due immediately — that's
        # stress wearing a replay label, not a replay
        raise ValueError("replay policy needs tick_seconds > 0")
    if policy == "serial":
        max_concurrent = 1
    pending = sorted(trace, key=lambda j: j.get("arrival", 0))
    inflight: List[Dict] = []
    latencies: List[float] = []
    failed = 0
    peak_inflight = 0
    t0 = time.perf_counter()
    idx = 0
    while pending or inflight:
        while pending and len(inflight) < max_concurrent:
            if policy == "replay":
                due = t0 + pending[0].get("arrival", 0) * tick_seconds
                if time.perf_counter() < due:
                    break  # not yet arrived in trace time
            entry = pending.pop(0)
            # --mode load degrades per-entry: a trace without a load
            # model (pre-round-5 rumen output) replays as a sleep job
            # instead of crashing mid-run with jobs in flight
            use_load = bool(entry.get("load")) and mode in ("load",
                                                            "auto")
            if use_load:
                job = _make_load_job(Job, class_ref, rm_addr, default_fs,
                                     entry, idx, out_root, cpu_fraction)
            else:
                job = _make_sleep_job(Job, class_ref, rm_addr, default_fs,
                                      entry, idx, out_root, sleep_ms)
            job.submit()
            inflight.append({"job": job, "start": time.perf_counter()})
            idx += 1
            peak_inflight = max(peak_inflight, len(inflight))
        still = []
        for rec in inflight:
            try:
                ok = rec["job"].wait_for_completion(timeout=0.05)
                latencies.append(time.perf_counter() - rec["start"])
                if not ok:
                    failed += 1
            except TimeoutError:
                still.append(rec)
        inflight = still
        # completion poll cadence, not a failure retry
        time.sleep(0.05)  # lint: disable=rpc/retry-no-backoff
    dt = time.perf_counter() - t0
    lat = sorted(latencies)

    def pct(p):
        return round(lat[min(len(lat) - 1, int(p * len(lat)))], 3) \
            if lat else None
    return {"jobs": idx, "failed": failed, "policy": policy,
            "peak_inflight": peak_inflight,
            "wall_seconds": round(dt, 2),
            "job_latency_s": {"p50": pct(0.5), "p95": pct(0.95),
                              "max": pct(1.0)}}


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="gridmix")
    ap.add_argument("trace")
    ap.add_argument("--rm", required=True)
    ap.add_argument("--fs", required=True)
    ap.add_argument("--sleep-ms", type=int, default=100)
    ap.add_argument("--concurrent", type=int, default=4)
    ap.add_argument("--mode", choices=["auto", "load", "sleep"],
                    default="auto")
    ap.add_argument("--cpu-fraction", type=float, default=0.5)
    ap.add_argument("--policy", choices=["stress", "replay", "serial"],
                    default="stress")
    ap.add_argument("--tick-seconds", type=float, default=0.05,
                    help="real seconds per trace arrival tick "
                    "(replay policy)")
    args = ap.parse_args(argv)
    with open(args.trace) as f:
        trace = json.load(f)
    host, _, port = args.rm.rpartition(":")
    print(json.dumps(run_trace((host, int(port)), args.fs, trace,
                               sleep_ms=args.sleep_ms,
                               max_concurrent=args.concurrent,
                               mode=args.mode,
                               cpu_fraction=args.cpu_fraction,
                               policy=args.policy,
                               tick_seconds=args.tick_seconds)))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
