"""Pipes — run C++ Mapper/Reducer binaries as MR tasks.

Parity with the reference tool (ref: hadoop-tools/hadoop-pipes —
Submitter.java launches a job whose tasks drive a C++ child written
against Pipes.hh). The C++ API lives in native/src/pipes.hh; a pipes
binary handles both phases (``prog map`` / ``prog reduce``) over the
streaming line protocol, so the job machinery is the ordinary
streaming bridge with the program wired into both commands.

  from hadoop_tpu.tools.pipes import pipes_job
  job = pipes_job(rm, fs_uri, "/in", "/out",
                  program="/path/to/htpu-pipes-wordcount")
"""

from __future__ import annotations

import os
from typing import Optional

from hadoop_tpu.tools.streaming import streaming_job


def pipes_job(rm_addr, default_fs: str, input_path: str,
              output_path: str, *, program: str,
              num_reduces: int = 1):
    """Build the MR job for one pipes binary (ref: Submitter.runJob).
    ``program`` must be executable on every NodeManager host (localize
    it beforehand or use a shared path — the reference ships it via the
    distributed cache, the same contract)."""
    if not os.path.exists(program):
        raise FileNotFoundError(f"pipes program not found: {program}")
    if not os.access(program, os.X_OK):
        raise PermissionError(f"pipes program not executable: {program}")
    return streaming_job(
        rm_addr, default_fs, input_path, output_path,
        mapper=f"{program} map", reducer=f"{program} reduce",
        num_reduces=num_reduces)


def example_wordcount_binary() -> Optional[str]:
    """The in-tree pipes example, built by the native Makefile."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "native", "htpu-pipes-wordcount")
    return path if os.path.exists(path) else None
