"""Resource Estimator — size a recurring job's reservation from history.

Parity with the reference service (ref: hadoop-tools/
hadoop-resourceestimator — its SkylineStore collects a recurring
pipeline's past runs' resource skylines, the LpSolver estimates the
next run's needs, and the result feeds the ReservationSystem): here
the rumen trace chain (tools/rumen.py over the JobHistory done-dir)
provides the past runs, the estimate is a robust percentile over them,
and ``make_reservation`` emits the scheduler Reservation record the
capacity scheduler admits (yarn/scheduler.py).

    est = estimate(traces)               # {containers, mb, duration_ms}
    res = make_reservation("nightly-etl", est, start, ...)
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from hadoop_tpu.yarn.records import Resource


def estimate(runs: List[Dict], percentile: float = 0.9,
             headroom: float = 1.1) -> Dict:
    """Estimate from past runs of ONE recurring job (rumen trace
    entries). Percentile-of-history × headroom — the role the
    reference's solver plays, collapsed to the robust statistic its
    docs recommend validating against."""
    if not runs:
        raise ValueError("no history to estimate from")

    def pct(values: List[float]) -> float:
        v = sorted(values)
        return v[min(len(v) - 1, int(percentile * len(v)))]

    containers = pct([r.get("containers", 1) for r in runs])
    mb = pct([r.get("mb", 1024) for r in runs])
    dur = pct([r.get("task_ms", {}).get("max", 0) or
               r.get("task_ms", {}).get("mean", 0) or 60_000
               for r in runs])
    return {
        "containers": max(1, int(containers * headroom + 0.5)),
        "mb": max(128, int(mb * headroom + 0.5)),
        "duration_ms": max(1000, int(dur * headroom + 0.5)),
        "runs_observed": len(runs),
        "percentile": percentile,
    }


def make_reservation(reservation_id: str, est: Dict, start: float,
                     queue: str = "default",
                     deadline: Optional[float] = None):
    """Estimate → scheduler Reservation (ref: the estimator's output
    feeding ReservationSubmissionRequest)."""
    from hadoop_tpu.yarn.scheduler import Reservation
    dur_s = est["duration_ms"] / 1000.0
    return Reservation(
        reservation_id, queue, Resource(est["mb"], 1),
        est["containers"], start,
        deadline if deadline is not None else start + 2 * dur_s)


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="resourceestimator")
    ap.add_argument("trace", help="rumen trace json (one recurring job's runs)")
    ap.add_argument("--percentile", type=float, default=0.9)
    args = ap.parse_args(argv)
    with open(args.trace) as f:
        runs = json.load(f)
    print(json.dumps(estimate(runs, percentile=args.percentile)))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
