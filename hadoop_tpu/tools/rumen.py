"""Rumen — job-trace extraction from job history, feeding SLS/gridmix.

Parity with the reference trace chain (ref: hadoop-tools/hadoop-rumen —
TraceBuilder.java parses .jhist files into job traces; hadoop-gridmix
replays them): the done-dir histories the AMs publish
(mapreduce.history) fold into SLS-shaped job traces
(tools/sls.SyntheticTrace), so a cluster's real workload can be
replayed against any scheduler configuration.

  python -m hadoop_tpu.tools.rumen --fs htpu://... --out trace.json
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from hadoop_tpu.conf import Configuration
from hadoop_tpu.fs import FileSystem
from hadoop_tpu.mapreduce import history


def build_trace(fs: FileSystem,
                done_dir: str = history.DEFAULT_DONE_DIR,
                container_mb: int = 1024) -> List[Dict]:
    """One SLS job entry per finished job: arrival order = completion
    order in the done-dir, container demand = the job's task count.
    Ref: TraceBuilder.process → LoggedJob."""
    jobs: List[Dict] = []
    try:
        entries = sorted(st.path for st in fs.list_status(done_dir)
                         if st.is_dir)
    except (IOError, OSError, FileNotFoundError):
        return jobs
    for i, path in enumerate(entries):
        job_id = path.rstrip("/").rsplit("/", 1)[-1]
        events = list(history.read_events(fs, path))
        tasks = [e for e in events if e["type"] == history.TASK_FINISHED]
        finished = [e for e in events
                    if e["type"] == history.JOB_FINISHED]
        if not tasks:
            continue
        # Per-task runtime distribution — the trace fidelity rumen
        # exists for (ref: LoggedTask attempt runtimes feeding
        # gridmix's task models).
        durations = sorted(e.get("duration_ms", 0) for e in tasks)
        mean_ms = sum(durations) // len(durations)
        jobs.append({
            "app": f"application_1_{i + 1}_01",
            "job_id": job_id,
            "arrival": i,  # completion order; SLS spreads by this key
            "queue": "default",
            "containers": len(tasks),
            "maps": sum(1 for e in tasks if e.get("task_type") == "map"),
            "reduces": sum(1 for e in tasks
                           if e.get("task_type") == "reduce"),
            "mb": container_mb,
            "task_ms": {"mean": mean_ms,
                        "p50": durations[len(durations) // 2],
                        "max": durations[-1]},
            "load": _load_model(tasks),
            "state": finished[0]["state"] if finished else "UNKNOWN",
        })
    return jobs


def _load_model(tasks: List[Dict]) -> Dict:
    """Per-phase load shape from task counters — what gridmix's LoadJob
    replays (ref: LoggedTaskAttempt's resource/record fields feeding
    gridmix LoadJob + its ResourceUsageEmulatorPlugins)."""
    model: Dict[str, Dict] = {}
    for phase, in_key, out_keys in (
            ("map", "MAP_INPUT_RECORDS",
             ("MAP_OUTPUT_RECORDS", "MAP_OUTPUT_BYTES")),
            ("reduce", "REDUCE_INPUT_RECORDS",
             ("REDUCE_OUTPUT_RECORDS", None))):
        phase_tasks = [t for t in tasks if t.get("task_type") == phase]
        if not phase_tasks:
            continue
        n = len(phase_tasks)

        def csum(name):
            return sum((t.get("counters") or {})
                       .get("TaskCounter", {}).get(name, 0)
                       for t in phase_tasks)
        ms = sorted(t.get("duration_ms", 0) for t in phase_tasks)
        model[phase] = {
            "n": n,
            "ms": sum(ms) // n,
            "input_records": csum(in_key) // n,
            "output_records": csum(out_keys[0]) // n,
            "output_bytes": (csum(out_keys[1]) // n) if out_keys[1]
            else 0,
        }
    return model


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="rumen")
    ap.add_argument("--fs", required=True)
    ap.add_argument("--done-dir", default=history.DEFAULT_DONE_DIR)
    ap.add_argument("--out", default="-")
    args = ap.parse_args(argv)
    fs = FileSystem.get(args.fs, Configuration())
    try:
        trace = build_trace(fs, args.done_dir)
    finally:
        fs.close()
    body = json.dumps(trace, indent=2)
    if args.out == "-":
        print(body)
    else:
        with open(args.out, "w") as f:
            f.write(body)
        print(json.dumps({"jobs": len(trace), "out": args.out}))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
