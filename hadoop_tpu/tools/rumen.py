"""Rumen — job-trace extraction from job history, feeding SLS/gridmix.

Parity with the reference trace chain (ref: hadoop-tools/hadoop-rumen —
TraceBuilder.java parses .jhist files into job traces; hadoop-gridmix
replays them): the done-dir histories the AMs publish
(mapreduce.history) fold into SLS-shaped job traces
(tools/sls.SyntheticTrace), so a cluster's real workload can be
replayed against any scheduler configuration.

  python -m hadoop_tpu.tools.rumen --fs htpu://... --out trace.json
"""

from __future__ import annotations

import json
import zlib
from typing import Dict, List, Optional

from hadoop_tpu.conf import Configuration
from hadoop_tpu.fs import FileSystem
from hadoop_tpu.mapreduce import history


def build_trace(fs: FileSystem,
                done_dir: str = history.DEFAULT_DONE_DIR,
                container_mb: int = 1024) -> List[Dict]:
    """One SLS job entry per finished job: arrival order = completion
    order in the done-dir, container demand = the job's task count.
    Ref: TraceBuilder.process → LoggedJob."""
    jobs: List[Dict] = []
    try:
        entries = sorted(st.path for st in fs.list_status(done_dir)
                         if st.is_dir)
    except (IOError, OSError, FileNotFoundError):
        return jobs
    for i, path in enumerate(entries):
        job_id = path.rstrip("/").rsplit("/", 1)[-1]
        events = list(history.read_events(fs, path))
        tasks = [e for e in events if e["type"] == history.TASK_FINISHED]
        finished = [e for e in events
                    if e["type"] == history.JOB_FINISHED]
        if not tasks:
            continue
        # Per-task runtime distribution — the trace fidelity rumen
        # exists for (ref: LoggedTask attempt runtimes feeding
        # gridmix's task models).
        durations = sorted(e.get("duration_ms", 0) for e in tasks)
        mean_ms = sum(durations) // len(durations)
        jobs.append({
            "app": f"application_1_{i + 1}_01",
            "job_id": job_id,
            "arrival": i,  # completion order; SLS spreads by this key
            "queue": "default",
            "containers": len(tasks),
            "maps": sum(1 for e in tasks if e.get("task_type") == "map"),
            "reduces": sum(1 for e in tasks
                           if e.get("task_type") == "reduce"),
            "mb": container_mb,
            "task_ms": {"mean": mean_ms,
                        "p50": durations[len(durations) // 2],
                        "max": durations[-1]},
            "load": _load_model(tasks),
            "state": finished[0]["state"] if finished else "UNKNOWN",
        })
    return jobs


def _load_model(tasks: List[Dict]) -> Dict:
    """Per-phase load shape from task counters — what gridmix's LoadJob
    replays (ref: LoggedTaskAttempt's resource/record fields feeding
    gridmix LoadJob + its ResourceUsageEmulatorPlugins)."""
    model: Dict[str, Dict] = {}
    for phase, in_key, out_keys in (
            ("map", "MAP_INPUT_RECORDS",
             ("MAP_OUTPUT_RECORDS", "MAP_OUTPUT_BYTES")),
            ("reduce", "REDUCE_INPUT_RECORDS",
             ("REDUCE_OUTPUT_RECORDS", None))):
        phase_tasks = [t for t in tasks if t.get("task_type") == phase]
        if not phase_tasks:
            continue
        n = len(phase_tasks)

        def csum(name):
            return sum((t.get("counters") or {})
                       .get("TaskCounter", {}).get(name, 0)
                       for t in phase_tasks)
        ms = sorted(t.get("duration_ms", 0) for t in phase_tasks)
        model[phase] = {
            "n": n,
            "ms": sum(ms) // n,
            "input_records": csum(in_key) // n,
            "output_records": csum(out_keys[0]) // n,
            "output_bytes": (csum(out_keys[1]) // n) if out_keys[1]
            else 0,
        }
    return model


def _iter_json_objects(text: str):
    """A reference trace file is a STREAM of JSON objects (jackson's
    MappingIterator), optionally a single array — handle both."""
    text = text.lstrip()
    if text.startswith("["):
        yield from json.loads(text)
        return
    dec = json.JSONDecoder()
    pos = 0
    n = len(text)
    while pos < n:
        obj, end = dec.raw_decode(text, pos)
        yield obj
        pos = end
        while pos < n and text[pos] in " \r\n\t,":
            pos += 1


def load_reference_trace(text: str, container_mb: int = 1024,
                         tick_ms: int = 1000) -> List[Dict]:
    """Convert a trace written by the REFERENCE tooling into this
    framework's canonical trace, so an existing Hadoop deployment can
    replay its production workloads here unchanged. Two dialects are
    recognized per job object:

    - SLS input format (ref: hadoop-sls SLSRunner SLS json mode /
      RumenToSLSConverter output): ``am.type``, ``job.start.ms``,
      ``job.queue.name``, ``job.tasks[{container.start.ms, ...}]``.
    - rumen LoggedJob (ref: hadoop-rumen TraceBuilder output, the keys
      RumenToSLSConverter.java:164-211 reads): ``jobID``,
      ``submitTime``, ``mapTasks``/``reduceTasks`` with ``attempts``.

    Arrival ticks are normalized to the earliest job's start. Reference
    traces carry no counter-level load model, so entries replay as
    sleep jobs in gridmix (its documented degradation) while SLS gets
    full fidelity."""
    raw: List[Dict] = []
    for obj in _iter_json_objects(text):
        if not isinstance(obj, dict):
            continue
        if "job.tasks" in obj or "am.type" in obj:        # SLS dialect
            tasks = obj.get("job.tasks") or []
            durs = sorted(
                max(0, int(t.get("container.end.ms", 0)) -
                    int(t.get("container.start.ms", 0)))
                for t in tasks) or [0]
            start = obj.get("job.start.ms")
            raw.append({
                "job_id": str(obj.get("job.id", f"job_{len(raw)}")),
                "start_ms": int(start) if start is not None else None,
                "queue": obj.get("job.queue.name", "default"),
                "user": obj.get("job.user", "default"),
                "containers": max(1, len(tasks)),
                "maps": sum(1 for t in tasks
                            if t.get("container.type") != "reduce"),
                "reduces": sum(1 for t in tasks
                               if t.get("container.type") == "reduce"),
                "durs": durs,
            })
        elif "jobID" in obj or "submitTime" in obj:       # rumen dialect
            maps = obj.get("mapTasks") or []
            reds = obj.get("reduceTasks") or []

            def att_durs(tasks):
                out = []
                for t in tasks:
                    for a in (t.get("attempts") or []):
                        out.append(max(0, int(a.get("finishTime", 0)) -
                                       int(a.get("startTime", 0))))
                return out
            durs = sorted(att_durs(maps) + att_durs(reds)) or [0]
            start = obj.get("submitTime")
            raw.append({
                "job_id": str(obj.get("jobID", f"job_{len(raw)}")),
                "start_ms": int(start) if start is not None else None,
                "queue": obj.get("queue", "default"),
                "user": obj.get("user", "default"),
                "containers": max(1, len(maps) + len(reds)),
                "maps": len(maps),
                "reduces": len(reds),
                "durs": durs,
            })
    if not raw:
        return []
    # Normalize arrivals to the earliest EXPLICIT start: a job missing
    # its start key arrives at tick 0 rather than poisoning t0 (epoch-ms
    # jobs would otherwise land at ~1e9 ticks and never be submitted).
    known = [j["start_ms"] for j in raw if j["start_ms"] is not None]
    t0 = min(known) if known else 0
    jobs: List[Dict] = []
    for i, j in enumerate(sorted(
            raw, key=lambda x: (x["start_ms"] is None,
                                x["start_ms"] or 0))):
        durs = j.pop("durs")
        start = j.pop("start_ms")
        jobs.append({
            # the trace app field is an ATTEMPT id —
            # application_<ts>_<seq>_<attempt> (records.ApplicationId +
            # attempt, same shape SyntheticTrace emits); the ts field
            # is a deterministic digest of the job id so merged/
            # concatenated traces don't collide
            "app": f"application_{zlib.crc32(j['job_id'].encode())}"
                   f"_{i + 1:04d}_01",
            "arrival": 0 if start is None
            else (start - t0) // max(1, tick_ms),
            "mb": container_mb,
            "task_ms": {"mean": sum(durs) // len(durs),
                        "p50": durs[len(durs) // 2],
                        "max": durs[-1]},
            "state": "SUCCEEDED",
            **j,
        })
    return jobs


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="rumen")
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--fs", help="extract from a cluster's history dir")
    src.add_argument("--convert",
                     help="convert a reference SLS/rumen json trace file")
    ap.add_argument("--done-dir", default=history.DEFAULT_DONE_DIR)
    ap.add_argument("--out", default="-")
    args = ap.parse_args(argv)
    if args.convert:
        with open(args.convert) as f:
            trace = load_reference_trace(f.read())
    else:
        fs = FileSystem.get(args.fs, Configuration())
        try:
            trace = build_trace(fs, args.done_dir)
        finally:
            fs.close()
    body = json.dumps(trace, indent=2)
    if args.out == "-":
        print(body)
    else:
        with open(args.out, "w") as f:
            f.write(body)
        print(json.dumps({"jobs": len(trace), "out": args.out}))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
