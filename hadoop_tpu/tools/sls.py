"""SLS — scheduler load simulator: the REAL scheduler under synthetic load.

Parity with the reference simulator (ref: hadoop-tools/hadoop-sls/.../
SLSRunner.java:105 — it drives a real ResourceManager with simulated
NMs (NMSimulator) and AMs (AMSimulator) from job traces, reporting
scheduler throughput and allocation latency): here the real
``make_scheduler`` product (fifo/capacity/fair) is driven directly with
simulated node heartbeats and app request/ack cycles, and the report is
decisions/sec + time-to-first-allocation percentiles.

  python -m hadoop_tpu.tools.sls --nodes 500 --apps 50 --scheduler capacity
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional

from hadoop_tpu.conf import Configuration
from hadoop_tpu.yarn.records import (ApplicationId, ContainerId, NodeId,
                                     Resource, ResourceRequest)
from hadoop_tpu.yarn.scheduler import make_scheduler


class SyntheticTrace:
    """Jobs to replay: (arrival_tick, queue, num_containers, container_mb).
    The reference reads rumen/SLS json traces; the synthetic generator
    covers the same shape (ref: SLSRunner's SYNTH input mode)."""

    def __init__(self, num_apps: int, containers_per_app: int,
                 queues: List[str], arrival_spread: int):
        self.jobs = []
        for i in range(num_apps):
            self.jobs.append({
                "app": f"application_1_{i + 1}_01",
                "arrival": (i * arrival_spread) // max(num_apps, 1),
                "queue": queues[i % len(queues)],
                "containers": containers_per_app,
                "mb": 1024,
            })

    @classmethod
    def from_file(cls, path: str) -> "SyntheticTrace":
        self = cls.__new__(cls)
        with open(path) as f:
            self.jobs = json.load(f)
        return self


def run(num_nodes: int = 100, num_apps: int = 20,
        containers_per_app: int = 50, scheduler: str = "capacity",
        node_mb: int = 8192, ticks: int = 1000,
        trace: Optional[SyntheticTrace] = None,
        conf: Optional[Configuration] = None) -> Dict:
    """Tick-driven simulation: each tick every node heartbeats once and
    each live app drains its allocations (the AM allocate cycle)."""
    conf = conf or Configuration(load_defaults=False)
    conf.set_if_unset("yarn.resourcemanager.scheduler.class", scheduler)

    app_seq = {}

    def cid_factory(attempt_id, seq):
        parts = attempt_id.rsplit("_", 1)
        return ContainerId(ApplicationId.parse(parts[0]), int(parts[1]),
                           seq)

    sched = make_scheduler(conf, cid_factory)
    nodes = []
    for i in range(num_nodes):
        nid = NodeId(f"host{i:05d}", 9000)
        sched.add_node(nid, Resource(node_mb, 16), f"host{i:05d}:9000")
        nodes.append(nid)

    trace = trace or SyntheticTrace(
        num_apps, containers_per_app,
        queues=conf.get_list("sls.queues", ["default"]),
        arrival_spread=max(1, ticks // 4))

    pending = sorted(trace.jobs, key=lambda j: j["arrival"])
    live: Dict[str, Dict] = {}
    decisions = 0
    first_alloc_latency: List[int] = []
    t0 = time.perf_counter()
    tick = 0
    for tick in range(ticks):
        while pending and pending[0]["arrival"] <= tick:
            job = pending.pop(0)
            sched.add_app(job["app"], job["queue"], "sls")
            sched.allocate(job["app"], [ResourceRequest(
                1, job["containers"], Resource(job["mb"], 1))], [])
            live[job["app"]] = {"job": job, "got": 0, "start": tick,
                                "first": None}
        for nid in nodes:
            sched.node_heartbeat(nid)
        done = []
        for app_id, st in live.items():
            allocated, _ = sched.allocate(app_id, [], [])
            if allocated and st["first"] is None:
                st["first"] = tick
                first_alloc_latency.append(tick - st["start"])
            st["got"] += len(allocated)
            decisions += len(allocated)
            if st["got"] >= st["job"]["containers"]:
                done.append(app_id)
        for app_id in done:
            sched.remove_app(app_id)
            del live[app_id]
        if not pending and not live:
            break
    dt = time.perf_counter() - t0
    lat = sorted(first_alloc_latency)

    def pct(p):
        return lat[min(len(lat) - 1, int(p * len(lat)))] if lat else None
    return {
        "scheduler": scheduler,
        "nodes": num_nodes,
        "apps": len(trace.jobs),
        "containers_allocated": decisions,
        "ticks_used": tick + 1,
        "wall_seconds": round(dt, 3),
        "decisions_per_sec": round(decisions / dt, 1) if dt else 0.0,
        "first_alloc_latency_ticks": {
            "p50": pct(0.5), "p95": pct(0.95), "max": lat[-1] if lat else None},
        "unfinished_apps": len(live) + len(pending),
    }


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="sls")
    ap.add_argument("--nodes", type=int, default=100)
    ap.add_argument("--apps", type=int, default=20)
    ap.add_argument("--containers", type=int, default=50)
    ap.add_argument("--scheduler", default="capacity",
                    choices=["fifo", "capacity", "fair"])
    ap.add_argument("--ticks", type=int, default=1000)
    ap.add_argument("--trace", help="json trace file (SLS SYNTH shape)")
    args = ap.parse_args(argv)
    trace = SyntheticTrace.from_file(args.trace) if args.trace else None
    print(json.dumps(run(args.nodes, args.apps, args.containers,
                         args.scheduler, ticks=args.ticks, trace=trace)))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
