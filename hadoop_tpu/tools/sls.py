"""SLS — scheduler load simulator: the REAL scheduler under synthetic load.

Parity with the reference simulator (ref: hadoop-tools/hadoop-sls/.../
SLSRunner.java:105 — it drives a real ResourceManager with simulated
NMs (NMSimulator) and AMs (AMSimulator) from job traces, reporting
scheduler throughput and allocation latency): here the real
``make_scheduler`` product (fifo/capacity/fair) is driven directly with
simulated node heartbeats and app request/ack cycles, and the report is
decisions/sec + time-to-first-allocation percentiles.

  python -m hadoop_tpu.tools.sls --nodes 500 --apps 50 --scheduler capacity
"""

from __future__ import annotations

import json
import logging
import time
from typing import Dict, List, Optional

from hadoop_tpu.conf import Configuration
from hadoop_tpu.yarn.records import (ApplicationId, ContainerId, NodeId,
                                     Resource, ResourceRequest)
from hadoop_tpu.yarn.scheduler import make_scheduler

log = logging.getLogger(__name__)


class SyntheticTrace:
    """Jobs to replay: (arrival_tick, queue, num_containers, container_mb).
    The reference reads rumen/SLS json traces; the synthetic generator
    covers the same shape (ref: SLSRunner's SYNTH input mode)."""

    def __init__(self, num_apps: int, containers_per_app: int,
                 queues: List[str], arrival_spread: int):
        self.jobs = []
        for i in range(num_apps):
            self.jobs.append({
                "app": f"application_1_{i + 1}_01",
                "arrival": (i * arrival_spread) // max(num_apps, 1),
                "queue": queues[i % len(queues)],
                "containers": containers_per_app,
                "mb": 1024,
            })

    @classmethod
    def from_file(cls, path: str) -> "SyntheticTrace":
        self = cls.__new__(cls)
        with open(path) as f:
            self.jobs = json.load(f)
        return self


def run(num_nodes: int = 100, num_apps: int = 20,
        containers_per_app: int = 50, scheduler: str = "capacity",
        node_mb: int = 8192, ticks: int = 1000,
        trace: Optional[SyntheticTrace] = None,
        conf: Optional[Configuration] = None) -> Dict:
    """Tick-driven simulation: each tick every node heartbeats once and
    each live app drains its allocations (the AM allocate cycle)."""
    conf = conf or Configuration(load_defaults=False)
    conf.set_if_unset("yarn.resourcemanager.scheduler.class", scheduler)

    app_seq = {}

    def cid_factory(attempt_id, seq):
        parts = attempt_id.rsplit("_", 1)
        return ContainerId(ApplicationId.parse(parts[0]), int(parts[1]),
                           seq)

    sched = make_scheduler(conf, cid_factory)
    nodes = []
    for i in range(num_nodes):
        nid = NodeId(f"host{i:05d}", 9000)
        sched.add_node(nid, Resource(node_mb, 16), f"host{i:05d}:9000")
        nodes.append(nid)

    trace = trace or SyntheticTrace(
        num_apps, containers_per_app,
        queues=conf.get_list("sls.queues", ["default"]),
        arrival_spread=max(1, ticks // 4))

    pending = sorted(trace.jobs, key=lambda j: j["arrival"])
    live: Dict[str, Dict] = {}
    decisions = 0
    first_alloc_latency: List[int] = []
    t0 = time.perf_counter()
    tick = 0
    for tick in range(ticks):
        while pending and pending[0]["arrival"] <= tick:
            job = pending.pop(0)
            sched.add_app(job["app"], job["queue"], "sls")
            sched.allocate(job["app"], [ResourceRequest(
                1, job["containers"], Resource(job["mb"], 1))], [])
            live[job["app"]] = {"job": job, "got": 0, "start": tick,
                                "first": None}
        for nid in nodes:
            sched.node_heartbeat(nid)
        done = []
        for app_id, st in live.items():
            allocated, _ = sched.allocate(app_id, [], [])
            if allocated and st["first"] is None:
                st["first"] = tick
                first_alloc_latency.append(tick - st["start"])
            st["got"] += len(allocated)
            decisions += len(allocated)
            if st["got"] >= st["job"]["containers"]:
                done.append(app_id)
        for app_id in done:
            sched.remove_app(app_id)
            del live[app_id]
        if not pending and not live:
            break
    dt = time.perf_counter() - t0
    lat = sorted(first_alloc_latency)

    def pct(p):
        return lat[min(len(lat) - 1, int(p * len(lat)))] if lat else None
    return {
        "scheduler": scheduler,
        "nodes": num_nodes,
        "apps": len(trace.jobs),
        "containers_allocated": decisions,
        "ticks_used": tick + 1,
        "wall_seconds": round(dt, 3),
        "decisions_per_sec": round(decisions / dt, 1) if dt else 0.0,
        "first_alloc_latency_ticks": {
            "p50": pct(0.5), "p95": pct(0.95), "max": lat[-1] if lat else None},
        "unfinished_apps": len(live) + len(pending),
    }


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="sls")
    ap.add_argument("--nodes", type=int, default=100)
    ap.add_argument("--apps", type=int, default=20)
    ap.add_argument("--containers", type=int, default=50)
    ap.add_argument("--scheduler", default="capacity",
                    choices=["fifo", "capacity", "fair"])
    ap.add_argument("--ticks", type=int, default=1000)
    ap.add_argument("--trace", help="json trace file (SLS SYNTH shape)")
    args = ap.parse_args(argv)
    trace = SyntheticTrace.from_file(args.trace) if args.trace else None
    print(json.dumps(run(args.nodes, args.apps, args.containers,
                         args.scheduler, ticks=args.ticks, trace=trace)))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())


# --------------------------------------------------------------- RM mode

def run_rm(num_nodes: int = 1000, num_apps: int = 20,
           containers_per_app: int = 20, scheduler: str = "capacity",
           node_mb: int = 8192, sweeps: int = 30,
           register_threads: int = 16,
           conf: Optional[Configuration] = None) -> Dict:
    """Drive a REAL ResourceManager daemon over REAL RPC: ``num_nodes``
    simulated NodeManagers register + heartbeat (NMSimulator role), and
    per-app AM simulators register/allocate over AMRMProtocol
    (AMSimulator role) — the reference SLSRunner architecture, with the
    RM taken as a black box behind its three RPC services.

    All simulated NMs advertise ONE shared fake ContainerManager
    endpoint; AM "launches" land there, handing the attempt id to an AM
    simulator thread.
    """
    import queue as _queue
    import threading
    from concurrent.futures import ThreadPoolExecutor

    from hadoop_tpu.ipc import Client, Server, get_proxy
    from hadoop_tpu.yarn.client import YarnClient
    from hadoop_tpu.yarn.records import (ApplicationSubmissionContext,
                                         ContainerLaunchContext)
    from hadoop_tpu.yarn.rm import ResourceManager

    conf = conf or Configuration(load_defaults=False)
    conf.set_if_unset("yarn.resourcemanager.scheduler.class", scheduler)
    # Simulated NMs sweep in batches; generous liveness so a slow sweep
    # on a loaded host doesn't mark the fleet dead mid-run.
    conf.set_if_unset("yarn.nm.liveness-monitor.expiry-interval", "600")
    conf.set_if_unset("yarn.am.liveness-monitor.expiry-interval", "600")

    import tempfile
    state_dir = tempfile.mkdtemp(prefix="sls-rm-")
    rm = ResourceManager(conf, state_dir=state_dir)
    rm.init(conf)
    rm.start()

    launched: "_queue.Queue[str]" = _queue.Queue()

    class _FakeContainerManager:
        """Accepts every AM launch; surfaces the attempt id."""

        def start_container(self, container_wire: Dict,
                            ctx_wire: Dict) -> Dict:
            env = ctx_wire.get("e", {})
            att = env.get("HTPU_ATTEMPT_ID")
            if att:
                launched.put(att)
            return {"ok": True}

        def stop_container(self, container_id_wire: Dict) -> bool:
            return True

    fake_nm = Server(conf, num_handlers=2, name="sls-fake-nm")
    fake_nm.register_protocol("ContainerManagerProtocol",
                              _FakeContainerManager())
    fake_nm.start()
    nm_address = f"127.0.0.1:{fake_nm.port}"

    rpc = Client(conf)
    rm_addr = ("127.0.0.1", rm.port)
    tracker = get_proxy("ResourceTrackerProtocol", rm_addr, client=rpc)
    amrm = get_proxy("AMRMProtocol", rm_addr, client=rpc)

    results = {"heartbeats": 0, "allocated": 0}
    first_alloc_ms: List[float] = []
    submit_times: Dict[str, float] = {}
    res_lock = threading.Lock()

    try:
        nodes = [NodeId(f"host{i:05d}", 9000) for i in range(num_nodes)]
        pool = ThreadPoolExecutor(max_workers=register_threads)
        t_reg0 = time.perf_counter()
        list(pool.map(lambda nid: tracker.register_node_manager(
            nid.to_wire(), Resource(node_mb, 16).to_wire(), nm_address),
            nodes, chunksize=max(1, num_nodes // register_threads)))
        register_s = time.perf_counter() - t_reg0

        # AM simulator: register, ask, drain, finish.
        def am_sim(attempt_id: str) -> None:
            app_key = attempt_id.rsplit("_", 1)[0]
            amrm.register_application_master(attempt_id, "sls://")
            asks = [ResourceRequest(1, containers_per_app,
                                    Resource(1024, 1)).to_wire()]
            got = 0
            first = None
            deadline = time.monotonic() + 120.0
            resp = amrm.allocate(attempt_id, asks, [])
            while got < containers_per_app and \
                    time.monotonic() < deadline:
                n = len(resp["allocated"])
                if n and first is None:
                    first = time.perf_counter()
                got += n
                if got >= containers_per_app:
                    break
                time.sleep(0.05)
                resp = amrm.allocate(attempt_id, [], [])
            with res_lock:
                results["allocated"] += got
                if first is not None and app_key in submit_times:
                    first_alloc_ms.append(
                        (first - submit_times[app_key]) * 1000.0)
            amrm.finish_application_master(attempt_id, "SUCCEEDED")

        am_pool = ThreadPoolExecutor(max_workers=min(num_apps, 16))
        am_futures = []

        def am_dispatcher() -> None:
            seen = 0
            while seen < num_apps:
                try:
                    att = launched.get(timeout=60.0)
                except _queue.Empty:
                    return
                am_futures.append(am_pool.submit(am_sim, att))
                seen += 1

        dispatcher = threading.Thread(target=am_dispatcher, daemon=True)
        dispatcher.start()

        # Submit apps through the real client service.
        yc = YarnClient(rm_addr, conf)
        queues = conf.get_list("sls.queues", ["default"])
        t0 = time.perf_counter()
        for i in range(num_apps):
            app_id, _ = yc.create_application()
            ctx = ApplicationSubmissionContext(
                app_id, f"sls-app-{i}",
                ContainerLaunchContext(["true"], {}),
                am_resource=Resource(512, 1),
                queue=queues[i % len(queues)])
            submit_times[str(app_id)] = time.perf_counter()
            yc.submit_application(ctx, wait_accepted=False)

        # NM heartbeat sweeps: every simulated node, over real RPC.
        # Sweeps continue until every AM simulator drained its asks (the
        # scheduler only hands out containers at heartbeat time), with
        # ``sweeps`` as the MINIMUM measured and a wall-clock ceiling.
        hb_t0 = time.perf_counter()
        sweep_times = []
        target = num_apps * containers_per_app
        hb_deadline = hb_t0 + 180.0
        n_sweeps = 0
        while True:
            s0 = time.perf_counter()
            list(pool.map(lambda nid: tracker.node_heartbeat(
                nid.to_wire(), []), nodes,
                chunksize=max(1, num_nodes // register_threads)))
            sweep_times.append(time.perf_counter() - s0)
            n_sweeps += 1
            with res_lock:
                results["heartbeats"] += num_nodes
                got = results["allocated"]
            if n_sweeps >= sweeps and (got >= target
                                       or time.perf_counter()
                                       > hb_deadline):
                break
        hb_dt = time.perf_counter() - hb_t0

        dispatcher.join(timeout=30.0)
        for f in am_futures:
            f.result(timeout=60.0)
        total_dt = time.perf_counter() - t0

        lat = sorted(first_alloc_ms)

        def pct(p):
            return round(lat[min(len(lat) - 1, int(p * len(lat)))], 1) \
                if lat else None

        return {
            "mode": "rm-rpc",
            "scheduler": scheduler,
            "nodes": num_nodes,
            "apps": num_apps,
            "node_register_seconds": round(register_s, 2),
            "heartbeats": results["heartbeats"],
            "heartbeats_per_sec": round(results["heartbeats"] / hb_dt, 1)
            if hb_dt else 0.0,
            "heartbeat_sweep_p50_s": round(
                sorted(sweep_times)[len(sweep_times) // 2], 3)
            if sweep_times else None,
            "containers_allocated": results["allocated"],
            "decisions_per_sec": round(results["allocated"] / total_dt, 1)
            if total_dt else 0.0,
            "first_alloc_latency_ms": {
                "p50": pct(0.5), "p95": pct(0.95),
                "max": round(lat[-1], 1) if lat else None},
            "wall_seconds": round(total_dt, 2),
        }
    finally:
        try:
            yc.close()
        except (OSError, RuntimeError) as e:
            log.debug("yarn client close failed: %s", e)
        for p in ("pool", "am_pool"):
            ex = locals().get(p)
            if ex is not None:
                ex.shutdown(wait=False)
        rpc.stop()
        fake_nm.stop()
        rm.stop()
        import shutil as _shutil
        _shutil.rmtree(state_dir, ignore_errors=True)
