"""Streaming — run mappers/reducers as external processes.

Parity with the reference bridge (ref: hadoop-tools/hadoop-streaming
(14 K LoC) — PipeMapper/PipeReducer feed records over the child's
stdin/stdout as ``key<TAB>value`` lines; StreamJob wires the conf): user
commands see exactly that contract here. A pump thread feeds stdin while
the task thread consumes parsed stdout lines, so arbitrarily large
streams flow with bounded buffering (the reference's
PipedInputStream/OutputStream pair).

  streaming_job(rm, fs, input, output, mapper="/bin/sed -e s/a/b/",
                reducer="/usr/bin/wc -l")   # reducer optional (map-only)

Line protocol (ref: streaming's KeyValueTextInputFormat defaults): a
mapper input line is ``key\\tvalue``; output lines split on the first tab
(no tab → whole line is the key, empty value). The reducer sees its
group's lines contiguously, key-sorted — identical to the reference.
"""

from __future__ import annotations

import logging
import shlex
import subprocess
import threading
from typing import Iterator, List, Optional, Tuple

from hadoop_tpu.mapreduce.api import Mapper, Reducer

log = logging.getLogger(__name__)


def _parse_line(line: bytes) -> Tuple[bytes, bytes]:
    key, sep, val = line.partition(b"\t")
    return key, val


class _Pipe:
    """One external process with a stdin pump and a stdout line reader."""

    def __init__(self, command: str):
        self.proc = subprocess.Popen(
            shlex.split(command), stdin=subprocess.PIPE,
            stdout=subprocess.PIPE, bufsize=1 << 20)
        self._out_lines: List[bytes] = []
        self._out_done = threading.Event()
        self._reader = threading.Thread(target=self._drain, daemon=True)
        self._reader.start()

    def _drain(self) -> None:
        try:
            for line in self.proc.stdout:
                self._out_lines.append(line.rstrip(b"\n"))
        finally:
            self._out_done.set()

    def feed(self, line: bytes) -> None:
        self.proc.stdin.write(line + b"\n")

    def finish(self, timeout: float = 60.0) -> List[bytes]:
        self.proc.stdin.close()
        if not self._out_done.wait(timeout):
            self.proc.kill()
            raise IOError("streaming child produced no EOF in time")
        rc = self.proc.wait(timeout=timeout)
        if rc != 0:
            raise IOError(f"streaming child exited {rc}")
        return self._out_lines


class StreamMapper(Mapper):
    """Ref: streaming PipeMapper. Feeds every record, emits every output
    line once the child closes (simple batch contract — the child is
    line-buffered and free-running, so memory is bounded by its output)."""

    def setup(self, ctx):
        self._pipe = _Pipe(ctx.conf["stream.map.command"])

    def map(self, key: bytes, value: bytes, ctx) -> None:
        self._pipe.feed(value if not key else key + b"\t" + value)

    def cleanup(self, ctx):
        for line in self._pipe.finish():
            k, v = _parse_line(line)
            ctx.emit(k, v)


class TextValueStreamMapper(StreamMapper):
    """Text-input convenience: feed only the line (TextInputFormat keys
    are byte offsets, which streaming children don't want)."""

    def map(self, key: bytes, value: bytes, ctx) -> None:
        self._pipe.feed(value)


class StreamReducer(Reducer):
    """Ref: streaming PipeReducer — the child sees the sorted
    ``key\\tvalue`` stream with groups contiguous."""

    def setup(self, ctx):
        self._pipe = _Pipe(ctx.conf["stream.reduce.command"])

    def reduce(self, key: bytes, values: Iterator[bytes], ctx) -> None:
        for v in values:
            self._pipe.feed(key + b"\t" + v)

    def cleanup(self, ctx):
        for line in self._pipe.finish():
            k, v = _parse_line(line)
            ctx.emit(k, v)


def streaming_job(rm_addr, default_fs: str, input_path: str,
                  output_path: str, *, mapper: str,
                  reducer: Optional[str] = None, num_reduces: int = 1):
    """Build the streaming Job. Ref: StreamJob.setJobConf."""
    from hadoop_tpu.mapreduce import Job
    from hadoop_tpu.mapreduce.api import class_ref
    job = (Job(rm_addr, default_fs, name="streamjob")
           .set_mapper(class_ref(TextValueStreamMapper))
           .add_input_path(input_path)
           .set_output_path(output_path)
           .set("stream.map.command", mapper))
    if reducer:
        job.set_reducer(class_ref(StreamReducer)) \
           .set("stream.reduce.command", reducer) \
           .set_num_reduces(num_reduces)
    else:
        job.set_num_reduces(0)
    return job


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import json
    ap = argparse.ArgumentParser(prog="streaming")
    ap.add_argument("--rm", required=True)
    ap.add_argument("--fs", required=True)
    ap.add_argument("--input", required=True)
    ap.add_argument("--output", required=True)
    ap.add_argument("--mapper", required=True)
    ap.add_argument("--reducer")
    ap.add_argument("--reduces", type=int, default=1)
    args = ap.parse_args(argv)
    host, _, port = args.rm.rpartition(":")
    job = streaming_job((host, int(port)), args.fs, args.input,
                        args.output, mapper=args.mapper,
                        reducer=args.reducer, num_reduces=args.reduces)
    ok = job.wait_for_completion()
    print(json.dumps({"ok": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    import sys
    sys.exit(main())
