from hadoop_tpu.tracing.tracer import (Span, SpanContext, Tracer,
                                       carry_context, current_context,
                                       current_span, global_tracer)

__all__ = ["Tracer", "Span", "SpanContext", "current_span",
           "current_context", "carry_context", "global_tracer"]
