from hadoop_tpu.tracing.tracer import Tracer, Span, SpanContext, current_span

__all__ = ["Tracer", "Span", "SpanContext", "current_span"]
