"""Per-process span collector: bounded ring buffer + slow-trace flight
recorder behind every daemon's ``/ws/v1/traces`` endpoints.

The tracer's in-memory ``finished`` list is a test convenience; this is
the production receiver: finished spans land in a bounded ring (drops
counted, never blocking the hot path), and any span whose duration
crosses its conf-keyed slow threshold promotes its WHOLE trace — every
buffered span sharing the trace id — into a retained flight-recorder
buffer and logs one structured line. The flight recorder is how a slow
``/v1/generate`` or a stalled training step keeps its cross-plane
evidence (the DataNode hop, the collective, the checkpoint fence) after
the ring has churned past it.

Thresholds (milliseconds; 0 disables a rule):

  ``tracing.slow.rpc.ms``      RPC handler + client spans   (default 300)
  ``tracing.slow.xceiver.ms``  ``dfs.xceiver.*`` block ops  (default 500)
  ``tracing.slow.step.ms``     ``trainer.step*``            (default 1000)
  ``tracing.slow.serving.ms``  ``serving.*`` door/engine    (default 1000)

Sizing: ``tracing.collector.max-spans`` (ring, default 4096) and
``tracing.flight.max-traces`` (retained slow traces, default 32).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from hadoop_tpu.tracing.tracer import Span, Tracer, global_tracer

log = logging.getLogger(__name__)

# span-name prefix → (conf key, default ms); first match wins, the rpc
# rule is the catch-all (RPC server spans are named <daemon>.<method>).
# Long-by-design bulk spans get their OWN rules so routine checkpoint
# writes / multi-packet client reads don't trip the 300 ms RPC rule and
# churn the flight recorder with expected traffic.
_THRESHOLD_RULES = (
    ("dfs.xceiver.", "tracing.slow.xceiver.ms", 500.0),
    ("dfs.client.", "tracing.slow.client.ms", 2000.0),
    ("trainer.ckpt.", "tracing.slow.ckpt.ms", 30000.0),
    ("trainer.step", "tracing.slow.step.ms", 1000.0),
    ("serving.", "tracing.slow.serving.ms", 1000.0),
    ("", "tracing.slow.rpc.ms", 300.0),
)


class SpanCollector:
    """Bounded ring of finished spans + flight recorder of slow traces."""

    def __init__(self, max_spans: int = 4096, max_traces: int = 32):
        self._lock = threading.Lock()
        self.max_spans = max_spans
        self._ring: deque = deque(maxlen=max_spans)   # guarded-by: _lock
        self.dropped = 0                              # guarded-by: _lock
        self._slow: deque = deque(maxlen=max_traces)  # guarded-by: _lock
        self.slow_promoted = 0                        # guarded-by: _lock
        # conf key → ms, resolved through configure(); starts at defaults
        self._thresholds: Dict[str, float] = {
            key: default for _, key, default in _THRESHOLD_RULES}

    # ------------------------------------------------------------- config

    def configure(self, conf) -> None:
        """Resolve thresholds and sizes from a daemon's Configuration.
        Process-global like the tracer itself: the last daemon to start
        in a shared-process minicluster wins, which is fine — they share
        one conf lineage."""
        for _, key, default in _THRESHOLD_RULES:
            self._thresholds[key] = conf.get_float(key, default)
        max_spans = conf.get_int("tracing.collector.max-spans",
                                 self.max_spans)
        if max_spans != self.max_spans:
            with self._lock:
                self.max_spans = max_spans
                self._ring = deque(self._ring, maxlen=max_spans)
        with self._lock:
            cur_max = self._slow.maxlen
        max_traces = conf.get_int("tracing.flight.max-traces", cur_max)
        if max_traces != cur_max:
            with self._lock:
                self._slow = deque(self._slow, maxlen=max_traces)

    def threshold_ms_for(self, name: str) -> float:
        for prefix, key, _ in _THRESHOLD_RULES:
            if name.startswith(prefix):
                return self._thresholds[key]
        return self._thresholds["tracing.slow.rpc.ms"]

    # ----------------------------------------------------------- receiver

    def receive(self, span: Span) -> None:
        """Tracer receiver: ring-buffer the span; promote its trace when
        it crossed the slow threshold."""
        ms = span.duration_ms()
        threshold = self.threshold_ms_for(span.name)
        slow = 0 < threshold <= ms
        retained = 0
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(span)
            if slow:
                trace = [s for s in self._ring
                         if s.trace_id == span.trace_id]
                # one slot per TRACE: a multi-plane trace whose client
                # read, xceiver hop, and ckpt write all trip their
                # thresholds must refresh one entry, not occupy three
                # of the few retained slots (evicting distinct traces)
                existing = next((t for t in self._slow
                                 if t["trace_id"] == span.trace_id),
                                None)
                spans = [s.to_dict() for s in trace]
                if existing is not None:
                    # merge: spans the ring already churned past live
                    # only in the retained entry — keep them
                    seen = {s["span_id"] for s in spans}
                    spans = [s for s in existing["spans"]
                             if s["span_id"] not in seen] + spans
                    self._slow.remove(existing)
                self._slow.append({
                    "trace_id": span.trace_id,
                    "trigger": span.name,
                    "trigger_ms": round(ms, 2),
                    "threshold_ms": threshold,
                    "retained_at": time.time(),
                    "spans": spans,
                })
                if existing is None:
                    self.slow_promoted += 1
                retained = len(spans)
        if slow:
            # exactly one structured line per promotion — greppable,
            # join-able on trace_id with every other daemon's log
            log.warning(
                "slow-trace trace_id=%016x trigger=%s ms=%.1f "
                "threshold_ms=%.0f spans_retained=%d",
                span.trace_id, span.name, ms, threshold, retained)

    # ------------------------------------------------------------ queries

    def snapshot(self, trace_id=None, limit: int = 0) -> Dict:
        """``trace_id``: one id or a collection of candidate ids (the
        HTTP handler passes both the decimal and hex readings of an
        ambiguous query string)."""
        with self._lock:
            spans = list(self._ring)
            dropped = self.dropped
        if trace_id is not None:
            wanted = (set(trace_id) if isinstance(trace_id, (set, list,
                                                             tuple))
                      else {trace_id})
            spans = [s for s in spans if s.trace_id in wanted]
        if limit > 0:
            spans = spans[-limit:]
        return {"spans": [s.to_dict() for s in spans],
                "dropped": dropped, "max_spans": self.max_spans}

    def slow_traces(self) -> Dict:
        with self._lock:
            return {"traces": list(self._slow),
                    "promoted": self.slow_promoted,
                    "max_traces": self._slow.maxlen}

    def reset_for_tests(self) -> None:
        with self._lock:
            self._ring.clear()
            self._slow.clear()
            self.dropped = 0
            self.slow_promoted = 0
            # a prior test's conf (e.g. a near-zero serving threshold)
            # must not leak promotions into later tests
            self._thresholds = {key: default
                                for _, key, default in _THRESHOLD_RULES}


_collector: Optional[SpanCollector] = None
_collector_lock = threading.Lock()


def span_collector(tracer: Optional[Tracer] = None) -> SpanCollector:
    """Process-wide collector, installed as a receiver on the global
    tracer (or ``tracer``) on first use."""
    global _collector
    with _collector_lock:
        if _collector is None:
            _collector = SpanCollector()
            (tracer or global_tracer()).add_receiver(_collector.receive)
        return _collector
