"""Distributed tracing: spans created client-side, propagated in RPC headers,
resumed server-side around handler execution.

Capability parity with the reference's HTrace-4 integration (ref:
hadoop-common/pom.xml:286-287; span creation hdfs/DFSClient.java:1563;
propagation ipc/Server.java:121-123 SpanId in RPC headers; runtime-configurable
receivers tracing/TracerConfigurationManager.java, TraceAdmin.java).

A Span carries (trace_id, span_id, parent_id, sampled); the active span lives
in a contextvar so nested ``with tracer.span(...)`` calls parent correctly
across threads spawned with the span-aware helpers below (``carry_context``
wraps a callable so the spawning thread's active span survives into the new
thread — the seam the async checkpoint writer and hedged-read pool ride).

Sampling is decided ONCE, at root-span creation, and the verdict travels in
``SpanContext`` across every wire hop — children (local or remote) inherit
it, so a trace is delivered all-or-nothing. (The seed flipped a coin per
*finished* span in ``_deliver``, which shredded every trace at
sample_rate < 1.0: each span of one trace was kept or dropped
independently.)

Receivers are callables fed finished spans; the in-memory list backs tests
and ``tracing.collector.SpanCollector`` is the production receiver behind
``/ws/v1/traces``.
"""

from __future__ import annotations

import contextvars
import logging
import random
import threading
import time
from typing import Callable, Dict, List, Optional

log = logging.getLogger(__name__)

_active: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "htpu_active_span", default=None)


class SpanContext:
    """Wire form of a span: what travels in RPC / data-transfer / HTTP
    headers. ``sampled`` is the root's sampling verdict — every hop
    honors it instead of re-rolling."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: int, span_id: int, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    def to_wire(self) -> Dict[str, int]:
        return {"t": self.trace_id, "s": self.span_id,
                "sm": 1 if self.sampled else 0}

    @classmethod
    def from_wire(cls, d: Optional[Dict[str, int]]) -> Optional["SpanContext"]:
        if not d:
            return None
        # pre-sampled-bit peers omit "sm": treat as sampled (the old
        # behavior for a delivered context)
        return cls(d["t"], d["s"], bool(d.get("sm", 1)))

    def to_header(self) -> str:
        """Compact HTTP-header form (``X-Htpu-Trace``)."""
        return f"{self.trace_id:x}:{self.span_id:x}:{int(self.sampled)}"

    @classmethod
    def from_header(cls, h: Optional[str]) -> Optional["SpanContext"]:
        if not h:
            return None
        try:
            t, s, sm = h.split(":")
            return cls(int(t, 16), int(s, 16), sm != "0")
        except (ValueError, AttributeError):
            return None


class Span:
    def __init__(self, tracer: "Tracer", name: str, trace_id: int,
                 parent_id: Optional[int], sampled: bool = True):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = random.getrandbits(63)
        self.parent_id = parent_id
        self.sampled = sampled
        self.start = time.time()
        self.end: Optional[float] = None
        self.annotations: List[str] = []
        self.kv: Dict[str, str] = {}
        self._token = None

    def annotate(self, msg: str) -> None:
        self.annotations.append(msg)

    def add_kv(self, k: str, v: str) -> None:
        self.kv[k] = v

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id, self.sampled)

    def duration_ms(self) -> float:
        return ((self.end if self.end is not None else time.time())
                - self.start) * 1e3

    def __enter__(self) -> "Span":
        self._token = _active.set(self)
        return self

    def __exit__(self, *exc) -> bool:
        self.finish()
        return False

    def finish(self) -> None:
        if self.end is None:
            self.end = time.time()
            if self._token is not None:
                _active.reset(self._token)
                self._token = None
            self.tracer._deliver(self)

    def to_dict(self) -> Dict:
        return {
            "name": self.name, "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id, "start": self.start, "end": self.end,
            "annotations": list(self.annotations), "kv": dict(self.kv),
        }


def parse_trace_id_candidates(raw: str) -> List[int]:
    """THE reading of a user-supplied trace id, shared by every query
    surface (per-daemon ``/ws/v1/traces?trace_id=``, the fleet
    doctor's ``/ws/v1/fleet/traces/<id>``): an explicit ``0x`` form is
    hex; an ambiguous all-digit string is tried as BOTH hex and
    decimal — span JSON prints ids decimal while the slow-trace log
    line and fleet endpoints print ``016x``, and either paste must
    resolve. Hex first (the printed fleet form); callers that filter
    by membership treat the result as a set. Empty list = unparseable."""
    raw = raw.strip().lower()
    base16 = raw[2:] if raw.startswith("0x") else raw
    bases = ((16, base16),) if raw.startswith("0x") \
        else ((16, base16), (10, raw))
    out: List[int] = []
    for base, s in bases:
        try:
            v = int(s, base)
        except ValueError:
            continue
        if v not in out:
            out.append(v)
    return out


def current_span() -> Optional[Span]:
    return _active.get()


def current_context() -> Optional[SpanContext]:
    """Wire context of the active span, if any — what a client attaches
    to an outgoing RPC / data-transfer op / HTTP request."""
    sp = _active.get()
    return sp.context() if sp is not None else None


def carry_context(fn: Callable) -> Callable:
    """Span-aware thread seam: capture the CALLER's contextvars (incl.
    the active span) and run ``fn`` under them in whatever thread
    eventually calls the wrapper. Spans created inside the target
    thread then parent into the spawning trace instead of starting
    orphan roots — the helper behind the async checkpoint writer and
    the hedged-read pool (the async seams ISSUE 4 opened)."""
    ctx = contextvars.copy_context()

    def run(*args, **kwargs):
        return ctx.run(fn, *args, **kwargs)
    return run


class Tracer:
    """Per-process tracer with root-decided sampling and pluggable
    receivers."""

    def __init__(self, name: str = "htpu", sample_rate: float = 1.0,
                 rng: Optional[random.Random] = None):
        self.name = name
        self.sample_rate = sample_rate
        self._rng = rng or random
        self._receivers: List[Callable[[Span], None]] = []
        self._lock = threading.Lock()
        self.finished: List[Span] = []  # in-memory receiver (tests, /tracing)
        self._keep_in_memory = True
        self.max_kept = 1000

    def add_receiver(self, fn: Callable[[Span], None]) -> None:
        with self._lock:
            self._receivers.append(fn)

    def span(self, name: str, parent: Optional[SpanContext] = None) -> Span:
        """New span: child of ``parent`` (wire context), else of the active
        span, else a new trace root. Children inherit the root's sampling
        verdict; only a ROOT rolls the dice — so a trace is delivered
        all-or-nothing. Unsampled traces still produce Span objects
        (cheap) but aren't delivered."""
        cur = _active.get()
        if parent is not None:
            return Span(self, name, parent.trace_id, parent.span_id,
                        sampled=parent.sampled)
        if cur is not None:
            return Span(self, name, cur.trace_id, cur.span_id,
                        sampled=cur.sampled)
        sampled = (self.sample_rate >= 1.0 or
                   self._rng.random() < self.sample_rate)
        return Span(self, name, random.getrandbits(63), None,
                    sampled=sampled)

    def _deliver(self, span: Span) -> None:
        if not span.sampled:
            return
        with self._lock:
            if self._keep_in_memory:
                self.finished.append(span)
                if len(self.finished) > self.max_kept:
                    del self.finished[: len(self.finished) // 2]
            receivers = list(self._receivers)
        for r in receivers:
            try:
                r(span)
            except Exception as e:  # noqa: BLE001 — receiver is user code
                log.debug("span receiver %r failed: %s", r, e)

    def set_sample_rate(self, rate: float) -> None:
        """Runtime reconfiguration (ref: TracerConfigurationManager)."""
        self.sample_rate = rate


_global_tracer = Tracer()


def global_tracer() -> Tracer:
    return _global_tracer
