"""Distributed tracing: spans created client-side, propagated in RPC headers,
resumed server-side around handler execution.

Capability parity with the reference's HTrace-4 integration (ref:
hadoop-common/pom.xml:286-287; span creation hdfs/DFSClient.java:1563;
propagation ipc/Server.java:121-123 SpanId in RPC headers; runtime-configurable
receivers tracing/TracerConfigurationManager.java, TraceAdmin.java).

A Span carries (trace_id, span_id, parent_id); the active span lives in a
contextvar so nested ``with tracer.span(...)`` calls parent correctly across
threads spawned with Span-aware helpers. Receivers are callables fed finished
spans; the default in-memory receiver backs tests and the /tracing endpoint.
"""

from __future__ import annotations

import contextvars
import logging
import random
import threading
import time
from typing import Callable, Dict, List, Optional

log = logging.getLogger(__name__)

_active: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "htpu_active_span", default=None)


class SpanContext:
    """Wire form of a span: what travels in RPC headers."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: int, span_id: int):
        self.trace_id = trace_id
        self.span_id = span_id

    def to_wire(self) -> Dict[str, int]:
        return {"t": self.trace_id, "s": self.span_id}

    @classmethod
    def from_wire(cls, d: Optional[Dict[str, int]]) -> Optional["SpanContext"]:
        if not d:
            return None
        return cls(d["t"], d["s"])


class Span:
    def __init__(self, tracer: "Tracer", name: str, trace_id: int,
                 parent_id: Optional[int]):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = random.getrandbits(63)
        self.parent_id = parent_id
        self.start = time.time()
        self.end: Optional[float] = None
        self.annotations: List[str] = []
        self.kv: Dict[str, str] = {}
        self._token = None

    def annotate(self, msg: str) -> None:
        self.annotations.append(msg)

    def add_kv(self, k: str, v: str) -> None:
        self.kv[k] = v

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def __enter__(self) -> "Span":
        self._token = _active.set(self)
        return self

    def __exit__(self, *exc) -> bool:
        self.finish()
        return False

    def finish(self) -> None:
        if self.end is None:
            self.end = time.time()
            if self._token is not None:
                _active.reset(self._token)
                self._token = None
            self.tracer._deliver(self)

    def to_dict(self) -> Dict:
        return {
            "name": self.name, "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id, "start": self.start, "end": self.end,
            "annotations": list(self.annotations), "kv": dict(self.kv),
        }


def current_span() -> Optional[Span]:
    return _active.get()


class Tracer:
    """Per-process tracer with sampling and pluggable receivers."""

    def __init__(self, name: str = "htpu", sample_rate: float = 1.0):
        self.name = name
        self.sample_rate = sample_rate
        self._receivers: List[Callable[[Span], None]] = []
        self._lock = threading.Lock()
        self.finished: List[Span] = []  # in-memory receiver (tests, /tracing)
        self._keep_in_memory = True
        self.max_kept = 1000

    def add_receiver(self, fn: Callable[[Span], None]) -> None:
        with self._lock:
            self._receivers.append(fn)

    def span(self, name: str, parent: Optional[SpanContext] = None) -> Span:
        """New span: child of ``parent`` (wire context), else of the active
        span, else a new trace root. Unsampled traces still produce Span
        objects (cheap) but aren't delivered."""
        cur = _active.get()
        if parent is not None:
            return Span(self, name, parent.trace_id, parent.span_id)
        if cur is not None:
            return Span(self, name, cur.trace_id, cur.span_id)
        return Span(self, name, random.getrandbits(63), None)

    def _deliver(self, span: Span) -> None:
        if self.sample_rate < 1.0 and random.random() > self.sample_rate:
            return
        with self._lock:
            if self._keep_in_memory:
                self.finished.append(span)
                if len(self.finished) > self.max_kept:
                    del self.finished[: len(self.finished) // 2]
            receivers = list(self._receivers)
        for r in receivers:
            try:
                r(span)
            except Exception as e:  # noqa: BLE001 — receiver is user code
                log.debug("span receiver %r failed: %s", r, e)

    def set_sample_rate(self, rate: float) -> None:
        """Runtime reconfiguration (ref: TracerConfigurationManager)."""
        self.sample_rate = rate


_global_tracer = Tracer()


def global_tracer() -> Tracer:
    return _global_tracer
