from hadoop_tpu.util.crc import crc32c, DataChecksum
from hadoop_tpu.util.misc import Daemon, free_port, StopWatch, PauseMonitor

__all__ = ["crc32c", "DataChecksum", "Daemon", "free_port", "StopWatch", "PauseMonitor"]
