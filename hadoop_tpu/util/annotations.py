"""API-stability classification decorators.

Parity with hadoop-annotations (ref: hadoop-common-project/
hadoop-annotations/src/main/java/org/apache/hadoop/classification/
InterfaceAudience.java + InterfaceStability.java — every public Hadoop
class declares who may depend on it and how much it may change between
releases; docs and compat checkers key off the annotations).

Python rendition: decorators that stamp ``_api_audience`` /
``_api_stability`` on the object and record it in a registry, so a
compat report (``api_report()``) can enumerate the public surface —
the role the reference's annotation processor plays at build time.

    from hadoop_tpu.util.annotations import audience, stability

    @audience.public
    @stability.stable
    class FileSystem: ...
"""

from __future__ import annotations

from typing import Dict, List, Tuple

_REGISTRY: Dict[str, Tuple[str, str]] = {}


def _qualname(obj) -> str:
    mod = getattr(obj, "__module__", "?")
    return f"{mod}.{getattr(obj, '__qualname__', repr(obj))}"


def _stamp(obj, key: str, value: str):
    setattr(obj, f"_api_{key}", value)
    name = _qualname(obj)
    aud, stab = _REGISTRY.get(name, ("", ""))
    _REGISTRY[name] = (value, stab) if key == "audience" else (aud, value)
    return obj


class audience:
    """Who may depend on this API (ref: InterfaceAudience)."""

    @staticmethod
    def public(obj):
        return _stamp(obj, "audience", "Public")

    @staticmethod
    def limited_private(*projects: str):
        def deco(obj):
            return _stamp(obj, "audience",
                          f"LimitedPrivate({','.join(projects)})")
        return deco

    @staticmethod
    def private(obj):
        return _stamp(obj, "audience", "Private")


class stability:
    """How much this API may change (ref: InterfaceStability)."""

    @staticmethod
    def stable(obj):
        return _stamp(obj, "stability", "Stable")

    @staticmethod
    def evolving(obj):
        return _stamp(obj, "stability", "Evolving")

    @staticmethod
    def unstable(obj):
        return _stamp(obj, "stability", "Unstable")


def api_report() -> List[Dict[str, str]]:
    """The annotated public surface, for compat tooling/docs."""
    return [{"name": name, "audience": aud or "Private",
             "stability": stab or "Unstable"}
            for name, (aud, stab) in sorted(_REGISTRY.items())]
