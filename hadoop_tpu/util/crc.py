"""Bulk CRC32C checksums for the storage data plane.

Role parity with the reference's native CRC (ref:
hadoop-common/src/main/native/src/org/apache/hadoop/util/bulk_crc32.c,
NativeCrc32.c; Java wrapper util/DataChecksum.java): every storage packet
carries one CRC per 512-byte chunk, verified at each pipeline hop.

Backend selection mirrors the optional-native policy (BUILDING.txt:173-183):
1. libhadoop_tpu.so (C++ slice-by-8, built from hadoop_tpu/native/) via ctypes
2. pure-Python table-driven fallback (slow, always available)
"""

from __future__ import annotations

import struct

from hadoop_tpu import native as _nat

_CASTAGNOLI = 0x82F63B78


def native_available() -> bool:
    return _nat.available()


# ---------------------------------------------------------------- pure python

def _make_table():
    tbl = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ _CASTAGNOLI if c & 1 else c >> 1
        tbl.append(c)
    return tbl


_TABLE = _make_table()


def _crc32c_py(crc: int, data: bytes) -> int:
    crc ^= 0xFFFFFFFF
    tbl = _TABLE
    for b in data:
        crc = tbl[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def crc32c(data, crc: int = 0) -> int:
    """CRC32C (Castagnoli) of ``data``, continuing from ``crc``."""
    if isinstance(data, memoryview):
        data = bytes(data)
    if _nat.available():
        return _nat.crc32c(crc, data)
    return _crc32c_py(crc, data)


class ChecksumError(IOError):
    def __init__(self, msg: str, pos: int = -1):
        super().__init__(msg)
        self.pos = pos


class DataChecksum:
    """Chunked checksum codec: one u32 CRC32C per ``bytes_per_chunk`` bytes.

    Ref: util/DataChecksum.java — the object every packet-level producer and
    verifier shares (BlockReceiver, BlockSender, FSOutputSummer).
    """

    HEADER_LEN = 5  # type byte + u32 bytes_per_chunk, ref: DataChecksum.getHeader

    TYPE_NULL = 0
    TYPE_CRC32C = 2

    def __init__(self, bytes_per_chunk: int = 512, ctype: int = TYPE_CRC32C):
        if bytes_per_chunk <= 0:
            raise ValueError("bytes_per_chunk must be positive")
        self.bytes_per_chunk = bytes_per_chunk
        self.type = ctype

    @property
    def checksum_size(self) -> int:
        return 0 if self.type == self.TYPE_NULL else 4

    def header(self) -> bytes:
        return struct.pack(">BI", self.type, self.bytes_per_chunk)

    @classmethod
    def from_header(cls, hdr: bytes) -> "DataChecksum":
        t, bpc = struct.unpack(">BI", hdr[:5])
        return cls(bpc, t)

    def checksums_for(self, data) -> bytes:
        """Concatenated big-endian u32 CRCs, one per chunk of ``data``."""
        if self.type == self.TYPE_NULL:
            return b""
        if _nat.available():
            buf = bytes(data) if isinstance(data, memoryview) else data
            return _nat.crc32c_chunked(buf, self.bytes_per_chunk)
        mv = memoryview(data)
        out = bytearray()
        for off in range(0, len(mv), self.bytes_per_chunk):
            c = crc32c(mv[off:off + self.bytes_per_chunk])
            out += struct.pack(">I", c)
        return bytes(out)

    def verify(self, data, sums: bytes, base_pos: int = 0) -> None:
        """Raise ChecksumError at the first corrupt chunk.
        Ref: DataChecksum.verifyChunkedSums."""
        if self.type == self.TYPE_NULL:
            return
        mv = memoryview(data)
        n_chunks = (len(mv) + self.bytes_per_chunk - 1) // self.bytes_per_chunk
        if len(sums) < 4 * n_chunks:
            raise ChecksumError(
                f"need {4 * n_chunks} checksum bytes, got {len(sums)}")
        if _nat.available():
            buf = data if isinstance(data, bytes) else bytes(mv)
            bad = _nat.crc32c_verify(buf, self.bytes_per_chunk, sums)
            if bad >= 0:
                off = bad * self.bytes_per_chunk
                expect = struct.unpack_from(">I", sums, 4 * bad)[0]
                actual = crc32c(buf[off:off + self.bytes_per_chunk])
                raise ChecksumError(
                    f"checksum mismatch at chunk {bad} "
                    f"(stream offset {base_pos + off}): "
                    f"expected {expect:#010x} got {actual:#010x}",
                    pos=base_pos + off)
            return
        for i in range(n_chunks):
            off = i * self.bytes_per_chunk
            expect = struct.unpack_from(">I", sums, 4 * i)[0]
            actual = crc32c(mv[off:off + self.bytes_per_chunk])
            if actual != expect:
                raise ChecksumError(
                    f"checksum mismatch at chunk {i} "
                    f"(stream offset {base_pos + off}): "
                    f"expected {expect:#010x} got {actual:#010x}",
                    pos=base_pos + off)
