"""Small shared utilities.

Ref analogs: util/Daemon.java (daemon threads), util/StopWatch.java,
util/JvmPauseMonitor.java:47 (here: a GC/GIL stall detector based on wall-clock
drift of a sleeper thread), NetUtils (ephemeral port helpers).
"""

from __future__ import annotations

import logging
import socket
import threading
import time
from typing import Callable, List, Optional

log = logging.getLogger(__name__)


class Daemon(threading.Thread):
    """Named daemon thread. Ref: util/Daemon.java."""

    def __init__(self, target: Callable, name: str, args=(), kwargs=None):
        super().__init__(target=target, name=name, args=args,
                         kwargs=kwargs or {}, daemon=True)


def parse_addr_list(spec):
    """Parse a comma-separated ``host:port`` list into [(host, port)].
    Raises on a missing/non-numeric port instead of silently mis-splitting
    (ref: NetUtils.createSocketAddr's strict parsing)."""
    out = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        host, sep, port = part.rpartition(":")
        if not sep or not port.isdigit():
            raise ValueError(f"invalid host:port {part!r} in {spec!r}")
        out.append((host or "127.0.0.1", int(port)))
    return out


def free_port(host: str = "127.0.0.1") -> int:
    """Ephemeral port for minicluster daemons (ref: MiniDFSCluster port=0 use)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


class StopWatch:
    def __init__(self, start: bool = True):
        self._t0 = time.monotonic() if start else None
        self._elapsed = 0.0

    def start(self) -> "StopWatch":
        self._t0 = time.monotonic()
        return self

    def stop(self) -> float:
        if self._t0 is not None:
            self._elapsed += time.monotonic() - self._t0
            self._t0 = None
        return self._elapsed

    def elapsed(self) -> float:
        if self._t0 is not None:
            return self._elapsed + (time.monotonic() - self._t0)
        return self._elapsed


class PauseMonitor:
    """Detects interpreter stalls (GC, GIL convoys, host overload) by measuring
    oversleep of a fixed-interval sleeper. Ref: util/JvmPauseMonitor.java:47 —
    same detection principle (sleep 500ms, warn when the wakeup is late).
    """

    def __init__(self, warn_threshold_s: float = 1.0, interval_s: float = 0.5,
                 on_pause: Optional[Callable[[float], None]] = None):
        self.warn_threshold_s = warn_threshold_s
        self.interval_s = interval_s
        self.pauses: List[float] = []
        self._on_pause = on_pause
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = Daemon(self._run, "pause-monitor")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2 * self.interval_s + 1)

    def _run(self) -> None:
        while not self._stop.is_set():
            t0 = time.monotonic()
            self._stop.wait(self.interval_s)
            overslept = (time.monotonic() - t0) - self.interval_s
            if overslept > self.warn_threshold_s:
                self.pauses.append(overslept)
                log.warning("Detected pause of ~%.2fs (threshold %.2fs)",
                            overslept, self.warn_threshold_s)
                if self._on_pause:
                    self._on_pause(overslept)


# Shared retry randomness: one process-wide generator so tests can seed
# it (misc.RETRY_RNG.seed(0)) and get deterministic delay sequences
# without monkeypatching every retry site.
import random as _random  # noqa: E402 — grouped with its consumer

RETRY_RNG = _random.Random()


def backoff_delay(base_s: float, attempt: int, max_s: float = 30.0,
                  rng=None) -> float:
    """Exponential backoff with full-range jitter (ref:
    io/retry/RetryPolicies.exponentialBackoffRetry — delay doubles per
    attempt, then is scaled by a random factor in [0.5, 1.5) so a fleet
    of clients never retries in lockstep)."""
    rng = RETRY_RNG if rng is None else rng
    return min(max_s, base_s * (2 ** attempt)) * (0.5 + rng.random())


class RetryOnException:
    """Bounded retry helper for idempotent host-side calls; delays grow
    exponentially with jitter (util.misc.backoff_delay)."""

    def __init__(self, attempts: int = 3, delay_s: float = 0.1, backoff: float = 2.0,
                 retryable=(OSError, ConnectionError), max_delay_s: float = 30.0):
        self.attempts = attempts
        self.delay_s = delay_s
        self.backoff = backoff
        self.retryable = retryable
        self.max_delay_s = max_delay_s

    def call(self, fn: Callable, *args, **kwargs):
        for i in range(self.attempts):
            try:
                return fn(*args, **kwargs)
            except self.retryable:
                if i == self.attempts - 1:
                    raise
                # honor the caller's growth factor (backoff=1.0 means
                # constant-with-jitter) — same jitter law as backoff_delay
                delay = min(self.max_delay_s,
                            self.delay_s * (self.backoff ** i))
                time.sleep(delay * (0.5 + RETRY_RNG.random()))


def local_host_names() -> set:
    """Names/addresses that mean "this host" — shared by the short-circuit
    read lane and the local shuffle fetch lane (ref: the reference's
    DomainSocketFactory.getPathInfo locality check)."""
    import socket as _socket
    names = {"127.0.0.1", "localhost", "::1"}
    try:
        hn = _socket.gethostname()
        names.add(hn)
        names.add(_socket.gethostbyname(hn))
    except OSError:
        pass
    return names


def check_dir(path: str, min_free_bytes: int = 0) -> None:
    """Health-check a storage directory: exists (created if needed),
    writable, readable, and above the free-space floor — raising
    DiskErrorException-style OSError otherwise (ref: util/DiskChecker
    .java checkDir + the DN's startup/failed-volume policy)."""
    import os
    try:
        os.makedirs(path, exist_ok=True)
    except OSError as e:
        raise OSError(f"cannot create storage dir {path}: {e}") from e
    if not os.access(path, os.W_OK):
        raise OSError(f"storage dir {path} is not writable")
    if not os.access(path, os.R_OK):
        raise OSError(f"storage dir {path} is not readable")
    probe = os.path.join(path, ".disk-check")
    try:
        with open(probe, "w") as f:
            f.write("ok")
        os.remove(probe)
    except OSError as e:
        raise OSError(f"storage dir {path} failed write probe: {e}") from e
    if min_free_bytes:
        st = os.statvfs(path)
        free = st.f_bavail * st.f_frsize
        if free < min_free_bytes:
            raise OSError(f"storage dir {path} below free-space floor: "
                          f"{free} < {min_free_bytes}")
