"""hadoop_tpu.yarn — cluster resource management.

Capability-equivalent rebuild of YARN (ref: hadoop-yarn-project): a
ResourceManager (app lifecycle state machines over an async dispatcher,
pluggable FIFO/capacity schedulers, AM liveness), node agents that launch
containers as real processes with TPU chips as a first-class resource
dimension, and client libraries (YarnClient / AMRMClient / NMClient).
"""
