"""YARN client libraries: YarnClient, AMRMClient, NMClient.

Parity with the reference client layer (ref: hadoop-yarn-client
YarnClientImpl.java:333 submitApplication (+ polling loop :384),
AMRMClient.java / AMRMClientImpl, NMClientImpl.java).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, Dict, List, Optional, Tuple

from hadoop_tpu.conf import Configuration
from hadoop_tpu.ipc import Client, get_proxy
from hadoop_tpu.yarn.records import (ApplicationId, ApplicationReport,
                                     ApplicationSubmissionContext, AppState,
                                     Container, ContainerId,
                                     ContainerLaunchContext, ContainerStatus,
                                     Resource, ResourceRequest)

log = logging.getLogger(__name__)


class YarnClient:
    """Ref: YarnClientImpl.java."""

    def __init__(self, rm_addr: Tuple[str, int],
                 conf: Optional[Configuration] = None):
        self.conf = conf or Configuration()
        self._client = Client(self.conf)
        self.rm = get_proxy("ClientRMProtocol", rm_addr, client=self._client)

    def create_application(self) -> Tuple[ApplicationId, Resource]:
        resp = self.rm.get_new_application()
        return (ApplicationId.from_wire(resp["app_id"]),
                Resource.from_wire(resp["max_resource"]))

    def submit_application(self, ctx: ApplicationSubmissionContext,
                           wait_accepted: bool = True,
                           timeout: float = 30.0) -> ApplicationId:
        """Submit + poll until past NEW/SUBMITTED.
        Ref: YarnClientImpl.submitApplication:333 (poll :384)."""
        self.rm.submit_application(ctx.to_wire())
        if wait_accepted:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                report = self.application_report(ctx.app_id)
                if report.state not in (AppState.NEW, AppState.SUBMITTED):
                    return ctx.app_id
                time.sleep(0.1)
            raise TimeoutError(f"{ctx.app_id} still not accepted")
        return ctx.app_id

    def application_report(self, app_id: ApplicationId) -> ApplicationReport:
        return ApplicationReport.from_wire(
            self.rm.get_application_report(app_id.to_wire()))

    def wait_for_completion(self, app_id: ApplicationId,
                            timeout: float = 300.0) -> ApplicationReport:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            report = self.application_report(app_id)
            if report.state in AppState.TERMINAL:
                return report
            time.sleep(0.2)
        raise TimeoutError(f"{app_id} did not finish in {timeout}s")

    def kill_application(self, app_id: ApplicationId) -> None:
        self.rm.kill_application(app_id.to_wire())

    def list_applications(self) -> List[ApplicationReport]:
        return [ApplicationReport.from_wire(d)
                for d in self.rm.list_applications()]

    def cluster_metrics(self) -> Dict:
        return self.rm.get_cluster_metrics()

    def nodes(self) -> List[Dict]:
        return self.rm.get_nodes()

    def close(self) -> None:
        self._client.stop()


class AMRMClient:
    """The AM's RM-facing helper: ask/release bookkeeping around the
    allocate heartbeat. Ref: AMRMClientImpl.java."""

    def __init__(self, attempt_id: str, rm_addr: Tuple[str, int],
                 conf: Optional[Configuration] = None):
        self.attempt_id = attempt_id
        self.conf = conf or Configuration()
        self._client = Client(self.conf)
        self.rm = get_proxy("AMRMProtocol", rm_addr, client=self._client)
        self._asks: List[ResourceRequest] = []
        self._releases: List[ContainerId] = []
        # set when allocate() had to re-register after an RM restart; the
        # AM should then resend asks for still-pending work (the RM's ask
        # table restarted empty) and clear the flag
        self.resynced = False

    @classmethod
    def from_env(cls, conf: Optional[Configuration] = None) -> "AMRMClient":
        """Inside an AM container, identity arrives via env (set by the
        AMLauncher — ref: ApplicationConstants.Environment)."""
        attempt_id = os.environ["HTPU_ATTEMPT_ID"]
        host, port = os.environ["HTPU_RM_ADDRESS"].rsplit(":", 1)
        return cls(attempt_id, (host, int(port)), conf)

    def register(self, tracking_url: str = "") -> Dict:
        return self.rm.register_application_master(self.attempt_id,
                                                   tracking_url)

    def add_request(self, priority: int, count: int, capability: Resource,
                    host: str = "*") -> None:
        self._asks.append(ResourceRequest(priority, count, capability, host))

    def release(self, container_id: ContainerId) -> None:
        self._releases.append(container_id)

    def allocate(self, progress: float = 0.0
                 ) -> Tuple[List[Container], List[ContainerStatus]]:
        asks, self._asks = self._asks, []
        releases, self._releases = self._releases, []
        try:
            resp = self.rm.allocate(self.attempt_id,
                                    [a.to_wire() for a in asks],
                                    [r.to_wire() for r in releases],
                                    progress)
        except Exception as e:  # noqa: BLE001
            if "unknown attempt" not in str(e):
                self._asks = asks + self._asks
                self._releases = releases + self._releases
                raise
            # RM restarted (work-preserving): re-register and resend the
            # outstanding ask table (ref: AMRMClientImpl.registerAgain on
            # ApplicationMasterNotRegisteredException)
            log.warning("RM lost attempt state; re-registering %s",
                        self.attempt_id)
            self.register()
            self.resynced = True
            resp = self.rm.allocate(self.attempt_id,
                                    [a.to_wire() for a in asks],
                                    [r.to_wire() for r in releases],
                                    progress)
        return ([Container.from_wire(c) for c in resp["allocated"]],
                [ContainerStatus.from_wire(s) for s in resp["completed"]])

    def unregister(self, final_status: str = "SUCCEEDED",
                   diagnostics: str = "") -> None:
        self.rm.finish_application_master(self.attempt_id, final_status,
                                          diagnostics)

    def close(self) -> None:
        self._client.stop()


class NMClient:
    """Start/stop containers on node agents. Ref: NMClientImpl.java."""

    def __init__(self, conf: Optional[Configuration] = None):
        self.conf = conf or Configuration()
        self._client = Client(self.conf)

    def _nm(self, container: Container):
        host, port = container.nm_address.rsplit(":", 1)
        return get_proxy("ContainerManagerProtocol", (host, int(port)),
                         client=self._client)

    def start_container(self, container: Container,
                        ctx: ContainerLaunchContext) -> None:
        self._nm(container).start_container(container.to_wire(),
                                            ctx.to_wire())

    def stop_container(self, container: Container) -> None:
        self._nm(container).stop_container(container.container_id.to_wire())

    def container_status(self, container: Container) -> Optional[ContainerStatus]:
        d = self._nm(container).get_container_status(
            container.container_id.to_wire())
        return None if d is None else ContainerStatus.from_wire(d)

    def close(self) -> None:
        self._client.stop()
