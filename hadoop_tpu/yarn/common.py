"""Event bus + declarative state machines — the RM/NM substrate.

Parity with yarn-common's core machinery (ref:
yarn/event/AsyncDispatcher.java:51, yarn/state/StateMachineFactory.java:46):
every daemon-side lifecycle object (app, attempt, container) is a state
machine whose transitions fire on events delivered by a single dispatcher
thread — serialization by design, no per-object locking.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from hadoop_tpu.service import AbstractService

log = logging.getLogger(__name__)


class Event:
    __slots__ = ("etype", "payload")

    def __init__(self, etype: str, payload: Any = None):
        self.etype = etype
        self.payload = payload

    def __repr__(self):
        return f"Event({self.etype})"


class AsyncDispatcher(AbstractService):
    """Single-threaded event loop with per-type handler registry.
    Ref: yarn/event/AsyncDispatcher.java."""

    def __init__(self, name: str = "dispatcher"):
        super().__init__(name)
        self._queue: "queue.Queue[Optional[Tuple[str, Event]]]" = queue.Queue()
        self._handlers: Dict[str, Callable[[Event], None]] = {}
        self._thread: Optional[threading.Thread] = None
        self._drained = threading.Event()

    def register(self, category: str, handler: Callable[[Event], None]) -> None:
        self._handlers[category] = handler

    def dispatch(self, category: str, event: Event) -> None:
        self._queue.put((category, event))

    def handler(self, category: str) -> Callable[[Event], None]:
        return lambda ev: self.dispatch(category, ev)

    def service_start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"{self.name}-thread")
        self._thread.start()

    def service_stop(self) -> None:
        self._queue.put(None)
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _run(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            category, event = item
            handler = self._handlers.get(category)
            if handler is None:
                log.warning("No handler for category %r (%r)", category, event)
                continue
            try:
                handler(event)
            except Exception:
                # Ref: AsyncDispatcher logs & continues (RM crash-on-error is
                # opt-in via yarn.dispatcher.exit-on-error).
                log.exception("Error dispatching %r to %r", event, category)

    def drain(self, timeout: float = 5.0) -> bool:
        """Test helper: wait until the queue momentarily empties."""
        import time
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._queue.empty():
                return True
            time.sleep(0.01)
        return False


class InvalidStateTransitionError(RuntimeError):
    def __init__(self, state: str, event: str):
        super().__init__(f"invalid event {event!r} in state {state!r}")
        self.state = state
        self.event = event


class StateMachineFactory:
    """Declarative transition table, instantiated per stateful object.

    Ref: yarn/state/StateMachineFactory.java — addTransition(pre, post,
    event, hook) with multi-post-state transitions whose hook returns the
    actual post state.

        factory = (StateMachineFactory("NEW")
            .add("NEW", "SUBMITTED", "start", on_start)
            .add("SUBMITTED", ("ACCEPTED", "FAILED"), "attempt_added", pick))
        sm = factory.make(owner)
        sm.handle("start", payload)
    """

    def __init__(self, initial_state: str):
        self.initial_state = initial_state
        # (state, event) -> (post_states tuple, hook)
        self._table: Dict[Tuple[str, str], Tuple[Tuple[str, ...], Optional[Callable]]] = {}

    def add(self, pre: str, post, event: str,
            hook: Optional[Callable] = None) -> "StateMachineFactory":
        posts = (post,) if isinstance(post, str) else tuple(post)
        self._table[(pre, event)] = (posts, hook)
        return self

    def add_many(self, pres: List[str], post, event: str,
                 hook: Optional[Callable] = None) -> "StateMachineFactory":
        for pre in pres:
            self.add(pre, post, event, hook)
        return self

    def make(self, owner: Any) -> "StateMachine":
        return StateMachine(self, owner)


class StateMachine:
    def __init__(self, factory: StateMachineFactory, owner: Any):
        self._factory = factory
        self.owner = owner
        self.state = factory.initial_state

    def handle(self, event: str, payload: Any = None) -> str:
        key = (self.state, event)
        entry = self._factory._table.get(key)
        if entry is None:
            raise InvalidStateTransitionError(self.state, event)
        posts, hook = entry
        if hook is None:
            assert len(posts) == 1, "multi-state transition requires a hook"
            self.state = posts[0]
            return self.state
        result = hook(self.owner, payload)
        if len(posts) == 1:
            self.state = posts[0]
        else:
            if result not in posts:
                raise RuntimeError(
                    f"hook returned {result!r}, not one of {posts}")
            self.state = result
        return self.state

    def can_handle(self, event: str) -> bool:
        return (self.state, event) in self._factory._table
